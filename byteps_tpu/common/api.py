"""Horovod-compatible top-level API.

Mirrors the reference's plugin surface — init/shutdown/suspend/resume, rank/
size/local_rank/local_size, declare, push_pull(_async)/synchronize/poll,
broadcast_parameters/broadcast_optimizer_state, get_pushpull_speed
(reference: byteps/torch/__init__.py:23-28, byteps/common/__init__.py:52-139,
byteps/torch/ops.py:157-236) — re-mapped onto JAX's single-controller model:

  - a *worker* is a JAX process (host); devices a process drives are its
    "local GPUs", but unlike the reference (one process per GPU,
    communicator.cc:60-96) the intra-host tier needs no UDS/shm machinery —
    the in-jit mesh collectives cover it.
  - eager push_pull reduces across processes via a jitted collective
    (multihost_utils); inside jit, use byteps_tpu.ops.collectives /
    DistributedOptimizer, which is the hot path.

The eager path exists for API parity and for small out-of-graph tensors
(metric averaging, parameter broadcast), exactly the role the reference's
synchronous handle API plays for torch.
"""

from __future__ import annotations

import dataclasses
import functools
import os
import threading
import time
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import doctor as doctor_mod
from . import devprof, flightrec, signals, telemetry
from .config import Config, get_config
from .logging import get_logger, set_level, set_rank
from ..core.native import get_core

PyTree = Any


@dataclasses.dataclass
class _State:
    initialized: bool = False
    config: Optional[Config] = None
    step: int = 0
    step_start_us: Optional[int] = None
    jax_dist_initialized: bool = False
    handles: Dict[int, Any] = dataclasses.field(default_factory=dict)
    lock: threading.Lock = dataclasses.field(default_factory=threading.Lock)
    ps_session: Optional[Any] = None  # PS-mode client session, when enabled
    exporter: Optional[Any] = None    # TelemetryExporter, when enabled
    trace_atexit: bool = False        # crash-flush guard registered
    # Elastic membership: the last fetched view (get_membership /
    # the on_membership_change poller), the registered callback, and the
    # poller plumbing.  size() reads the cached view, so a resize is
    # visible to the training loop without a wire fetch per step.
    membership: Optional[dict] = None
    membership_cb: Optional[Any] = None
    membership_poll_stop: Optional[Any] = None
    membership_poll_thread: Optional[Any] = None
    membership_poll_interval: float = 2.0
    # Windowed key-signal plane + doctor (BYTEPS_TPU_SIGNAL_WINDOW_S>0):
    # the SignalPlane rolls one summary per window, the DoctorEngine
    # evaluates the rules over it; the final verdict is emitted exactly
    # once (shutdown or the atexit guard, whichever runs first).
    signal_plane: Optional[Any] = None
    doctor: Optional[Any] = None
    doctor_verdict_done: bool = False
    doctor_atexit: bool = False
    # Adaptive-compression tuner (BYTEPS_TPU_TUNER=1): chained onto the
    # same window stream as the doctor; worker 0 proposes CMD_CODEC
    # switches, everyone else observes/adopts.
    tuner: Optional[Any] = None
    # PS-tier autoscaler (BYTEPS_TPU_AUTOSCALE=1): chained after the
    # doctor on the same window stream; worker 0 only.
    autoscaler: Optional[Any] = None
    # Fleet observability plane (BYTEPS_TPU_FLEET=1, PS mode): every
    # worker publishes its window summary via CMD_WINDOW; worker 0
    # additionally fetches the merged CMD_FLEET view each window and
    # runs the fleet doctor + goodput ledger over it.
    fleet_engine: Optional[Any] = None       # fleet-rule DoctorEngine (w0)
    fleet_view: Optional[dict] = None        # last merged CMD_FLEET view
    fleet_windows: Optional[list] = None     # last aligned window stream
    fleet_ledger: Optional[dict] = None      # last window's goodput ledger
    fleet_published: Optional[Any] = None    # this worker's publish ring
    # Hierarchical reduction (BYTEPS_TPU_HIERARCHY=1, PS mode): the
    # HierarchicalReducer push_pull_tree/push_pull_async route through —
    # slice-reduce in-graph, leader-only wire round, broadcast back.
    # None (default) keeps the flat path byte-identical.
    hierarchy: Optional[Any] = None


_state = _State()


def _require_init():
    if not _state.initialized:
        raise RuntimeError("byteps_tpu not initialized; call bps.init() first")


# ---------------------------------------------------------------------------
# Lifecycle (reference: operations.cc:28-119)
# ---------------------------------------------------------------------------
def _configure_cpu_collectives() -> None:
    """Cross-process collectives on the CPU platform need a collectives
    backend (gloo, compiled into jaxlib); TPU's ICI/DCN needs nothing.  Must
    run before the first backend creation.  The setting only affects CPU
    client creation, so it is applied unconditionally — platform
    autodetection may resolve to cpu without JAX_PLATFORMS ever being set.
    BYTEPS_TPU_CPU_COLLECTIVES overrides the implementation
    ("gloo" | "mpi")."""
    impl = os.environ.get("BYTEPS_TPU_CPU_COLLECTIVES", "gloo").strip()
    try:
        jax.config.update("jax_cpu_collectives_implementation", impl)
    except Exception as e:  # unknown impl name / too-old jax
        get_logger().warning("could not set cpu collectives impl %r: %s",
                             impl, e)


def _reset_jax_backends() -> None:
    """Drop cached XLA clients and the api-level topology caches
    (jax.process_count & co are memoized) so the next backend creation sees
    the *current* jax.distributed world.  This is what makes elastic resize
    possible: the reference re-runs ps-lite StartAsync against new DMLC_*
    envs (reference: operations.cc:107-119); JAX caches its client, so an
    equivalent re-init requires explicitly forgetting the old backend.

    Raises rather than warns on failure: proceeding with a stale backend
    would silently keep the old world size — wrong averages or a hang."""
    from jax._src import xla_bridge as xb
    xb._clear_backends()
    jax.clear_caches()
    from jax._src import util as _jax_util
    _jax_util.clear_all_caches()


def init(lazy: bool = True) -> None:
    """Initialize the framework.

    If the DMLC_* multi-host envs describe a JAX distributed run
    (coordinator + process id), `jax.distributed.initialize` is called so the
    process joins the global mesh — the analog of the reference's ps-lite
    StartAsync + scheduler barrier (reference: global.cc:283-297).
    """
    if _state.initialized:
        return
    cfg = get_config(refresh=True)
    _state.config = cfg
    if cfg.num_worker > 1 and os.environ.get("BYTEPS_TPU_JAX_DIST", "0") == "1":
        # Multi-host: map the reference's scheduler to JAX's coordinator.
        _configure_cpu_collectives()
        jax.distributed.initialize(
            coordinator_address=f"{cfg.scheduler_uri}:{cfg.scheduler_port}",
            num_processes=cfg.num_worker,
            process_id=cfg.worker_id,
        )
        _state.jax_dist_initialized = True
    set_level(cfg.log_level)   # honor a refreshed level on init/resume
    core = get_core()
    if cfg.trace_on:
        # Honor the window from the start: with START_STEP > 0 the tracer
        # (and the traced wire flags the server records spans for) stays
        # off until mark_step enters the window — the same law mark_step
        # applies at every boundary.
        core.trace_enable(cfg.trace_start_step <= _state.step
                          <= cfg.trace_end_step)
        if not _state.trace_atexit:
            # Crash flush: a run that dies mid-window (exception, failed
            # watchdog) still leaves a usable trace file — atexit runs on
            # interpreter teardown either way, and a clean shutdown()
            # already drained the buffer so the guard is then a no-op.
            import atexit
            atexit.register(_dump_trace_on_exit)
            _state.trace_atexit = True
    if cfg.ps_mode and cfg.role == "worker":
        try:
            from ..server.client import PSSession
        except ImportError as e:
            raise RuntimeError(
                "BYTEPS_TPU_PS_MODE=1 requires the PS server tier "
                "(byteps_tpu.server.client), which is missing from this "
                "build") from e
        _state.ps_session = PSSession.from_config(cfg)
        _state.ps_session.barrier()
        if cfg.evict_timeout_s > 0:
            # Elasticity armed: size()/averages must follow an eviction
            # even when the app never registers a callback or calls
            # get_membership() — dividing by a stale launch count would
            # silently corrupt every post-eviction gradient.  Fixed jobs
            # (timeout 0) start no poller and send no extra traffic.
            _start_membership_poller(cfg.membership_poll_s)
        if cfg.trace_on:
            # Clock alignment at trace-enable (NTP midpoint over
            # timestamped CMD_PINGs) + the periodic re-sync thread, so
            # server spans land on this worker's timeline.  An old
            # server only loses the server half of the trace.
            try:
                _state.ps_session.sync_clocks()
                _state.ps_session.start_clock_sync()
            except Exception as e:
                get_logger().warning(
                    "server clock sync unavailable (%s); trace will "
                    "carry worker spans only", e)
    if cfg.hierarchy:
        # Hierarchical reduction (docs/architecture.md "Hierarchical
        # reduction"): slice-reduce in-graph, one leader per slice on
        # the wire.  PS mode only — the in-graph collective plane
        # already composes its own hierarchy through the mesh axes.
        if _state.ps_session is None:
            get_logger().warning(
                "BYTEPS_TPU_HIERARCHY=1 outside PS mode is a no-op: "
                "the collective plane reduces intra-slice in-graph "
                "already (dp/ici_dp mesh axes) — the knob arms the PS "
                "tier's leader-aware push_pull only")
        else:
            from ..parallel import hierarchy as hierarchy_mod
            _state.hierarchy = hierarchy_mod.maybe_reducer(
                _state.ps_session)
            if _state.hierarchy is not None:
                h = _state.hierarchy
                get_logger().info(
                    "hierarchical reduction armed: slice=%d size=%d "
                    "members=%s leader=%s", h.slice_id, h.slice_size,
                    h.group.members, h.leader())
                # Misconfig check while it is still cheap to name: a
                # flat server under leader-only pushes would otherwise
                # just hang every round until the wait timeout.
                mismatch = h.verify_topology()
                if mismatch:
                    get_logger().error(
                        "hierarchical topology mismatch: %s", mismatch)
    _state.initialized = True
    # Black-box flight recorder: lifecycle events always record (bounded
    # in-memory ring, no I/O); postmortem bundles + the faulthandler
    # crash file arm only when BYTEPS_TPU_POSTMORTEM_DIR is set.  The
    # extra provider hands the bundle writer this process's cached
    # membership/step/session sections — local state only, no wire.
    flightrec.set_extra_provider(_postmortem_extra)
    flightrec.record("init", role=cfg.role, rank=rank(), size=size())
    if cfg.postmortem_dir:
        flightrec.arm_postmortem(cfg.postmortem_dir)
    if size() > 1:
        # Rank-tag the log prefix now that init() knows it: multi-worker
        # stderr interleaves indistinguishably otherwise.  Single-worker
        # runs (and everything logged before init) keep the old format.
        set_rank(rank())
    _register_builtin_collectors()
    if cfg.devprof:
        # Device plane (common/devprof.py): arm the profiler, run the
        # init-time sentinel probe (the re-probe rides every window
        # roll below), and hand the flight recorder its `device` bundle
        # section.  Off (default): none of this exists — zero gauges,
        # zero frames, the trainer hooks are a None check.
        prof = devprof.arm(intended_platform=cfg.device_platform,
                           worker=cfg.worker_id,
                           telemetry_on=cfg.telemetry_on)
        probe = prof.probe()
        if probe.get("fallback"):
            get_logger().error(
                "device sentinel convicted a fallback at init: %s",
                probe.get("reason"))
        flightrec.set_extra_provider(prof.flight_section, name="device")
    # One knob, one meaning: the plane arms iff SIGNAL_WINDOW_S > 0.
    # Deliberately NOT gated on BYTEPS_TELEMETRY_ON (which only governs
    # the throughput/step-time feeds) — a hidden second condition would
    # make "I set the window and got no doctor" undiagnosable.
    if cfg.signal_window_s > 0:
        _start_signal_plane(cfg)
    elif cfg.tuner:
        get_logger().warning(
            "BYTEPS_TPU_TUNER=1 but the signal plane is off "
            "(BYTEPS_TPU_SIGNAL_WINDOW_S=0): the tuner consumes the "
            "plane's classified windows and cannot run without it — "
            "set a window to arm the loop")
    if cfg.metrics_port > 0 or cfg.metrics_log:
        try:
            _state.exporter = telemetry.TelemetryExporter(
                telemetry.get_registry(), port=cfg.metrics_port,
                jsonl_path=cfg.metrics_log,
                max_log_mb=cfg.metrics_log_mb,
                refresh=_refresh_server_metrics,
                routes=_signal_routes()).start()
        except OSError as e:
            # A taken port / unwritable log path must not kill training —
            # the metrics plane is an observer, never a dependency.
            get_logger().error(
                "metrics exporter failed to start "
                "(BYTEPS_TPU_METRICS_PORT=%d, BYTEPS_TPU_METRICS_LOG=%r): "
                "%s — continuing without it", cfg.metrics_port,
                cfg.metrics_log, e)
            _state.exporter = None
    get_logger().info(
        "byteps_tpu initialized: role=%s rank=%d/%d local_size=%d devices=%d",
        cfg.role, rank(), size(), local_size(), jax.device_count())


def shutdown() -> None:
    if not _state.initialized:
        return
    flightrec.record("shutdown", step=_state.step)
    if _state.membership_poll_stop is not None:
        _state.membership_poll_stop.set()
        _state.membership_poll_stop = None
        _state.membership_poll_thread = None
        _state.membership_cb = None
    _state.membership = None
    # Close the signal plane's last window and emit the doctor verdict
    # BEFORE the session teardown: the final roll's CMD_STATS refresh
    # and the verdict's finding set both want the live session.
    _stop_signal_plane()
    if _state.exporter is not None:
        # Before the session teardown: the exporter's refresh hook polls
        # the live session for CMD_STATS.
        _state.exporter.stop()
        _state.exporter = None
    # Dump BEFORE the session teardown: the merged export drains the
    # server-side span ring over the live connections — and BEFORE the
    # device plane disarms, so a run that never reached its trace end
    # step still gets its device lanes in the final merged export.
    _maybe_dump_trace(final=True)
    prof = devprof.active()
    if prof is not None:
        # Freeze the bundle's device section to the final snapshot (the
        # same static-provider law _stop_signal_plane applies): bundles
        # dumped after shutdown still answer "was it on-chip?".
        snap = prof.flight_section()
        flightrec.set_extra_provider(lambda: snap, name="device")
        devprof.disarm()
    if _state.hierarchy is not None:
        # Retire this session's SliceGroup from the process registry: a
        # re-init must meet fresh rendezvous counters (a failed round
        # can leave them desynced), while groups other in-process
        # workers hold stay untouched.
        from ..parallel.hierarchy import drop_slice_group
        drop_slice_group(_state.hierarchy.group)
    _state.hierarchy = None
    if _state.ps_session is not None:
        _state.ps_session.close()
        _state.ps_session = None
    if _state.jax_dist_initialized:
        # Required for elastic resume: a second jax.distributed.initialize
        # raises unless the first is torn down.
        jax.distributed.shutdown()
        _state.jax_dist_initialized = False
    _state.initialized = False


def suspend() -> None:
    """Elastic suspend: tear down communication, keep the registry so keys
    stay stable on resume (reference: operations.cc:96-105)."""
    shutdown()


def resume(num_workers: int, num_servers: int = 0) -> None:
    """Elastic resume with a new cluster size.  Re-reads env config and
    re-declares all tensors in original order so key assignment is unchanged
    (reference: operations.cc:107-119, global.cc:446-451).

    When the collective tier is in use (BYTEPS_TPU_JAX_DIST=1), the XLA
    backend is rebuilt for the new world size.  Device arrays created before
    suspend() belong to the old backend and must be staged through host
    memory across the resize (np.asarray before suspend, re-feed after
    resume) — the analog of the reference's requirement that tensors be
    re-declared against the new ps-lite session.
    """
    if _state.initialized:
        # resume() implies the previous session is over; make that true
        # before tearing down backends under live arrays.
        suspend()
    os.environ["DMLC_NUM_WORKER"] = str(num_workers)
    os.environ["DMLC_NUM_SERVER"] = str(num_servers)
    if os.environ.get("BYTEPS_TPU_JAX_DIST", "0") == "1":
        # Both grow and shrink need a fresh client: the cached one pins the
        # previous world's process count and gloo context.
        _reset_jax_backends()
    core = get_core()
    # The registry is preserved across suspend (the whole point); walk it so
    # any native-side rebuild keeps the original order.
    names = [core.declared_name(i) for i in range(core.num_declared())]
    init(lazy=True)
    for n in names:
        if n is not None:
            core.declare_tensor(n)


# ---------------------------------------------------------------------------
# Topology (reference: common/__init__.py:83-128)
# ---------------------------------------------------------------------------
def _env_cluster(cfg) -> bool:
    """True when the DMLC_* envs describe a multi-worker cluster that JAX's
    process topology doesn't know about (PS mode, or pre-jax.distributed
    launch): rank/size must come from the env, as the reference's do
    (reference: communicator.cc:60-96)."""
    return cfg.num_worker > 1 and not _state.jax_dist_initialized


def rank() -> int:
    cfg = _state.config or get_config()
    if cfg.global_rank is not None:
        return cfg.global_rank
    if _state.ps_session is not None or _env_cluster(cfg):
        return cfg.worker_id
    return jax.process_index()


def size() -> int:
    cfg = _state.config or get_config()
    if _state.ps_session is not None or _env_cluster(cfg):
        # Elastic membership: once the epoch has ever advanced, the world
        # is the LIVE worker set, not the launch-time DMLC_NUM_WORKER —
        # averages and per-rank sharding must rescale with it.  The view
        # is the cached one (refreshed by get_membership() and the
        # on_membership_change poller), so this stays a dict read on the
        # hot path; a fixed-membership job (epoch 0 / nothing cached)
        # keeps the launch count exactly.
        m = _state.membership
        if m is not None and int(m.get("epoch", 0)) > 0:
            return max(1, len(m.get("alive", ())))
        return cfg.num_worker
    return jax.process_count()


def local_rank() -> int:
    cfg = _state.config or get_config()
    return cfg.local_rank


def local_size() -> int:
    return jax.local_device_count()


# ---------------------------------------------------------------------------
# Declaration & keys (reference: global.cc:427-451, operations.cc:301-311)
# ---------------------------------------------------------------------------
def declare(name: str) -> int:
    """Assign (or look up) the deterministic key for a named tensor."""
    return get_core().declare_tensor(name)


def declared_key(name: str) -> int:
    return get_core().get_declared_key(name)


def register_compressor(name: str, kwargs: dict) -> int:
    """Register inter-node compression for a named tensor's PS traffic.

    The kwargs use the same strings as the reference registry
    ({"compressor": "onebit", ...}; reference: mxnet/__init__.py:236-317)
    and are shipped to the server at the tensor's INIT so it can
    decompress-sum(-recompress) (reference: operations.cc:396-408).
    Returns the declared key.  No-op outside PS mode: the collective plane
    configures compression via DistributedOptimizer instead.
    """
    _require_init()
    dk = declare(name)
    if _state.ps_session is not None:
        _state.ps_session.register_compressor(dk, kwargs)
    return dk


def get_ps_session():
    """The live PS-mode session, or None (collective mode).  Used by
    AsyncPSTrainer and power users driving the KV tier directly."""
    return _state.ps_session


# ---------------------------------------------------------------------------
# Elastic membership (docs/elasticity.md): the worker set is an
# epoch-versioned, server-negotiated table.  Joins happen implicitly (a new
# worker's init() HELLO admits it at the next epoch boundary); leaves are
# explicit (bps.leave()); evictions are lease expiries when
# BYTEPS_TPU_EVICT_TIMEOUT_S > 0.  size() follows the live set once the
# epoch has ever advanced.
# ---------------------------------------------------------------------------
def leave(drain_timeout_s: float = 60.0) -> None:
    """Gracefully exit the worker membership (PS mode).

    Drains this worker's in-flight rounds, then removes it from every
    server's membership at the next epoch boundary — survivors' open
    rounds re-finalize without it and their size() shrinks at their next
    membership refresh.  Call it before shutdown() when the departure is
    planned (autoscaler scale-down, preemption notice); an unplanned death
    is covered by lease eviction instead.  No-op outside PS mode (the
    collective plane resizes through suspend()/resume())."""
    _require_init()
    if _state.ps_session is None:
        get_logger().warning(
            "bps.leave() outside PS mode is a no-op: collective-plane "
            "resizes go through suspend()/resume()")
        return
    _state.ps_session.leave(drain_timeout_s)


def get_ring() -> dict:
    """The elastic PS server ring (CMD_RING): epoch, vnodes, member
    (id, host, port) rows, per-server keys_owned and draining flags.
    Requires PS mode with the ring armed (``BYTEPS_TPU_RING=1``);
    returns a fixed single-epoch synthetic view otherwise.  A pre-ring
    server surfaces as a clean "server too old" error, never a hang."""
    _require_init()
    sess = _state.ps_session
    if sess is None or not getattr(sess, "ring_armed", False):
        cfg = _state.config or get_config()
        n = max(1, cfg.num_server) if sess is not None else 0
        return {"epoch": 0, "armed": 0, "vnodes": cfg.ring_vnodes,
                "servers": [{"id": i} for i in range(n)]}
    return sess.get_ring()


def drain_ps_server(server_id: int, timeout_s: float = 120.0,
                    shutdown: bool = False) -> dict:
    """Gracefully scale the PS tier down by one server (CMD_DRAIN).

    The target streams every owned key's state — declared meta, merge
    store, published round, completed_round, the open round's
    contributor set — to its new consistent-hash owner, then answers
    every later frame with a redirect; sums are exact across the
    migration boundary.  Blocks until the target owns zero keys;
    ``shutdown=True`` also retires the process.  Requires PS mode with
    the ring armed (``BYTEPS_TPU_RING=1`` on workers and servers).
    Call it from ONE worker (the autoscaler's controller); the rest
    discover the new epoch through redirects and re-plan on their own.
    """
    _require_init()
    if _state.ps_session is None:
        raise RuntimeError(
            "bps.drain_ps_server() requires PS mode (BYTEPS_TPU_PS_MODE=1)")
    return _state.ps_session.drain_server(server_id, timeout_s=timeout_s,
                                          shutdown=shutdown)


def get_membership(refresh: bool = True) -> dict:
    """The current worker membership: ``{"epoch", "workers": {id:
    {"alive", "age_ms"}}, "alive": [ids], "barrier": {...}}``.

    In PS mode this is the server-negotiated epoch-versioned table
    (merged across servers); ``refresh=False`` returns the cached view
    without touching the wire.  Outside PS mode (or before the first
    fetch with refresh off) it synthesizes the fixed launch world —
    epoch 0, every rank alive.  Fetches also feed the
    ``bps_membership_epoch`` / ``bps_workers_alive`` /
    ``bps_worker_alive`` gauges."""
    _require_init()
    if _state.ps_session is not None and refresh:
        m = _state.ps_session.membership()
        _state.membership = m
        telemetry.update_membership(m)
        return m
    if _state.membership is not None:
        return _state.membership
    n = size()
    return {"epoch": 0,
            "workers": {i: {"alive": True, "age_ms": 0.0}
                        for i in range(n)},
            "alive": list(range(n)), "barrier": {}}


def _start_membership_poller(interval: float) -> None:
    """Idempotently start the CMD_MEMBERS poller: refresh the cached
    membership view (what size() reads) and the liveness gauges every
    ``interval`` seconds, and fire the registered callback on each epoch
    change.  Started by init() whenever elasticity is armed
    (BYTEPS_TPU_EVICT_TIMEOUT_S > 0) — so size() tracks an eviction even
    when no callback was registered and nothing else polls — and by
    on_membership_change() for callback users."""
    # The interval lives in _state so a later caller (e.g.
    # on_membership_change(cb, poll_s=0.2) after init() auto-started the
    # poller at the config default) retunes the LIVE poller instead of
    # being silently ignored; the loop re-reads it every cycle, so the
    # new cadence takes effect after at most one old interval.
    _state.membership_poll_interval = max(0.05, float(interval))
    if _state.membership_poll_thread is not None:
        return
    stop = threading.Event()
    _state.membership_poll_stop = stop

    def _poll():
        last_epoch = (int(_state.membership.get("epoch", 0))
                      if _state.membership else 0)
        while not stop.wait(_state.membership_poll_interval):
            sess = _state.ps_session
            if sess is None:
                return
            try:
                m = sess.membership(timeout=5.0)
            except Exception as e:
                get_logger().debug("membership poll failed: %s", e)
                continue
            _state.membership = m       # size() follows before the cb runs
            telemetry.update_membership(m)
            if int(m.get("epoch", 0)) != last_epoch:
                last_epoch = int(m.get("epoch", 0))
                flightrec.record("membership_epoch", epoch=last_epoch,
                                 alive=list(m.get("alive", ())))
                cb = _state.membership_cb
                if cb is not None:
                    try:
                        cb(m)
                    except Exception:
                        get_logger().exception(
                            "membership-change callback failed")

    t = threading.Thread(target=_poll, daemon=True,
                         name="bps-membership-poll")
    _state.membership_poll_thread = t
    t.start()


def on_membership_change(callback, poll_s: Optional[float] = None) -> None:
    """Register ``callback(membership)`` to fire when the membership
    epoch changes (join, leave, or eviction), so the training loop can
    rescale — re-derive per-rank sharding, LR scaling, data splits —
    without polling by hand.  size()/rank() already follow the new epoch
    by the time the callback runs.

    A background poller (every ``poll_s`` seconds, default
    ``BYTEPS_TPU_MEMBERSHIP_POLL_S``) re-fetches CMD_MEMBERS while a
    callback is registered — or, regardless of callbacks, while
    elasticity is armed (``BYTEPS_TPU_EVICT_TIMEOUT_S > 0``), so size()
    follows evictions either way.  ``on_membership_change(None)``
    unregisters the callback (the poller keeps running if elasticity
    armed it; otherwise it stops) — an unregistered fixed-membership job
    sends no extra wire traffic.  PS mode only."""
    _require_init()
    cfg = _state.config or get_config()
    if callback is None:
        _state.membership_cb = None
        if cfg.evict_timeout_s <= 0 and _state.membership_poll_stop \
                is not None:
            _state.membership_poll_stop.set()
            _state.membership_poll_stop = None
            _state.membership_poll_thread = None
        return
    if _state.ps_session is None:
        raise RuntimeError(
            "bps.on_membership_change() requires PS mode "
            "(BYTEPS_TPU_PS_MODE=1); the collective plane resizes "
            "through suspend()/resume()")
    _state.membership_cb = callback
    _start_membership_poller(poll_s if poll_s is not None
                             else cfg.membership_poll_s)


# ---------------------------------------------------------------------------
# Eager push_pull (reference: torch/ops.py:157-236)
# ---------------------------------------------------------------------------
def _eager_sum_across_processes(x: jax.Array) -> jax.Array:
    """True all-reduce across worker processes.

    One device per process carries the payload on a 1-D mesh; summing the
    process-sharded axis into a replicated output makes XLA emit an
    AllReduce — O(bytes) on the wire instead of the O(world*bytes) of a
    process_allgather + local sum, and one host crossing total (reference
    analog: the reference never gathers either — workers exchange exactly
    one summed copy through the PS tier, server.cc SUM_RECV).
    """
    x = jnp.asarray(x)
    devs, sharded, replicated, reduce_fn = _allreduce_plumbing(
        tuple(jax.devices()))
    shard = jax.device_put(x[None], devs[jax.process_index()])
    g = jax.make_array_from_single_device_arrays(
        (len(devs),) + x.shape, sharded, [shard])
    return jnp.asarray(reduce_fn(g).addressable_data(0))


@functools.lru_cache(maxsize=8)
def _allreduce_plumbing(all_devices: tuple):
    """Mesh + jitted sum-reduction for the eager all-reduce, cached per
    device set — a fresh lambda per call would miss jax.jit's cache (keyed
    on function identity) and retrace every eager push_pull."""
    from jax.sharding import Mesh, NamedSharding
    from jax.sharding import PartitionSpec as P

    by_proc: dict = {}
    for d in all_devices:
        by_proc.setdefault(d.process_index, d)
    devs = [by_proc[i] for i in sorted(by_proc)]
    mesh = Mesh(np.array(devs), ("w",))
    sharded = NamedSharding(mesh, P("w"))
    replicated = NamedSharding(mesh, P())
    reduce_fn = jax.jit(lambda a: a.sum(axis=0), out_shardings=replicated)
    return devs, sharded, replicated, reduce_fn


def push_pull(tensor: jax.Array, name: Optional[str] = None,
              average: bool = True, priority: int = 0,
              compression=None) -> jax.Array:
    """Synchronous eager all-reduce across worker processes.

    For the in-graph hot path use DistributedOptimizer /
    ops.collectives.bucketed_tree_all_reduce instead.
    """
    h = push_pull_async(tensor, name=name, average=average, priority=priority,
                        compression=compression)
    return synchronize(h)


def push_pull_sparse(name: str, indices, rows) -> "np.ndarray":
    """Row-sparse push_pull against a declared server-resident embedding
    key (docs/sparse-embedding.md): merge this worker's ``(indices,
    rows)`` gradient into the key's open round and return the published
    rows for the same indices — wire bytes proportional to touched
    rows, never to table size.  PS mode only; most callers want the
    sharded :class:`bps.EmbeddingTable` wrapper instead, which also
    owns declaration and optimizer arming."""
    _require_init()
    if _state.ps_session is None:
        raise RuntimeError(
            "push_pull_sparse needs PS mode (the row-sparse plane is a "
            "PS-tier feature; the collective plane has no lookup tier)")
    return _state.ps_session.push_pull_sparse(declare(name), indices,
                                              rows)


def push_pull_tree(tree: PyTree, name: Optional[str] = None,
                   average: bool = True, compression=None,
                   leaf_names=None, fusion_bytes: Optional[int] = None
                   ) -> PyTree:
    """Sum/average EVERY leaf of a pytree across workers.

    The eager plugins' gradient lists ride this (reference analog: DDP
    gradient batching, torch/parallel/distributed.py:235-243; per-tensor
    eager push_pull pays one crossing per gradient).

    With fusion enabled (``BYTEPS_TPU_FUSION_BYTES`` > 0, the default
    1 MiB; the ``fusion_bytes`` argument overrides per call), leaves
    below the threshold are packed by the fusion planner
    (common/fusion.py) into dtype-homogeneous, size-capped buckets in
    reverse backprop order; each bucket rides ONE wire key at the max
    priority of its members, and larger leaves keep their own key and
    backprop-position priority — so the PS dispatcher sends last-layer
    buckets first while earlier buckets still stage (the overlap the
    priority ScheduledQueues exist for), instead of one all-or-nothing
    f32 vector that can't overlap with anything.

    With fusion DISABLED (``BYTEPS_TPU_FUSION_BYTES=0``), floating
    leaves are flattened into one f32 vector reduced through a single
    push_pull — byte-identical to the pre-fusion wire path.

    Two classes of leaves are deliberately never fused/batched:
      - non-floating leaves (ints, bools): an f32 round-trip corrupts
        values above 2^24 and truncates averages — they ride individual
        exact push_pulls;
      - leaves whose `leaf_names[i]` has a PS wire compressor registered
        (register_compressor): folding them into a shared key would
        silently drop the user's compression config — they keep their own
        named push_pull so the compressed wire still applies.
    `leaf_names` aligns with the FLATTENED leaf order (for a dict tree:
    sorted keys).  Unnamed leaves get deterministic names derived from
    the batch name + the leaf's TREE PATH (stable under structural
    growth elsewhere in the tree, unlike a flat index).
    """
    _require_init()
    paths_leaves, treedef = jax.tree_util.tree_flatten_with_path(tree)
    if not paths_leaves:
        return tree
    leaves = [jnp.asarray(l) for _, l in paths_leaves]
    metas = [(l.shape, l.dtype, int(l.size)) for l in leaves]
    cfg = _state.config or get_config()
    if fusion_bytes is not None:
        fb = int(fusion_bytes)
    else:
        # Knob plane: an actuated FUSION_BYTES (CMD_KNOB) overrides the
        # launch config — live_fusion_bytes() applies any staged switch
        # whose round boundary this session has reached, so every worker
        # flips to the new threshold at the same round and the
        # composition-derived bucket keys line up fleet-wide.
        fb = (_state.ps_session.live_fusion_bytes()
              if _state.ps_session is not None else None)
        if fb is None:
            fb = cfg.fusion_bytes

    compressed_keys = (set(_state.ps_session._compressors)
                       if _state.ps_session is not None else set())

    def separate(i, l) -> bool:
        if not jnp.issubdtype(l.dtype, jnp.floating):
            return True
        if compressed_keys and leaf_names is not None:
            return get_core().get_declared_key(
                str(leaf_names[i])) in compressed_keys
        return False

    sep_idx = [i for i, l in enumerate(leaves) if separate(i, l)]
    batch_idx = [i for i in range(len(leaves)) if i not in set(sep_idx)]

    if name is None:
        # Key the batch by its structure + leaf signature so every worker
        # maps the same gradient set to the same declared key, and distinct
        # sets (partial backwards, several optimizers with same-shaped
        # params) get distinct keys/PS buffers.
        import hashlib
        sig = hashlib.md5(
            (str(treedef) + "|".join(f"{s}:{d}" for s, d, _ in metas))
            .encode()).hexdigest()[:12]
        name = f"byteps_tpu.tree.{sig}"

    def leaf_name(i: int) -> str:
        # Deterministic per-leaf name: explicit, or batch name + TREE PATH
        # — an unnamed push would auto-declare a FRESH key on every call
        # and grow the registry unboundedly, and an index-derived name
        # would re-key every separated leaf whenever the tree gains or
        # loses an unrelated leaf.
        if leaf_names is not None:
            return str(leaf_names[i])
        return f"{name}{jax.tree_util.keystr(paths_leaves[i][0])}"

    if fb > 0 and len(batch_idx) > 1:
        outs = _fused_tree_push_pull(
            name, leaves, metas, sep_idx, batch_idx, leaf_name,
            average, compression, fb)
        return jax.tree.unflatten(treedef, outs)

    outs: list = [None] * len(leaves)
    for i in sep_idx:
        # Non-float leaves are separated precisely for exactness: a lossy
        # intra-node cast (fp16) would corrupt them worse than the f32
        # batch they were pulled out of.
        comp = (compression
                if jnp.issubdtype(metas[i][1], jnp.floating) else None)
        outs[i] = jnp.asarray(
            push_pull(leaves[i], name=leaf_name(i), average=average,
                      compression=comp)).astype(metas[i][1])
    if batch_idx:
        flat = (jnp.concatenate([leaves[i].ravel().astype(jnp.float32)
                                 for i in batch_idx])
                if len(batch_idx) > 1
                else leaves[batch_idx[0]].ravel().astype(jnp.float32))
        out = jnp.asarray(push_pull(flat, name=name, average=average,
                                    compression=compression))
        o = 0
        for i in batch_idx:
            shp, dt, n = metas[i]
            outs[i] = out[o:o + n].reshape(shp).astype(dt)
            o += n
    return jax.tree.unflatten(treedef, outs)


def _fused_tree_push_pull(name, leaves, metas, sep_idx, batch_idx,
                          leaf_name, average, compression, fb) -> list:
    """Dispatch a tree through the fusion planner.

    Builds dtype-homogeneous buckets over the fusable leaves, then sends
    every dispatch unit (bucket, over-threshold solo leaf, forced-solo
    exact/compressed leaf) in priority-descending order.  In PS mode the
    whole set rides PSSession.push_pull_group, so the scheduler sees all
    units before the first dispatch; in collective mode the units are
    issued as concurrent async push_pulls and synchronized together.
    """
    from .fusion import plan_buckets

    plan = plan_buckets(
        tuple((i, metas[i][2], str(metas[i][1]),
               jnp.dtype(metas[i][1]).itemsize) for i in batch_idx), fb)
    plan.record_use()

    # Dispatch units: (unit_name, payload, priority, compression, scatter)
    # where scatter = [(leaf_idx, num_elems), ...] in pack order.
    units = []
    for b in plan.buckets:
        members = [(li, n) for li, n in b.members]
        packed = (jnp.concatenate([leaves[li].ravel() for li, _ in members])
                  if len(members) > 1 else leaves[members[0][0]].ravel())
        units.append((f"{name}.{b.tag}", packed, b.priority, compression,
                      members))
    for li, prio in plan.solo:
        units.append((leaf_name(li), leaves[li].ravel(), prio, compression,
                      [(li, metas[li][2])]))
    for i in sep_idx:
        # Forced-solo leaves (non-float exactness, registered wire
        # compressors) join the same priority-ordered dispatch, minus any
        # lossy intra-node cast for non-floats.  Raveled like every other
        # unit: scatter() below slices elements, and a 0-d payload would
        # not even be sliceable.
        comp = (compression
                if jnp.issubdtype(metas[i][1], jnp.floating) else None)
        units.append((leaf_name(i), leaves[i].ravel(), i, comp,
                      [(i, metas[i][2])]))
    units.sort(key=lambda u: -u[2])

    outs: list = [None] * len(leaves)

    def scatter(members, vec) -> None:
        off = 0
        for li, n in members:
            shp, dt, _ = metas[li]
            outs[li] = jnp.asarray(vec[off:off + n]).reshape(shp).astype(dt)
            off += n

    sess = _state.ps_session
    if sess is not None:
        from ..ops.compression import Compression
        hier = _state.hierarchy
        rkey = None
        if hier is not None:
            # Hierarchical reduction: slice-reduce every unit's RAW f32
            # payload in one in-graph psum BEFORE any wire compression
            # (the leader's codec then encodes the slice sum once).
            # The rendezvous key is the unit key tuple — deterministic
            # across workers regardless of unrelated traffic.  The f32
            # cast here is NOT a new precision loss for the forced-solo
            # non-float units: the PS wire is f32 for every payload
            # (PSSession._stage casts), so flat PS mode already sums
            # them in f32 — the slice psum is the same precision class.
            rkey = tuple(declare(nm) for nm, _, _, _, _ in units)
            reduced = hier.reduce_payloads(
                rkey, [np.asarray(p, np.float32).ravel()
                       for _, p, _, _, _ in units])
            units = [(nm, jnp.asarray(red), prio, comp, members)
                     for (nm, _p, prio, comp, members), red
                     in zip(units, reduced)]
            if not hier.is_leader:
                # Followers never touch the data plane: the leader's
                # broadcast delivers the round's averaged unit outputs.
                skipped = sum(int(np.size(r)) * 4 for r in reduced)
                for (nm, p, _, _, _) in units:
                    _debug_sample("push", nm, p)
                outs_vecs = hier.await_outs(rkey, skipped_bytes=skipped)
                for (nm, _, _, _, members), vec in zip(units, outs_vecs):
                    scatter(members, jnp.asarray(vec))
                    _debug_sample("pull", nm, vec)
                return outs
        from ..server.client import KnobReplan
        # Units whose KEY IDENTITY derives from the fusion plan (buckets
        # and plan solos — a different FUSION_BYTES re-composes them).
        # Registered with the session so a mid-flight FUSION_BYTES
        # switch withdraws their pushes with KnobReplan instead of
        # merging old-layout bytes into orphaned keys; forced-solo units
        # keep layout-independent keys and replay in place.
        plan_unit_names = ({f"{name}.{b.tag}" for b in plan.buckets}
                           | {leaf_name(li) for li, _ in plan.solo})
        pulled_vecs = []
        unit_bytes = sum(int(p.size * p.dtype.itemsize)
                         for _, p, _, _, _ in units)
        for attempt in range(3):
            items, ctxs, fusion_dks = [], [], []
            for nm, payload, prio, comp, members in units:
                _debug_sample("push", nm, payload)
                comp = comp or Compression.none
                wire, ctx = comp.compress(payload)
                dk = declare(nm)
                if nm in plan_unit_names:
                    fusion_dks.append(dk)
                if len(members) > 1 and get_core().trace_on:
                    # Fused bucket inside a trace window: record its
                    # member-leaf names so trace spans carry the real
                    # parameters in args.members (the analyzer's
                    # slow-bucket attribution).  Gated like every other
                    # trace feed — an untraced run must not build name
                    # lists per step.
                    sess.set_trace_members(
                        dk, [leaf_name(li) for li, _ in members])
                items.append((dk, wire, prio))
                ctxs.append((comp, ctx))
            if fusion_dks:
                sess.note_fusion_keys(fusion_dks)
            failed: set = set()
            replan_err = None
            try:
                handles = sess.push_pull_group(items)
                for (nm, _, _, _, members), h, (comp, ctx) in zip(
                        units, handles, ctxs):
                    try:
                        out = comp.decompress(jnp.asarray(h.wait()), ctx)
                    except KnobReplan as kr:
                        if hier is not None:
                            # The slice broadcast can't re-plan under a
                            # follower's feet — surface it like any
                            # other wire failure.
                            raise
                        failed.update(li for li, _ in members)
                        replan_err = kr
                        continue
                    if average:
                        out = out / size()
                    scatter(members, out)
                    _debug_sample("pull", nm, out)
                    if hier is not None:
                        pulled_vecs.append(
                            np.asarray(out, np.float32).ravel())
            except Exception as e:
                if hier is not None:
                    # Slice followers are blocked on the broadcast — a
                    # leader-side wire failure must fail the whole
                    # slice's round loudly, not strand it.
                    hier.publish_failure(rkey, e)
                raise
            if not failed:
                break
            if attempt == 2:
                raise replan_err
            # A FUSION_BYTES switch withdrew some units mid-flight:
            # re-plan the FULL fusable set under the live threshold
            # (every worker re-plans identically — the switch is global
            # and boundary-synchronized, so the new composition-derived
            # bucket keys line up fleet-wide), then re-dispatch only the
            # units carrying a withdrawn leaf.  Idempotent CMD_INIT
            # declares the new bucket keys; withdrawn handles never
            # advanced their round, so the replay stages the same round.
            live_fb = sess.live_fusion_bytes()
            if live_fb is not None:
                fb = live_fb
            plan = plan_buckets(
                tuple((i, metas[i][2], str(metas[i][1]),
                       jnp.dtype(metas[i][1]).itemsize)
                      for i in batch_idx), fb)
            plan.record_use()
            units = []
            for b in plan.buckets:
                members = [(li, n) for li, n in b.members]
                if not any(li in failed for li, _ in members):
                    continue
                packed = (jnp.concatenate(
                    [leaves[li].ravel() for li, _ in members])
                    if len(members) > 1
                    else leaves[members[0][0]].ravel())
                units.append((f"{name}.{b.tag}", packed, b.priority,
                              compression, members))
            for li, prio in plan.solo:
                if li in failed:
                    units.append((leaf_name(li), leaves[li].ravel(),
                                  prio, compression,
                                  [(li, metas[li][2])]))
            units.sort(key=lambda u: -u[2])
            plan_unit_names = {u[0] for u in units}
        if hier is not None:
            hier.publish_outs(rkey, pulled_vecs)
        cfg = _state.config or get_config()
        if cfg.telemetry_on:
            telemetry.record_pushpull(unit_bytes)
    else:
        handles = [push_pull_async(payload, name=nm, average=average,
                                   priority=prio, compression=comp)
                   for nm, payload, prio, comp, _ in units]
        for (nm, _, _, _, members), h in zip(units, handles):
            scatter(members, jnp.asarray(synchronize(h)))
    return outs


def _debug_sample(stage: str, name: str, tensor) -> None:
    """BYTEPS_DEBUG_SAMPLE_TENSOR: log a sample of the named tensor at a
    host-visible pipeline stage (reference: core_loops.cc:36-66 samples at
    every queue stage; here the eager path's host stages are push-entry
    and post-synchronize).  Substring match.  Written straight to stderr
    like the C++ server's BYTEPS_SERVER_DEBUG — setting the env IS the
    opt-in, independent of BYTEPS_LOG_LEVEL."""
    cfg = _state.config or get_config()
    pat = cfg.debug_sample_tensor
    if not pat or pat not in name:
        return
    import sys
    arr = np.asarray(tensor, dtype=np.float32).ravel()
    head = ", ".join(f"{v:.6g}" for v in arr[:4])
    sys.stderr.write(
        f"[byteps_tpu DEBUG_SAMPLE] {stage} name={name} "
        f"shape={tuple(np.shape(tensor))} "
        f"dtype={getattr(tensor, 'dtype', '?')} "
        f"norm2={float(np.linalg.norm(arr)):.6g} "
        f"sum={float(arr.sum()):.6g} first=[{head}]\n")
    sys.stderr.flush()


def push_pull_async(tensor: jax.Array, name: Optional[str] = None,
                    average: bool = True, priority: int = 0,
                    compression=None) -> int:
    _require_init()
    from ..ops.compression import Compression
    compression = compression or Compression.none
    tensor = jnp.asarray(tensor)
    if name is None:
        name = f"byteps_tpu.tensor_{get_core().num_declared()}"
    _debug_sample("push", name, tensor)
    dk = declare(name)
    core = get_core()
    handle = core.handle_allocate()
    t0 = core.trace_now_us()
    hier = _state.hierarchy
    if _state.ps_session is not None and hier is not None:
        # Hierarchical reduction: slice-reduce the RAW tensor in-graph
        # first; only the slice leader compresses and rides the wire,
        # and the decompressed pull broadcasts back — so a follower's
        # push_pull costs zero wire bytes.  The intra-slice reduce is
        # f32 (in-graph psum); the wire codec then applies to the slice
        # sum once instead of S per-chip gradients.
        shape, dt = tensor.shape, tensor.dtype

        def _leader_dispatch(reduced, comp=compression, prio=priority):
            wire, cctx = comp.compress(jnp.asarray(reduced))
            inner = _state.ps_session.push_pull_async(
                dk, wire, priority=prio)

            class _Decomp:
                def done(self):
                    return inner.done()

                def wait(self, timeout=300.0):
                    return np.asarray(
                        comp.decompress(jnp.asarray(inner.wait(timeout)),
                                        cctx), np.float32)

            return _Decomp()

        ph = hier.dispatch_round(
            dk, np.asarray(tensor, np.float32).ravel(),
            priority=priority, leader_dispatch=_leader_dispatch)

        def _resolve(ph=ph, shape=shape, dt=dt, avg=average):
            out = jnp.asarray(ph.wait()).reshape(shape)
            return (out / size() if avg else out).astype(dt)

        _resolve.ps_handle = ph
        cfg = _state.config or get_config()
        if cfg.telemetry_on and getattr(ph, "carried_wire", True):
            # Followers sent nothing: recording their tensor bytes would
            # make the push/pull counters deny the very traffic
            # reduction the saved-bytes counter reports (the fused path
            # skips follower recording the same way).
            telemetry.record_pushpull(tensor.size * tensor.dtype.itemsize)
        with _state.lock:
            _state.handles[handle] = (_resolve, name, t0)
        return handle
    wire, ctx = compression.compress(tensor)
    if _state.ps_session is not None:
        # True async: partitions go through the session's priority-scheduled
        # dispatcher; the handle resolves on the last partition's pull.
        ps_handle = _state.ps_session.push_pull_async(
            dk, wire, priority=priority)

        def _resolve(ph=ps_handle, comp=compression, cctx=ctx, avg=average):
            out = jnp.asarray(ph.wait())
            out = comp.decompress(out, cctx)
            return out / size() if avg else out

        _resolve.ps_handle = ps_handle
        out = _resolve
    else:
        cfg0 = _state.config or get_config()
        if size() > 1 or cfg0.force_distributed:
            # BYTEPS_FORCE_DISTRIBUTED exercises the real communication
            # path even at world size 1 — the reference's test hook
            # (reference: global.cc:149-152, tests/meta_test.py:27-33).
            out = _eager_sum_across_processes(wire)
        else:
            out = wire  # sum over a single worker
        out = compression.decompress(out, ctx)
        if average:
            out = out / size()
    cfg = _state.config or get_config()
    if cfg.telemetry_on:
        telemetry.record_pushpull(tensor.size * tensor.dtype.itemsize)
    with _state.lock:
        _state.handles[handle] = (out, name, t0)
    return handle


def synchronize(handle: int) -> jax.Array:
    """Block until the handle's communication completes (reference:
    torch/ops.py:222-236 spins on PollHandle; JAX gives us
    block_until_ready)."""
    with _state.lock:
        if handle not in _state.handles:
            raise ValueError(
                f"unknown or already-synchronized handle {handle}")
        out, name, t0 = _state.handles.pop(handle)
    if callable(out):  # PS-mode deferred result
        out = out()
    out = jax.block_until_ready(out)
    _debug_sample("pull", name, out)
    core = get_core()
    core.handle_mark_done(handle)
    core.trace_record(name, "PUSH_PULL", t0, core.trace_now_us() - t0)
    core.handle_release(handle)
    return out


def poll(handle: int) -> bool:
    """True if the async op has completed.  JAX's async dispatch means the
    value exists as soon as dispatch returns; completion == buffer ready.
    Raises ValueError for a handle that was never allocated or was already
    synchronized (matching the reference's check in torch/ops.cc poll)."""
    with _state.lock:
        entry = _state.handles.get(handle)
    if entry is None:
        status = get_core().handle_poll(handle)
        if status == -1:
            raise ValueError(
                f"unknown or already-synchronized handle {handle}")
        return status == 1
    out = entry[0]
    if callable(out):  # PS-mode: completed when the last partition pulled
        ph = getattr(out, "ps_handle", None)
        return ph.done() if ph is not None else True
    try:
        # Committed when the underlying buffer is ready.
        return out.is_ready() if hasattr(out, "is_ready") else True
    except Exception:
        return True


# ---------------------------------------------------------------------------
# Broadcast (reference: torch/__init__.py:259-409 — implemented there as
# zero-non-root + push_pull sum; multihost_utils gives us the direct op)
# ---------------------------------------------------------------------------
def broadcast_parameters(params: PyTree, root_rank: int = 0) -> PyTree:
    """Make `params` identical on every worker, taking root_rank's values."""
    _require_init()
    if size() == 1:
        return params
    from jax.experimental import multihost_utils
    return multihost_utils.broadcast_one_to_all(
        params, is_source=rank() == root_rank)


def broadcast_optimizer_state(opt_state: PyTree, root_rank: int = 0) -> PyTree:
    """Optimizer-state counterpart of broadcast_parameters.  optax states are
    pytrees of arrays/scalars, so one tree broadcast covers what the reference
    does with per-scalar tensor-ization (reference: torch/__init__.py:293-409)."""
    return broadcast_parameters(opt_state, root_rank)


# ---------------------------------------------------------------------------
# Telemetry & tracing (reference: global.cc:712-767, 463-579)
# ---------------------------------------------------------------------------
def _register_builtin_collectors() -> None:
    """Attach the legacy stats surfaces to the registry as collectors.

    snapshot()/the Prometheus endpoint then export bps_codec_*,
    bps_transport_* and bps_fusion_* values that are *identical by
    construction* to get_codec_stats()/get_transport_stats()/
    get_fusion_stats() — the registry reads through the same accessors at
    snapshot time instead of keeping shadow counters that could drift.
    Idempotent (re-registering replaces the same name).
    """
    reg = telemetry.get_registry()
    # Late-bound lambdas: the accessors are defined further down this
    # module and only need to exist at snapshot time.
    reg.register_collector("codec", lambda: get_codec_stats())
    reg.register_collector("transport", lambda: get_transport_stats())
    reg.register_collector("fusion", lambda: get_fusion_stats())


_register_builtin_collectors()


def _refresh_server_metrics() -> None:
    """Exporter refresh hook: fold a fresh CMD_STATS poll into the
    registry (round-lag gauges + straggler warning) so every scrape and
    JSONL line carries scrape-fresh server state.  Quiet outside PS mode
    and while the server is unreachable — the endpoint must keep serving
    worker-side metrics even when the PS tier is the thing that broke."""
    if _state.ps_session is None:
        return
    try:
        get_server_stats()
    except Exception as e:
        get_logger().debug("CMD_STATS poll failed: %s", e)


def get_metrics() -> dict:
    """One isolated snapshot of the unified metrics registry.

    Includes every registered counter/gauge/histogram (push RTT,
    dispatcher queue wait/depth, codec encode/decode latency, step time,
    push-pull bytes, round-lag gauges) plus the collector-backed
    bps_codec_* / bps_transport_* / bps_fusion_* values, which match the
    legacy ``get_*_stats()`` accessors exactly.  Purely local — it never
    touches the network; use :func:`get_server_stats` for a live
    CMD_STATS poll.
    """
    return telemetry.get_registry().snapshot()


def get_server_stats() -> dict:
    """Live server-side stats over the wire (CMD_STATS), merged across
    servers: per-key merge counts / completed rounds / pending-pull
    depth / pushed bytes, per-worker push counts and round position, and
    server wire bytes in/out.  Also folds per-worker round lag into the
    ``bps_worker_round_lag`` gauges and logs a straggler warning for any
    worker trailing by more than ``BYTEPS_TPU_STRAGGLER_ROUNDS``.

    Returns the all-zero shape outside PS mode.  Raises a "server too
    old" RuntimeError against a pre-CMD_STATS server (the unknown
    command draws an error status, never a hang).
    """
    if _state.ps_session is None:
        return {"bytes_in": 0, "bytes_out": 0, "async": False,
                "num_workers": 0, "keys": {}, "workers": {},
                "round_lag": {}}
    cfg = _state.config or get_config()
    stats = _state.ps_session.server_stats()
    stats["round_lag"] = telemetry.update_round_lag(
        stats, cfg.straggler_rounds)
    if "members" in stats:
        # CMD_STATS carries the membership view too (epoch + per-worker
        # lease age): feed the liveness gauges so every scrape can tell
        # an evicted worker from a slow one.  Old servers omit it.
        telemetry.update_membership(
            {"epoch": stats.get("epoch", 0), "workers": stats["members"]})
    if stats.get("servers"):
        # Elastic PS ring: feed bps_ring_epoch / bps_server_alive /
        # bps_keys_owned so every scrape can tell a dead or draining
        # server from a slow one.  Old servers omit these keys.
        telemetry.update_ring(stats)
    # Server-resident optimizer plane: bps_param_version{key=} +
    # bps_opt_slot_bytes{server=}.  Quiet (no gauges registered) unless
    # some key actually runs a server-side update stage.
    telemetry.update_server_opt(stats)
    # Row-sparse embedding plane: bps_embed_rows_served_total +
    # bps_embed_table_bytes{server=}.  Quiet unless a table exists.
    telemetry.update_embed(stats)
    # Chain-replication plane: bps_repl_lag_rounds{server=} +
    # bps_repl_bytes_total.  Quiet unless BYTEPS_TPU_REPL is armed.
    telemetry.update_repl(stats)
    # Fleet observability plane: bps_fleet_windows_held{server=} +
    # bps_fleet_publishes_total.  Quiet unless BYTEPS_TPU_FLEET is
    # armed on the server tier.
    telemetry.update_fleet(stats)
    return stats


def _postmortem_extra() -> dict:
    """Bundle sections the flight recorder collects at dump time —
    strictly LOCAL state (cached membership view, step counter): a
    bundle is written exactly when the wire may be broken, so nothing
    here may block on it.  The live PSSession registers its own
    "session" provider (transport/audit/ring/health) at construction,
    so those sections ride every bundle without being computed twice."""
    out: dict = {"step": _state.step}
    if _state.membership is not None:
        out["membership"] = _state.membership
    return out


def _start_signal_plane(cfg) -> None:
    """Arm the windowed key-signal plane + doctor engine
    (``BYTEPS_TPU_SIGNAL_WINDOW_S`` > 0; docs/monitoring.md "Doctor").

    The plane is strictly local: its one optional wire touch is the
    per-window CMD_STATS refresh (PS mode, best-effort) that keeps the
    round-lag/ring gauges window-fresh — the same poll every metrics
    scrape already does.  The doctor's findings ride the log, the
    flight recorder, ``bps_doctor_findings_total`` and
    ``bps.get_diagnosis()``; postmortem bundles gain a ``diagnosis``
    section (+ the recent window history) through the flight-recorder
    provider registered here."""
    eng = doctor_mod.DoctorEngine()
    sess = _state.ps_session
    providers = {}
    if sess is not None:
        providers = {"transport": sess.transport_stats,
                     "health": sess.health_snapshot,
                     "audit": sess.audit_stats}
    prof = devprof.active()
    if prof is not None:
        # Device plane: the provider IS the window roll — it re-probes
        # the sentinel, drains the step accumulators, and updates the
        # MFU/fallback gauges; the returned section rides the summary
        # for the device_fallback / mfu_regression rules (and the fleet
        # publish doc).  Works with or without a PS session — the
        # device side has no wire dependency.
        providers["device"] = prof.window_roll

    def _refresh():
        if _state.ps_session is None:
            return None
        try:
            return get_server_stats()
        except Exception as e:
            get_logger().debug("signal window CMD_STATS poll failed: %s",
                               e)
            return None

    tuner = None
    if cfg.tuner:
        if sess is None:
            get_logger().warning(
                "BYTEPS_TPU_TUNER=1 outside PS mode: the tuner drives "
                "the PS wire codec table and has nothing to tune here")
        else:
            from . import tuner as tuner_mod
            # One proposer per job (worker 0): racing proposers would
            # converge through the server's epoch arbitration anyway,
            # but a single control loop keeps decisions explainable.
            tuner = tuner_mod.Tuner(
                sess, propose=(cfg.worker_id == 0),
                hold=cfg.tuner_hold, blacklist=cfg.tuner_blacklist,
                margin_rounds=cfg.tuner_margin_rounds,
                regress_frac=cfg.tuner_regress_frac)

    autoscaler = None
    if cfg.autoscale:
        if sess is None:
            get_logger().warning(
                "BYTEPS_TPU_AUTOSCALE=1 outside PS mode: the autoscaler "
                "drives the PS server ring and has nothing to scale here")
        elif not sess.ring_armed:
            get_logger().warning(
                "BYTEPS_TPU_AUTOSCALE=1 without the elastic ring "
                "(BYTEPS_TPU_RING=1): drain/join need ring transitions")
        elif cfg.worker_id == 0:
            # One scaler per job (worker 0, the tuner law): racing
            # scalers would propose conflicting ring transitions.
            from . import autoscaler as autoscaler_mod
            root_port = int(os.environ.get("DMLC_PS_ROOT_PORT") or 0)
            autoscaler = autoscaler_mod.Autoscaler(
                sess,
                autoscaler_mod.SubprocessExecutor(
                    root_port, num_workers=cfg.num_worker),
                min_servers=cfg.autoscale_min,
                max_servers=cfg.autoscale_max,
                hold=cfg.autoscale_hold,
                cooldown=cfg.autoscale_cooldown,
                up_mb=cfg.autoscale_up_mb,
                down_mb=cfg.autoscale_down_mb,
                doctor=eng)

    # Fleet observability plane (BYTEPS_TPU_FLEET=1, docs/monitoring.md
    # "Fleet plane"): chained onto the same window stream.  Every worker
    # publishes one compact CMD_WINDOW frame per roll; worker 0 fetches
    # the merged CMD_FLEET view, runs the fleet doctor + goodput ledger
    # over it, and — when the autoscaler is armed — feeds the scaler the
    # FLEET view instead of its own possibly-blind local one.  All of it
    # rides the window-roll thread, off the push_pull critical path.
    fleet_eng = None
    fleet_on = bool(cfg.fleet and sess is not None
                    and getattr(sess, "_fleet_wire", False))
    if fleet_on:
        import collections
        _state.fleet_published = collections.deque(
            maxlen=max(1, cfg.fleet_windows))
        if cfg.worker_id == 0:
            fleet_eng = doctor_mod.DoctorEngine(
                rules=doctor_mod.FLEET_RULES)
            _state.fleet_engine = fleet_eng

    def _fleet_pass(summary):
        from . import goodput as goodput_mod
        open_ids = [f.get("rule") for f in
                    (eng.diagnosis().get("open") or [])]
        doc = doctor_mod.fleet_publish_doc(
            summary, cfg.worker_id,
            clock=sess.fleet_clock_offset(),
            open_findings=open_ids,
            codecs=sess.codec_table())
        if sess.publish_window(int(doc.get("window") or 0), doc):
            _state.fleet_published.append(doc)
        if fleet_eng is None:
            return
        view = sess.fetch_fleet()
        _state.fleet_view = view
        fw = doctor_mod.fleet_windows_from_view(view)
        _state.fleet_windows = fw
        if not fw:
            return
        # The engine keeps its own history; feed only windows it has
        # not seen (aligned rows for OLD indexes may still gain late
        # workers, but re-observing them would reset finding identity).
        last_seen = getattr(_fleet_pass, "_last_idx", -1)
        for w in fw:
            if w["window"] > last_seen:
                fleet_eng.observe(w)
                _fleet_pass._last_idx = w["window"]
        try:
            led = goodput_mod.fleet_ledger(fw[-1])
            _state.fleet_ledger = led
            goodput_mod.update_goodput(led)
        except Exception:
            get_logger().exception("goodput ledger failed")
        if autoscaler is not None:
            fs = autoscaler_mod.fleet_summary(fw[-1])
            if fs is not None:
                autoscaler.observe(fs)

    def _on_window(summary):
        eng.observe(summary)
        if tuner is not None:
            try:
                tuner.observe(summary)
            except Exception:
                get_logger().exception("tuner window pass failed")
        if fleet_on:
            try:
                _fleet_pass(summary)
            except Exception:
                get_logger().exception("fleet window pass failed")
        if autoscaler is not None and not fleet_on:
            # Fleet-armed runs feed the scaler the merged view inside
            # _fleet_pass; unarmed runs keep the local-summary feed.
            try:
                autoscaler.observe(summary)
            except Exception:
                get_logger().exception("autoscale window pass failed")

    plane = signals.arm(window_s=cfg.signal_window_s,
                        history=cfg.signal_history,
                        refresh=_refresh, providers=providers,
                        on_window=_on_window)
    _state.signal_plane = plane
    _state.doctor = eng
    _state.tuner = tuner
    _state.autoscaler = autoscaler
    _state.doctor_verdict_done = False
    flightrec.set_extra_provider(
        lambda: {"diagnosis": eng.diagnosis(),
                 "signals": plane.history()},
        name="doctor")
    if fleet_on:
        # Postmortem bundles gain a "fleet" section: this worker's
        # published ring (the exact docs its CMD_WINDOW frames carried
        # — what fleet_view_from_bundles merges for offline parity) and,
        # on worker 0, the last merged view + fleet diagnosis.
        flightrec.set_extra_provider(_fleet_extra, name="fleet")
    if not _state.doctor_atexit:
        # Crash guard: a run that never reaches shutdown() still logs
        # its one-line verdict (and the postmortem bundle's diagnosis
        # section is dumped by flightrec's own atexit hook).
        import atexit
        atexit.register(_emit_doctor_verdict)
        _state.doctor_atexit = True


def _fleet_extra() -> dict:
    """The postmortem bundle's ``fleet`` section (strictly local state:
    a bundle dumps when the wire may be broken, so no CMD_FLEET fetch
    here — worker 0's section carries its LAST successful fetch).
    Providers merge FLAT into ``extra``, so the payload nests itself
    under the ``fleet`` key the offline readers
    (doctor.fleet_view_from_bundles, postmortem.fleet_section) expect."""
    out: dict = {"published": list(_state.fleet_published or ())}
    cfg = _state.config
    if cfg is not None:
        out["worker"] = cfg.worker_id
    if _state.fleet_view is not None:
        out["view"] = _state.fleet_view
    if _state.fleet_engine is not None:
        out["diagnosis"] = _state.fleet_engine.diagnosis()
    if _state.fleet_ledger is not None:
        out["goodput"] = _state.fleet_ledger
    return {"fleet": out}


def _emit_doctor_verdict() -> None:
    """Log the final doctor verdict exactly once per plane lifetime."""
    eng = _state.doctor
    if eng is None or _state.doctor_verdict_done:
        return
    _state.doctor_verdict_done = True
    try:
        line = eng.verdict_line()
        diag = eng.diagnosis()
        if diag.get("healthy"):
            get_logger().info(line)
        else:
            get_logger().warning(line)
    except Exception:
        pass


def _stop_signal_plane() -> None:
    if _state.signal_plane is None:
        return
    try:
        _state.signal_plane.stop(final_roll=True)   # close the last window
    except Exception:
        pass
    _emit_doctor_verdict()
    # Freeze the final diagnosis + window history into a static provider:
    # the atexit postmortem bundle (flightrec's own exit hook runs AFTER
    # shutdown) must still carry the run's verdict, or the one bundle an
    # operator actually reads would be the one missing the diagnosis.
    try:
        final = {"diagnosis": _state.doctor.diagnosis(),
                 "signals": _state.signal_plane.history()}
        flightrec.set_extra_provider(lambda: final, name="doctor")
    except Exception:
        flightrec.set_extra_provider(None, name="doctor")
    if _state.fleet_published is not None:
        # Same freeze for the fleet section: the atexit bundle must
        # still carry the published ring after the state is torn down.
        try:
            fleet_final = _fleet_extra()
            flightrec.set_extra_provider(lambda: fleet_final,
                                         name="fleet")
        except Exception:
            flightrec.set_extra_provider(None, name="fleet")
    signals.disarm()
    _state.signal_plane = None
    _state.doctor = None
    _state.tuner = None
    _state.fleet_engine = None


def _signal_routes() -> dict:
    """JSON routes for the metrics endpoint: ``/signals`` (the window
    history — what tools/bps_doctor.py polls in live mode) and
    ``/diagnosis`` (the doctor's current verdict — what the bps_top
    panel shows).  Empty when the plane is off: the endpoint then 404s
    the paths, which the consumers treat as "not armed"."""
    if _state.signal_plane is None:
        return {}
    plane, eng = _state.signal_plane, _state.doctor

    def _signals_payload():
        hist = plane.history()
        # "window" = the newest CLOSED window's index — pollers align
        # scrapes across workers by it instead of guessing from wall
        # clocks (the fleet plane's alignment key).
        return {"schema": signals.SCHEMA,
                "window_s": plane.window_s,
                "window": (hist[-1].get("window") if hist else -1),
                "windows": hist}

    routes = {"/signals": _signals_payload,
              "/diagnosis": lambda: eng.diagnosis()}
    if _state.tuner is not None:
        tuner = _state.tuner
        routes["/tuner"] = lambda: tuner.state()
    if _state.fleet_published is not None:
        routes["/fleet"] = get_fleet
    if devprof.active() is not None:
        routes["/device"] = get_device_profile
    return routes


def get_key_signals() -> dict:
    """The signal plane's last closed window: per-key ``KeySignal``
    records — wire bytes/throughput, critical-path component shares
    (queue/push_wire/serve/encode/decode), value-plane health, and the
    ``wire_bound | compute_bound | straggler_bound | tiny | unhealthy``
    classification.  The adaptive-compression tuner's input surface.
    Returns the empty shape when the plane is off
    (``BYTEPS_TPU_SIGNAL_WINDOW_S=0``)."""
    if _state.signal_plane is None:
        return {"schema": signals.SCHEMA, "armed": False, "window": -1,
                "keys": {}}
    out = _state.signal_plane.key_signals()
    out["armed"] = True
    return out


def get_diagnosis() -> dict:
    """The doctor's current verdict: open findings (severity-ranked,
    each with rule id, subject, evidence, and a playbook anchor into
    docs/troubleshooting.md), plus the recent finding history.  Returns
    ``{"armed": False, "healthy": True}`` when the plane is off."""
    if _state.doctor is None:
        return {"armed": False, "healthy": True, "open": [],
                "findings_total": 0}
    return _state.doctor.diagnosis()


def get_device_profile() -> dict:
    """The device plane's live profile (``BYTEPS_TPU_DEVPROF=1``):
    the last sentinel probe (actual vs intended platform, fallback
    conviction), lifetime and recent per-step device times
    (dispatch → ``block_until_ready``), the last window's MFU when
    ``cost_analysis()`` reports FLOPs, and the cost-analysis cache
    counters.  Served on the metrics endpoint as ``/device``.  Returns
    ``{"armed": False, ...}`` when the plane is off."""
    prof = devprof.active()
    if prof is None:
        return {"armed": False, "platform": None, "mfu": None,
                "steps_total": 0, "device_s_total": 0.0,
                "mean_step_ms": None}
    return prof.profile()


def get_fleet() -> dict:
    """The fleet observability plane's merged view (``BYTEPS_TPU_FLEET=1``,
    PS mode): the last CMD_FLEET fetch (per-worker window rings), the
    ALIGNED window stream, the fleet doctor's verdict over it, and the
    last goodput ledger.  What ``bps_doctor --fleet`` polls live and
    the bps_top fleet panel renders.  Non-zero-worker processes publish
    but do not fetch, so they return only their own published ring;
    ``{"armed": False}`` when the plane is off."""
    if _state.fleet_published is None:
        return {"armed": False, "workers": {}, "windows": [],
                "diagnosis": {"healthy": True, "open": []}}
    out: dict = {"armed": True,
                 "published": list(_state.fleet_published),
                 "view": _state.fleet_view or {},
                 "windows": _state.fleet_windows or []}
    if _state.fleet_engine is not None:
        out["diagnosis"] = _state.fleet_engine.diagnosis()
    if _state.fleet_ledger is not None:
        out["goodput"] = _state.fleet_ledger
    return out


def get_tuner() -> dict:
    """The adaptive-compression tuner's state (``BYTEPS_TPU_TUNER=1``):
    per-key dial position / class history / blacklist state, total
    switches and reverts, and the advisory knob proposals
    (FUSION_BYTES / COMPRESS_THREADS / PARTITION_BYTES / WIRE_CONNS —
    logged, never silently applied).  ``{"armed": False}`` when the
    tuner is off."""
    if _state.tuner is None:
        return {"armed": False, "switches_total": 0, "keys": {},
                "knob_proposals": []}
    return _state.tuner.state()


def get_autoscaler() -> dict:
    """The PS-tier autoscaler's state (``BYTEPS_TPU_AUTOSCALE=1``):
    executed action records (dir/window/server), up/down totals, the
    live hysteresis streaks and cooldown horizon, and the last
    pressure-to-action detection latency.  ``{"armed": False}`` when
    the loop is off (or this worker is not worker 0)."""
    if _state.autoscaler is None:
        return {"armed": False, "actions_up": 0, "actions_down": 0,
                "actions": []}
    out = _state.autoscaler.stats()
    out["armed"] = True
    return out


def get_hierarchy() -> dict:
    """The hierarchical-reduction plane's state (``BYTEPS_TPU_HIERARCHY=1``,
    PS mode): slice topology (id/size/members), the CURRENT leader under
    the membership epoch, whether this worker is it, and the counters —
    leader vs follower wire rounds, in-graph slice reductions, and
    ``wire_bytes_saved`` (push+pull payload bytes followers never sent,
    the ``bps_hierarchy_wire_bytes_saved_total`` counter's source).
    ``{"armed": False}`` in flat mode."""
    if _state.hierarchy is None:
        return {"armed": False, "slice_size": 1, "is_leader": True,
                "leader_rounds": 0, "follower_rounds": 0,
                "intra_reduces": 0, "wire_bytes_saved": 0}
    return _state.hierarchy.snapshot()


def get_health() -> dict:
    """The gradient-health monitor's last per-key samples
    (``BYTEPS_TPU_HEALTH_SAMPLE_ROUNDS`` > 0, PS mode): ``{"sample_rounds",
    "nonfinite_total", "keys": {name: {"norm", "absmax", "nonfinite",
    "ef_residual_norm", ...}}}`` — the same values the ``bps_grad_*``
    gauges export.  The all-empty shape outside PS mode or with the
    monitor off."""
    empty = {"sample_rounds": 0, "nonfinite_total": 0, "keys": {}}
    if _state.ps_session is None:
        return empty
    return _state.ps_session.health_snapshot() or empty


def get_audit(cross_check: bool = False) -> dict:
    """The consistency auditor's verdicts (``BYTEPS_TPU_AUDIT=1``, PS
    mode; docs/monitoring.md "Auditing & postmortem").

    Default: the local counters — audited pulls checked, digest
    mismatches, lost/skewed rounds, plus the last verdict's detail.  No
    wire traffic.  ``cross_check=True`` instead fetches every server's
    CMD_AUDIT publish-digest window and compares this worker's last-K
    pulled digests against it, returning the mismatching / lost rounds
    with their contributor sets — run it (on any worker) when a
    mismatch ERROR fires or a loss curve goes sideways."""
    if _state.ps_session is None:
        return {"armed": False, "checked": 0, "mismatches": 0,
                "round_skew": 0, "unverified": 0, "last": None}
    if cross_check:
        return _state.ps_session.audit_check()
    return _state.ps_session.audit_stats()


def get_pushpull_speed() -> tuple:
    """(timestamp, MB/s) moving average, like byteps_get_pushpull_speed.

    Reimplemented on the telemetry registry: every push_pull records its
    logical tensor bytes via ``telemetry.record_pushpull``, which feeds
    both the cumulative ``bps_pushpull_bytes_total`` counter and a
    10-second moving window; this returns ``bytes_in_window / 1e6 /
    window_seconds`` — numerically equivalent to the retired native-core
    window (core.cc bps_telemetry_speed_mbps: same window length, same
    sum-over-window-divided-by-window definition), but served from the
    same registry the /metrics endpoint exports, so the two can never
    disagree.
    """
    return (time.time(), telemetry.pushpull_speed_mbps())


def get_codec_stats() -> Dict[str, int]:
    """Counters from the PS-mode codec pipeline (BYTEPS_TPU_COMPRESS_THREADS):
    parts encoded/decoded off the caller/receiver threads and the pool's
    busy time in µs.  All-zero outside PS mode or with the pipeline
    disabled (compress_threads=0) — used by tools/wire_bench.py to prove
    where codec work actually ran."""
    if _state.ps_session is not None:
        return _state.ps_session.codec_stats()
    from ..server.codec_pool import CompressionPool
    return dict(CompressionPool.ZERO_STATS)


def get_transport_stats() -> Dict[str, int]:
    """Counters from the PS transport layer.  Fault tolerance
    (BYTEPS_TPU_RECONNECT_ATTEMPTS / _STALL_TIMEOUT_S): successful
    reconnects, exhausted backoff budgets, partitions replayed (push leg /
    pull leg), partitions parked (currently / ever), and stall-watchdog
    trips.  Raw speed: receive-pool `pool_hits`/`pool_misses`/
    `pool_buffers_held`, aggregate `lane_bytes_total`/
    `lane_outstanding_bytes`, and a per-lane `lanes` row list ({server,
    lane, transport(tcp|uds), bytes_total, outstanding_bytes, sends} —
    the byte-credit scheduler's working signal).  The get_codec_stats()
    analog for the transport layer; all-zero outside PS mode.  Numeric
    keys export through the metrics registry's transport collector
    (`bps_transport_*`); the `lanes` list is accessor-only.  Used by the
    chaos/transport tests and BENCH_FAULT=1 / BENCH_WIRE=1 bench.py."""
    if _state.ps_session is not None:
        return _state.ps_session.transport_stats()
    from ..server.client import PSSession
    # Fresh `lanes` list per call: a shallow dict() would hand every
    # caller (and the class template itself) the same mutable [].
    return {**PSSession.TRANSPORT_ZERO_STATS, "lanes": []}


def get_fusion_stats() -> Dict[str, int]:
    """Counters from the fusion-bucket layer (BYTEPS_TPU_FUSION_BYTES):
    buckets built, leaves fused vs solo, payload bytes per class, wire
    message chains saved, and streaming-flush causes (size-cap vs
    FLUSH_MS deadline vs explicit flush()/close() drain), plus the
    in-graph collective plane's plan counts.  The get_codec_stats()
    analog for fusion.  The wire-plane counters are all-zero with fusion
    disabled; `ingraph_plans`/`ingraph_buckets` track the collective
    plane's BucketPlan activity regardless (that plane packs at
    BYTEPS_PARTITION_BYTES and is not gated by the fusion knob).  Used by
    tools/wire_bench.py to prove where small tensors actually rode."""
    from .fusion import get_stats
    return get_stats()


def timeline_start_step() -> int:
    cfg = _state.config or get_config()
    return cfg.trace_start_step


def mark_step() -> None:
    """Advance the training-step counter driving the trace window
    (reference gates tracing on BYTEPS_TRACE_START/END_STEP,
    global.cc:113-124).  Within the window each step contributes a
    STEP timeline event; in-graph collective detail comes from
    jax.profiler, which this windowing composes with."""
    cfg = _state.config or get_config()
    core = get_core()
    now = core.trace_now_us()
    if cfg.trace_on and _state.step_start_us is not None \
            and cfg.trace_start_step <= _state.step <= cfg.trace_end_step:
        core.trace_record(f"step_{_state.step}", "STEP",
                          _state.step_start_us, now - _state.step_start_us)
    if cfg.telemetry_on and _state.step_start_us is not None:
        # Per-step wall time: the trace only keeps this inside its window;
        # the registry keeps the full-run distribution live.
        telemetry.get_registry().histogram(
            "bps_step_time_seconds",
            bounds=telemetry.STEP_TIME_BUCKETS,
            help="wall time between consecutive mark_step() calls"
        ).observe((now - _state.step_start_us) / 1e6)
    _state.step += 1
    _state.step_start_us = now
    if cfg.trace_on:
        core.trace_enable(cfg.trace_start_step <= _state.step
                          <= cfg.trace_end_step)
        if _state.step == cfg.trace_end_step + 1:
            _maybe_dump_trace()


def _maybe_dump_trace(final: bool = False, exiting: bool = False) -> None:
    cfg = _state.config or get_config()
    core = get_core()
    if not cfg.trace_on or core.trace_count() == 0:
        return
    d = os.path.join(cfg.trace_dir, str(local_rank()))
    os.makedirs(d, exist_ok=True)
    path = os.path.join(d, "comm.json")
    core.trace_dump(path, rank())
    _merge_server_trace(path, exiting=exiting)


def _dump_trace_on_exit() -> None:
    """atexit guard: flush whatever the tracer still holds (crashed or
    watchdog-failed runs never reach mark_step's window-end dump)."""
    try:
        _maybe_dump_trace(final=True, exiting=True)
    except Exception:
        pass


def _merge_server_trace(path: str, exiting: bool = False) -> None:
    """Fold server-side spans into the freshly-dumped worker trace file.

    The result is ONE Chrome/Perfetto JSON per worker with a process lane
    per host: this worker's spans on pid=rank, each PS server's
    offset-corrected spans on pid=SERVER_PID_BASE+idx (named via
    process_name metadata).  Fusion-bucket spans gain ``args.members``
    (the real parameters riding the bucket), and the file is run through
    the critical-path analyzer to feed the live
    ``bps_step_critical_path_*`` gauges.  Every step is best-effort: a
    dead server tier still leaves the plain worker trace behind.
    """
    import json
    from . import trace_analysis
    sess = _state.ps_session
    try:
        with open(path) as f:
            doc = json.load(f)
        events = doc.get("traceEvents", [])
        # tid present on metadata too: older consumers iterate e["tid"]
        # over the whole file.
        hier = _state.hierarchy
        wname = f"worker{rank()}"
        if hier is not None:
            # Per-slice lanes: the worker's process lane names its slice
            # and role, so a hierarchical trace reads as slices (leader
            # lanes carrying wire spans, follower lanes without them).
            wname += (f" slice{hier.slice_id}"
                      + (" leader" if hier.is_leader else ""))
        meta = [{"name": "process_name", "ph": "M", "pid": rank(),
                 "tid": 0, "args": {"name": wname}}]
        if sess is not None:
            core = get_core()
            try:
                # Bounded budgets everywhere: a blackholed server must
                # not pin shutdown() or a mid-training window-end dump
                # for the API-default ping+fetch budget (~80s/server).
                # Offset accuracy comes from min-RTT filtering, not
                # sample count, so the smaller ping budget costs nothing
                # on a healthy network.  The atexit (crash) path cuts
                # harder still — fail fast, keep the worker half.
                if exiting:
                    spans = sess.fetch_server_trace(
                        timeout=2.0, ping_timeout=1.0, ping_samples=2)
                else:
                    spans = sess.fetch_server_trace(
                        timeout=5.0, ping_timeout=2.0, ping_samples=3)
            except Exception as e:
                get_logger().warning("server trace unavailable: %s", e)
                spans = []
            seen_servers = set()
            for s in spans:
                dk, pidx = s["key"] >> 16, s["key"] & 0xFFFF
                nm = core.declared_name(dk) or f"key_{dk}"
                seen_servers.add(s["server"])
                args = {"key": s["key"], "round": s["round"],
                        "worker": s["worker"], "bytes": s["bytes"]}
                if hier is not None:
                    # Slice attribution on server spans: which slice's
                    # leader pushed this partition.
                    args["slice"] = s["worker"] // hier.slice_size
                events.append({
                    "name": f"{nm}.part{pidx}", "cat": "comm", "ph": "X",
                    "ts": s["ts_us"], "dur": s["dur_us"],
                    "pid": trace_analysis.SERVER_PID_BASE + s["server"],
                    "tid": s["stage"],
                    "args": args})
            for i in sorted(seen_servers):
                meta.append({"name": "process_name", "ph": "M",
                             "pid": trace_analysis.SERVER_PID_BASE + i,
                             "tid": 0, "args": {"name": f"server{i}"}})
            members = sess.trace_members()
            if members:
                for e in events:
                    k = (e.get("args") or {}).get("key")
                    if k is not None and (k >> 16) in members:
                        e["args"]["members"] = members[k >> 16]
        prof = devprof.active()
        if prof is not None:
            # Device lane (pid = DEVICE_PID_BASE + rank): the profiler's
            # step spans are stamped on the same monotonic-µs timebase
            # as the wire spans (core.trace_now_us), so they merge with
            # no offset — one timeline finally shows compute, codec,
            # and wire end to end.
            dev_events = prof.trace_events(rank())
            if dev_events:
                events.extend(dev_events)
                meta.append({
                    "name": "process_name", "ph": "M",
                    "pid": trace_analysis.DEVICE_PID_BASE + rank(),
                    "tid": 0,
                    "args": {"name": f"device{rank()} "
                             f"({(prof.profile().get('platform') or '?')}"
                             f")"}})
        doc["traceEvents"] = meta + events
        with open(path, "w") as f:
            json.dump(doc, f)
    except Exception:
        get_logger().exception("merged trace export failed")
        return
    try:
        result = trace_analysis.analyze(doc["traceEvents"], worker=rank())
        trace_analysis.update_critical_path_gauges(result)
    except Exception:
        get_logger().exception("critical-path analysis failed")


def current_step() -> int:
    return _state.step
