"""Consistent-hash ring for the elastic PS server tier.

This is the worker-side half of the ONE placement law shared with the
C++ server (core/server.cc, ``namespace ring``): a splitmix64-hashed
ring with ``BYTEPS_TPU_RING_VNODES`` virtual nodes per server.  A
partition key is owned by the server whose first virtual-node point is
clockwise-at-or-after the key's point.  Both sides must compute
bit-identical owners — asserted by tests/test_server_elastic.py against
the ctypes export ``bps_ring_owner`` — because the server REJECTS
frames for keys it does not own (status ``MOVED``) once the ring epoch
has ever advanced, and a placement disagreement would livelock every
push into a redirect loop.

Placement law by mode:
  - ring UNARMED (``BYTEPS_TPU_RING`` unset, the default): the legacy
    fixed hash (core.key_to_server, djb2/modulo) — wire traffic is
    byte-identical to the pre-ring code, and no ring frame is ever sent.
  - ring ARMED: the ring over the CURRENT member set, from epoch 0 on.
    Consistent hashing's stability is what makes elasticity cheap:
    adding a server moves ~1/N of the keys (all of them TO the joiner),
    removing one moves only ITS keys (all of them to survivors) — keys
    owned by unaffected servers never move, so state handoff is a
    one-directional stream and exactness is a per-key property.

The ring table is epoch-versioned like the PR-7 worker membership:
every server join/drain/eviction bumps the epoch, servers accept a
``CMD_RING_SET`` only for a newer epoch, and a fixed topology stays at
epoch 0 forever.
"""

from __future__ import annotations

import struct
from typing import Dict, List, Optional, Tuple

_M64 = (1 << 64) - 1

DEFAULT_VNODES = 64


def splitmix64(x: int) -> int:
    """The shared 64-bit mixer (bit-identical to server.cc ring::Mix64)."""
    x = (x + 0x9E3779B97F4A7C15) & _M64
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & _M64
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & _M64
    return x ^ (x >> 31)


def vnode_point(server_id: int, vnode: int) -> int:
    """Ring point of one virtual node.  ``id+1`` so server 0's points
    are not the bare vnode indices; the shift keeps id and vnode in
    disjoint bit ranges before mixing."""
    return splitmix64((((server_id + 1) << 32) | vnode) & _M64)


def key_point(key: int) -> int:
    return splitmix64(key & _M64)


def build_points(server_ids, vnodes: int = DEFAULT_VNODES
                 ) -> List[Tuple[int, int]]:
    """Sorted [(point, server_id)] for the given member set."""
    pts = [(vnode_point(s, v), s)
           for s in server_ids for v in range(vnodes)]
    pts.sort()
    return pts


def owner_of(key: int, points: List[Tuple[int, int]]) -> int:
    """Server id owning ``key``: first vnode point >= the key's point,
    wrapping to the smallest point (classic consistent hashing)."""
    if not points:
        raise ValueError("ring has no members")
    kp = key_point(key)
    lo, hi = 0, len(points)
    while lo < hi:
        mid = (lo + hi) // 2
        if points[mid][0] < kp:
            lo = mid + 1
        else:
            hi = mid
    return points[lo % len(points)][1]


def successor_of(key: int, points: List[Tuple[int, int]]) -> int:
    """Replication target for ``key``: the owner of the ring with the
    key's OWNER's vnodes removed — i.e. the next DISTINCT server along
    the ring.  This is the Python mirror of the C++ `repl_points_` law
    (server.cc CMD_REPL): owner and successor must agree from both
    sides, or a failover would look for the replica on the wrong
    server.  Raises ValueError on a single-member ring (no distinct
    successor exists; the owner self-acks there)."""
    own = owner_of(key, points)
    rest = [(p, s) for p, s in points if s != own]
    if not rest:
        raise ValueError("ring has a single member: no successor")
    return owner_of(key, rest)


class RingTable:
    """One worker's view of the server ring: epoch, members (id ->
    address), and the precomputed point table.

    ``servers`` is ``[(id, host, port), ...]``.  Addresses are what THIS
    worker dials (they may be chaos-proxy addresses in tests); the
    server tier keeps its own peer address book for migrations.
    """

    def __init__(self, servers: List[Tuple[int, str, int]],
                 vnodes: int = DEFAULT_VNODES, epoch: int = 0):
        self.epoch = int(epoch)
        self.vnodes = max(1, int(vnodes))
        self.servers: List[Tuple[int, str, int]] = [
            (int(i), str(h), int(p)) for i, h, p in servers]
        self._points = build_points([i for i, _, _ in self.servers],
                                    self.vnodes)

    # -- placement ----------------------------------------------------------
    def owner(self, key: int) -> int:
        return owner_of(key, self._points)

    def successor(self, key: int) -> int:
        """The key's replication target (see ``successor_of``)."""
        return successor_of(key, self._points)

    def ids(self) -> List[int]:
        return [i for i, _, _ in self.servers]

    def address(self, server_id: int) -> Optional[Tuple[str, int]]:
        for i, h, p in self.servers:
            if i == server_id:
                return h, p
        return None

    # -- transitions --------------------------------------------------------
    def without(self, server_id: int) -> "RingTable":
        """The next-epoch ring with ``server_id`` removed (drain /
        failover proposal)."""
        rest = [(i, h, p) for i, h, p in self.servers if i != server_id]
        if not rest:
            raise ValueError("cannot remove the last ring member")
        return RingTable(rest, self.vnodes, self.epoch + 1)

    def with_server(self, server_id: int, host: str,
                    port: int) -> "RingTable":
        """The next-epoch ring with a joiner added (scale-up)."""
        rest = [(i, h, p) for i, h, p in self.servers if i != server_id]
        rest.append((int(server_id), str(host), int(port)))
        return RingTable(rest, self.vnodes, self.epoch + 1)

    # -- wire formats -------------------------------------------------------
    # Client -> server (CMD_RING_SET / CMD_DRAIN payload) is binary —
    # the C++ side stays free of JSON parsing:
    #   u64 epoch | u32 vnodes | u32 n | n x (u32 id | u16 port |
    #   u8 host_len | host_utf8)
    def to_wire(self) -> bytes:
        out = [struct.pack("<QII", self.epoch, self.vnodes,
                           len(self.servers))]
        for i, h, p in self.servers:
            hb = h.encode()
            out.append(struct.pack("<IHB", i, p, len(hb)) + hb)
        return b"".join(out)

    # Server -> client (CMD_RING response / MOVED payload) is JSON.
    @classmethod
    def from_json(cls, doc: dict) -> "RingTable":
        servers = [(int(s["id"]), str(s.get("host", "")),
                    int(s.get("port", 0)))
                   for s in doc.get("servers", [])]
        return cls(servers, int(doc.get("vnodes", DEFAULT_VNODES)),
                   int(doc.get("epoch", 0)))

    def describe(self) -> Dict:
        return {"epoch": self.epoch, "vnodes": self.vnodes,
                "servers": [{"id": i, "host": h, "port": p}
                            for i, h, p in self.servers]}


def moved_fraction(old: RingTable, new: RingTable,
                   keys) -> float:
    """Fraction of ``keys`` whose owner differs between two rings — the
    stability metric the ring exists for (adding one of N+1 servers
    should move ~1/(N+1) of the keys, and only TO the new server)."""
    keys = list(keys)
    if not keys:
        return 0.0
    moved = sum(1 for k in keys if old.owner(k) != new.owner(k))
    return moved / len(keys)
