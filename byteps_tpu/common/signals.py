"""Windowed per-key signal plane: the substrate `bps doctor` runs on.

PRs 4/5/10 built three *passive* observability planes — the metrics
registry (time-domain aggregates), the distributed trace (time-domain
detail, windowed), and the value-domain auditor/health monitor.
Joining them was a human job: run bps_top, trace_analyze and
postmortem.py separately and correlate by eye.  This module is the
join: a windowed per-key aggregator that folds

  - **wire-domain** worker-side timers, always on and O(ns)-class per
    partition (queue wait, push RTT, serve wait = push-ack → pull-data,
    codec encode/decode) — the cheap stand-in for the trace plane's
    critical-path components when tracing is not armed,
  - **the metrics registry** snapshot (round lag, transport/fusion/codec
    counters, grad-health and audit gauges), and
  - **value-plane** verdicts (health/audit provider sections),

into one stable ``KeySignal`` record per key per window, each carrying a
classification::

    wire_bound | compute_bound | straggler_bound | tiny | unhealthy

exposed as ``bps.get_key_signals()`` — the exact interface the future
adaptive-compression tuner consumes (ROADMAP: arXiv 2105.07829), and
the input stream ``common/doctor.py`` evaluates its rules over each
window.

Cost model: ``BYTEPS_TPU_SIGNAL_WINDOW_S=0`` (off) arms nothing — the
hot-path feeds are a module-global None check and the wire is untouched
either way (the plane is strictly local; asserted byte-identical by
tests/test_signals.py against a recording stub).  Armed, the per-part
feed is a dict update under a short lock (~µs-class, once per partition
round trip) and the window roll is one registry snapshot + O(keys)
arithmetic per window.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from .logging import get_logger

SCHEMA = "bps-signal-window-v1"

# The classification vocabulary — stable: the adaptive-compression tuner
# and the doctor rules key off these strings.
CLASSES = ("wire_bound", "compute_bound", "straggler_bound", "tiny",
           "unhealthy")

# A key whose mean pushed partition payload is below this is "tiny":
# per-message overhead dominates its cost and neither compressing harder
# nor blaming the wire makes sense — the fusion layer is its remedy.
TINY_BYTES = 64 * 1024

# Distinct keys tracked per window before new ones aggregate under
# "_other" — bounds the window memory on pathological declare churn.
MAX_KEYS = 512

DEFAULT_WINDOW_S = 10.0
DEFAULT_HISTORY = 32

# Gauge families that are only as fresh as the last successful
# CMD_STATS refresh — dropped from a window whose refresh failed, so
# the doctor never diagnoses off frozen pre-outage values.
STALE_SERVER_GAUGES = ("bps_worker_round_lag", "bps_keys_owned",
                       "bps_server_alive", "bps_server_migrations",
                       "bps_ring_epoch", "bps_membership_epoch",
                       "bps_workers_alive", "bps_worker_alive")


class _KeyAcc:
    """One key's in-window accumulator (hot-path side)."""

    __slots__ = ("pushes", "push_bytes", "pull_bytes", "wire_bytes",
                 "queue_s", "rtt_s", "serve_s", "encode_s", "decode_s")

    def __init__(self):
        self.pushes = 0
        self.push_bytes = 0     # logical tensor bytes (pre-codec)
        self.pull_bytes = 0
        self.wire_bytes = 0     # encoded push-leg bytes actually sent
        self.queue_s = 0.0
        self.rtt_s = 0.0
        self.serve_s = 0.0
        self.encode_s = 0.0
        self.decode_s = 0.0


def classify(rec: dict, tiny_bytes: int = TINY_BYTES) -> str:
    """Classify one KeySignal record (pure — shared by the live plane,
    the doctor's tests, and any offline consumer).

    Order matters: value-domain damage trumps everything (a NaN-storming
    key must never be tuned as merely "wire bound"), tininess trumps the
    share comparison (a 2 KiB bias's timings are all overhead).  The
    remaining three pick the dominant critical-path component:

      - ``wire_bound``: queue wait + push RTT dominate — the key's bytes
        are what the dispatcher and the wire are busy with (compress
        harder / raise WIRE_CONNS / fuse less).
      - ``compute_bound``: codec encode+decode dominate (compress less /
        more COMPRESS_THREADS).
      - ``straggler_bound``: serve wait dominates — the span from push
        ack to pull data, which is the server's merge wait on *other*
        workers' pushes (plus the pull wire); the per-worker round-lag
        gauges name which peer.

    Boundary law (PR 20): ``compute`` here is CODEC compute only —
    encode + decode, the seconds the tuner can actually trade against
    the wire by switching codecs.  Measured DEVICE compute (the
    ``device_compute`` component the devprof plane contributes to fleet
    docs and the goodput ledger) is deliberately excluded: a model
    whose matmuls dominate the step must never read as
    ``compute_bound`` and trick the tuner into compressing less — that
    knob cannot buy device FLOPs back.
    """
    health = rec.get("health") or {}
    if health.get("nonfinite") or rec.get("audit_bad"):
        return "unhealthy"
    pushes = rec.get("pushes", 0)
    if pushes and rec.get("push_bytes", 0) / pushes < tiny_bytes:
        return "tiny"
    comps = rec.get("components") or {}
    wire = comps.get("queue", 0.0) + comps.get("push_wire", 0.0)
    compute = comps.get("encode", 0.0) + comps.get("decode", 0.0)
    straggler = comps.get("serve", 0.0)
    best = max(wire, compute, straggler)
    if best <= 0.0:
        return "tiny" if pushes == 0 else "wire_bound"
    if best == straggler:
        return "straggler_bound"
    if best == compute:
        return "compute_bound"
    return "wire_bound"


class SignalPlane:
    """The windowed aggregator.

    ``note_part``/``note_codec`` are the hot-path feeds (called by the
    PS session per partition round trip / codec job).  ``roll()`` closes
    the current window: swaps the accumulators, snapshots the metrics
    registry (scalars only), collects the provider sections
    (transport/health/audit — local state) and the refresh result
    (server stats — the one wire poll, best-effort), classifies every
    key, and appends the finished **window summary** to a bounded
    history.  ``on_window`` (the doctor engine) sees each summary as it
    closes.

    A background thread calls ``roll()`` every ``window_s``; tests call
    it synchronously instead.
    """

    def __init__(self, window_s: float = DEFAULT_WINDOW_S,
                 history: int = DEFAULT_HISTORY,
                 refresh: Optional[Callable[[], Optional[dict]]] = None,
                 providers: Optional[Dict[str, Callable[[], dict]]] = None,
                 on_window: Optional[Callable[[dict], None]] = None):
        self.window_s = max(0.05, float(window_s))
        self._lock = threading.Lock()
        self._acc: Dict[str, _KeyAcc] = {}
        self._refresh = refresh
        self._providers = dict(providers or {})
        self._on_window = on_window
        self._history: deque = deque(maxlen=max(1, int(history)))
        self._window_idx = 0
        self._last_roll_mono = time.monotonic()
        self._last_event_mono = self._last_roll_mono
        # Audit verdicts already seen: the session's `last` verdict is
        # sticky for its lifetime, but a key is "unhealthy" only in the
        # window its verdict actually LANDED — one transient mismatch
        # must not brand a key forever.
        self._audit_seen = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- hot-path feeds -----------------------------------------------------
    @staticmethod
    def _base(label: str) -> str:
        # Partition labels are "<tensor>.partN"; signals aggregate per
        # tensor key.
        return label.rsplit(".part", 1)[0] if ".part" in label else label

    def _get_acc(self, label: str) -> _KeyAcc:
        acc = self._acc.get(label)
        if acc is None:
            if len(self._acc) >= MAX_KEYS:
                label = "_other"
                acc = self._acc.get(label)
                if acc is not None:
                    return acc
            acc = self._acc[label] = _KeyAcc()
        return acc

    def note_part(self, label: str, push_bytes: int, pull_bytes: int,
                  queue_s: float = 0.0, rtt_s: float = 0.0,
                  serve_s: float = 0.0,
                  wire_bytes: Optional[int] = None) -> None:
        """One completed partition round trip's timers.

        ``push_bytes``/``pull_bytes`` are LOGICAL tensor bytes — the
        tininess classification and the tuner must see the key's real
        size, not its post-codec blob (a 1 MiB key onebit-compressed to
        32 KiB is a compressed medium key, not a "tiny" one).
        ``wire_bytes`` is the encoded push payload actually sent (same
        as push_bytes for raw parts)."""
        base = self._base(label)
        with self._lock:
            acc = self._get_acc(base)
            acc.pushes += 1
            acc.push_bytes += int(push_bytes)
            acc.pull_bytes += int(pull_bytes)
            acc.wire_bytes += int(push_bytes if wire_bytes is None
                                  else wire_bytes)
            if queue_s > 0:
                acc.queue_s += queue_s
            if rtt_s > 0:
                acc.rtt_s += rtt_s
            if serve_s > 0:
                acc.serve_s += serve_s

    def note_codec(self, label: str, stage: str, dur_us: float) -> None:
        """One codec job's latency (stage = "encode" | "decode")."""
        base = self._base(label)
        s = max(0.0, float(dur_us)) / 1e6
        with self._lock:
            acc = self._get_acc(base)
            if stage == "encode":
                acc.encode_s += s
            else:
                acc.decode_s += s

    # -- window roll --------------------------------------------------------
    def _collect_metrics(self) -> dict:
        """Scalar slice of the registry snapshot — what the doctor rules
        consume.  Histogram dicts are dropped: counter/gauge series carry
        every rule input, and scalars keep window summaries JSON-light
        (they ride postmortem bundles and the /signals route)."""
        try:
            from . import telemetry
            snap = telemetry.get_registry().snapshot()
            return {k: v for k, v in snap.items()
                    if isinstance(v, (int, float))}
        except Exception:
            get_logger().debug("signal metrics snapshot failed",
                               exc_info=True)
            return {}

    def _collect_events(self, lo: float, upto: float) -> Dict[str, int]:
        """Flight-recorder event-kind counts for (``lo``, ``upto``] —
        the barrier/stall pattern input.  The upper bound matters: the
        roll itself can take a while (the CMD_STATS refresh is a wire
        poll), and an event recorded DURING it must land in exactly one
        window, the next one."""
        try:
            from . import flightrec
            counts: Dict[str, int] = {}
            for ev in flightrec.get_recorder().events():
                if lo < ev.get("mono", 0.0) <= upto:
                    k = ev.get("kind", "?")
                    counts[k] = counts.get(k, 0) + 1
            return counts
        except Exception:
            return {}

    def roll(self, now: Optional[float] = None) -> dict:
        """Close the current window and return its summary."""
        now = time.monotonic() if now is None else now
        with self._lock:
            # ALL window bookkeeping swaps under the one lock: roll() is
            # public (tests, bench) and may race the background thread —
            # each event interval and accumulator batch must belong to
            # exactly one window.
            acc, self._acc = self._acc, {}
            idx = self._window_idx
            self._window_idx += 1
            prev_roll = self._last_roll_mono
            self._last_roll_mono = now
            ev_lo = self._last_event_mono
            self._last_event_mono = now
        dur = max(1e-6, now - prev_roll)

        server = None
        if self._refresh is not None:
            try:
                server = self._refresh()
            except Exception as e:
                get_logger().debug("signal window refresh failed: %s", e)
            if server:
                # Keep the rows the rules read (per-server ownership +
                # bytes) and the scalar totals; drop the per-key map and
                # per-worker tables — a thousand-key model would
                # otherwise ship its whole CMD_STATS payload in every
                # retained window, bundle, and /signals response.
                # EXCEPTION: server-resident-optimizer rows (opt_mode
                # != 0) survive as a minimal `opt_keys` slice — the
                # param_version_stall rule needs completed_round vs
                # param_version per armed key, and armed keys are the
                # model's few declared tensors, not the key space.
                opt_keys = {
                    str(k): {"completed_round":
                                 int(row.get("completed_round", 0)),
                             "param_version":
                                 int(row.get("param_version", 0)),
                             "opt_mode": int(row.get("opt_mode", 0))}
                    for k, row in (server.get("keys") or {}).items()
                    if isinstance(row, dict)
                    and int(row.get("opt_mode", 0))}
                server = {k: v for k, v in server.items()
                          if k not in ("keys", "workers", "members")}
                if opt_keys:
                    server["opt_keys"] = opt_keys
        sections: Dict[str, dict] = {}
        for name, fn in self._providers.items():
            try:
                sections[name] = fn() or {}
            except Exception:
                pass
        metrics = self._collect_metrics()
        events = self._collect_events(lo=ev_lo, upto=now)

        if self._refresh is not None and server is None:
            # The per-window CMD_STATS refresh failed (or there is no
            # session): the registry's server-derived gauges are frozen
            # pre-outage values — evaluating lag/ownership rules over
            # them would e.g. name a "persistent straggler" whose real
            # story is a dead server.  Strip them; the counter/event
            # rules (stall, audit, pool) still see this window.
            metrics = {k: v for k, v in metrics.items()
                       if not k.startswith(STALE_SERVER_GAUGES)}

        health_keys = (sections.get("health") or {}).get("keys") or {}
        audit_sec = sections.get("audit") or {}
        audit_events = (int(audit_sec.get("mismatches", 0) or 0)
                        + int(audit_sec.get("round_skew", 0) or 0))
        audit_bad_key = None
        if audit_events > self._audit_seen:
            last = audit_sec.get("last") or {}
            bad = last.get("label") or last.get("key")
            # Verdicts carry PARTITION labels ("tensor.part3");
            # accumulator keys are base labels — strip or the compare
            # below can never match and 'unhealthy' never fires.
            audit_bad_key = self._base(str(bad)) if bad else None
        self._audit_seen = max(self._audit_seen, audit_events)

        keys: Dict[str, dict] = {}
        for label, a in acc.items():
            rec = {
                "key": label,
                "pushes": a.pushes,
                "push_bytes": a.push_bytes,
                "pull_bytes": a.pull_bytes,
                "wire_bytes": a.wire_bytes,
                "wire_mbps": (a.wire_bytes + a.pull_bytes) / 1e6 / dur,
                "components": {
                    "queue": a.queue_s, "push_wire": a.rtt_s,
                    "serve": a.serve_s, "encode": a.encode_s,
                    "decode": a.decode_s,
                },
                "rtt_mean_s": (a.rtt_s / a.pushes) if a.pushes else 0.0,
            }
            total = sum(rec["components"].values())
            rec["shares"] = {k: (v / total if total > 0 else 0.0)
                             for k, v in rec["components"].items()}
            h = health_keys.get(label)
            if h:
                rec["health"] = {"norm": h.get("norm"),
                                 "absmax": h.get("absmax"),
                                 "nonfinite": h.get("nonfinite", 0)}
            if audit_bad_key == label:
                rec["audit_bad"] = True
            rec["class"] = classify(rec)
            keys[label] = rec

        # Same-instant wall/mono anchor pair: "ts" (wall) and "mono"
        # are sampled at DIFFERENT instants (mono at roll start, wall
        # here, with the whole summary build in between), which is fine
        # for humans but not for cross-worker alignment — the fleet
        # merge maps one worker's monotonic durations onto another's
        # wall timeline through this pair, so both clocks must be read
        # back-to-back (the flightrec bundle "clock" law).
        anchor_wall, anchor_mono = time.time(), time.monotonic()
        summary = {
            "schema": SCHEMA,
            "window": idx,
            "ts": anchor_wall,
            "mono": now,
            "anchor": {"wall": anchor_wall, "mono": anchor_mono},
            "dur_s": dur,
            "keys": keys,
            "metrics": metrics,
            "events": events,
        }
        if server:
            summary["server"] = server
        for name in ("transport", "health", "audit", "device"):
            if sections.get(name):
                summary[name] = sections[name]
        self._history.append(summary)
        if self._on_window is not None:
            try:
                self._on_window(summary)
            except Exception:
                get_logger().exception("signal window consumer failed")
        return summary

    # -- read surfaces ------------------------------------------------------
    def history(self) -> List[dict]:
        return list(self._history)

    def key_signals(self) -> dict:
        """The last closed window's per-key records — the
        ``bps.get_key_signals()`` payload (and the adaptive-compression
        tuner's input)."""
        if not self._history:
            return {"schema": SCHEMA, "window": -1, "window_s":
                    self.window_s, "keys": {}}
        last = self._history[-1]
        return {"schema": SCHEMA, "window": last["window"],
                "window_s": self.window_s, "ts": last["ts"],
                "keys": last["keys"]}

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "SignalPlane":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="bps-signal-window")
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.window_s):
            try:
                self.roll()
            except Exception:
                get_logger().exception("signal window roll failed")

    def stop(self, final_roll: bool = True) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5)
        if final_roll:
            try:
                self.roll()   # short runs still close one window
            except Exception:
                pass


# ---------------------------------------------------------------------------
# Module singleton: the hot-path feeds go through these so an unarmed
# process (BYTEPS_TPU_SIGNAL_WINDOW_S=0, or no init) pays one global
# read + None check per call site.
# ---------------------------------------------------------------------------
_plane: Optional[SignalPlane] = None
_plane_lock = threading.Lock()


def plane() -> Optional[SignalPlane]:
    return _plane


def arm(window_s: float = DEFAULT_WINDOW_S, history: int = DEFAULT_HISTORY,
        refresh=None, providers=None, on_window=None,
        start_thread: bool = True) -> SignalPlane:
    """Install (and optionally start) the process-wide signal plane.
    Idempotent per process: re-arming replaces the previous plane (after
    stopping its thread)."""
    global _plane
    with _plane_lock:
        if _plane is not None:
            _plane.stop(final_roll=False)
        _plane = SignalPlane(window_s=window_s, history=history,
                             refresh=refresh, providers=providers,
                             on_window=on_window)
        if start_thread:
            _plane.start()
        return _plane


def disarm(final_roll: bool = False) -> None:
    global _plane
    with _plane_lock:
        if _plane is not None:
            _plane.stop(final_roll=final_roll)
            _plane = None


def note_part(label: str, push_bytes: int, pull_bytes: int,
              queue_s: float = 0.0, rtt_s: float = 0.0,
              serve_s: float = 0.0,
              wire_bytes: Optional[int] = None) -> None:
    p = _plane
    if p is not None:
        p.note_part(label, push_bytes, pull_bytes, queue_s=queue_s,
                    rtt_s=rtt_s, serve_s=serve_s, wire_bytes=wire_bytes)


def note_codec(label: str, stage: str, dur_us: float) -> None:
    p = _plane
    if p is not None:
        p.note_codec(label, stage, dur_us)
