"""Black-box flight recorder + postmortem bundles.

The observability planes built so far are either LIVE (metrics endpoint,
bps_top) or windowed-and-fetched (traces): when a run dies — SIGKILL'd
server, wedged round, NaN storm — the state transitions that explain it
were scattered across WARNING logs on N hosts, most of them rotated away
or never captured.  This module is the black box: a bounded, lock-light
in-memory ring of structured events (connects/drops/replays,
ring/membership epoch changes, round completions, watchdog/barrier
trips, audit verdicts, non-finite gradients), dumped — by the stall
watchdog, the failover path, the auditor's first mismatch, and an
atexit/faulthandler hook — into a self-contained JSON **postmortem
bundle**: events + final metrics snapshot + config + membership/ring/
transport state.  ``tools/postmortem.py`` merges bundles from several
workers into one clock-aligned timeline and names the first divergent
event.

Cost model: ``record()`` is a dict build + deque append (~µs) and the
ring is bounded (``BYTEPS_TPU_FLIGHTREC_EVENTS``, default 4096; 0
disables recording entirely).  Bundles are written ONLY when
``BYTEPS_TPU_POSTMORTEM_DIR`` names a directory — an unarmed run never
touches the filesystem.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from .logging import get_logger

DEFAULT_EVENTS = 4096

BUNDLE_SCHEMA = "bps-postmortem-v1"


class FlightRecorder:
    """Bounded ring of structured events.

    ``record()`` runs on hot-ish paths (per-round markers, transport
    transitions), so it takes one short lock around a deque append —
    no I/O, no formatting; events are rendered only at dump time.
    """

    def __init__(self, capacity: int = DEFAULT_EVENTS):
        self.capacity = max(0, int(capacity))
        self._ring: deque = deque(maxlen=self.capacity or 1)
        self._count = 0
        self._lock = threading.Lock()

    def record(self, kind: str, **fields: Any) -> None:
        if self.capacity <= 0:
            return
        ev = {"t": time.time(), "mono": time.monotonic(), "kind": kind}
        ev.update(fields)
        with self._lock:
            self._ring.append(ev)
            self._count += 1

    def events(self) -> List[dict]:
        with self._lock:
            return [dict(e) for e in self._ring]

    @property
    def dropped(self) -> int:
        with self._lock:
            return max(0, self._count - len(self._ring))

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._count = 0


_recorder: Optional[FlightRecorder] = None
_rec_lock = threading.Lock()
# Named bundle-section providers ("api" = the api layer's step/cached
# membership; "session" = the live PSSession's transport/audit/ring/
# health sections) — each runs ONCE per dump, merged in name order.
_providers: Dict[str, Callable[[], dict]] = {}
_armed = False
_fault_file = None          # keeps the faulthandler stream alive


def _capacity_from_env() -> int:
    v = os.environ.get("BYTEPS_TPU_FLIGHTREC_EVENTS")
    if v is None or v == "":
        return DEFAULT_EVENTS
    try:
        return max(0, int(v))
    except ValueError:
        get_logger().warning(
            "ignoring invalid BYTEPS_TPU_FLIGHTREC_EVENTS=%r "
            "(want an event count; 0 disables)", v)
        return DEFAULT_EVENTS


def get_recorder() -> FlightRecorder:
    global _recorder
    with _rec_lock:
        if _recorder is None:
            _recorder = FlightRecorder(_capacity_from_env())
        return _recorder


def reset(capacity: Optional[int] = None) -> FlightRecorder:
    """Testing hook: fresh recorder (optionally with an explicit
    capacity, else re-read from the environment)."""
    global _recorder
    with _rec_lock:
        _recorder = FlightRecorder(
            _capacity_from_env() if capacity is None else capacity)
        return _recorder


def record(kind: str, **fields: Any) -> None:
    """Append one structured event to the process-wide flight ring."""
    get_recorder().record(kind, **fields)


def set_extra_provider(fn: Optional[Callable[[], dict]],
                       name: str = "api") -> None:
    """Register a named bundle-section provider (None unregisters).
    Sections are collected best-effort at dump time; a provider must
    not touch the wire — a bundle is written exactly when the wire may
    be the broken part."""
    if fn is None:
        _providers.pop(name, None)
    else:
        _providers[name] = fn


def remove_extra_provider(name: str, owner: Any = None) -> None:
    """Unregister `name` — only if the registered provider is still
    `owner`'s bound method when an owner is given, so a closed session
    cannot knock out a newer session's provider (bound methods are
    fresh objects per attribute access, so identity is compared on
    ``__self__``, not the callable)."""
    cur = _providers.get(name)
    if owner is None or getattr(cur, "__self__", None) is owner:
        _providers.pop(name, None)


def postmortem_dir() -> str:
    """Resolved at call time (not import) so tests and late-configured
    jobs can arm bundles without re-importing."""
    return os.environ.get("BYTEPS_TPU_POSTMORTEM_DIR", "")


def _rank() -> int:
    for var in ("BYTEPS_GLOBAL_RANK", "DMLC_WORKER_ID"):
        v = os.environ.get(var)
        if v not in (None, ""):
            try:
                return int(v)
            except ValueError:
                pass
    return 0


def _sanitize(obj):
    """Make a metrics/extra tree strict-JSON-safe: histogram +Inf bucket
    bounds (and any other non-finite float) become strings — a bare
    ``Infinity`` in the output would make the bundle unparseable by
    exactly the tool it exists for.  Delegates to the one shared walk
    (telemetry.json_safe, also behind the /signals and /diagnosis
    routes) so bundles and routes can never encode the same value
    differently."""
    from .telemetry import json_safe
    return json_safe(obj)


def dump_bundle(reason: str, extra: Optional[dict] = None,
                directory: Optional[str] = None) -> Optional[str]:
    """Write one self-contained postmortem bundle; returns its path, or
    None when bundles are unarmed (no ``BYTEPS_TPU_POSTMORTEM_DIR``).
    Never raises — the dump path runs inside failure handlers."""
    try:
        d = directory if directory is not None else postmortem_dir()
        if not d:
            return None
        os.makedirs(d, exist_ok=True)
        rec = get_recorder()
        rank = _rank()
        doc: Dict[str, Any] = {
            "schema": BUNDLE_SCHEMA,
            "reason": reason,
            "rank": rank,
            "host": socket.gethostname(),
            "pid": os.getpid(),
            # The wall/mono pair anchors this process's monotonic event
            # timestamps onto the wall clock, which is what
            # tools/postmortem.py aligns bundles from different workers
            # by (each event also carries its own wall time).
            "clock": {"wall": time.time(), "mono": time.monotonic()},
            "config": {k: v for k, v in sorted(os.environ.items())
                       if k.startswith(("BYTEPS", "DMLC"))},
            "events_dropped": rec.dropped,
            "events": rec.events(),
        }
        try:
            from . import telemetry
            doc["metrics"] = telemetry.get_registry().snapshot()
        except Exception:
            doc["metrics"] = {}
        sections: Dict[str, Any] = {}
        for pname in sorted(_providers):
            fn = _providers.get(pname)
            if fn is None:
                continue
            try:
                sections.update(fn() or {})
            except Exception:
                get_logger().debug("postmortem provider %r failed",
                                   pname, exc_info=True)
        if extra:
            sections.update(extra)
        doc["extra"] = sections
        name = (f"bps-postmortem-r{rank}-{reason}-"
                f"{os.getpid()}-{int(time.time() * 1000)}.json")
        path = os.path.join(d, name)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(_sanitize(doc), f)
        os.replace(tmp, path)
        get_logger().error(
            "postmortem bundle written: %s (reason=%s, %d events; render "
            "with: python tools/postmortem.py %s)", path, reason,
            len(doc["events"]), d)
        return path
    except Exception:
        get_logger().exception("postmortem bundle dump failed")
        return None


def arm_postmortem(directory: Optional[str] = None) -> bool:
    """Idempotently arm the crash hooks: an atexit bundle (a run that
    dies mid-flight still leaves its black box behind) and a
    ``faulthandler`` traceback file next to the bundles for fatal
    signals (SIGSEGV/SIGABRT — states Python-level hooks never see).
    Returns True when armed (a directory is configured)."""
    global _armed, _fault_file
    d = directory if directory is not None else postmortem_dir()
    if not d or _armed:
        return _armed
    try:
        os.makedirs(d, exist_ok=True)
        import atexit
        atexit.register(_dump_on_exit)
        try:
            import faulthandler
            _fault_file = open(
                os.path.join(d, f"bps-faulthandler-r{_rank()}-"
                                f"{os.getpid()}.log"), "w")
            faulthandler.enable(file=_fault_file)
        except Exception:
            get_logger().debug("faulthandler arm failed", exc_info=True)
        _armed = True
        get_logger().info(
            "flight recorder armed: postmortem bundles -> %s", d)
    except Exception:
        get_logger().exception("postmortem arm failed")
    return _armed


def _dump_on_exit() -> None:
    try:
        record("exit")
        dump_bundle("exit")
    except Exception:
        pass
