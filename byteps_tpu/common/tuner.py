"""Adaptive-compression tuner: the self-tuning control loop that picks
the wire codec (and proposes the knobs) per key, per signal window.

BytePS ships a static compression registry — the user picks a codec per
tensor up front and lives with it, even though the right choice depends
on whether a key is wire-bound or compute-bound *right now*.  This
module closes that loop (arXiv 2105.07829, "Compressed Communication
for Distributed Training: Adaptive Methods and System"): each window it
walks the signal plane's classified ``KeySignal`` records
(``bps.get_key_signals()``, PR 12) and steps every key along the dial

    raw -> onebit -> elias -> qblock

  - ``wire_bound`` keys (queue wait + push RTT dominate) step toward
    harder codecs — their bytes are what the dispatcher and the wire
    are busy with;
  - ``compute_bound`` and ``tiny`` keys step toward raw — codec work
    (or per-message overhead) dominates, so compressing harder only
    moves the bottleneck;
  - ``straggler_bound`` keys are left alone — the serve wait is peers'
    pushes, and no local codec changes that;
  - ``unhealthy`` keys are PINNED raw and the tuner backs off — the
    doctor's nonfinite/audit verdicts trump bandwidth, always.

Decisions are hysteretic so the loop cannot oscillate: a key must hold
its class for ``hold`` consecutive windows before a switch, every
switch is re-measured the next window and REVERTED (then blacklisted
for ``blacklist`` windows) if the key's per-push round time regressed
by more than ``regress_frac``, and keys carrying a user-configured
off-dial codec (topk/randomk/dense dithering) are never touched.

Actuation rides the CMD_CODEC renegotiation protocol
(``PSSession.propose_codec``): epoch-versioned, applied at a declared
future round boundary on every worker and the server atomically, EF
residuals carried across the switch.  Only ONE worker proposes
(worker 0 by default) — the others run the same loop in observe mode,
polling the codec table and relying on the server's CODEC_STALE
backstop, so racing proposers can't fight.

The same loop also inspects the global knobs —
``BYTEPS_TPU_FUSION_BYTES``, ``BYTEPS_TPU_COMPRESS_THREADS``,
``BYTEPS_PARTITION_BYTES``, ``BYTEPS_TPU_WIRE_CONNS`` — and PROPOSES
adjustments where the evidence supports them.  None of these are
safely re-appliable mid-job in this codebase (fusion bytes change
bucket key identity, the codec pool's width and the lane pools are
fixed at session init, partition size changes the key space), so
proposals are logged once and surfaced through ``bps.get_tuner()``,
never silently applied — restart with the suggested values.

Armed by ``BYTEPS_TPU_TUNER=1`` (requires the signal plane,
``BYTEPS_TPU_SIGNAL_WINDOW_S`` > 0).  Off by default: nothing is
constructed, no CMD_CODEC frame is ever sent, and the wire is
byte-identical to the untuned run (asserted by tests/test_tuner.py
against a recording stub).
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Dict, List, Optional

from .logging import get_logger

# The dial, softest to hardest.  Position names are stable — the docs'
# class->action table, bps_top's tuner column and the tests key off
# them.  "qblock" (EQuARX-flavored blockwise int4, arXiv 2506.17615) is
# the aggressive end: dense layout, deterministic, cheap flat-loop
# encode/decode, EF-capable on both legs.
DIAL = ("raw", "onebit", "elias", "qblock")

DIAL_KWARGS = {
    "raw": None,
    "onebit": {"compressor": "onebit", "ef": "vanilla"},
    "elias": {"compressor": "dithering", "k": "15", "coding": "elias",
              "ef": "vanilla"},
    "qblock": {"compressor": "qblock", "bits": "4", "block": "256",
               "ef": "vanilla"},
}

# Wire comp ids for the bps_codec_active gauge / bps_top column.
DIAL_COMP_ID = {"raw": 0, "onebit": 1, "elias": 4, "qblock": 5}

DEFAULT_HOLD = 2          # windows a class must persist before a switch
DEFAULT_BLACKLIST = 8     # windows a reverted key stays frozen
DEFAULT_MARGIN_ROUNDS = 2  # switch takes effect this many rounds ahead
DEFAULT_REGRESS_FRAC = 0.2  # per-push time regression that triggers revert


def dial_of(comp) -> Optional[int]:
    """Map a session compressor (or None) onto a dial position; None if
    the key carries an off-dial user codec the tuner must not touch."""
    if comp is None:
        return 0
    name = getattr(comp, "name", None)
    if name == "onebit":
        return 1
    if name == "dithering" and getattr(comp, "coding", "") == "elias":
        return 2
    if name == "qblock":
        return 3
    return None


class _KeyTune:
    """One key's controller state."""

    __slots__ = ("dial", "classes", "blacklist_until", "pinned",
                 "baseline_ms", "eval_window", "prev_dial", "switches",
                 "declared_key", "off_dial_warned")

    def __init__(self, dial: int, declared_key: Optional[int]):
        self.dial = dial                 # current DIAL index
        self.classes: deque = deque(maxlen=16)
        self.blacklist_until = -1        # window index; -1 = clear
        self.pinned = False              # unhealthy -> raw, frozen
        self.baseline_ms: Optional[float] = None   # per-push time
        self.eval_window = -1            # window whose summary judges the
        #                                  last switch (-1 = none pending)
        self.prev_dial = dial
        self.switches = 0
        self.declared_key = declared_key
        self.off_dial_warned = False


class Tuner:
    """The control loop.  ``observe(summary)`` is chained onto the
    signal plane's ``on_window`` (after the doctor), so it runs once per
    closed window on the plane's thread — never on the hot path."""

    def __init__(self, session, propose: bool = True,
                 hold: int = DEFAULT_HOLD,
                 blacklist: int = DEFAULT_BLACKLIST,
                 margin_rounds: int = DEFAULT_MARGIN_ROUNDS,
                 regress_frac: float = DEFAULT_REGRESS_FRAC,
                 max_dial: int = len(DIAL) - 1):
        self._session = session
        self.propose = bool(propose)
        self.hold = max(1, int(hold))
        self.blacklist = max(1, int(blacklist))
        self.margin_rounds = max(1, int(margin_rounds))
        self.regress_frac = max(0.0, float(regress_frac))
        self.max_dial = min(len(DIAL) - 1, max(0, int(max_dial)))
        self._lock = threading.Lock()
        self._keys: Dict[str, _KeyTune] = {}
        self._window = -1
        self.switches_total = 0
        self.reverts_total = 0
        self._proposals: List[dict] = []
        self._proposed_knobs: set = set()
        from . import telemetry as _tm
        reg = _tm.get_registry()
        self._m_switches = reg.counter(
            "bps_tuner_switches_total",
            help="codec renegotiations the tuner initiated")
        self._reg = reg

    # -- the per-window pass ------------------------------------------------
    def observe(self, summary: dict) -> None:
        # Poll BEFORE taking the tuner lock: CMD_CODEC GETs are blocking
        # wire round trips (up to seconds against a slow server), and
        # holding the lock across them would stall get_tuner()/the
        # /tuner route — and, since observe runs on the signal plane's
        # on_window callback, the window rolls behind it.  The poll only
        # touches session state under the session's own locks.
        try:
            # Everyone polls: the proposer to catch races it lost,
            # observers to learn pending switches before their round
            # counters cross the boundary (CODEC_STALE remains the
            # correctness backstop either way).
            self._session.poll_codec()
        except Exception:
            get_logger().debug("tuner codec poll failed", exc_info=True)
        with self._lock:
            self._window = int(summary.get("window", self._window + 1))
            for label, rec in (summary.get("keys") or {}).items():
                if label == "_other" or not rec.get("pushes"):
                    continue
                try:
                    self._observe_key(label, rec)
                except Exception:
                    get_logger().exception("tuner pass failed for key %r",
                                           label)
            self._propose_knobs(summary)

    def _resolve_key(self, label: str) -> Optional[int]:
        try:
            from ..core.native import get_core
            dk = get_core().get_declared_key(label)
            if dk is not None and dk >= 0:
                return int(dk)
        except Exception:
            pass
        if label.startswith("key_"):
            try:
                return int(label[4:])
            except ValueError:
                return None
        return None

    def _state_for(self, label: str) -> Optional[_KeyTune]:
        kt = self._keys.get(label)
        if kt is None:
            dk = self._resolve_key(label)
            if dk is None:
                return None
            comp = self._session._compressors.get(dk)
            kt = self._keys[label] = _KeyTune(dial_of(comp) or 0, dk)
            if dial_of(comp) is None:
                kt.dial = -1          # off-dial user codec: hands off
        return kt

    def _per_push_ms(self, rec: dict) -> float:
        comps = rec.get("components") or {}
        pushes = max(1, int(rec.get("pushes", 1)))
        return sum(comps.values()) / pushes * 1e3

    def _observe_key(self, label: str, rec: dict) -> None:
        kt = self._state_for(label)
        if kt is None:
            return
        cls = rec.get("class", "")
        kt.classes.append(cls)
        if kt.dial < 0:
            if not kt.off_dial_warned:
                kt.off_dial_warned = True
                get_logger().info(
                    "tuner: key %s carries a user-configured off-dial "
                    "codec; leaving it alone", label)
            return
        # A switch the fleet has not finished applying (the session still
        # carries a pending CMD_CODEC entry for this key) must neither be
        # judged nor re-proposed: on slow-stepping jobs the effective
        # round can lie windows away, and re-proposing would stage an
        # ever-later boundary that never gets crossed (a livelock that
        # also inflates the thrash counters).
        pending = bool(getattr(self._session, "_codec_next",
                               {}).get(kt.declared_key))
        # Keep the mirror honest on non-proposing workers (and after
        # CODEC_STALE adoptions): the session's actual compressor wins —
        # but never while a pending switch is still in flight, where
        # "actual" is by construction the OLD codec.
        actual = dial_of(self._session._compressors.get(kt.declared_key))
        if actual is not None and actual != kt.dial \
                and kt.eval_window < 0 and not pending:
            kt.dial = actual
        per_push = self._per_push_ms(rec)
        # Post-switch evaluation: the first full window AFTER the switch
        # actually applied judges it — a regression reverts and
        # blacklists, success re-baselines.
        if kt.eval_window >= 0 and self._window > kt.eval_window:
            if pending:
                kt.eval_window = self._window   # not applied yet: wait
            else:
                kt.eval_window = -1
                if (kt.baseline_ms is not None and self.regress_frac > 0
                        and per_push > kt.baseline_ms
                        * (1.0 + self.regress_frac)):
                    self.reverts_total += 1
                    kt.blacklist_until = self._window + self.blacklist
                    get_logger().warning(
                        "tuner: switch of key %s to %s regressed "
                        "per-push time %.2fms -> %.2fms; reverting to "
                        "%s and blacklisting for %d windows", label,
                        DIAL[kt.dial], kt.baseline_ms, per_push,
                        DIAL[kt.prev_dial], self.blacklist)
                    self._switch(label, kt, kt.prev_dial, "revert")
                    return
                kt.baseline_ms = per_push
        if kt.baseline_ms is None:
            kt.baseline_ms = per_push
        # Value-domain damage trumps bandwidth: pin unhealthy keys raw
        # and back off; unpin only after a full healthy hold.
        if cls == "unhealthy":
            if kt.dial != 0:
                get_logger().warning(
                    "tuner: key %s is unhealthy; pinning raw", label)
                self._switch(label, kt, 0, "unhealthy")
            kt.pinned = True
            kt.blacklist_until = max(kt.blacklist_until,
                                     self._window + self.blacklist)
            return
        if kt.pinned:
            healthy = list(kt.classes)[-self.hold:]
            if len(healthy) >= self.hold and all(
                    c != "unhealthy" for c in healthy):
                kt.pinned = False
            else:
                return
        if not self.propose or pending \
                or self._window <= kt.blacklist_until \
                or kt.eval_window >= 0:
            return
        # Hysteresis: the class must have held for `hold` windows.
        recent = list(kt.classes)[-self.hold:]
        if len(recent) < self.hold or len(set(recent)) != 1:
            return
        target = kt.dial
        if cls == "wire_bound":
            target = min(kt.dial + 1, self.max_dial)
        elif cls in ("compute_bound", "tiny"):
            target = max(kt.dial - 1, 0)
        if target != kt.dial:
            kt.baseline_ms = self._per_push_ms(rec)
            self._switch(label, kt, target, cls)

    def _switch(self, label: str, kt: _KeyTune, target: int,
                why: str) -> None:
        if not self.propose or kt.declared_key is None:
            kt.dial = target
            return
        try:
            res = self._session.propose_codec(
                kt.declared_key, DIAL_KWARGS[DIAL[target]],
                margin_rounds=self.margin_rounds)
        except Exception as e:
            get_logger().warning("tuner: codec proposal for %s failed: %s",
                                 label, e)
            kt.blacklist_until = self._window + 2   # retry later, no spin
            return
        kt.prev_dial, kt.dial = kt.dial, target
        kt.switches += 1
        self.switches_total += 1
        kt.classes.clear()              # fresh hysteresis for the new codec
        if why in ("revert", "unhealthy"):
            # A revert (or a safety pin) is terminal, not an experiment:
            # judging IT against the pre-switch baseline could flip the
            # key right back onto the codec that just regressed — the
            # oscillation the blacklist exists to prevent.  Re-baseline
            # from the next ambient window instead.
            kt.eval_window = -1
            kt.baseline_ms = None
        else:
            # A forward switch lands mid-window; judge it on the FIRST
            # FULL window after it has applied.
            kt.eval_window = self._window + 1
        self._m_switches.inc()
        self._reg.counter(
            "bps_tuner_key_switches_total", labels={"key": label},
            help="tuner codec switches per key (the thrash signal)").inc()
        get_logger().info(
            "tuner: key %s %s -> %s (%s; effective round %s, %s)",
            label, DIAL[kt.prev_dial], DIAL[target], why,
            res.get("effective_round"),
            "accepted" if res.get("accepted") else "superseded")

    # -- advisory knob proposals --------------------------------------------
    def _propose_knobs(self, summary: dict) -> None:
        keys = summary.get("keys") or {}
        if not keys:
            return
        from .config import get_config
        cfg = get_config()
        counts: Dict[str, int] = {}
        for rec in keys.values():
            counts[rec.get("class", "?")] = counts.get(
                rec.get("class", "?"), 0) + 1
        total = sum(counts.values())

        def propose(knob: str, current, suggested, reason: str,
                    appliable: bool = False) -> None:
            if knob in self._proposed_knobs:
                return
            self._proposed_knobs.add(knob)
            row = {"knob": knob, "current": current,
                   "proposed": suggested, "reason": reason,
                   "applied": False, "window": self._window}
            self._proposals.append(row)
            # None of these knobs are safely re-appliable mid-job here
            # (bucket identity / fixed pools / key space) — log, never
            # silently apply.
            get_logger().info(
                "tuner proposal (advisory, NOT auto-applied — restart "
                "with it): %s=%s (now %s): %s", knob, suggested, current,
                reason)

        if counts.get("tiny", 0) > total / 2 and cfg.fusion_bytes > 0:
            propose("BYTEPS_TPU_FUSION_BYTES", cfg.fusion_bytes,
                    cfg.fusion_bytes * 2,
                    f"{counts['tiny']}/{total} keys are tiny (<64KiB "
                    f"mean payload): per-message overhead dominates — "
                    f"bigger fusion buckets amortize it")
        if counts.get("compute_bound", 0) > total / 2:
            propose("BYTEPS_TPU_COMPRESS_THREADS", cfg.compress_threads,
                    max(4, cfg.compress_threads * 2),
                    f"{counts['compute_bound']}/{total} keys are "
                    f"compute-bound: codec work dominates their round "
                    f"time — widen the codec pool")
        if counts.get("wire_bound", 0) > total / 2:
            at_max = all(
                kt.dial >= self.max_dial for kt in self._keys.values()
                if kt.dial >= 0)
            if at_max and self._keys:
                propose("BYTEPS_TPU_WIRE_CONNS", cfg.wire_conns,
                        cfg.wire_conns * 2,
                        f"{counts['wire_bound']}/{total} keys stay "
                        f"wire-bound at the hardest codec: more data "
                        f"lanes per server is the next dial")
                propose("BYTEPS_PARTITION_BYTES", cfg.partition_bytes,
                        max(1 << 20, cfg.partition_bytes // 2),
                        "wire-bound at the hardest codec: smaller "
                        "partitions overlap push/pull legs more finely")

    # -- read surface -------------------------------------------------------
    def state(self) -> dict:
        """The ``bps.get_tuner()`` payload."""
        with self._lock:
            keys = {}
            for label, kt in self._keys.items():
                keys[label] = {
                    "codec": DIAL[kt.dial] if kt.dial >= 0 else "user",
                    "dial": kt.dial,
                    "class_history": list(kt.classes),
                    "pinned": kt.pinned,
                    "blacklisted_until": kt.blacklist_until,
                    "baseline_per_push_ms": kt.baseline_ms,
                    "switches": kt.switches,
                }
            return {
                "armed": True,
                "proposer": self.propose,
                "window": self._window,
                "dial": list(DIAL),
                "switches_total": self.switches_total,
                "reverts_total": self.reverts_total,
                "keys": keys,
                "knob_proposals": [dict(p) for p in self._proposals],
            }
