"""Adaptive-compression tuner: the self-tuning control loop that picks
the wire codec (and proposes the knobs) per key, per signal window.

BytePS ships a static compression registry — the user picks a codec per
tensor up front and lives with it, even though the right choice depends
on whether a key is wire-bound or compute-bound *right now*.  This
module closes that loop (arXiv 2105.07829, "Compressed Communication
for Distributed Training: Adaptive Methods and System"): each window it
walks the signal plane's classified ``KeySignal`` records
(``bps.get_key_signals()``, PR 12) and steps every key along the dial

    raw -> onebit -> elias -> qblock

  - ``wire_bound`` keys (queue wait + push RTT dominate) step toward
    harder codecs — their bytes are what the dispatcher and the wire
    are busy with;
  - ``compute_bound`` and ``tiny`` keys step toward raw — codec work
    (or per-message overhead) dominates, so compressing harder only
    moves the bottleneck;
  - ``straggler_bound`` keys are left alone — the serve wait is peers'
    pushes, and no local codec changes that;
  - ``unhealthy`` keys are PINNED raw and the tuner backs off — the
    doctor's nonfinite/audit verdicts trump bandwidth, always.

Decisions are hysteretic so the loop cannot oscillate: a key must hold
its class for ``hold`` consecutive windows before a switch, every
switch is re-measured the next window and REVERTED (then blacklisted
for ``blacklist`` windows) if the key's per-push round time regressed
by more than ``regress_frac``, and keys carrying a user-configured
off-dial codec (topk/randomk/dense dithering) are never touched.

Actuation rides the CMD_CODEC renegotiation protocol
(``PSSession.propose_codec``): epoch-versioned, applied at a declared
future round boundary on every worker and the server atomically, EF
residuals carried across the switch.  Only ONE worker proposes
(worker 0 by default) — the others run the same loop in observe mode,
polling the codec table and relying on the server's CODEC_STALE
backstop, so racing proposers can't fight.

The same loop also inspects the global knobs.  Three of them —
``BYTEPS_TPU_FUSION_BYTES``, ``BYTEPS_TPU_COMPRESS_THREADS``,
``BYTEPS_TPU_WIRE_CONNS`` — are ACTUATED through the knob plane
(``PSSession.propose_knobs``, CMD_KNOB): an epoch-versioned global
table applied at a declared round boundary on the server and every
worker atomically, with the KNOB_STALE replay as the backstop (the
CMD_CODEC law, generalized).  Gate with ``BYTEPS_TPU_KNOB_ACTUATE=0``
to fall back to advisory-only.  ``BYTEPS_PARTITION_BYTES`` remains
advisory — partition size changes the pkey space itself, which no
boundary handshake can re-map mid-job — logged once and surfaced
through ``bps.get_tuner()``; restart with the suggested value.

When a machine-readable cost model is present (``wire_bench.py
--codec-sweep --json`` persists one to ``BYTEPS_TPU_KNOB_COST_MODEL``,
default ``~/.cache/byteps_tpu/codec_cost_model.json``), the tuner is
PREDICTIVE from a cold start: for each key's first window it computes
per-dial predicted push time — encode at the measured encode MB/s +
(payload / ratio) over the key's measured wire MB/s + decode — and
jumps straight to the predicted-best codec instead of stepping the
dial one notch per window.  The hysteretic react/revert/blacklist loop
stays armed as the safety net: a predictive jump is judged on the next
window like any other switch and reverted if it regressed.

Armed by ``BYTEPS_TPU_TUNER=1`` (requires the signal plane,
``BYTEPS_TPU_SIGNAL_WINDOW_S`` > 0).  Off by default: nothing is
constructed, no CMD_CODEC frame is ever sent, and the wire is
byte-identical to the untuned run (asserted by tests/test_tuner.py
against a recording stub).
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Dict, List, Optional

from .logging import get_logger

# The dial, softest to hardest.  Position names are stable — the docs'
# class->action table, bps_top's tuner column and the tests key off
# them.  "qblock" (EQuARX-flavored blockwise int4, arXiv 2506.17615) is
# the aggressive end: dense layout, deterministic, cheap flat-loop
# encode/decode, EF-capable on both legs.
DIAL = ("raw", "onebit", "elias", "qblock")

DIAL_KWARGS = {
    "raw": None,
    "onebit": {"compressor": "onebit", "ef": "vanilla"},
    "elias": {"compressor": "dithering", "k": "15", "coding": "elias",
              "ef": "vanilla"},
    "qblock": {"compressor": "qblock", "bits": "4", "block": "256",
               "ef": "vanilla"},
}

# Wire comp ids for the bps_codec_active gauge / bps_top column.
DIAL_COMP_ID = {"raw": 0, "onebit": 1, "elias": 4, "qblock": 5}

# Dial position -> wire_bench --codec-sweep codec name (the cost-model
# table's row key).  The sweep benches the EF-carrying variants — the
# same kwargs DIAL_KWARGS actuates.
DIAL_SWEEP_NAME = {"raw": "raw", "onebit": "onebit+ef",
                   "elias": "elias+ef", "qblock": "qblock4+ef"}

DEFAULT_HOLD = 2          # windows a class must persist before a switch
DEFAULT_BLACKLIST = 8     # windows a reverted key stays frozen
DEFAULT_MARGIN_ROUNDS = 2  # switch takes effect this many rounds ahead
DEFAULT_REGRESS_FRAC = 0.2  # per-push time regression that triggers revert


def dial_of(comp) -> Optional[int]:
    """Map a session compressor (or None) onto a dial position; None if
    the key carries an off-dial user codec the tuner must not touch."""
    if comp is None:
        return 0
    name = getattr(comp, "name", None)
    if name == "onebit":
        return 1
    if name == "dithering" and getattr(comp, "coding", "") == "elias":
        return 2
    if name == "qblock":
        return 3
    return None


def cost_model_path() -> str:
    """The stable cost-model path shared by the producer (wire_bench.py
    --codec-sweep --json persists here) and the consumer (the predictive
    tuner seeds from here): BYTEPS_TPU_KNOB_COST_MODEL, else the
    per-user cache default."""
    import os
    p = os.environ.get("BYTEPS_TPU_KNOB_COST_MODEL", "")
    if not p:
        try:
            from .config import get_config
            p = get_config().knob_cost_model
        except Exception:
            p = ""
    return p or os.path.expanduser(
        "~/.cache/byteps_tpu/codec_cost_model.json")


class CostModel:
    """Per-codec encode/decode throughput + ratio table, seeded from the
    ``wire_bench.py --codec-sweep`` ground truth.

    ``predict_push_s(dial_name, size_bytes, wire_mbps)`` models one
    push's wire-visible cost: encode the payload at the benched encode
    MB/s, ship ``size/ratio`` bytes at the key's MEASURED wire MB/s
    (the signal plane's per-key number — the model supplies the codec
    half, the live window supplies the network half), decode at the
    benched decode MB/s.  Rows are matched by nearest benched size."""

    def __init__(self, rows: List[dict], path: str = ""):
        self.path = path
        self._by_codec: Dict[str, List[dict]] = {}
        for r in rows or []:
            try:
                self._by_codec.setdefault(str(r["codec"]), []).append({
                    "size_bytes": int(r["size_bytes"]),
                    "encode_MBps": (float(r["encode_MBps"])
                                    if r.get("encode_MBps") else None),
                    "decode_MBps": (float(r["decode_MBps"])
                                    if r.get("decode_MBps") else None),
                    "ratio": float(r.get("ratio") or 1.0),
                })
            except (KeyError, TypeError, ValueError):
                continue
        for rows_ in self._by_codec.values():
            rows_.sort(key=lambda r: r["size_bytes"])

    def __len__(self) -> int:
        return sum(len(v) for v in self._by_codec.values())

    @classmethod
    def load(cls, path: Optional[str] = None) -> Optional["CostModel"]:
        """Best-effort load; None when the table is absent/unreadable
        (the tuner then runs purely hysteretic — never an error)."""
        import json
        import os
        p = path or cost_model_path()
        try:
            with open(p) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return None
        rows = doc.get("codec_sweep") if isinstance(doc, dict) else doc
        cm = cls(rows or [], path=p)
        return cm if len(cm) else None

    def _row(self, codec: str, size_bytes: int) -> Optional[dict]:
        rows = self._by_codec.get(codec)
        if not rows:
            return None
        return min(rows, key=lambda r: abs(r["size_bytes"] - size_bytes))

    def predict_push_s(self, dial_name: str, size_bytes: int,
                      wire_mbps: float) -> Optional[float]:
        if size_bytes <= 0 or wire_mbps <= 0:
            return None
        wire_bps = wire_mbps * 1e6
        if dial_name == "raw":
            return size_bytes / wire_bps
        row = self._row(DIAL_SWEEP_NAME.get(dial_name, dial_name),
                        size_bytes)
        if row is None:
            return None
        t = (size_bytes / max(1.0, row["ratio"])) / wire_bps
        if row["encode_MBps"]:
            t += size_bytes / (row["encode_MBps"] * 1e6)
        if row["decode_MBps"]:
            t += size_bytes / (row["decode_MBps"] * 1e6)
        return t

    def best_dial(self, size_bytes: int, wire_mbps: float,
                  max_dial: int) -> Optional[int]:
        """argmin of predicted push time over the dial — None when the
        table can't price this point (missing rows, no wire measure)."""
        best, best_t = None, None
        for d in range(0, max(0, int(max_dial)) + 1):
            t = self.predict_push_s(DIAL[d], size_bytes, wire_mbps)
            if t is None:
                continue
            if best_t is None or t < best_t:
                best, best_t = d, t
        return best


class _KeyTune:
    """One key's controller state."""

    __slots__ = ("dial", "classes", "blacklist_until", "pinned",
                 "baseline_ms", "eval_window", "prev_dial", "switches",
                 "declared_key", "off_dial_warned", "predicted")

    def __init__(self, dial: int, declared_key: Optional[int]):
        self.dial = dial                 # current DIAL index
        self.classes: deque = deque(maxlen=16)
        self.blacklist_until = -1        # window index; -1 = clear
        self.pinned = False              # unhealthy -> raw, frozen
        self.baseline_ms: Optional[float] = None   # per-push time
        self.eval_window = -1            # window whose summary judges the
        #                                  last switch (-1 = none pending)
        self.prev_dial = dial
        self.switches = 0
        self.declared_key = declared_key
        self.off_dial_warned = False
        self.predicted = False           # cold-start jump spent (one-shot)


class Tuner:
    """The control loop.  ``observe(summary)`` is chained onto the
    signal plane's ``on_window`` (after the doctor), so it runs once per
    closed window on the plane's thread — never on the hot path."""

    def __init__(self, session, propose: bool = True,
                 hold: int = DEFAULT_HOLD,
                 blacklist: int = DEFAULT_BLACKLIST,
                 margin_rounds: int = DEFAULT_MARGIN_ROUNDS,
                 regress_frac: float = DEFAULT_REGRESS_FRAC,
                 max_dial: int = len(DIAL) - 1,
                 cost_model: Optional[CostModel] = None):
        self._session = session
        self.propose = bool(propose)
        self.hold = max(1, int(hold))
        self.blacklist = max(1, int(blacklist))
        self.margin_rounds = max(1, int(margin_rounds))
        self.regress_frac = max(0.0, float(regress_frac))
        self.max_dial = min(len(DIAL) - 1, max(0, int(max_dial)))
        self._lock = threading.Lock()
        self._keys: Dict[str, _KeyTune] = {}
        self._window = -1
        self.switches_total = 0
        self.reverts_total = 0
        self.predict_jumps_total = 0
        self._proposals: List[dict] = []
        self._proposed_knobs: set = set()
        self._knob_last: Dict[str, int] = {}   # env name -> window actuated
        # Predictive seed: the wire_bench --codec-sweep table, when one
        # has been persisted.  Absent -> purely hysteretic (the pre-
        # cost-model behavior, byte-identical decisions).
        self._cost_model = (cost_model if cost_model is not None
                            else CostModel.load())
        from . import telemetry as _tm
        reg = _tm.get_registry()
        self._m_switches = reg.counter(
            "bps_tuner_switches_total",
            help="codec renegotiations the tuner initiated")
        self._reg = reg

    # -- the per-window pass ------------------------------------------------
    def observe(self, summary: dict) -> None:
        # Poll BEFORE taking the tuner lock: CMD_CODEC GETs are blocking
        # wire round trips (up to seconds against a slow server), and
        # holding the lock across them would stall get_tuner()/the
        # /tuner route — and, since observe runs on the signal plane's
        # on_window callback, the window rolls behind it.  The poll only
        # touches session state under the session's own locks.
        try:
            # Everyone polls: the proposer to catch races it lost,
            # observers to learn pending switches before their round
            # counters cross the boundary (CODEC_STALE remains the
            # correctness backstop either way).
            self._session.poll_codec()
        except Exception:
            get_logger().debug("tuner codec poll failed", exc_info=True)
        try:
            # Same law for the GLOBAL knob table: observers learn a
            # pending CMD_KNOB switch before their round crosses its
            # boundary (KNOB_STALE remains the correctness backstop).
            # Gated on knob_actuate so BYTEPS_TPU_KNOB_ACTUATE=0
            # restores the pre-knob-plane wire byte stream exactly —
            # advisory proposals read only the session-local mirror.
            from .config import get_config
            poll_knobs = getattr(self._session, "poll_knobs", None)
            if poll_knobs is not None and get_config().knob_actuate:
                poll_knobs()
        except Exception:
            get_logger().debug("tuner knob poll failed", exc_info=True)
        with self._lock:
            self._window = int(summary.get("window", self._window + 1))
            for label, rec in (summary.get("keys") or {}).items():
                if label == "_other" or not rec.get("pushes"):
                    continue
                try:
                    self._observe_key(label, rec)
                except Exception:
                    get_logger().exception("tuner pass failed for key %r",
                                           label)
            self._propose_knobs(summary)

    def _resolve_key(self, label: str) -> Optional[int]:
        try:
            from ..core.native import get_core
            dk = get_core().get_declared_key(label)
            if dk is not None and dk >= 0:
                return int(dk)
        except Exception:
            pass
        if label.startswith("key_"):
            try:
                return int(label[4:])
            except ValueError:
                return None
        return None

    def _state_for(self, label: str) -> Optional[_KeyTune]:
        kt = self._keys.get(label)
        if kt is None:
            dk = self._resolve_key(label)
            if dk is None:
                return None
            comp = self._session._compressors.get(dk)
            kt = self._keys[label] = _KeyTune(dial_of(comp) or 0, dk)
            if dial_of(comp) is None:
                kt.dial = -1          # off-dial user codec: hands off
        return kt

    def _per_push_ms(self, rec: dict) -> float:
        comps = rec.get("components") or {}
        pushes = max(1, int(rec.get("pushes", 1)))
        return sum(comps.values()) / pushes * 1e3

    def _observe_key(self, label: str, rec: dict) -> None:
        kt = self._state_for(label)
        if kt is None:
            return
        cls = rec.get("class", "")
        kt.classes.append(cls)
        if kt.dial < 0:
            if not kt.off_dial_warned:
                kt.off_dial_warned = True
                get_logger().info(
                    "tuner: key %s carries a user-configured off-dial "
                    "codec; leaving it alone", label)
            return
        # A switch the fleet has not finished applying (the session still
        # carries a pending CMD_CODEC entry for this key) must neither be
        # judged nor re-proposed: on slow-stepping jobs the effective
        # round can lie windows away, and re-proposing would stage an
        # ever-later boundary that never gets crossed (a livelock that
        # also inflates the thrash counters).
        pending = bool(getattr(self._session, "_codec_next",
                               {}).get(kt.declared_key))
        # Keep the mirror honest on non-proposing workers (and after
        # CODEC_STALE adoptions): the session's actual compressor wins —
        # but never while a pending switch is still in flight, where
        # "actual" is by construction the OLD codec.
        actual = dial_of(self._session._compressors.get(kt.declared_key))
        if actual is not None and actual != kt.dial \
                and kt.eval_window < 0 and not pending:
            kt.dial = actual
        per_push = self._per_push_ms(rec)
        # Post-switch evaluation: the first full window AFTER the switch
        # actually applied judges it — a regression reverts and
        # blacklists, success re-baselines.
        if kt.eval_window >= 0 and self._window > kt.eval_window:
            if pending:
                kt.eval_window = self._window   # not applied yet: wait
            else:
                kt.eval_window = -1
                if (kt.baseline_ms is not None and self.regress_frac > 0
                        and per_push > kt.baseline_ms
                        * (1.0 + self.regress_frac)):
                    self.reverts_total += 1
                    kt.blacklist_until = self._window + self.blacklist
                    get_logger().warning(
                        "tuner: switch of key %s to %s regressed "
                        "per-push time %.2fms -> %.2fms; reverting to "
                        "%s and blacklisting for %d windows", label,
                        DIAL[kt.dial], kt.baseline_ms, per_push,
                        DIAL[kt.prev_dial], self.blacklist)
                    self._switch(label, kt, kt.prev_dial, "revert")
                    return
                kt.baseline_ms = per_push
        if kt.baseline_ms is None:
            kt.baseline_ms = per_push
        # Value-domain damage trumps bandwidth: pin unhealthy keys raw
        # and back off; unpin only after a full healthy hold.
        if cls == "unhealthy":
            if kt.dial != 0:
                get_logger().warning(
                    "tuner: key %s is unhealthy; pinning raw", label)
                self._switch(label, kt, 0, "unhealthy")
            kt.pinned = True
            kt.blacklist_until = max(kt.blacklist_until,
                                     self._window + self.blacklist)
            return
        if kt.pinned:
            healthy = list(kt.classes)[-self.hold:]
            if len(healthy) >= self.hold and all(
                    c != "unhealthy" for c in healthy):
                kt.pinned = False
            else:
                return
        if not self.propose or pending \
                or self._window <= kt.blacklist_until \
                or kt.eval_window >= 0:
            return
        # Predictive cold start: with a cost model present, this key's
        # FIRST observed window prices every dial position — benched
        # enc/dec throughput + (payload/ratio) over the key's measured
        # wire MB/s — and jumps straight to the predicted minimum
        # instead of stepping one notch per hold period.  One-shot per
        # key; the jump is judged next window like any switch (the
        # hysteretic revert/blacklist loop is the safety net), and the
        # ambient loop keeps adapting from wherever the jump landed.
        if (self._cost_model is not None and not kt.predicted
                and cls not in ("unhealthy", "straggler_bound")):
            kt.predicted = True
            size = int(rec.get("push_bytes", 0)
                       / max(1, int(rec.get("pushes", 1))))
            best = self._cost_model.best_dial(
                size, float(rec.get("wire_mbps", 0.0)), self.max_dial)
            if best is not None and best != kt.dial:
                self.predict_jumps_total += 1
                kt.baseline_ms = per_push
                get_logger().info(
                    "tuner: cost model predicts %s for key %s "
                    "(%d B payload @ %.1f wire MB/s) — jumping from %s",
                    DIAL[best], label, size, rec.get("wire_mbps", 0.0),
                    DIAL[kt.dial])
                self._switch(label, kt, best, "predict")
                return
        # Hysteresis: the class must have held for `hold` windows.
        recent = list(kt.classes)[-self.hold:]
        if len(recent) < self.hold or len(set(recent)) != 1:
            return
        target = kt.dial
        if cls == "wire_bound":
            target = min(kt.dial + 1, self.max_dial)
        elif cls in ("compute_bound", "tiny"):
            target = max(kt.dial - 1, 0)
        if target != kt.dial:
            kt.baseline_ms = self._per_push_ms(rec)
            self._switch(label, kt, target, cls)

    def _switch(self, label: str, kt: _KeyTune, target: int,
                why: str) -> None:
        if not self.propose or kt.declared_key is None:
            kt.dial = target
            return
        try:
            res = self._session.propose_codec(
                kt.declared_key, DIAL_KWARGS[DIAL[target]],
                margin_rounds=self.margin_rounds)
        except Exception as e:
            get_logger().warning("tuner: codec proposal for %s failed: %s",
                                 label, e)
            kt.blacklist_until = self._window + 2   # retry later, no spin
            return
        kt.prev_dial, kt.dial = kt.dial, target
        kt.switches += 1
        self.switches_total += 1
        kt.classes.clear()              # fresh hysteresis for the new codec
        if why in ("revert", "unhealthy"):
            # A revert (or a safety pin) is terminal, not an experiment:
            # judging IT against the pre-switch baseline could flip the
            # key right back onto the codec that just regressed — the
            # oscillation the blacklist exists to prevent.  Re-baseline
            # from the next ambient window instead.
            kt.eval_window = -1
            kt.baseline_ms = None
        else:
            # A forward switch lands mid-window; judge it on the FIRST
            # FULL window after it has applied.
            kt.eval_window = self._window + 1
        self._m_switches.inc()
        self._reg.counter(
            "bps_tuner_key_switches_total", labels={"key": label},
            help="tuner codec switches per key (the thrash signal)").inc()
        get_logger().info(
            "tuner: key %s %s -> %s (%s; effective round %s, %s)",
            label, DIAL[kt.prev_dial], DIAL[target], why,
            res.get("effective_round"),
            "accepted" if res.get("accepted") else "superseded")

    # -- knob proposals (actuated via CMD_KNOB where safe) ------------------

    # env name -> knob-plane name for the three knobs CMD_KNOB actuates.
    # BYTEPS_PARTITION_BYTES is deliberately absent: partition size
    # changes the pkey space itself, which no boundary handshake can
    # re-map mid-job — it stays advisory.
    _ACTUATED = {"BYTEPS_TPU_FUSION_BYTES": "fusion_bytes",
                 "BYTEPS_TPU_COMPRESS_THREADS": "compress_threads",
                 "BYTEPS_TPU_WIRE_CONNS": "wire_conns"}
    # Windows between actuated sets of the same knob — the knob-plane
    # hysteresis (the doctor's knob_thrash rule fires at >2 switches in
    # 6 windows; the cooldown keeps a healthy loop well under it).
    KNOB_COOLDOWN = 8

    def _propose_knobs(self, summary: dict) -> None:
        keys = summary.get("keys") or {}
        if not keys:
            return
        from .config import get_config
        cfg = get_config()
        counts: Dict[str, int] = {}
        for rec in keys.values():
            counts[rec.get("class", "?")] = counts.get(
                rec.get("class", "?"), 0) + 1
        total = sum(counts.values())
        # Live knob values win over launch config once a switch landed —
        # doubling from the LAUNCH value after an actuation would propose
        # a stale target forever.
        live: Dict[str, int] = {}
        can_actuate = (self.propose and cfg.knob_actuate
                       and hasattr(self._session, "propose_knobs"))
        if hasattr(self._session, "knob_table"):
            try:
                live = self._session.knob_table().get("live", {}) or {}
            except Exception:
                live = {}
        cur_fb = int(live.get("fusion_bytes", cfg.fusion_bytes))
        cur_ct = int(live.get("compress_threads", cfg.compress_threads))
        cur_wc = int(live.get("wire_conns", cfg.wire_conns))

        def propose(knob: str, current, suggested, reason: str) -> None:
            plane_name = self._ACTUATED.get(knob)
            actuate = can_actuate and plane_name is not None
            if actuate:
                last = self._knob_last.get(knob)
                if (last is not None
                        and self._window - last < self.KNOB_COOLDOWN):
                    return
                if int(suggested) == int(current):
                    return
            elif knob in self._proposed_knobs:
                return
            row = {"knob": knob, "current": current,
                   "proposed": suggested, "reason": reason,
                   "applied": False, "window": self._window}
            if actuate:
                # Graduated from advisory: ride the knob plane — an
                # epoch-versioned CMD_KNOB set, applied at a round
                # boundary on every participant atomically.
                self._knob_last[knob] = self._window
                try:
                    res = self._session.propose_knobs(
                        {plane_name: int(suggested)},
                        margin_rounds=cfg.knob_margin_rounds)
                except Exception as e:
                    get_logger().warning(
                        "tuner: knob actuation %s=%s failed: %s",
                        knob, suggested, e)
                    row["error"] = str(e)
                else:
                    row["applied"] = bool(res.get("accepted"))
                    row["epoch"] = res.get("epoch")
                    row["effective_round"] = res.get("effective_round")
                    get_logger().info(
                        "tuner knob actuation: %s=%s (was %s) at round "
                        ">= %s: %s", knob, suggested, current,
                        res.get("effective_round"), reason)
            else:
                self._proposed_knobs.add(knob)
                get_logger().info(
                    "tuner proposal (advisory, NOT auto-applied — "
                    "restart with it): %s=%s (now %s): %s", knob,
                    suggested, current, reason)
            self._proposals.append(row)

        if counts.get("tiny", 0) > total / 2 and cur_fb > 0:
            propose("BYTEPS_TPU_FUSION_BYTES", cur_fb, cur_fb * 2,
                    f"{counts['tiny']}/{total} keys are tiny (<64KiB "
                    f"mean payload): per-message overhead dominates — "
                    f"bigger fusion buckets amortize it")
        if counts.get("compute_bound", 0) > total / 2:
            propose("BYTEPS_TPU_COMPRESS_THREADS", cur_ct,
                    max(4, cur_ct * 2),
                    f"{counts['compute_bound']}/{total} keys are "
                    f"compute-bound: codec work dominates their round "
                    f"time — widen the codec pool")
        if counts.get("wire_bound", 0) > total / 2:
            at_max = all(
                kt.dial >= self.max_dial for kt in self._keys.values()
                if kt.dial >= 0)
            if at_max and self._keys:
                propose("BYTEPS_TPU_WIRE_CONNS", cur_wc, cur_wc * 2,
                        f"{counts['wire_bound']}/{total} keys stay "
                        f"wire-bound at the hardest codec: more data "
                        f"lanes per server is the next dial")
                propose("BYTEPS_PARTITION_BYTES", cfg.partition_bytes,
                        max(1 << 20, cfg.partition_bytes // 2),
                        "wire-bound at the hardest codec: smaller "
                        "partitions overlap push/pull legs more finely")

    # -- read surface -------------------------------------------------------
    def state(self) -> dict:
        """The ``bps.get_tuner()`` payload."""
        with self._lock:
            keys = {}
            for label, kt in self._keys.items():
                keys[label] = {
                    "codec": DIAL[kt.dial] if kt.dial >= 0 else "user",
                    "dial": kt.dial,
                    "class_history": list(kt.classes),
                    "pinned": kt.pinned,
                    "blacklisted_until": kt.blacklist_until,
                    "baseline_per_push_ms": kt.baseline_ms,
                    "switches": kt.switches,
                }
            knob_table = None
            if hasattr(self._session, "knob_table"):
                try:
                    knob_table = self._session.knob_table()
                except Exception:
                    knob_table = None
            return {
                "armed": True,
                "proposer": self.propose,
                "window": self._window,
                "dial": list(DIAL),
                "switches_total": self.switches_total,
                "reverts_total": self.reverts_total,
                "predict_jumps_total": self.predict_jumps_total,
                "cost_model": ({"path": self._cost_model.path,
                                "rows": len(self._cost_model)}
                               if self._cost_model is not None else None),
                "knob_table": knob_table,
                "keys": keys,
                "knob_proposals": [dict(p) for p in self._proposals],
            }
