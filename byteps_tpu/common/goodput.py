"""Goodput ledger: partition fleet wall-time EXACTLY into categories.

Per fleet-window (the aligned view ``doctor.fleet_windows_from_view``
produces), every worker's published wall-time is split into six
categories that sum EXACTLY to the total — no "other" bucket, no
unaccounted residue (asserted, like trace_analysis's critical-path
decomposition):

  compute          what remains after everything below is claimed —
                   time the worker was doing useful local work
  wire             push/pull serialization + socket time: the queue,
                   push_wire, encode and decode component seconds
  straggler_wait   server-side serve time — where waiting for the
                   slowest worker's round materializes under the
                   synchronous push_pull contract
  stall            barrier timeouts / stall watchdog events
  recovery         reconnects, replays, audit round losses
  disruption       deliberate control-plane churn: ring/membership
                   epochs, codec/knob switches, autoscale drains

``goodput_pct`` is compute's share of the total.  Category seconds come
from two sources of different fidelity: component seconds are MEASURED
(the KeySignal decomposition), event categories are ESTIMATED (each
event claims a fixed slice of the residual, scaled down when
oversubscribed) — the ledger is exact by construction either way, the
split between estimated categories is the approximate part.

Armed via the same plane as everything fleet (``BYTEPS_TPU_FLEET``);
exports ``bps_fleet_goodput_pct`` plus per-category gauges, and feeds
the ``BENCH_FLEET=1`` headline numbers in bench.py.
"""

from typing import Dict, List, Optional

from .telemetry import MetricsRegistry, get_registry

# The exact partition, in claim order.  compute is always LAST: it is
# the remainder, never claimed directly.
CATEGORIES = ("compute", "wire", "straggler_wait", "stall",
              "recovery", "disruption")

# Event-kind → category.  Matching is by exact kind, then by prefix
# before the first "_" (so future barrier_* kinds stay stalls without
# a table edit).
_EVENT_CATEGORY = {
    "barrier_timeout": "stall",
    "barrier_wait": "stall",
    "stall": "stall",
    "watchdog": "stall",
    "reconnected": "recovery",
    "conn_drop": "recovery",
    "conn_gave_up": "recovery",
    "replay": "recovery",
    "audit_lost_round": "recovery",
    "promote": "recovery",
    "ring_epoch": "disruption",
    "membership_epoch": "disruption",
    "knob_switch": "disruption",
    "codec_switch": "disruption",
    "evicted": "disruption",
    "autoscale": "disruption",
    "drain": "disruption",
}
_PREFIX_CATEGORY = {"barrier": "stall", "conn": "recovery",
                    "audit": "recovery"}

# Each event claims this many seconds of the window's residual time.
# A deliberate coarse estimate — when events oversubscribe the residual
# their claims scale down proportionally, so the partition stays exact.
EVENT_CLAIM_S = 1.0

# Σ|categories| == total must hold to this RELATIVE tolerance; beyond
# it the ledger raises — an inexact partition is a bug, not a rounding
# footnote.
_REL_TOL = 1e-6


def event_category(kind: str) -> Optional[str]:
    """Category an event kind bills to, or None (uncategorized events
    cost nothing — they are informational, e.g. init/shutdown)."""
    cat = _EVENT_CATEGORY.get(kind)
    if cat:
        return cat
    return _PREFIX_CATEGORY.get(kind.split("_", 1)[0])


def worker_ledger(doc: dict) -> Dict[str, float]:
    """Partition ONE worker's published window (a fleet publish doc)
    into category seconds summing exactly to its wall time (dur_s).

    Measured component seconds claim first (scaled down proportionally
    if they exceed wall — components can overlap in time); event
    claims split what remains; compute is the exact remainder.  With
    the devprof plane armed the doc carries a measured
    ``device_compute`` component (block_until_ready device seconds):
    it claims alongside wire/wait and lands IN the compute bucket, so
    ``compute`` becomes measured-device-seconds + unexplained remainder
    instead of pure inference.  Docs without it (devprof off, pre-PR-20
    workers) partition exactly as before — device_compute=0 is
    arithmetically the old ledger."""
    wall = max(0.0, float(doc.get("dur_s") or 0.0))
    comps = doc.get("components") or {}
    wire = sum(float(comps.get(c) or 0.0)
               for c in ("queue", "push_wire", "encode", "decode"))
    wait = float(comps.get("serve") or 0.0)
    dev = max(0.0, float(comps.get("device_compute") or 0.0))
    wire, wait = max(0.0, wire), max(0.0, wait)
    measured = wire + wait + dev
    if measured > wall and measured > 0.0:
        scale = wall / measured
        wire *= scale
        wait *= scale
        dev *= scale
    residual = wall - wire - wait - dev
    claims = {"stall": 0.0, "recovery": 0.0, "disruption": 0.0}
    for kind, n in (doc.get("events") or {}).items():
        cat = event_category(str(kind))
        if cat in claims:
            claims[cat] += max(0, int(n)) * EVENT_CLAIM_S
    claimed = sum(claims.values())
    if claimed > residual and claimed > 0.0:
        scale = residual / claimed
        claims = {c: v * scale for c, v in claims.items()}
        claimed = residual
    ledger = {"compute": dev + (residual - claimed), "wire": wire,
              "straggler_wait": wait, **claims}
    total = sum(ledger.values())
    if abs(total - wall) > _REL_TOL * max(1.0, wall):
        raise AssertionError(
            f"goodput ledger is not an exact partition: "
            f"sum={total!r} wall={wall!r} doc window="
            f"{doc.get('window')!r} worker={doc.get('worker')!r}")
    return ledger


def fleet_ledger(fleet_window: dict) -> dict:
    """Sum every worker's ledger for one aligned fleet window.

    Returns {"window", "n_workers", "total_s", "seconds": {cat: s},
    "pct": {cat: share}, "goodput_pct"}; the exact-partition law holds
    for the sum too (asserted)."""
    seconds = {c: 0.0 for c in CATEGORIES}
    workers = fleet_window.get("workers") or {}
    for doc in workers.values():
        for c, v in worker_ledger(doc).items():
            seconds[c] += v
    total = sum(seconds.values())
    wall = sum(max(0.0, float(d.get("dur_s") or 0.0))
               for d in workers.values())
    if abs(total - wall) > _REL_TOL * max(1.0, wall):
        raise AssertionError(
            f"fleet ledger is not an exact partition: "
            f"sum={total!r} wall={wall!r} window="
            f"{fleet_window.get('window')!r}")
    pct = {c: (100.0 * v / total if total > 0.0 else 0.0)
           for c, v in seconds.items()}
    return {"window": fleet_window.get("window"),
            "n_workers": len(workers),
            "total_s": total,
            "seconds": seconds,
            "pct": pct,
            "goodput_pct": pct["compute"]}


def update_goodput(ledger: dict,
                   registry: Optional[MetricsRegistry] = None) -> None:
    """Export one fleet ledger to the registry:
    ``bps_fleet_goodput_pct`` plus
    ``bps_fleet_time_pct{category=}`` per category.  Callers only
    invoke this when the fleet plane is armed, so there is no gauge
    when BYTEPS_TPU_FLEET is off (the quiet-when-unarmed law)."""
    reg = registry or get_registry()
    reg.gauge("bps_fleet_goodput_pct",
              help="share of fleet wall-time spent computing "
                   "(goodput ledger, per fleet window)").set(
                  float(ledger.get("goodput_pct") or 0.0))
    for cat in CATEGORIES:
        reg.gauge("bps_fleet_time_pct",
                  help="fleet wall-time share per goodput category "
                       "(categories sum exactly to 100)",
                  labels={"category": cat}).set(
                      float((ledger.get("pct") or {}).get(cat, 0.0)))
