"""Fusion-bucket layer between the gradient tree and the wire.

BytePS's priority scheduler only pays off when gradients hit the wire as
backprop produces them, but per-key declare/push/ack overhead dominates
once payloads shrink (hundreds of layernorm scales and biases per
transformer), while the all-or-nothing whole-tree flatten fuses
EVERYTHING into one f32 vector that can't overlap with backprop at all
and upcasts every leaf.  This module is the middle ground (reference
analog: the reference's tensor partitioning, operations.cc:140-180,
generalised to many-small-tensors *packing*; DDP gradient bucketing,
torch/parallel/distributed.py:235-243):

  - leaves below ``BYTEPS_TPU_FUSION_BYTES`` are packed into
    dtype-homogeneous, size-capped buckets assigned in **reverse
    backprop order** (the tail of the flattened tree — produced first by
    the backward pass — fills bucket 0);
  - each bucket rides ONE wire key and inherits the max priority of its
    members, so the priority-scheduled dispatcher (client.py) sends
    last-layer buckets first while earlier layers are still being
    produced — the overlap the ScheduledQueues exist for;
  - leaves at/above the threshold keep their own key and their own
    backprop-position priority (per-leaf overlap is already optimal for
    them);
  - bucket *names* are a pure function of the member composition, so the
    same tree maps to the same declared keys on every worker, on every
    call, and across the elastic re-declare/restart path
    (common/api.py resume()).

The same segment-packing algorithm also drives the in-graph collective
plane (``ops.collectives.BucketPlan`` routes through
:func:`plan_segments`), so bucket composition logic lives in exactly one
place.  ``BYTEPS_TPU_FUSION_BYTES=0`` disables fusion everywhere it is
consulted, restoring per-leaf / whole-tree behavior byte-for-byte.
"""

from __future__ import annotations

import functools
import hashlib
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

# ---------------------------------------------------------------------------
# Counters (the get_codec_stats() analog for the fusion layer).
# ---------------------------------------------------------------------------
ZERO_STATS: Dict[str, int] = {
    "plans_used": 0,            # fusion plans applied to a dispatch
    "buckets_built": 0,         # fused buckets dispatched
    "leaves_fused": 0,          # leaves that rode a fused bucket
    "leaves_solo": 0,           # leaves >= threshold (own key, own priority)
    "fused_bytes": 0,           # payload bytes that rode fused buckets
    "solo_bytes": 0,            # payload bytes that rode solo keys
    "wire_messages_saved": 0,   # per-leaf chains avoided: fused - buckets
    "full_flushes": 0,          # streaming buckets closed by the size cap
    "deadline_flushes": 0,      # streaming buckets closed by FLUSH_MS
    "drain_flushes": 0,         # streaming buckets closed by flush()/close()
    "ingraph_plans": 0,         # collective-plane BucketPlans built
    "ingraph_buckets": 0,       # buckets in those plans
    "row_batch_plans": 0,       # sparse row-pull batching plans built
    "row_batches": 0,           # batched row-pull wire units in them
}

_stats = dict(ZERO_STATS)
_stats_lock = threading.Lock()


def _bump(**kw) -> None:
    with _stats_lock:
        for k, v in kw.items():
            _stats[k] += v


def get_stats() -> Dict[str, int]:
    with _stats_lock:
        return dict(_stats)


def reset_stats() -> None:
    with _stats_lock:
        for k in _stats:
            _stats[k] = 0


# ---------------------------------------------------------------------------
# The planner.
# ---------------------------------------------------------------------------
class Bucket:
    """One fused dispatch unit: a dtype-homogeneous run of small leaves.

    ``members`` is ``((leaf_idx, num_elems), ...)`` in pack order (reverse
    backprop order: the member produced last in the forward pass — first
    in backward — is packed first).  ``priority`` is the max member
    priority, i.e. the backprop position of that first member.
    """

    __slots__ = ("index", "dtype", "members", "num_elems", "nbytes",
                 "priority", "sig")

    def __init__(self, index: int, dtype: str,
                 members: Tuple[Tuple[int, int], ...], itemsize: int):
        self.index = index
        self.dtype = dtype
        self.members = members
        self.num_elems = sum(n for _, n in members)
        self.nbytes = self.num_elems * itemsize
        self.priority = max(li for li, _ in members)
        self.sig = hashlib.md5(
            "|".join(f"{li}:{n}" for li, n in members).encode()
        ).hexdigest()[:8]

    @property
    def tag(self) -> str:
        """Deterministic wire-name suffix: a pure function of the member
        composition, so the bucket maps to the same declared key on every
        worker/call and across the re-declare/restart path."""
        return f"fb{self.index}.{self.dtype}x{self.num_elems}.{self.sig}"


class FusionPlan:
    """Static fused-dispatch plan for one leaf signature.

    ``buckets`` are ordered by descending priority (the order they should
    hit the wire); ``solo`` is ``((leaf_idx, priority), ...)`` for leaves
    at/above the threshold, which keep their own key.
    """

    def __init__(self, buckets: Tuple[Bucket, ...],
                 solo: Tuple[Tuple[int, int], ...], fusion_bytes: int,
                 solo_bytes: int):
        self.buckets = buckets
        self.solo = solo
        self.fusion_bytes = fusion_bytes
        self.fused_bytes = sum(b.nbytes for b in buckets)
        self.solo_bytes = solo_bytes
        self.leaves_fused = sum(len(b.members) for b in buckets)

    def record_use(self) -> None:
        """Count one application of this plan (plans are cached; stats
        track dispatches, not cache builds)."""
        _bump(plans_used=1,
              buckets_built=len(self.buckets),
              leaves_fused=self.leaves_fused,
              leaves_solo=len(self.solo),
              fused_bytes=self.fused_bytes,
              solo_bytes=self.solo_bytes,
              wire_messages_saved=max(
                  0, self.leaves_fused - len(self.buckets)))


@functools.lru_cache(maxsize=256)
def plan_buckets(items: Tuple[Tuple[int, int, str, int], ...],
                 fusion_bytes: int,
                 cap_bytes: Optional[int] = None) -> FusionPlan:
    """Build (or fetch the cached) fusion plan for a leaf signature.

    ``items``: ``((leaf_idx, num_elems, dtype_str, itemsize), ...)`` for
    the fusable leaves, in FORWARD (declaration) order; ``leaf_idx`` is
    the leaf's global backprop position and doubles as its priority (the
    last leaf — first gradient out of backward — has the max priority).

    Leaves with ``nbytes >= fusion_bytes`` go solo.  The rest pack into
    per-dtype buckets capped at ``cap_bytes`` (default ``fusion_bytes``),
    scanning in REVERSE order so bucket 0 holds the latest leaves and
    carries the highest priority — buckets then dispatch in
    priority-descending order, preserving backprop overlap.
    """
    cap = cap_bytes or fusion_bytes
    solo: List[Tuple[int, int]] = []
    solo_bytes = 0
    open_members: Dict[str, List[Tuple[int, int]]] = {}
    open_bytes: Dict[str, int] = {}
    open_itemsize: Dict[str, int] = {}
    buckets: List[Bucket] = []

    def close(dtype: str) -> None:
        buckets.append(Bucket(len(buckets), dtype,
                              tuple(open_members.pop(dtype)),
                              open_itemsize[dtype]))
        open_bytes.pop(dtype)

    for li, n, dtype, itemsize in reversed(items):
        nbytes = n * itemsize
        if fusion_bytes <= 0 or nbytes >= fusion_bytes:
            solo.append((li, li))
            solo_bytes += nbytes
            continue
        if dtype in open_members and open_bytes[dtype] + nbytes > cap:
            close(dtype)
        open_members.setdefault(dtype, []).append((li, n))
        open_bytes[dtype] = open_bytes.get(dtype, 0) + nbytes
        open_itemsize[dtype] = itemsize
    # Flush remainder buckets in the deterministic order they were opened
    # (sorted by the max member priority, which is descending already for
    # a single dtype; across dtypes, sort to keep the contract explicit).
    for dtype in sorted(open_members,
                        key=lambda d: -max(li for li, _ in open_members[d])):
        close(dtype)
    buckets.sort(key=lambda b: -b.priority)
    for i, b in enumerate(buckets):
        # Re-index after the sort so bucket indices follow dispatch order;
        # composition (members/sig) is untouched, so names stay stable.
        b.index = i
    solo.sort(key=lambda s: -s[1])
    return FusionPlan(tuple(buckets), tuple(solo), fusion_bytes, solo_bytes)


def plan_segments(sizes: Sequence[int], capacity_elems: int,
                  reverse: bool = True) -> List[List[Tuple[int, int, int]]]:
    """Segment-packing used by the in-graph collective plane: split/pack
    leaves into buckets of ``capacity_elems``, spilling large leaves
    across buckets.  Each bucket is ``[(leaf_idx, start, length), ...]``.

    This is the whole-tree packing the XLA plane wants (slicing is free
    in-graph, and the psum dtype is uniform there); the wire plane uses
    :func:`plan_buckets`, which never splits a leaf — a solo leaf rides
    the session's own partitioner instead.
    """
    order = list(range(len(sizes)))
    if reverse:
        order.reverse()
    buckets: List[List[Tuple[int, int, int]]] = []
    cur: List[Tuple[int, int, int]] = []
    cur_n = 0
    for li in order:
        remaining = sizes[li]
        start = 0
        while remaining > 0:
            take = min(remaining, capacity_elems - cur_n)
            cur.append((li, start, take))
            start += take
            remaining -= take
            cur_n += take
            if cur_n >= capacity_elems:
                buckets.append(cur)
                cur, cur_n = [], 0
    if cur:
        buckets.append(cur)
    _bump(ingraph_plans=1, ingraph_buckets=len(buckets))
    return buckets


def plan_row_batches(nrows: int, row_width: int, max_bytes: int,
                     overhead_bytes: int = 32) -> List[Tuple[int, int]]:
    """Batching plan for row-sparse embedding pulls: coalesce ``nrows``
    row lookups (each ``row_width`` f32 elements on the response leg)
    into the fewest wire units whose response payload stays under
    ``max_bytes`` — many small per-row round trips become one batched
    request per slot (docs/sparse-embedding.md).  Returns half-open
    ``(start, stop)`` slices over the caller's sorted index array.

    ``overhead_bytes`` covers the sparse header + param_version trailer;
    the index stream itself is elias-coded and strictly smaller than the
    row payload, so the row leg is the binding term.  A single row wider
    than the cap still ships alone — a lookup can never be split.
    """
    if nrows <= 0:
        return []
    row_bytes = max(1, int(row_width) * 4)
    per_batch = max(1, (max(1, int(max_bytes)) - overhead_bytes)
                    // row_bytes)
    batches = [(start, min(nrows, start + per_batch))
               for start in range(0, nrows, per_batch)]
    _bump(row_batch_plans=1, row_batches=len(batches))
    return batches


# ---------------------------------------------------------------------------
# Streaming face: incremental producers (backward hooks, callback-driven
# plugins) that see one gradient at a time.
# ---------------------------------------------------------------------------
class FusionBuffer:
    """Streaming fusion accumulator with a deadline flush.

    Incremental gradient producers (the torch/tf eager plugins' backward
    hooks) can't hand the planner a whole tree; they ``add()`` leaves as
    backprop emits them.  Small leaves accumulate into per-dtype open
    buckets that flush when full (``fusion_bytes``) — and, crucially,
    after ``flush_ms`` milliseconds even when NOT full, so a straggler
    tail (the front layers' last few biases) never sits in a half-empty
    bucket waiting for members that aren't coming
    (``BYTEPS_TPU_FUSION_FLUSH_MS``).

    ``dispatch(packed, members, priority)`` receives the concatenated
    flat numpy payload, ``[(name, shape, num_elems), ...]`` scatter
    metadata, and the bucket priority (max member priority).  Leaves at/
    above the threshold dispatch immediately on their own.
    """

    def __init__(self, dispatch: Callable[[Any, list, int], None],
                 fusion_bytes: Optional[int] = None,
                 flush_ms: Optional[float] = None):
        import numpy as np
        from .config import get_config
        cfg = get_config()
        self._np = np
        self.dispatch = dispatch
        self.fusion_bytes = (cfg.fusion_bytes if fusion_bytes is None
                             else int(fusion_bytes))
        self.flush_ms = (cfg.fusion_flush_ms if flush_ms is None
                         else float(flush_ms))
        # dtype -> [(name, flat, orig_shape, priority)]
        self._open: Dict[str, list] = {}
        self._open_bytes: Dict[str, int] = {}
        self._opened_at: Dict[str, float] = {}
        self._cv = threading.Condition()
        self._closed = False
        self._flusher = None
        if self.flush_ms > 0 and self.fusion_bytes > 0:
            self._flusher = threading.Thread(
                target=self._deadline_loop, daemon=True,
                name="bps-fusion-flush")
            self._flusher.start()

    def add(self, name: str, array, priority: int = 0) -> None:
        np = self._np
        arr = np.asarray(array)
        flat = arr.ravel()
        if self.fusion_bytes <= 0 or flat.nbytes >= self.fusion_bytes:
            _bump(leaves_solo=1, solo_bytes=int(flat.nbytes))
            self.dispatch(flat, [(name, arr.shape, flat.size)], priority)
            return
        dtype = str(flat.dtype)
        flushed = None
        with self._cv:
            if self._closed:
                raise RuntimeError("FusionBuffer is closed")
            if (dtype in self._open
                    and self._open_bytes[dtype] + flat.nbytes
                    > self.fusion_bytes):
                flushed = self._take_locked(dtype, "full_flushes")
            if dtype not in self._open:
                self._open[dtype] = []
                self._open_bytes[dtype] = 0
                self._opened_at[dtype] = time.monotonic()
                self._cv.notify_all()     # wake the deadline flusher
            self._open[dtype].append((name, flat, arr.shape, priority))
            self._open_bytes[dtype] += flat.nbytes
        if flushed is not None:
            self.dispatch(*flushed)

    def _take_locked(self, dtype: str, counter: str) -> tuple:
        """Pop one open bucket and build its dispatch payload.  Caller
        MUST invoke self.dispatch(*result) AFTER releasing the lock — a
        dispatch callback can block on the wire (or the sequential-use
        guard) for seconds, and holding _cv through that would stall
        every concurrent add() and the deadline flusher."""
        members = self._open.pop(dtype)
        self._open_bytes.pop(dtype)
        self._opened_at.pop(dtype)
        np = self._np
        flats = [f for _, f, _, _ in members]
        packed = np.concatenate(flats) if len(flats) > 1 else flats[0]
        meta = [(nm, shape, f.size) for nm, f, shape, _ in members]
        prio = max(p for _, _, _, p in members)
        _bump(buckets_built=1, leaves_fused=len(members),
              fused_bytes=int(packed.nbytes),
              wire_messages_saved=len(members) - 1, **{counter: 1})
        return packed, meta, prio

    def flush(self) -> None:
        """Flush every open bucket now (end of the backward pass)."""
        with self._cv:
            flushed = [self._take_locked(d, "drain_flushes")
                       for d in list(self._open)]
        for f in flushed:
            self.dispatch(*f)

    def _deadline_loop(self) -> None:
        while True:
            flushed = []
            with self._cv:
                while not self._closed and not self._opened_at:
                    self._cv.wait()
                if self._closed:
                    return
                now = time.monotonic()
                deadline = min(self._opened_at.values()) \
                    + self.flush_ms / 1e3
                if now < deadline:
                    self._cv.wait(timeout=deadline - now)
                    continue
                for dtype in [d for d, t in list(self._opened_at.items())
                              if now >= t + self.flush_ms / 1e3]:
                    flushed.append(
                        self._take_locked(dtype, "deadline_flushes"))
            for f in flushed:
                self.dispatch(*f)

    def close(self) -> None:
        with self._cv:
            if self._closed:
                flushed = []
            else:
                flushed = [self._take_locked(d, "drain_flushes")
                           for d in list(self._open)]
                self._closed = True
                self._cv.notify_all()
        for f in flushed:
            self.dispatch(*f)
        if self._flusher is not None:
            self._flusher.join(timeout=5)
