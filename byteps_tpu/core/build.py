"""Build the native host core (`libbyteps_core.so`).

The reference builds its C++ core through setup.py extensions
(reference: setup.py:249-337).  Here the core is framework-independent host
logic, so a plain g++ shared-object build is enough; it is (re)built lazily on
first import when the sources are newer than the binary.
"""

from __future__ import annotations

import os
import subprocess
import sys

_CORE_DIR = os.path.dirname(os.path.abspath(__file__))
_SOURCES = ["core.cc", "server.cc"]
_LIB_NAME = "libbyteps_core.so"
_LIB_NAME_TSAN = "libbyteps_core_tsan.so"


def _tsan() -> bool:
    """BYTEPS_TPU_TSAN=1 builds/loads a ThreadSanitizer variant — the race
    coverage for the host scheduler/server the reference never had
    (SURVEY §5: 'CI does not run sanitizers')."""
    return os.environ.get("BYTEPS_TPU_TSAN", "0") == "1"


def lib_path() -> str:
    return os.path.join(_CORE_DIR, _LIB_NAME_TSAN if _tsan() else _LIB_NAME)


def _needs_build() -> bool:
    lib = lib_path()
    if not os.path.exists(lib):
        return True
    lib_mtime = os.path.getmtime(lib)
    for src in _SOURCES:
        p = os.path.join(_CORE_DIR, src)
        if os.path.exists(p) and os.path.getmtime(p) > lib_mtime:
            return True
    return False


def build(force: bool = False, verbose: bool = False) -> str:
    """Compile the native core if needed; returns the .so path.

    Raises CalledProcessError on compile failure (callers fall back to the
    pure-Python implementation in that case).
    """
    if not force and not _needs_build():
        return lib_path()
    srcs = [os.path.join(_CORE_DIR, s) for s in _SOURCES
            if os.path.exists(os.path.join(_CORE_DIR, s))]
    cmd = [
        "g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
        "-fvisibility=hidden", "-o", lib_path(), *srcs,
    ]
    if _tsan():
        cmd.insert(1, "-fsanitize=thread")
        cmd.insert(1, "-g")
    if verbose:
        print(" ".join(cmd), file=sys.stderr)
    subprocess.run(cmd, check=True, capture_output=not verbose)
    return lib_path()


if __name__ == "__main__":
    build(force="--force" in sys.argv, verbose=True)
    print(lib_path())


_EXE_NAME = "bps_ps_server"
_EXE_NAME_TSAN = "bps_ps_server_tsan"


def exe_path() -> str:
    return os.path.join(_CORE_DIR, _EXE_NAME_TSAN if _tsan() else _EXE_NAME)


def build_server_exe(force: bool = False) -> str:
    """Standalone PS-server binary (required for TSAN, usable generally)."""
    src = os.path.join(_CORE_DIR, "server.cc")
    out = exe_path()
    if not force and os.path.exists(out) \
            and os.path.getmtime(out) >= os.path.getmtime(src):
        return out
    cmd = ["g++", "-O2", "-std=c++17", "-pthread", "-DBPS_SERVER_MAIN",
           "-o", out, src]
    if _tsan():
        cmd.insert(1, "-fsanitize=thread")
        cmd.insert(1, "-g")
    subprocess.run(cmd, check=True, capture_output=True)
    return out
