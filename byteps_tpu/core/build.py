"""Build the native host core (`libbyteps_core.so`).

The reference builds its C++ core through setup.py extensions
(reference: setup.py:249-337).  Here the core is framework-independent host
logic, so a plain g++ shared-object build is enough; it is (re)built lazily on
first import when the sources are newer than the binary.
"""

from __future__ import annotations

import os
import subprocess
import sys

_CORE_DIR = os.path.dirname(os.path.abspath(__file__))
_SOURCES = ["core.cc", "server.cc"]
_LIB_NAME = "libbyteps_core.so"


def lib_path() -> str:
    return os.path.join(_CORE_DIR, _LIB_NAME)


def _needs_build() -> bool:
    lib = lib_path()
    if not os.path.exists(lib):
        return True
    lib_mtime = os.path.getmtime(lib)
    for src in _SOURCES:
        p = os.path.join(_CORE_DIR, src)
        if os.path.exists(p) and os.path.getmtime(p) > lib_mtime:
            return True
    return False


def build(force: bool = False, verbose: bool = False) -> str:
    """Compile the native core if needed; returns the .so path.

    Raises CalledProcessError on compile failure (callers fall back to the
    pure-Python implementation in that case).
    """
    if not force and not _needs_build():
        return lib_path()
    srcs = [os.path.join(_CORE_DIR, s) for s in _SOURCES
            if os.path.exists(os.path.join(_CORE_DIR, s))]
    cmd = [
        "g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
        "-fvisibility=hidden", "-o", lib_path(), *srcs,
    ]
    if verbose:
        print(" ".join(cmd), file=sys.stderr)
    subprocess.run(cmd, check=True, capture_output=not verbose)
    return lib_path()


if __name__ == "__main__":
    build(force="--force" in sys.argv, verbose=True)
    print(lib_path())
