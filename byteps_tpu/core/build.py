"""Build the native host core (`libbyteps_core.so`).

The reference builds its C++ core through setup.py extensions
(reference: setup.py:249-337).  Here the core is framework-independent host
logic, so a plain g++ shared-object build is enough; it is (re)built lazily on
first import when the sources are newer than the binary.

Sanitizer variants (coverage the reference's CI never had, SURVEY §5):
`BYTEPS_TPU_TSAN=1` builds ThreadSanitizer, `BYTEPS_TPU_ASAN=1`
AddressSanitizer + UBSan.  Sanitizers apply ONLY to the standalone PS
server binary (server.serve() execs it): sanitizer runtimes cannot be
dlopen'd into a running interpreter — TSAN's dlopen fails loudly, ASan
init kills the process outright — so the in-process client/core library
is always the plain build.
"""

from __future__ import annotations

import os
import subprocess
import sys

_CORE_DIR = os.path.dirname(os.path.abspath(__file__))
_SOURCES = ["core.cc", "server.cc"]
_LIB_NAME = "libbyteps_core.so"

# env var -> (-fsanitize value, artifact suffix)
_SANITIZERS = (
    ("BYTEPS_TPU_TSAN", "thread", "_tsan"),
    ("BYTEPS_TPU_ASAN", "address,undefined", "_asan"),
)


def _sanitizer():
    """(fsanitize_value, suffix) for the first enabled sanitizer, else
    (None, "")."""
    for env, value, suffix in _SANITIZERS:
        if os.environ.get(env, "0") == "1":
            return value, suffix
    return None, ""


def sanitized() -> bool:
    """True when any sanitizer variant is selected (server must exec the
    standalone binary)."""
    return _sanitizer()[0] is not None


def lib_path() -> str:
    # Always the PLAIN library: this .so is ctypes-loaded into running
    # interpreters, where a sanitizer runtime cannot initialize.
    return os.path.join(_CORE_DIR, _LIB_NAME)


def _needs_build() -> bool:
    lib = lib_path()
    if not os.path.exists(lib):
        return True
    lib_mtime = os.path.getmtime(lib)
    for src in _SOURCES:
        p = os.path.join(_CORE_DIR, src)
        if os.path.exists(p) and os.path.getmtime(p) > lib_mtime:
            return True
    return False


def _san_flags() -> list:
    value, _ = _sanitizer()
    if value is None:
        return []
    flags = ["-g", f"-fsanitize={value}"]
    if "address" in value:
        flags.append("-fno-omit-frame-pointer")
    if "undefined" in value:
        # UBSan checks are recoverable by default: the binary would print
        # a report and keep running, and with the test fixtures routing
        # server stderr to DEVNULL the finding would vanish.  Make UB
        # abort so the CI leg actually fails.
        flags.append("-fno-sanitize-recover=undefined")
    return flags


def build(force: bool = False, verbose: bool = False) -> str:
    """Compile the native core if needed; returns the .so path.

    Raises CalledProcessError on compile failure (callers fall back to the
    pure-Python implementation in that case).
    """
    if not force and not _needs_build():
        return lib_path()
    srcs = [os.path.join(_CORE_DIR, s) for s in _SOURCES
            if os.path.exists(os.path.join(_CORE_DIR, s))]
    # -O3: the wire-codec inner loops (onebit expand, dense level
    # gather) only vectorize at -O3; measured ~2x on the codec micros
    # with no change anywhere else.  -ffp-contract=off: the codec's
    # byte-/EF-state-parity contract with the numpy reference requires
    # numpy's two-step rounding for mu*m + x — on FMA-baseline targets
    # (aarch64) -O3 would otherwise legally contract it to fmadd and
    # drift the two paths.
    cmd = [
        "g++", "-O3", "-ffp-contract=off", "-std=c++17", "-shared",
        "-fPIC", "-pthread", "-fvisibility=hidden", "-o", lib_path(),
        *srcs,
    ]
    if verbose:
        print(" ".join(cmd), file=sys.stderr)
    subprocess.run(cmd, check=True, capture_output=not verbose)
    return lib_path()


if __name__ == "__main__":
    build(force="--force" in sys.argv, verbose=True)
    print(lib_path())


_EXE_NAME = "bps_ps_server"


def exe_path() -> str:
    _, suffix = _sanitizer()
    return os.path.join(_CORE_DIR, f"{_EXE_NAME}{suffix}")


def build_server_exe(force: bool = False) -> str:
    """Standalone PS-server binary (required under sanitizers, usable
    generally)."""
    src = os.path.join(_CORE_DIR, "server.cc")
    out = exe_path()
    if not force and os.path.exists(out) \
            and os.path.getmtime(out) >= os.path.getmtime(src):
        return out
    cmd = ["g++", *_san_flags(), "-O3", "-ffp-contract=off", "-std=c++17",
           "-pthread", "-DBPS_SERVER_MAIN", "-o", out, src]
    subprocess.run(cmd, check=True, capture_output=True)
    return out
