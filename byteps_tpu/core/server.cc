// Native PS server tier: TCP KV server with engine threads.
//
// TPU-parity re-design of the reference server (reference:
// byteps/server/server.cc, byteps/server/queue.h — see SURVEY §2.3): a
// KVServer request handler feeding N engine threads through per-thread
// priority queues, summing pushed gradient partitions across workers and
// answering pulls from the merged buffer once every worker contributed.
// The ps-lite/ZMQ transport is replaced by a plain length-prefixed TCP
// protocol (the TPU data plane is XLA collectives; this tier exists for
// PS-mode parity: CPU-host-assisted aggregation, async training, elastic
// scenarios), and CUDA/NUMA specifics are dropped.
//
// Request : u8 cmd | u8 dtype | u16 flags | u32 req_id | u32 worker_id
//           | u64 key | u64 len | payload[len]
// Response: u8 status | u32 req_id | u64 key | u64 len | payload[len]
// cmds: 0 HELLO, 1 INIT, 2 PUSH, 3 PULL, 4 BARRIER, 5 SHUTDOWN, 6 PING
//
// req_id is client-chosen and echoed back, so one connection multiplexes
// many outstanding requests — the redesign of ps-lite's ZPush/ZPull
// completion callbacks (reference: core_loops.cc:536-616) that lets a
// worker pipeline per-partition pushes/pulls concurrently.
//
// INIT payload: u64 declared_len | u32 kwargs_len | kwargs_utf8.  The
// kwargs string registers a server-side compressor for the key — the
// analog of the reference's kCompressedPushPull init push
// (reference: operations.cc:396-408, server.cc:232-261).  The INIT
// response returns u64 completed_round so a reconnecting worker (crash
// restart / elastic rejoin) seeds its round counter from server state
// instead of 0 and cannot be served a stale previous-round pull.
//
// Threading model (mirrors the reference):
//   - acceptor thread + one reader thread per connection (parse & enqueue)
//   - kEngineThreads engine threads, each owning a PriorityQueue; a key is
//     assigned to the engine with the least accumulated bytes (reference:
//     server.h:149-173), so per-key state is single-threaded
//   - priority = per-key push count when scheduling is enabled — keys
//     closest to round completion run first (reference: queue.h:31-105)

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <sys/uio.h>
#include <unistd.h>

#include <atomic>
#include <cerrno>
#include <chrono>
#include <cmath>
#include <cstdio>
#include <cstdlib>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <queue>
#include <set>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace bps_server {

enum Cmd : uint8_t {
  kHello = 0, kInit = 1, kPush = 2, kPull = 3, kBarrier = 4,
  kShutdown = 5, kPing = 6,
  kLrScale = 7,  // f32 payload: one-shot rescale of the server-side EF
                 // error on every key (the reference's lr.s mechanism for
                 // the server-side VanillaErrorFeedback; rank 0 sends it
                 // once per LR change)
};
enum Status : uint8_t { kOk = 0, kError = 1 };
enum WireDtype : uint8_t {
  kF32 = 0,        // summed across workers
  kRaw = 1,        // last-write-wins bytes
  kCompressed = 2, // decompress-sum (recompress on pull if bidirectional)
  kSeed = 3,       // raw write applied ONLY if the key has never been
                   // pushed — idempotent store seeding that cannot reset a
                   // live training run when a worker joins late / rejoins
};

// ---------------------------------------------------------------------------
// Compressed-payload codec — the server side of the reference's
// decompress-sum-recompress engine (reference: server/server.cc:86-207,
// compressor/impl/*).  Wire layout (little-endian), chosen to match the
// worker-side numpy/JAX compressors bit-for-bit:
//   u8 comp_id | u32 n_elems | body
//   onebit(1):    f32 scale | u8 bits[ceil(n/8)]        (LSB-first, 1 = neg)
//   topk(2):      u32 k | i32 idx[k] | f32 val[k]
//   randomk(3):   u32 k | i32 idx[k] | f32 val[k]
//   dithering(4): u8 flags(bit0=natural, bit1=elias) | u8 s | f32 norm |...
//     dense (bit1=0): level bitstream [ceil(n*b/8)] | u8 signs[ceil(n/8)]
//                 (b = ceil(log2(s+1)); levels packed LSB-first at b bits —
//                 fixed-width so decode stays a flat loop)
//     elias (bit1=1): u32 nbits | stream — per NONZERO level,
//                 EliasDelta(index gap, prev=-1) | sign bit |
//                 EliasDelta(level); bits LSB-first within bytes, each
//                 code MSB-first (the reference's sparse entropy coding,
//                 compressor/impl/dithering.cc:51-120; bit-matched to
//                 server/wire.py _emit_bitstream)
// ---------------------------------------------------------------------------
namespace codec {

enum CompId : uint8_t {
  kNone = 0, kOnebit = 1, kTopk = 2, kRandomk = 3, kDithering = 4
};

struct Reader {
  const char* p;
  size_t left;
  bool Take(void* dst, size_t n) {
    if (n > left) return false;
    std::memcpy(dst, p, n);
    p += n;
    left -= n;
    return true;
  }
};

// Decompress `payload` into n*4 bytes of f32 at `out`. Returns false on a
// malformed payload (bad sizes / out-of-range indices).  `max_out` caps
// the CLAIMED decompressed size before the buffer is allocated: n comes
// off the wire, so a crafted 5-byte payload could otherwise demand a
// 16 GB allocation (bad_alloc in the engine thread) — the same hostile-
// frame class as the reader's length cap.
inline bool Decompress(const std::vector<char>& payload,
                       std::vector<char>* out,
                       size_t max_out = (1ULL << 30)) {
  Reader r{payload.data(), payload.size()};
  uint8_t comp = 0;
  uint32_t n = 0;
  if (!r.Take(&comp, 1) || !r.Take(&n, 4)) return false;
  if (static_cast<size_t>(n) * 4 > max_out) return false;
  out->assign(static_cast<size_t>(n) * 4, 0);
  float* dst = reinterpret_cast<float*>(out->data());
  switch (comp) {
    case kOnebit: {
      float scale = 0;
      if (!r.Take(&scale, 4)) return false;
      size_t nbytes = (n + 7) / 8;
      if (r.left < nbytes) return false;
      const unsigned char* bits =
          reinterpret_cast<const unsigned char*>(r.p);
      for (uint32_t i = 0; i < n; ++i) {
        int bit = (bits[i >> 3] >> (i & 7)) & 1;
        dst[i] = bit ? -scale : scale;
      }
      return true;
    }
    case kTopk:
    case kRandomk: {
      uint32_t k = 0;
      if (!r.Take(&k, 4)) return false;
      if (r.left < static_cast<size_t>(k) * 8) return false;
      // The payload starts at an odd header offset; memcpy keeps the
      // 4-byte loads aligned (UB otherwise, same pattern as Reader::Take).
      std::vector<int32_t> idx(k);
      std::vector<float> val(k);
      std::memcpy(idx.data(), r.p, static_cast<size_t>(k) * 4);
      std::memcpy(val.data(), r.p + static_cast<size_t>(k) * 4,
                  static_cast<size_t>(k) * 4);
      for (uint32_t i = 0; i < k; ++i) {
        if (idx[i] < 0 || static_cast<uint32_t>(idx[i]) >= n) return false;
        dst[idx[i]] += val[i];  // scatter-add (randomk may collide)
      }
      return true;
    }
    case kDithering: {
      uint8_t flags = 0, s = 0;
      float norm = 0;
      if (!r.Take(&flags, 1) || !r.Take(&s, 1) || !r.Take(&norm, 4))
        return false;
      if (s == 0) return false;
      bool natural_p = (flags & 1) != 0;
      if (flags & 2) {
        // Sparse elias stream (see layout comment above).
        uint32_t nbits = 0;
        if (!r.Take(&nbits, 4)) return false;
        size_t nbytes = (static_cast<size_t>(nbits) + 7) / 8;
        if (r.left < nbytes) return false;
        const unsigned char* stream =
            reinterpret_cast<const unsigned char*>(r.p);
        size_t pos = 0;
        auto take = [&]() -> int {
          int b = (stream[pos >> 3] >> (pos & 7)) & 1;
          ++pos;
          return b;
        };
        auto elias = [&](uint64_t* out) -> bool {
          if (pos >= nbits) return false;
          int zeros = 0;
          bool saw_one = false;
          while (pos < nbits) {
            if (take() == 1) { saw_one = true; break; }
            ++zeros;
          }
          if (!saw_one) return false;   // stream ended inside the prefix
          if (zeros == 0) { *out = 1; return true; }
          // Valid streams have zeros = LL-1 <= 5 (L <= 63 => LL <= 6); a
          // longer prefix is malformed, and letting it through would wrap
          // the 64-bit L reconstruction below past the L<=63 check.
          if (zeros > 6) return false;
          if (pos + zeros > nbits) return false;
          uint64_t L = 1;
          for (int i = 0; i < zeros; ++i) L = (L << 1) | take();
          if (L < 1 || L > 63 || pos + (L - 1) > nbits) return false;
          uint64_t v = 1;
          for (uint64_t i = 1; i < L; ++i) v = (v << 1) | take();
          *out = v;
          return true;
        };
        int64_t idx = -1;
        while (pos < nbits) {
          uint64_t gap = 0, lvl = 0;
          if (!elias(&gap)) return false;
          idx += static_cast<int64_t>(gap);
          if (idx < 0 || idx >= static_cast<int64_t>(n)) return false;
          if (pos >= nbits) return false;
          int sgn = take();
          if (!elias(&lvl) || lvl > s) return false;
          float mag;
          if (natural_p)
            mag = std::pow(2.0f, static_cast<float>(static_cast<int>(lvl)
                                                    - static_cast<int>(s)));
          else
            mag = static_cast<float>(lvl) / static_cast<float>(s);
          dst[idx] = (sgn ? -1.0f : 1.0f) * mag * norm;
        }
        return true;
      }
      // Levels ride an LSB-first bitstream at b = ceil(log2(s+1)) bits per
      // element (bit-matched to server/wire.py _pack_levels).
      int b = 0;
      for (unsigned v = s; v; v >>= 1) ++b;
      size_t lvlbytes = (static_cast<size_t>(n) * b + 7) / 8;
      size_t signbytes = (n + 7) / 8;
      if (r.left < lvlbytes + signbytes) return false;
      const unsigned char* stream =
          reinterpret_cast<const unsigned char*>(r.p);
      const unsigned char* signs = stream + lvlbytes;
      bool natural = (flags & 1) != 0;
      for (uint32_t i = 0; i < n; ++i) {
        size_t pos = static_cast<size_t>(i) * b;
        int j = 0;
        for (int t = 0; t < b; ++t) {
          size_t bitpos = pos + t;
          j |= ((stream[bitpos >> 3] >> (bitpos & 7)) & 1) << t;
        }
        float mag;
        if (natural)
          mag = j == 0 ? 0.0f
                       : std::pow(2.0f, static_cast<float>(j - s));
        else
          mag = static_cast<float>(j) / static_cast<float>(s);
        int bit = (signs[i >> 3] >> (i & 7)) & 1;
        dst[i] = (bit ? -1.0f : 1.0f) * mag * norm;
      }
      return true;
    }
    default:
      return false;
  }
}

// Re-compress the merged f32 buffer with onebit — the bidirectional pull
// leg (reference: impl/onebit.cc:34-66; server re-compresses merged grads).
inline void CompressOnebit(const std::vector<char>& store, bool scaled,
                           std::vector<char>* out) {
  size_t n = store.size() / 4;
  const float* x = reinterpret_cast<const float*>(store.data());
  size_t nbytes = (n + 7) / 8;
  out->assign(1 + 4 + 4 + nbytes, 0);
  char* p = out->data();
  p[0] = static_cast<char>(kOnebit);
  uint32_t n32 = static_cast<uint32_t>(n);
  std::memcpy(p + 1, &n32, 4);
  float scale = 1.0f;
  if (scaled && n > 0) {
    double acc = 0;
    for (size_t i = 0; i < n; ++i) acc += std::fabs(x[i]);
    scale = static_cast<float>(acc / static_cast<double>(n));
  }
  std::memcpy(p + 5, &scale, 4);
  unsigned char* bits = reinterpret_cast<unsigned char*>(p + 9);
  for (size_t i = 0; i < n; ++i)
    if (x[i] < 0.0f) bits[i >> 3] |= static_cast<unsigned char>(1u << (i & 7));
}

}  // namespace codec

#pragma pack(push, 1)
struct ReqHeader {
  uint8_t cmd;
  uint8_t dtype;   // 0 = f32 (summed); 1 = raw bytes (last-write-wins);
                   // 2 = compressed (decompress-sum, recompress on pull)
  uint16_t flags;
  uint32_t req_id;
  uint32_t worker_id;
  uint64_t key;
  uint64_t len;
};
struct RespHeader {
  uint8_t status;
  uint32_t req_id;
  uint64_t key;
  uint64_t len;
};
#pragma pack(pop)

struct Conn {
  int fd;
  std::mutex write_mu;
  // Set (by the owning reader) the first time anything that outlives the
  // reader records this conn: an engine task, a barrier waiter, or a
  // deferred pull.  A reader that exits with referenced still false may
  // close the fd immediately (nothing can Respond on it later) — this is
  // what reclaims fds from rejected/rogue connections; see ReaderLoop.
  bool referenced = false;
};

struct PendingPull {
  Conn* conn;
  uint32_t req_id = 0;
  uint64_t key;
  uint16_t want_round = 0;  // pull round (mod 2^16) the worker expects
};

// Per-key merge state — the reference's BytePSArray + update buffers
// (reference: server.h "UpdateBuf", server.cc:48-84).
struct KeyState {
  std::vector<char> store;     // in-progress merge buffer (f32 elements)
  std::vector<char> out;       // last completed round (served to pulls) —
                               // the reference's store_/update_buf split
                               // (reference: server.cc:48-84) that keeps a
                               // straggler's round-r pull valid while
                               // round r+1 is already merging
  std::set<uint32_t> seen;     // worker ids seen this round (dedup,
                               // reference: server.cc:150-177 seen_sender)
  uint64_t completed_round = 0;
  uint8_t dtype = 0;
  std::string kwargs;          // compressor registration (INIT payload)
  bool bidirectional = false;  // recompress merged buffer on the pull leg
  bool onebit_scaled = true;
  bool round_compressed = false;  // any push this round arrived compressed
  bool server_ef = false;      // vanilla error feedback on the recompress
                               // leg — carried across rounds (reference:
                               // the server registry layers EF too,
                               // skipping only momentum,
                               // compressor_registry.cc:39-56)
  std::vector<float> ef_err;   // requantization error, one slot per elem
  std::vector<PendingPull> pending;
  std::atomic<uint64_t> push_count{0};  // total pushes (schedule priority);
                                        // atomic: written by engine, read
                                        // by reader threads
};

struct Task {
  uint8_t cmd;
  uint8_t dtype;
  uint16_t flags;
  uint32_t req_id;
  uint32_t worker_id;
  uint64_t key;
  std::vector<char> payload;
  Conn* conn;
  uint64_t priority;  // higher = sooner when scheduling enabled
  uint64_t seq;       // FIFO tiebreak
};

struct TaskCmp {
  bool operator()(const Task& a, const Task& b) const {
    if (a.priority != b.priority) return a.priority < b.priority;
    return a.seq > b.seq;  // earlier first
  }
};

// Per-engine priority queue (reference: queue.h:31-105).
class EngineQueue {
 public:
  void Push(Task&& t) {
    std::lock_guard<std::mutex> lk(mu_);
    q_.push(std::move(t));
    cv_.notify_one();
  }
  bool Pop(Task* out) {
    std::unique_lock<std::mutex> lk(mu_);
    cv_.wait(lk, [&] { return !q_.empty() || stopped_; });
    if (q_.empty()) return false;
    // priority_queue has no non-const top-move; const_cast is the standard
    // workaround for move-only payloads.
    *out = std::move(const_cast<Task&>(q_.top()));
    q_.pop();
    return true;
  }
  void Stop() {
    std::lock_guard<std::mutex> lk(mu_);
    stopped_ = true;
    cv_.notify_all();
  }

 private:
  std::priority_queue<Task, std::vector<Task>, TaskCmp> q_;
  std::mutex mu_;
  std::condition_variable cv_;
  bool stopped_ = false;
};

class Server {
 public:
  Server(int port, int num_workers, int engine_threads, bool schedule,
         bool async_mode)
      : port_(port), num_workers_(num_workers),
        engine_threads_(engine_threads < 1 ? 1 : engine_threads),
        schedule_(schedule), async_(async_mode),
        queues_(engine_threads_), engine_load_(engine_threads_, 0) {
    // Server value tracing (reference: BYTEPS_SERVER_DEBUG(_KEY),
    // server.cc:124-201): log each push merge and round publish with the
    // f32 sum of the buffer, optionally filtered to one key.
    const char* dbg = std::getenv("BYTEPS_SERVER_DEBUG");
    debug_ = dbg && dbg[0] && !(dbg[0] == '0' && dbg[1] == '\0');
    const char* dk = std::getenv("BYTEPS_SERVER_DEBUG_KEY");
    debug_key_ = dk && dk[0] ? std::strtoull(dk, nullptr, 10) : ~0ULL;
    // Frame-size cap: h.len comes off the wire, so a corrupted client (or
    // a stray non-protocol connection) could otherwise drive a multi-GB
    // vector allocation -> bad_alloc -> the whole PS tier dies.  Partition
    // payloads are bounded by BYTEPS_PARTITION_BYTES (4MB default), so
    // 1GB default headroom is generous; oversize frames drop the one
    // connection, never the server.
    const char* mx = std::getenv("BYTEPS_SERVER_MAX_MSG_BYTES");
    if (mx && mx[0]) {
      // Strict parse: a human-style value ("4MB", "1e9") would otherwise
      // silently yield a tiny cap and the server would drop every
      // connection while looking healthy.
      char* end = nullptr;
      uint64_t v = std::strtoull(mx, &end, 10);
      if (end && *end == '\0' && v > 0) {
        max_msg_ = v;
      } else {
        std::fprintf(stderr,
                     "[byteps server] ignoring invalid "
                     "BYTEPS_SERVER_MAX_MSG_BYTES=%s (want a positive "
                     "integer byte count); using %llu\n",
                     mx, static_cast<unsigned long long>(max_msg_));
      }
    }
  }

  int Run() {
    listen_fd_ = socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd_ < 0) return 1;
    int one = 1;
    setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = htons(static_cast<uint16_t>(port_));
    if (bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr),
             sizeof(addr)) != 0)
      return 2;
    if (listen(listen_fd_, 64) != 0) return 3;

    for (int i = 0; i < engine_threads_; ++i)
      engines_.emplace_back(&Server::EngineLoop, this, i);

    while (!shutdown_.load()) {
      int fd = accept(listen_fd_, nullptr, nullptr);
      if (fd < 0) {
        // Transient accept failures (fd pressure, aborted handshakes,
        // signals) must not tear down the tier — existing sessions keep
        // training and new connections retry.  Anything else (EBADF from
        // the shutdown path closing the listener) ends the loop.
        if (errno == EINTR || errno == ECONNABORTED || errno == EMFILE ||
            errno == ENFILE || errno == ENOBUFS || errno == ENOMEM) {
          std::this_thread::sleep_for(std::chrono::milliseconds(20));
          continue;
        }
        break;
      }
      setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      auto* conn = new Conn{fd, {}};
      {
        std::lock_guard<std::mutex> lk(conns_mu_);
        conns_.push_back(conn);
      }
      readers_.emplace_back(&Server::ReaderLoop, this, conn);
    }
    for (auto& q : queues_) q.Stop();
    for (auto& t : engines_) t.join();
    {
      // Readers may be blocked in recv() on idle-but-open worker sockets;
      // a half-close unblocks them so join() terminates.
      std::lock_guard<std::mutex> lk(conns_mu_);
      for (auto* c : conns_) ::shutdown(c->fd, SHUT_RDWR);
    }
    for (auto& t : readers_) t.join();
    {
      std::lock_guard<std::mutex> lk(conns_mu_);
      for (auto* c : conns_) { close(c->fd); delete c; }
      conns_.clear();
    }
    close(listen_fd_);
    return 0;
  }

 private:
  static bool ReadFull(int fd, void* buf, size_t n) {
    char* p = static_cast<char*>(buf);
    while (n > 0) {
      ssize_t r = recv(fd, p, n, 0);
      if (r <= 0) return false;
      p += r;
      n -= static_cast<size_t>(r);
    }
    return true;
  }

  static bool WriteFull(int fd, const void* buf, size_t n) {
    const char* p = static_cast<const char*>(buf);
    while (n > 0) {
      ssize_t r = send(fd, p, n, MSG_NOSIGNAL);
      if (r <= 0) return false;
      p += r;
      n -= static_cast<size_t>(r);
    }
    return true;
  }

  static void Respond(Conn* c, uint8_t status, uint32_t req_id, uint64_t key,
                      const char* data, uint64_t len) {
    std::lock_guard<std::mutex> lk(c->write_mu);
    RespHeader h{status, req_id, key, len};
    // One sendmsg for header+payload: two send() calls under TCP_NODELAY
    // put the 21-byte header on the wire as its own packet (extra syscall
    // + packet + reader wakeup per response on the pull-heavy path).
    iovec iov[2] = {{&h, sizeof(h)},
                    {const_cast<char*>(data), static_cast<size_t>(len)}};
    msghdr msg{};
    msg.msg_iov = iov;
    msg.msg_iovlen = len ? 2 : 1;
    while (true) {
      ssize_t r = sendmsg(c->fd, &msg, MSG_NOSIGNAL);
      if (r < 0 && errno == EINTR) continue;  // signal mid-frame: resume,
                                              // or the stream desyncs
      if (r <= 0) return;   // peer gone: reader/engine paths tolerate
      size_t done = static_cast<size_t>(r);
      while (msg.msg_iovlen > 0 && done >= msg.msg_iov[0].iov_len) {
        done -= msg.msg_iov[0].iov_len;
        ++msg.msg_iov;
        --msg.msg_iovlen;
      }
      if (msg.msg_iovlen == 0) return;
      msg.msg_iov[0].iov_base =
          static_cast<char*>(msg.msg_iov[0].iov_base) + done;
      msg.msg_iov[0].iov_len -= done;
    }
  }

  // Key -> engine by least accumulated load (reference: server.h:149-173).
  int EngineFor(uint64_t key, uint64_t bytes) {
    std::lock_guard<std::mutex> lk(assign_mu_);
    auto it = key_engine_.find(key);
    if (it != key_engine_.end()) return it->second;
    int best = 0;
    for (int i = 1; i < engine_threads_; ++i)
      if (engine_load_[i] < engine_load_[best]) best = i;
    engine_load_[best] += bytes;
    key_engine_[key] = best;
    return best;
  }

  void ReaderLoop(Conn* conn) {
    ReqHeader h;
    while (!shutdown_.load()) {
      if (!ReadFull(conn->fd, &h, sizeof(h))) break;
      if (h.len > max_msg_) break;  // corrupt/hostile frame: drop the conn
      std::vector<char> payload(h.len);
      if (h.len && !ReadFull(conn->fd, payload.data(), h.len)) break;
      switch (h.cmd) {
        case kHello: {
          // HELLO advertises server mode: u8 async | u8 schedule.  Lets
          // clients fail fast on mode mismatches (e.g. weight-delta async
          // training against a sync server would silently train on deltas).
          char mode[2] = {static_cast<char>(async_ ? 1 : 0),
                          static_cast<char>(schedule_ ? 1 : 0)};
          Respond(conn, kOk, h.req_id, h.key, mode, 2);
          break;
        }
        case kPing:
          Respond(conn, kOk, h.req_id, h.key, nullptr, 0);
          break;
        case kLrScale: {
          // Fan out to every engine: per-key state is engine-owned, so
          // each engine rescales the ef_err of the keys assigned to it.
          // Highest priority so (under scheduling) the rescale runs ahead
          // of queued pushes; callers apply LR changes between steps.
          for (int i = 0; i < engine_threads_; ++i) {
            Task t;
            t.cmd = h.cmd;
            t.dtype = 0;
            t.flags = 0;
            t.req_id = h.req_id;
            t.worker_id = h.worker_id;
            t.key = 0;
            t.payload = payload;  // copy per engine
            t.conn = nullptr;     // the reader already acks
            t.seq = seq_.fetch_add(1);
            t.priority = UINT64_MAX;
            queues_[i].Push(std::move(t));
          }
          Respond(conn, kOk, h.req_id, h.key, nullptr, 0);
          break;
        }
        case kBarrier:
          conn->referenced = true;   // barrier waiters outlive the reader
          HandleBarrier(conn, h.req_id, h.key);
          break;
        case kShutdown:
          Respond(conn, kOk, h.req_id, h.key, nullptr, 0);
          shutdown_.store(true);
          // Unblock accept().
          { int s = socket(AF_INET, SOCK_STREAM, 0);
            sockaddr_in a{};
            a.sin_family = AF_INET;
            a.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
            a.sin_port = htons(static_cast<uint16_t>(port_));
            connect(s, reinterpret_cast<sockaddr*>(&a), sizeof(a));
            close(s); }
          return;
        default: {
          Task t;
          t.cmd = h.cmd;
          t.dtype = h.dtype;
          t.flags = h.flags;
          t.req_id = h.req_id;
          t.worker_id = h.worker_id;
          t.key = h.key;
          t.payload = std::move(payload);
          t.conn = conn;
          t.seq = seq_.fetch_add(1);
          t.priority = 0;
          // h is #pragma pack(1): h.key sits at offset 12, so binding
          // unordered_map::operator[]'s `const key_type&` directly to it
          // is UB (misaligned 8-byte reference — UBSan catches it under
          // the 4x2 soak).  Copy to an aligned local first.
          const uint64_t key = h.key;
          int idx = EngineFor(key, h.len);
          if (schedule_) {
            std::lock_guard<std::mutex> lk(store_mu_);
            t.priority = store_[key].push_count.load(
                std::memory_order_relaxed);  // closest-to-done first
          }
          conn->referenced = true;   // engine tasks/deferred pulls hold conn
          queues_[idx].Push(std::move(t));
        }
      }
    }
    // Reader exit (peer hung up, or we rejected an oversize frame): the
    // fd is closed/freed only at server shutdown, so half-close it here —
    // the peer sees EOF immediately instead of a silently dead socket.
    // Engine responses racing on this conn fail with EPIPE, which Respond
    // already tolerates (crashed-worker path).
    //
    // If NOTHING that outlives this reader ever recorded the conn (no
    // engine task, no barrier waiter — the rejected-rogue-frame case),
    // also close the fd now: otherwise a connect-and-send-garbage loop
    // leaks one fd per attempt until accept() hits EMFILE.  Referenced
    // conns keep their fd until shutdown (engine responses and deferred
    // pulls may still write; closing would let the fd number be reused
    // by a new accept and misdirect those writes).
    std::lock_guard<std::mutex> lk(conns_mu_);
    ::shutdown(conn->fd, SHUT_RDWR);
    if (!conn->referenced) {
      ::close(conn->fd);
      conn->fd = -1;   // shutdown-path cleanup tolerates EBADF
    }
  }

  void HandleBarrier(Conn* conn, uint32_t req_id, uint64_t gen) {
    // Waiters are grouped by generation so overlapping barriers (or a late
    // worker from generation g arriving amid generation g+1 waiters) can
    // never release a mixed group early.
    std::vector<PendingPull> to_release;
    {
      std::lock_guard<std::mutex> lk(barrier_mu_);
      auto& group = barrier_waiters_[gen];
      group.push_back({conn, req_id, gen});
      if (static_cast<int>(group.size()) >= num_workers_) {
        to_release.swap(group);
        barrier_waiters_.erase(gen);
      }
    }
    for (auto& w : to_release)
      Respond(w.conn, kOk, w.req_id, w.key, nullptr, 0);
  }

  void EngineLoop(int idx) {
    Task t;
    while (queues_[idx].Pop(&t)) {
      switch (t.cmd) {
        case kInit: HandleInit(t); break;
        case kPush: HandlePush(t); break;
        case kPull: HandlePull(t); break;
        case kLrScale: HandleLrScale(t, idx); break;
        default: Respond(t.conn, kError, t.req_id, t.key, nullptr, 0);
      }
    }
  }

  void HandleLrScale(Task& t, int idx) {
    if (t.payload.size() < 4) return;
    float scale = 1.0f;
    std::memcpy(&scale, t.payload.data(), 4);
    std::vector<uint64_t> keys;
    {
      std::lock_guard<std::mutex> lk(assign_mu_);
      for (auto& kv : key_engine_)
        if (kv.second == idx) keys.push_back(kv.first);
    }
    for (uint64_t k : keys) {
      KeyState& ks = StateFor(k);
      for (auto& e : ks.ef_err) e *= scale;
    }
  }

  KeyState& StateFor(uint64_t key) {
    std::lock_guard<std::mutex> lk(store_mu_);
    return store_[key];
  }

  void HandleInit(Task& t) {
    // Init allocates the merged store; like the reference's init push it is
    // idempotent and sized by the declared length (reference:
    // server.cc:270-298).  Payload: u64 declared_len | u32 kwargs_len |
    // kwargs (compressor registration, reference: server.cc:232-261).
    // Responds with u64 completed_round so reconnecting workers re-seed
    // their round counters from server state.
    KeyState& ks = StateFor(t.key);
    uint64_t n = 0;
    if (t.payload.size() >= 8)
      std::memcpy(&n, t.payload.data(), 8);
    if (t.payload.size() >= 12) {
      uint32_t klen = 0;
      std::memcpy(&klen, t.payload.data() + 8, 4);
      if (t.payload.size() >= 12 + klen) {
        ks.kwargs.assign(t.payload.data() + 12, klen);
        // "k=v,k=v" kwargs, same strings the reference ships in its
        // kCompressedPushPull init (reference: server.cc:232-261).
        ks.bidirectional =
            ks.kwargs.find("compressor=onebit") != std::string::npos;
        ks.onebit_scaled =
            ks.kwargs.find("onebit_scaling=0") == std::string::npos;
        ks.server_ef =
            ks.kwargs.find("ef=vanilla") != std::string::npos;
      }
    }
    if (ks.store.size() != n) {
      ks.store.assign(n, 0);
      ks.seen.clear();
    }
    ks.dtype = t.dtype;
    uint64_t round = ks.completed_round;
    Respond(t.conn, kOk, t.req_id, t.key,
            reinterpret_cast<const char*>(&round), sizeof(round));
  }

  void HandlePush(Task& t) {
    KeyState& ks = StateFor(t.key);
    if (t.dtype == kSeed) {
      // Store seeding for async weight-delta training: applied only if the
      // key has never been pushed, so a late-joining/rejoining worker
      // adopts the live global weights instead of resetting them.
      // Meaningless under sync rounds — reject there (fail fast beats a
      // silent round-counter desync).
      if (!async_) {
        Respond(t.conn, kError, t.req_id, t.key, nullptr, 0);
        return;
      }
      bool first = ks.push_count.load(std::memory_order_relaxed) == 0;
      ks.push_count.fetch_add(1, std::memory_order_relaxed);
      if (first) {
        ks.store = t.payload;
        ks.dtype = kF32;
      }
      ks.out = ks.store;
      Respond(t.conn, kOk, t.req_id, t.key, nullptr, 0);
      FlushPulls(ks, t.key);
      return;
    }
    // Compressed pushes are expanded to f32 before the merge — the
    // reference server's decompress-sum engine (server.cc:86-207).
    std::vector<char> scratch;
    const std::vector<char>* data = &t.payload;
    if (t.dtype == kCompressed) {
      if (!codec::Decompress(t.payload, &scratch, max_msg_)) {
        Respond(t.conn, kError, t.req_id, t.key, nullptr, 0);
        return;
      }
      data = &scratch;
      ks.round_compressed = true;
    }
    if (ks.store.size() != data->size()) {
      // Size changed mid-stream (re-declared tensor / missing INIT): restart
      // the merge consistently — clearing `seen` too, so earlier workers'
      // contributions are never silently discarded while the round counter
      // still advances on a wrong sum.
      ks.store.assign(data->size(), 0);
      ks.seen.clear();
    }
    ks.dtype = t.dtype == kCompressed ? kF32 : t.dtype;
    ks.push_count.fetch_add(1, std::memory_order_relaxed);
    DebugLog("push_recv", t.key, t.worker_id, ks.completed_round, *data);
    if (async_) {
      // Async PS mode: store += payload immediately, no round tracking
      // (reference: server.cc:319-323, BYTEPS_ENABLE_ASYNC).
      SumInto(ks, *data);
      ks.out = ks.store;
      DebugLog("async_merge", t.key, t.worker_id, ks.completed_round,
               ks.store);
      Respond(t.conn, kOk, t.req_id, t.key, nullptr, 0);
      FlushPulls(ks, t.key);
      return;
    }
    if (ks.seen.count(t.worker_id)) {
      // Duplicate within a round — ignore merge, still ack (reference dedups
      // by seen_sender, server.cc:150-177).
      Respond(t.conn, kOk, t.req_id, t.key, nullptr, 0);
      return;
    }
    if (ks.seen.empty()) {
      // COPY_FIRST (reference: server.cc:299-379)
      std::memcpy(ks.store.data(), data->data(), data->size());
    } else {
      SumInto(ks, *data);  // SUM_RECV
    }
    ks.seen.insert(t.worker_id);
    Respond(t.conn, kOk, t.req_id, t.key, nullptr, 0);
    if (static_cast<int>(ks.seen.size()) >= num_workers_) {
      // ALL_RECV: publish the completed round and start a fresh merge.
      // Bidirectional compressors re-compress the merged buffer for the
      // pull leg (reference: impl/onebit bidirectional, server engine).
      if (ks.round_compressed && ks.bidirectional) {
        size_t ne = ks.store.size() / 4;
        float* s = reinterpret_cast<float*>(ks.store.data());
        if (ks.server_ef) {
          // Vanilla EF on the requantization: fold last round's error into
          // the merged gradient before compressing (the store is a fresh
          // COPY_FIRST merge every round, so the in-place add is safe).
          if (ks.ef_err.size() != ne) ks.ef_err.assign(ne, 0.0f);
          for (size_t i = 0; i < ne; ++i) s[i] += ks.ef_err[i];
        }
        codec::CompressOnebit(ks.store, ks.onebit_scaled, &ks.out);
        if (ks.server_ef) {
          // The decoded onebit value is just +-scale with the sign bit
          // taken from the corrected gradient — compute the error inline
          // instead of a full decompress round-trip + allocation.
          float scale = 1.0f;
          std::memcpy(&scale, ks.out.data() + 5, 4);
          for (size_t i = 0; i < ne; ++i)
            ks.ef_err[i] = s[i] - (s[i] < 0.0f ? -scale : scale);
        }
        // Log BEFORE the increment so all_recv and its contributing
        // push_recv lines carry the same round number (the compressed
        // branch logs after the EF fold — the store it publishes).
        DebugLog("all_recv", t.key, t.worker_id, ks.completed_round,
                 ks.store);
      } else {
        DebugLog("all_recv", t.key, t.worker_id, ks.completed_round,
                 ks.store);
        // Publish by swap, not copy: `out` takes the merged round (what
        // pulls serve) and `store` inherits a stale same-size buffer that
        // the next round's COPY_FIRST fully overwrites — saving a
        // full-buffer memcpy per partition per round on the serve path.
        std::swap(ks.out, ks.store);
      }
      ks.completed_round++;
      ks.seen.clear();
      ks.round_compressed = false;
      FlushPulls(ks, t.key);
    }
  }

  void DebugLog(const char* stage, uint64_t key, uint32_t worker,
                uint64_t round, const std::vector<char>& buf) {
    if (!debug_ || (debug_key_ != ~0ULL && key != debug_key_)) return;
    // f32 sum + first value — the reference's per-stage sample shape
    // (sum_of_buffer; reference server.cc:124-201).
    double sum = 0.0;
    float first = 0.0f;
    size_t n = buf.size() / sizeof(float);
    const float* f = reinterpret_cast<const float*>(buf.data());
    if (n > 0) {
      first = f[0];
      for (size_t i = 0; i < n; ++i) sum += f[i];
    }
    std::fprintf(stderr,
                 "[byteps_tpu.server DEBUG] %s key=%llu worker=%u round=%llu"
                 " len=%zu f32_sum=%.6g first=%.6g\n",
                 stage, static_cast<unsigned long long>(key), worker,
                 static_cast<unsigned long long>(round), buf.size(), sum,
                 first);
  }

  void SumInto(KeyState& ks, const std::vector<char>& payload) {
    if (ks.dtype == kF32) {
      auto* dst = reinterpret_cast<float*>(ks.store.data());
      auto* src = reinterpret_cast<const float*>(payload.data());
      size_t n = payload.size() / sizeof(float);
      #pragma omp simd
      for (size_t i = 0; i < n; ++i) dst[i] += src[i];
    } else {
      std::memcpy(ks.store.data(), payload.data(), payload.size());
    }
  }

  void HandlePull(Task& t) {
    KeyState& ks = StateFor(t.key);
    // t.flags = the round (mod 2^16) the worker just pushed; its result is
    // ready once that round has been published.  The 16-bit compare (the
    // wire header carries u16 flags) aliases only if a worker's pull were
    // exactly 65,536 rounds stale — unreachable by protocol: the client's
    // sequential-use guard (client.py _stage_parts) serializes rounds per
    // key, so a pull's round is always completed_round or
    // completed_round - 1.  Asserted rather than assumed: a client that
    // violated the invariant would otherwise silently wait or read a
    // whole-epoch-stale buffer.
    uint16_t cur = static_cast<uint16_t>(ks.completed_round & 0xFFFF);
    uint16_t prev = static_cast<uint16_t>((ks.completed_round - 1) & 0xFFFF);
    if (!async_ && t.flags != cur && t.flags != prev) {
      Respond(t.conn, kError, t.req_id, t.key, nullptr, 0);
      return;
    }
    bool ready = async_ ||
        (ks.completed_round & 0xFFFF) != t.flags;
    if (ready) {
      Respond(t.conn, kOk, t.req_id, t.key, ks.out.data(), ks.out.size());
    } else {
      ks.pending.push_back({t.conn, t.req_id, t.key, t.flags});
    }
  }

  void FlushPulls(KeyState& ks, uint64_t key) {
    std::vector<PendingPull> still;
    for (auto& p : ks.pending) {
      if (async_ || (ks.completed_round & 0xFFFF) != p.want_round)
        Respond(p.conn, kOk, p.req_id, key, ks.out.data(), ks.out.size());
      else
        still.push_back(p);
    }
    ks.pending.swap(still);
  }

  int port_;
  int num_workers_;
  int engine_threads_;
  bool schedule_;
  bool async_;
  bool debug_ = false;
  uint64_t debug_key_ = ~0ULL;   // ~0 = all keys
  uint64_t max_msg_ = 1ULL << 30;  // wire frame cap (see ctor)
  int listen_fd_ = -1;

  std::vector<EngineQueue> queues_;
  std::vector<std::thread> engines_;
  std::vector<std::thread> readers_;

  std::mutex assign_mu_;
  std::unordered_map<uint64_t, int> key_engine_;
  std::vector<uint64_t> engine_load_;

  std::mutex store_mu_;
  std::map<uint64_t, KeyState> store_;

  std::mutex barrier_mu_;
  std::map<uint64_t, std::vector<PendingPull>> barrier_waiters_;

  std::mutex conns_mu_;
  std::vector<Conn*> conns_;

  std::atomic<bool> shutdown_{false};
  std::atomic<uint64_t> seq_{0};
};

}  // namespace bps_server

extern "C" {

// Blocking server entry, the analog of `byteps_server()`
// (reference: server.h:186, server/__init__.py:21-27).
__attribute__((visibility("default")))
int bps_ps_server_run(int port, int num_workers, int engine_threads,
                      int enable_schedule, int enable_async) {
  bps_server::Server s(port, num_workers, engine_threads,
                       enable_schedule != 0, enable_async != 0);
  return s.Run();
}

}  // extern "C"

#ifdef BPS_SERVER_MAIN
// Standalone executable entry (used for sanitizer builds, where the TSAN
// runtime must be loaded at process start and cannot be dlopen'd into an
// interpreter).  argv: port num_workers engine_threads schedule async
int main(int argc, char** argv) {
  if (argc != 6) return 64;
  return bps_ps_server_run(atoi(argv[1]), atoi(argv[2]), atoi(argv[3]),
                           atoi(argv[4]), atoi(argv[5]));
}
#endif
