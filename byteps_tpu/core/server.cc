// Native PS server tier: TCP KV server with engine threads.
//
// TPU-parity re-design of the reference server (reference:
// byteps/server/server.cc, byteps/server/queue.h — see SURVEY §2.3): a
// KVServer request handler feeding N engine threads through per-thread
// priority queues, summing pushed gradient partitions across workers and
// answering pulls from the merged buffer once every worker contributed.
// The ps-lite/ZMQ transport is replaced by a plain length-prefixed TCP
// protocol (the TPU data plane is XLA collectives; this tier exists for
// PS-mode parity: CPU-host-assisted aggregation, async training, elastic
// scenarios), and CUDA/NUMA specifics are dropped.
//
// Request : u8 cmd | u8 dtype | u16 flags | u32 req_id | u32 worker_id
//           | u64 key | u64 len | payload[len]
// Response: u8 status | u32 req_id | u64 key | u64 len | payload[len]
// cmds: 0 HELLO, 1 INIT, 2 PUSH, 3 PULL, 4 BARRIER, 5 SHUTDOWN, 6 PING,
//       7 LR_SCALE, 8 STATS, 9 TRACE, 10 LEAVE, 11 MEMBERS, 12 RING,
//       13 RING_SET, 14 DRAIN, 15 MIGRATE, 16 AUDIT
//
// req_id is client-chosen and echoed back, so one connection multiplexes
// many outstanding requests — the redesign of ps-lite's ZPush/ZPull
// completion callbacks (reference: core_loops.cc:536-616) that lets a
// worker pipeline per-partition pushes/pulls concurrently.
//
// INIT payload: u64 declared_len | u32 kwargs_len | kwargs_utf8.  The
// kwargs string registers a server-side compressor for the key — the
// analog of the reference's kCompressedPushPull init push
// (reference: operations.cc:396-408, server.cc:232-261).  The INIT
// response returns u64 completed_round so a reconnecting worker (crash
// restart / elastic rejoin) seeds its round counter from server state
// instead of 0 and cannot be served a stale previous-round pull.
//
// Threading model (mirrors the reference):
//   - acceptor thread + one reader thread per connection (parse & enqueue)
//   - kEngineThreads engine threads, each owning a PriorityQueue; a key is
//     assigned to the engine with the least accumulated bytes (reference:
//     server.h:149-173), so per-key state is single-threaded
//   - priority = per-key push count when scheduling is enabled — keys
//     closest to round completion run first (reference: queue.h:31-105)

#include <arpa/inet.h>
#if defined(__GLIBC__)
#include <malloc.h>
#endif
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <sys/uio.h>
#include <sys/un.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <cerrno>
#include <chrono>
#include <cmath>
#include <cstdio>
#include <cstdlib>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>
#include <queue>
#include <set>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace bps_server {

enum Cmd : uint8_t {
  kHello = 0, kInit = 1, kPush = 2, kPull = 3, kBarrier = 4,
  kShutdown = 5, kPing = 6,
  kLrScale = 7,  // f32 payload: one-shot rescale of the server-side EF
                 // error on every key (the reference's lr.s mechanism for
                 // the server-side VanillaErrorFeedback; rank 0 sends it
                 // once per LR change)
  kStats = 8,    // server-side telemetry (CMD_STATS): responds with a JSON
                 // snapshot of per-key merge counts / completed rounds /
                 // pending-pull depth, per-worker push counts and round
                 // position (the straggler-lag signal), and total wire
                 // bytes in/out.  Handled on the reader thread so stats
                 // never queue behind a wedged engine; an OLD server that
                 // predates this command routes it to an engine whose
                 // default arm responds kError — clients turn that into a
                 // "server too old" error, never a hang.
  kTrace = 9,    // server-side span tracer (CMD_TRACE): drains the bounded
                 // span ring (RECV / MERGE_WAIT / SUM / PUBLISH /
                 // PULL_SEND per traced key+round) as JSON, plus the
                 // server's monotonic clock for offset sanity.  Reader
                 // thread, same rationale and same old-server error path
                 // as kStats.  Spans are recorded ONLY for frames whose
                 // header flags carry kFlagTraced — the worker's trace
                 // window — so an untraced run records (and pays) nothing.
  kLeave = 10,   // graceful worker departure (CMD_LEAVE): the sender is
                 // removed from the membership at the next epoch boundary
                 // and open rounds re-finalize against the survivor set.
                 // Reader thread (a leave must land even past a wedged
                 // engine); old servers answer kError via the engine's
                 // default arm — clients surface "server too old".
  kMembers = 11, // membership snapshot (CMD_MEMBERS): epoch id, per-worker
                 // alive flag + last-seen age, and the worker ids arrived
                 // at each pending barrier generation, as JSON.  Reader
                 // thread, same old-server error path as kStats.
  kRing = 12,    // ring-table read (CMD_RING): the epoch-versioned
                 // consistent-hash server ring — epoch, vnodes, member
                 // (id, host, port) rows, draining flag, keys_owned — as
                 // JSON (flags bit0 = binary instead, the joiner's
                 // C++-side read).  Reader thread; an OLD server answers
                 // kError via the engine default arm, which clients turn
                 // into "server too old".
  kRingSet = 13, // ring-table write (CMD_RING_SET): binary next-epoch
                 // ring (common/ring.py RingTable.to_wire).  Applied only
                 // when the proposed epoch is NEWER than the local one
                 // (idempotent under racing proposers — every worker that
                 // observed the same server death proposes the same
                 // transition); the response is the resulting ring JSON
                 // either way, so a stale proposer converges on the
                 // authoritative table.  Applying fans a reshard task to
                 // every engine: keys whose new owner is another live
                 // server stream their state there (CMD_MIGRATE) and
                 // retire locally.
  kDrain = 14,   // graceful scale-down (CMD_DRAIN): CMD_RING_SET whose
                 // member set excludes THIS server, plus the draining
                 // mark.  From then on every owned key is migrated to its
                 // new owner (synchronously, state-before-redirect) and
                 // the frame that found it answered kMoved — "stop
                 // accepting new rounds, hand the state over, retire".
  kMigrate = 15, // server->server state handoff (CMD_MIGRATE): one key's
                 // full merge state — declared meta, merge store, the
                 // published `out` buffer, completed_round, seen /
                 // round_members (the pending open round), EF error —
                 // installed atomically on the receiving key's engine
                 // thread.  Sent with worker_id 0xFFFFFFFF so a migration
                 // can never touch worker leases.
  kAudit = 16,   // value-domain consistency auditor (CMD_AUDIT): the
                 // server's last-K (key -> [round, digest, epoch,
                 // contributors]) publish-digest window as JSON, so any
                 // worker can cross-check the digests of the rounds it
                 // pulled against what the server actually published —
                 // catching divergent sums, double-counts, and
                 // failover-lost rounds.  Reader thread (audit must
                 // answer past a wedged engine — a wedge is exactly when
                 // it is read); recorded only when BYTEPS_TPU_AUDIT=1
                 // arms the server, and an unarmed server answers
                 // {"armed":0} so a probing client downgrades cleanly.
                 // An OLD server routes the unknown command to an engine
                 // whose default arm answers kError — "server too old".
  kCodec = 17,   // per-key codec table (CMD_CODEC): epoch-versioned wire
                 // compressor renegotiation, the adaptive-compression
                 // tuner's control op.  flags bit0 = SET (payload:
                 // u32 epoch | u64 effective_round | u32 klen | kwargs;
                 // "" = raw): applied only when the proposed epoch is
                 // NEWER than the key's current one — the CMD_RING_SET
                 // idempotency law, so racing proposers converge — and
                 // the new codec takes effect at the first round boundary
                 // with completed_round >= effective_round, so no round
                 // ever mixes wire formats.  GET (bit0 clear) and SET
                 // both answer the authoritative codec JSON.  Engine
                 // thread (the table is per-key engine-owned state, like
                 // the round it gates).  Old servers answer kError via
                 // the engine default arm — "server too old".
  kOpt = 18,     // server-resident optimizer plane (CMD_OPT): per-key
                 // epoch-versioned optimizer declaration, modeled on the
                 // CMD_CODEC renegotiation law.  flags bit0 = SET
                 // (payload: u32 epoch | u64 effective_round | u32 klen |
                 // kwargs, e.g. "opt=adam,lr=0.001,..."; "" = off):
                 // applied only when the proposed epoch is NEWER than the
                 // key's current one (racing proposers converge), taking
                 // effect at the first round boundary with
                 // completed_round >= effective_round — no round ever
                 // mixes update modes (a round publishes EITHER the sum
                 // OR the post-update parameters, decided atomically at
                 // its publish).  flags bit1 = PARAM SEED (payload: raw
                 // f32 initial parameters): applied only while the key
                 // holds no params — idempotent across racing workers
                 // shipping the same broadcast weights, and harmless
                 // after a migration installed state.  GET (no flag bits)
                 // and both writes answer the authoritative opt JSON doc
                 // (epoch/pending/param_version/slots_crc...).  Engine
                 // thread (the table and the slots are per-key
                 // engine-owned state, exactly like the codec table).
                 // Old servers answer kError via the engine default arm —
                 // "server too old".
  kKnob = 19,    // GLOBAL knob plane (CMD_KNOB): the CMD_CODEC epoch law
                 // generalized from one key's wire format to the job's
                 // global performance knobs (fusion_bytes /
                 // compress_threads / wire_conns).  ONE epoch-versioned
                 // table per server, not per key.  flags bit0 = SET
                 // (payload: u32 epoch | u64 effective_round | u32 klen |
                 // kwargs "k=v,k=v"): applied only when the proposed
                 // epoch is NEWER than the current one (the CMD_RING_SET
                 // idempotency law — racing proposers converge), taking
                 // effect at the first round boundary with
                 // completed_round >= effective_round, so no round ever
                 // mixes fusion layouts, pool sizes, or lane sets.
                 // flags bit1 = ACK (payload: u32 epoch): the sending
                 // worker reports it has ADOPTED that epoch — the
                 // per-worker acked map is what the push-path backstop
                 // checks (kKnobStale below).  GET (no flag bits), SET
                 // and ACK all answer the authoritative knob JSON doc.
                 // Reader thread, like kStats: the table is global
                 // control-plane state, never engine-owned, and a SET
                 // must land even when an engine is wedged mid-round.
                 // Old servers answer kError via the engine default arm —
                 // "server too old".
  kRepl = 20,    // Chain replication (CMD_REPL): after every publish the
                 // ring owner streams the key's FULL serialized state —
                 // the CMD_MIGRATE blob verbatim (published out +
                 // completed_round + CMD_OPT slots + embed rows), so the
                 // format stays version-tolerant by construction — to
                 // its ring successor over the peer transport.  The
                 // receiver stores the blob only-if-newer (first 8 bytes
                 // = completed_round, the CMD_RING_SET idempotency law)
                 // and installs NOTHING until a failover re-homes the
                 // key onto it (MaybeAdoptReplica).  Reader thread, like
                 // kStats: a replica must land even when the receiver's
                 // engines are wedged, and the blob never touches
                 // engine-owned state while parked.  Unarmed
                 // (BYTEPS_TPU_REPL=0, the default) the command is
                 // rejected and no peer byte is ever sent — the wire is
                 // byte-identical to the pre-replication server.
  kWindow = 21,  // Fleet window publish (CMD_WINDOW): at each signal-
                 // window roll an armed worker ships its compact JSON
                 // window summary (key = window index, payload = the
                 // summary doc) to its rank-0 server, which parks it in
                 // a bounded per-worker ring (BYTEPS_TPU_FLEET_WINDOWS,
                 // default 32).  Reader thread, like kStats/kRepl: the
                 // ring is control-plane state and a publish must land
                 // even when every engine is wedged mid-round.  The
                 // payload is stored verbatim — the server never parses
                 // worker JSON.  Re-publish of an already-held window
                 // index replaces in place (idempotent retries).
                 // Unarmed (BYTEPS_TPU_FLEET=0, the default) the command
                 // answers kError and an armed client downgrades loudly
                 // at bootstrap (the kAudit probe law) — the unarmed
                 // wire is byte-identical to the pre-fleet server.
  kFleet = 22,   // Fleet view read (CMD_FLEET): answers the merged
                 // per-worker window rings as one JSON doc
                 // ({"armed":1,"cap":N,"server_id":S,
                 //   "workers":{"<wid>":[<summary>,...],...}} — worker
                 // blobs spliced raw, ordered by window index), so any
                 // single endpoint answers for the whole job.  Also the
                 // client's bootstrap probe: unarmed servers answer
                 // {"armed":0} (kOk — probing must not look like a
                 // wire error), old servers answer kError via the
                 // engine default arm, and either response downgrades
                 // the session's fleet plane before any CMD_WINDOW
                 // frame is ever sent.
};

// Request `dtype` marker on PULL frames: the worker asks for the 24-byte
// audit trailer (AuditTrailer below) appended to the pull payload.  Sent
// ONLY by an audit-armed client that probed an audit-armed server via
// CMD_AUDIT at session bootstrap, so the unarmed wire never carries it —
// byte-identical to the pre-audit protocol.  Deliberately far outside
// WireDtype's value range (pull frames historically always carry dtype
// 0, and an unarmed/old server ignores the pull dtype entirely, so a
// mixed deployment degrades to "no trailer", never to corruption).
enum : uint8_t { kAuditPullMark = 0xAD };

// Engine-internal task (never on the wire, far above any Cmd value): a
// membership transition fanned out to every engine so per-key round state
// — which is engine-owned — is mutated only on its owning thread.  The
// payload snapshots the transition (see MembershipTransition), so the
// handler never reads the live membership table.
enum : uint8_t { kMembershipTask = 200 };
// Engine-internal ring-reshard task (never on the wire): fanned to every
// engine when a new ring epoch lands, so each engine migrates the keys IT
// owns whose new ring owner is another server — per-key state mutates
// only on its owning thread, exactly like kMembershipTask.
enum : uint8_t { kRingTask = 201 };
// Engine-internal replication-ack flush (never on the wire): fanned to a
// key's engine when its ring successor acks a replica, so the pulls the
// zero-loss gate parked (ReplBlocked) are served on the thread that owns
// the key's round state — same single-writer law as the other tasks.
enum : uint8_t { kReplFlushTask = 202 };
// kMoved: this server is not (or no longer) the ring owner of the frame's
// key.  The response payload is the CURRENT ring table as JSON, so the
// client re-plans and re-routes without an extra round trip.  Emitted
// only once the ring epoch has advanced past 0 — a fixed-topology job
// (and any pre-ring client) never sees status 2.
// kCodecStale: a push's wire format does not match the key's codec-table
// entry for the round currently merging (the sender missed — or jumped
// ahead of — a CMD_CODEC renegotiation).  The response payload is the
// authoritative codec JSON; the client re-encodes the SAME gradient with
// the right codec and replays, so no round ever mixes wire formats and
// no contribution is lost.  Emitted only for keys whose codec epoch has
// advanced past 0 — a job that never renegotiates (and any pre-codec
// client) never sees status 3.
// kKnobStale: a sync-round push arrived from a worker that has not acked
// the CURRENT global knob epoch while the key's round is already at/past
// the switch's effective round — the sender missed a CMD_KNOB
// renegotiation and its staged work may ride a stale fusion layout, pool
// size, or lane set.  The response payload is the authoritative knob
// JSON; the worker adopts the table, re-applies its half of the switch,
// ACKs the epoch, and replays (re-planning its fusion buckets when the
// layout changed), so no round mixes knob configurations and no
// contribution is lost.  Emitted only once the knob epoch has advanced
// past 0 — a job that never renegotiates (and any pre-knob client) never
// sees status 4.
enum Status : uint8_t { kOk = 0, kError = 1, kMoved = 2, kCodecStale = 3,
                        kKnobStale = 4 };

// Header `flags` bit 15: this frame is inside the sending worker's trace
// window.  PUSH/PULL frames carry their round in the LOW 15 BITS always;
// bit 15 belongs exclusively to the marker — if untraced frames kept the
// full 16-bit round, a key's round counter reaching 32768 would bleed
// into the bit and make the server record (and pay for) spans across
// 32768 consecutive untraced rounds.  A run with tracing off is
// byte-identical to the pre-trace wire through round 32767 per key.
// A traced PING additionally asks for the server's clock in the response
// (the NTP-style offset estimation leg).  The round-aliasing distance
// drops from 65536 to 32768 stale rounds — equally unreachable by
// protocol (see HandlePull's invariant comment).
enum : uint16_t { kFlagTraced = 0x8000, kRoundMask = 0x7FFF };

// True when a frame's u16 round flags refer to `round`.  The ONE
// comparison for the push stale-round guard, the pull round check, and
// pending-pull flushes — worker round counters and server
// completed_round advance in lockstep, so both sides mask identically.
inline bool RoundMatch(uint16_t flags, uint64_t round) {
  return (flags & kRoundMask) == (round & kRoundMask);
}
enum WireDtype : uint8_t {
  kF32 = 0,        // summed across workers
  kRaw = 1,        // last-write-wins bytes
  kCompressed = 2, // decompress-sum (recompress on pull if bidirectional)
  kSeed = 3,       // raw write applied ONLY if the key has never been
                   // pushed — idempotent store seeding that cannot reset a
                   // live training run when a worker joins late / rejoins
  kSparseRows = 4, // row-sparse embedding traffic: push carries
                   // (index stream, dense rows), pull carries an index
                   // stream and is round-gated exactly like a dense pull
  kSparseRead = 5, // ungated sparse row read: served immediately from the
                   // current table (inference / pull-only sessions) —
                   // never parks, never touches round state
};

// Row-sparse block header, little-endian, 16 bytes.  Shared by push
// payloads (header | index stream | nrows*width f32 rows) and pull
// requests (header | index stream).  codec 0 = raw u32 LE indices,
// codec 1 = elias-delta over gaps of the sorted unique index list
// (first code = idx[0]+1, then idx[i]-idx[i-1], every code >= 1).
// Pull/read responses are `u64 param_version | nrows*width f32 rows`
// in request order.
struct SparseHdr {
  uint32_t nrows;
  uint32_t width;
  uint8_t codec;
  uint8_t pad0;
  uint16_t pad1;
  uint32_t idx_bytes;
};
static_assert(sizeof(SparseHdr) == 16, "sparse header layout");

// Decode a sparse index stream (see SparseHdr) into `out`.  Returns
// false on any malformed stream: truncated bytes, zero elias gaps, or
// an index walking past the u32 range.  Codec 1 yields sorted unique
// indices by construction (gaps >= 1); codec 0 preserves wire order.
// The bit-loop decoder is fine here — index streams are a few KB next
// to the row payload they describe, unlike the dithering codec's
// full-gradient elias streams.
static bool DecodeSparseIndices(const unsigned char* p, size_t nbytes,
                                uint32_t nrows, uint8_t codec,
                                std::vector<uint32_t>* out) {
  out->clear();
  out->reserve(nrows);
  if (codec == 0) {
    if (nbytes < static_cast<size_t>(nrows) * 4) return false;
    for (uint32_t i = 0; i < nrows; ++i) {
      uint32_t v;
      std::memcpy(&v, p + static_cast<size_t>(i) * 4, 4);
      out->push_back(v);
    }
    return true;
  }
  if (codec != 1) return false;
  size_t nbits = nbytes * 8, pos = 0;
  auto take = [&]() -> int {
    int b = (p[pos >> 3] >> (pos & 7)) & 1;
    ++pos;
    return b;
  };
  // Elias-delta, bit-matched to server/wire.py: bits LSB-first within
  // bytes, each code MSB-first (LL-1 zeros | L in LL bits | low L-1
  // bits of v).
  auto elias = [&](uint64_t* v) -> bool {
    int zeros = 0;
    bool one = false;
    while (pos < nbits) {
      if (take() == 1) { one = true; break; }
      ++zeros;
    }
    if (!one || zeros > 6) return false;
    if (zeros == 0) { *v = 1; return true; }
    if (pos + static_cast<size_t>(zeros) > nbits) return false;
    uint64_t L = 1;
    for (int i = 0; i < zeros; ++i) L = (L << 1) | take();
    if (L < 1 || L > 40 || pos + (L - 1) > nbits) return false;
    uint64_t x = 1;
    for (uint64_t i = 1; i < L; ++i) x = (x << 1) | take();
    *v = x;
    return true;
  };
  uint64_t idx = 0;
  for (uint32_t i = 0; i < nrows; ++i) {
    uint64_t gap = 0;
    if (!elias(&gap) || gap == 0) return false;
    idx = (i == 0) ? gap - 1 : idx + gap;
    if (idx > 0xFFFFFFFFULL) return false;
    out->push_back(static_cast<uint32_t>(idx));
  }
  return true;
}

// ---------------------------------------------------------------------------
// Compressed-payload codec — the server side of the reference's
// decompress-sum-recompress engine (reference: server/server.cc:86-207,
// compressor/impl/*).  Wire layout (little-endian), chosen to match the
// worker-side numpy/JAX compressors bit-for-bit:
//   u8 comp_id | u32 n_elems | body
//   onebit(1):    f32 scale | u8 bits[ceil(n/8)]        (LSB-first, 1 = neg)
//   topk(2):      u32 k | i32 idx[k] | f32 val[k]
//   randomk(3):   u32 k | i32 idx[k] | f32 val[k]
//   dithering(4): u8 flags(bit0=natural, bit1=elias) | u8 s | f32 norm |...
//     dense (bit1=0): level bitstream [ceil(n*b/8)] | u8 signs[ceil(n/8)]
//                 (b = ceil(log2(s+1)); levels packed LSB-first at b bits —
//                 fixed-width so decode stays a flat loop)
//     elias (bit1=1): u32 nbits | stream — per NONZERO level,
//                 EliasDelta(index gap, prev=-1) | sign bit |
//                 EliasDelta(level); bits LSB-first within bytes, each
//                 code MSB-first (the reference's sparse entropy coding,
//                 compressor/impl/dithering.cc:51-120; bit-matched to
//                 server/wire.py _emit_bitstream)
// ---------------------------------------------------------------------------
namespace codec {

enum CompId : uint8_t {
  kNone = 0, kOnebit = 1, kTopk = 2, kRandomk = 3, kDithering = 4,
  // EQuARX-flavored blockwise integer quantization (arXiv 2506.17615):
  //   qblock(5): u8 bits(4|8) | u16 block | f32 scale[nblocks] | ints
  // Per `block` elements one f32 scale = absmax/qmax, then each element
  // quantizes to round-half-even(x/scale) in [-qmax, qmax] (qmax =
  // 2^(bits-1)-1); bits=4 packs two two's-complement nibbles per byte,
  // low nibble first.  Dense layout, flat decode loop, deterministic
  // (no PRNG) — the aggressive end of the adaptive-compression dial,
  // with EF supported on both the worker leg and the server recompress
  // leg under the same law as onebit.
  kQblock = 5
};

struct Reader {
  const char* p;
  size_t left;
  bool Take(void* dst, size_t n) {
    if (n > left) return false;
    std::memcpy(dst, p, n);
    p += n;
    left -= n;
    return true;
  }
};

// Byte bit-reversal table, shared by the elias encoder (reversed-chunk
// appends) and decoder (MSB-first group reads from the LSB-first window).
const unsigned char kRev8[256] = {
#define R2(x) (x), (x) + 128, (x) + 64, (x) + 192
#define R4(x) R2(x), R2((x) + 32), R2((x) + 16), R2((x) + 48)
#define R6(x) R4(x), R4((x) + 8), R4((x) + 4), R4((x) + 12)
    R6(0), R6(2), R6(1), R6(3)
#undef R6
#undef R4
#undef R2
};

inline uint64_t RevBits(uint64_t v, int k) {
  // Reverse the low k bits of v (k <= 64): byte-table chunks.
  uint64_t r = 0;
  for (int sh = 0; sh < k; sh += 8)
    r = (r << 8) | kRev8[(v >> sh) & 0xFF];
  return r >> ((8 - (k & 7)) & 7);
}

// Decode a full wire blob into `dst` (caller-provided, n f32 slots;
// zeroed here).  Returns false on a malformed payload (bad sizes /
// out-of-range indices) or when the blob's element count differs from
// `n`.  Shared by the server engine (via Decompress below) and the
// worker-side ctypes binding bps_wire_decode — one decoder, one set of
// hostile-input checks.
inline bool DecompressTo(const char* data, size_t size, float* dst,
                         uint32_t n, bool zero_dst = true) {
  Reader r{data, size};
  uint8_t comp = 0;
  uint32_t wn = 0;
  if (!r.Take(&comp, 1) || !r.Take(&wn, 4)) return false;
  if (wn != n) return false;
  // Sparse formats (topk/randomk/elias) only scatter into dst, so it
  // must start zeroed — but the server path hands in a buffer its
  // vector::assign already zero-filled; zero_dst=false skips the
  // second full-buffer pass there (4MB per partition per round).
  if (zero_dst) std::memset(dst, 0, static_cast<size_t>(n) * 4);
  switch (comp) {
    case kOnebit: {
      float scale = 0;
      if (!r.Take(&scale, 4)) return false;
      size_t nbytes = (n + 7) / 8;
      if (r.left < nbytes) return false;
      const unsigned char* bits =
          reinterpret_cast<const unsigned char*>(r.p);
      // Scale-folded byte LUT: one 32-byte copy per input byte instead
      // of 8 shift-and-select ops per element.  The 8KB table build is
      // 2048 stores, so the fast path engages at n >= 2048 (one store
      // per element amortized); below that, the direct loop.
      if (n >= 2048) {
        float lut[256][8];
        for (unsigned v = 0; v < 256; ++v)
          for (int t = 0; t < 8; ++t)
            lut[v][t] = (v >> t) & 1 ? -scale : scale;
        uint32_t nfull = n / 8;
        for (uint32_t byte = 0; byte < nfull; ++byte)
          std::memcpy(dst + static_cast<size_t>(byte) * 8,
                      lut[bits[byte]], 32);
        for (uint32_t i = nfull * 8; i < n; ++i)
          dst[i] = (bits[i >> 3] >> (i & 7)) & 1 ? -scale : scale;
        return true;
      }
      for (uint32_t i = 0; i < n; ++i)
        dst[i] = (bits[i >> 3] >> (i & 7)) & 1 ? -scale : scale;
      return true;
    }
    case kTopk:
    case kRandomk: {
      uint32_t k = 0;
      if (!r.Take(&k, 4)) return false;
      if (r.left < static_cast<size_t>(k) * 8) return false;
      // The payload starts at an odd header offset; memcpy keeps the
      // 4-byte loads aligned (UB otherwise, same pattern as Reader::Take).
      std::vector<int32_t> idx(k);
      std::vector<float> val(k);
      std::memcpy(idx.data(), r.p, static_cast<size_t>(k) * 4);
      std::memcpy(val.data(), r.p + static_cast<size_t>(k) * 4,
                  static_cast<size_t>(k) * 4);
      for (uint32_t i = 0; i < k; ++i) {
        if (idx[i] < 0 || static_cast<uint32_t>(idx[i]) >= n) return false;
        dst[idx[i]] += val[i];  // scatter-add (randomk may collide)
      }
      return true;
    }
    case kDithering: {
      uint8_t flags = 0, s = 0;
      float norm = 0;
      if (!r.Take(&flags, 1) || !r.Take(&s, 1) || !r.Take(&norm, 4))
        return false;
      if (s == 0) return false;
      bool natural_p = (flags & 1) != 0;
      if (flags & 2) {
        // Sparse elias stream (see layout comment above).
        uint32_t nbits = 0;
        if (!r.Take(&nbits, 4)) return false;
        size_t nbytes = (static_cast<size_t>(nbits) + 7) / 8;
        if (r.left < nbytes) return false;
        const unsigned char* stream =
            reinterpret_cast<const unsigned char*>(r.p);
        size_t pos = 0;
        // Windowed reads: bits buffer in a register word refilled a byte
        // at a time (a per-bit memory load costs ~3 ns/bit; this is the
        // difference between a 0.06 and a 0.4 GB/s elias decoder).  The
        // refill never reads past `nbytes`, so a truncated payload still
        // fails cleanly via the pos/nbits bound checks.
        uint64_t window = 0;
        int wbits = 0;
        size_t bytepos = 0;
        auto refill = [&]() {
          while (wbits <= 56 && bytepos < nbytes) {
            window |= static_cast<uint64_t>(stream[bytepos++]) << wbits;
            wbits += 8;
          }
        };
        auto take = [&]() -> int {
          if (wbits == 0) {
            refill();
            if (wbits == 0) { ++pos; return 0; }  // past end; bounds
          }                                        // checks reject later
          int b = static_cast<int>(window & 1);
          window >>= 1;
          --wbits;
          ++pos;
          return b;
        };
        // MSB-first k-bit group read from the LSB-first stream window:
        // the next k stream bits, assembled high-to-low (what take_int
        // did bit-by-bit), is the bit-reversal of the window's low k
        // (RevBits — the same table the encoder appends through).
        auto rev = [](uint64_t v, int k) -> uint64_t {
          return RevBits(v, k);
        };
        auto elias = [&](uint64_t* out) -> bool {
          if (pos >= nbits) return false;
          refill();
          // Fast path: whole code resolved from the register window via
          // count-trailing-zeros (the prefix) + one reversed group read.
          // Valid streams from our encoders always land here (gap < 2^32
          // => L <= 32 => code <= 42 bits); anything longer or truncated
          // falls through to the bit-loop below, which preserves the
          // original malformed-stream semantics exactly.
          if (window != 0 && wbits >= 48) {
            int zeros = __builtin_ctzll(window);
            if (zeros <= 6 && pos + zeros < nbits) {
              if (zeros == 0) {
                window >>= 1; --wbits; ++pos;
                *out = 1;
                return true;
              }
              uint64_t L = (1ULL << zeros)
                  | rev((window >> (zeros + 1))
                            & ((1ULL << zeros) - 1), zeros);
              if (L <= 33 && pos + 2 * zeros + 1 + (L - 1) <= nbits
                  && static_cast<uint64_t>(wbits)
                         >= 2 * static_cast<uint64_t>(zeros) + L) {
                int used = 2 * zeros + 1;
                uint64_t low = rev((window >> used)
                                       & ((1ULL << (L - 1)) - 1),
                                   static_cast<int>(L) - 1);
                used += static_cast<int>(L) - 1;
                window >>= used;
                wbits -= used;
                pos += static_cast<size_t>(used);
                *out = (1ULL << (L - 1)) | low;
                return true;
              }
            }
          }
          int zeros = 0;
          bool saw_one = false;
          while (pos < nbits) {
            if (take() == 1) { saw_one = true; break; }
            ++zeros;
          }
          if (!saw_one) return false;   // stream ended inside the prefix
          if (zeros == 0) { *out = 1; return true; }
          // Valid streams have zeros = LL-1 <= 5 (L <= 63 => LL <= 6); a
          // longer prefix is malformed, and letting it through would wrap
          // the 64-bit L reconstruction below past the L<=63 check.
          if (zeros > 6) return false;
          if (pos + zeros > nbits) return false;
          uint64_t L = 1;
          for (int i = 0; i < zeros; ++i) L = (L << 1) | take();
          if (L < 1 || L > 63 || pos + (L - 1) > nbits) return false;
          uint64_t v = 1;
          for (uint64_t i = 1; i < L; ++i) v = (v << 1) | take();
          *out = v;
          return true;
        };
        int64_t idx = -1;
        while (pos < nbits) {
          uint64_t gap = 0, lvl = 0;
          if (!elias(&gap)) return false;
          idx += static_cast<int64_t>(gap);
          if (idx < 0 || idx >= static_cast<int64_t>(n)) return false;
          if (pos >= nbits) return false;
          int sgn = take();
          if (!elias(&lvl) || lvl > s) return false;
          float mag;
          if (natural_p)
            mag = std::pow(2.0f, static_cast<float>(static_cast<int>(lvl)
                                                    - static_cast<int>(s)));
          else
            mag = static_cast<float>(lvl) / static_cast<float>(s);
          dst[idx] = (sgn ? -1.0f : 1.0f) * mag * norm;
        }
        return true;
      }
      // Levels ride an LSB-first bitstream at b = ceil(log2(s+1)) bits per
      // element (bit-matched to server/wire.py _pack_levels).
      int b = 0;
      for (unsigned v = s; v; v >>= 1) ++b;
      size_t lvlbytes = (static_cast<size_t>(n) * b + 7) / 8;
      size_t signbytes = (n + 7) / 8;
      if (r.left < lvlbytes + signbytes) return false;
      const unsigned char* stream =
          reinterpret_cast<const unsigned char*>(r.p);
      const unsigned char* signs = stream + lvlbytes;
      bool natural = (flags & 1) != 0;
      // Dequantized magnitude per level, hoisted out of the loop
      // (s <= 255); the level read is a single windowed 16-bit load
      // (b <= 8 so a level spans at most 2 bytes) instead of b
      // bit-extracts.
      float magtab[256];
      for (unsigned j = 0; j < 256; ++j)   // all 2^b patterns (b <= 8):
        magtab[j] = natural                // out-of-range levels in a
            ? (j == 0 ? 0.0f               // corrupt payload dequantize
                      : std::pow(2.0f, static_cast<float>(  // the same way
                            static_cast<int>(j) - static_cast<int>(s))))
            : static_cast<float>(j) / static_cast<float>(s);
      const unsigned mask = (1u << b) - 1u;
      for (uint32_t i = 0; i < n; ++i) {
        size_t pos = static_cast<size_t>(i) * b;
        size_t byte = pos >> 3;
        unsigned w = stream[byte];
        if (byte + 1 < lvlbytes + signbytes)  // signs follow contiguously
          w |= static_cast<unsigned>(stream[byte + 1]) << 8;
        unsigned j = (w >> (pos & 7)) & mask;
        int bit = (signs[i >> 3] >> (i & 7)) & 1;
        dst[i] = (bit ? -1.0f : 1.0f) * magtab[j] * norm;
      }
      return true;
    }
    case kQblock: {
      uint8_t bits = 0;
      uint16_t block = 0;
      if (!r.Take(&bits, 1) || !r.Take(&block, 2)) return false;
      if ((bits != 4 && bits != 8) || block == 0) return false;
      uint64_t nblocks = (static_cast<uint64_t>(n) + block - 1) / block;
      size_t qbytes = bits == 8 ? n : (static_cast<size_t>(n) + 1) / 2;
      if (r.left < nblocks * 4 + qbytes) return false;
      const char* scales = r.p;
      const unsigned char* q =
          reinterpret_cast<const unsigned char*>(r.p) + nblocks * 4;
      for (uint64_t b = 0; b < nblocks; ++b) {
        float scale = 0;
        std::memcpy(&scale, scales + b * 4, 4);
        uint32_t lo = static_cast<uint32_t>(b * block);
        uint32_t hi = lo + block < n ? lo + block : n;
        if (bits == 8) {
          const signed char* qq = reinterpret_cast<const signed char*>(q);
          for (uint32_t i = lo; i < hi; ++i)
            dst[i] = static_cast<float>(qq[i]) * scale;
        } else {
          for (uint32_t i = lo; i < hi; ++i) {
            int v = (i & 1) ? (q[i >> 1] >> 4) : (q[i >> 1] & 0xF);
            v = (v ^ 8) - 8;   // sign-extend the two's-complement nibble
            dst[i] = static_cast<float>(v) * scale;
          }
        }
      }
      return true;
    }
    default:
      return false;
  }
}

// Server-engine entry: validates the CLAIMED decompressed size before
// the buffer is allocated — n comes off the wire, so a crafted 5-byte
// payload could otherwise demand a 16 GB allocation (bad_alloc in the
// engine thread), the same hostile-frame class as the reader's length
// cap.
inline bool Decompress(const std::vector<char>& payload,
                       std::vector<char>* out,
                       size_t max_out = (1ULL << 30)) {
  if (payload.size() < 5) return false;
  uint32_t n = 0;
  std::memcpy(&n, payload.data() + 1, 4);
  if (static_cast<size_t>(n) * 4 > max_out) return false;
  out->assign(static_cast<size_t>(n) * 4, 0);
  return DecompressTo(payload.data(), payload.size(),
                      reinterpret_cast<float*>(out->data()), n,
                      /*zero_dst=*/false);
}

// Sign bits of x[n] into bits[(n+7)/8], LSB-first, 1 = negative.  The
// ONE packing loop for both the server recompress leg and the worker's
// ctypes pack — branchless byte-register accumulation (a conditional
// store on ~random gradient signs mispredicts half the time, ~5 ns/elem).
// The tail ORs, so the final partial byte must arrive zeroed.
inline void PackSigns(const float* x, size_t n, unsigned char* bits) {
  size_t nfull = n / 8;
  for (size_t byte = 0; byte < nfull; ++byte) {
    const float* xi = x + byte * 8;
    unsigned b = 0;
    for (int t = 0; t < 8; ++t)
      b |= static_cast<unsigned>(xi[t] < 0.0f) << t;
    bits[byte] = static_cast<unsigned char>(b);
  }
  for (size_t i = nfull * 8; i < n; ++i)
    bits[i >> 3] |= static_cast<unsigned char>(
        static_cast<unsigned>(x[i] < 0.0f) << (i & 7));
}

// Re-compress the merged f32 buffer with onebit — the bidirectional pull
// leg (reference: impl/onebit.cc:34-66; server re-compresses merged grads).
inline void CompressOnebit(const std::vector<char>& store, bool scaled,
                           std::vector<char>* out) {
  size_t n = store.size() / 4;
  const float* x = reinterpret_cast<const float*>(store.data());
  size_t nbytes = (n + 7) / 8;
  out->assign(1 + 4 + 4 + nbytes, 0);
  char* p = out->data();
  p[0] = static_cast<char>(kOnebit);
  uint32_t n32 = static_cast<uint32_t>(n);
  std::memcpy(p + 1, &n32, 4);
  float scale = 1.0f;
  if (scaled && n > 0) {
    double acc = 0;
    for (size_t i = 0; i < n; ++i) acc += std::fabs(x[i]);
    scale = static_cast<float>(acc / static_cast<double>(n));
  }
  std::memcpy(p + 5, &scale, 4);
  PackSigns(x, n, reinterpret_cast<unsigned char*>(p + 9));
}

// Blockwise integer quantization encode (kQblock) — shared by the
// worker's ctypes export (bps_wire_encode_qblock) and the server's
// bidirectional recompress leg (CompressQblock), so both sides emit
// bit-identical payloads.  Per-element float ops match the numpy
// reference in server/wire.py exactly (true f32 division by the scale —
// NOT multiply-by-inverse, whose ULP drift would flip round-half-even
// boundaries — then rintf, both round-half-to-even like np.rint), so a
// C-encoded blob is indistinguishable from a numpy-encoded one.  When
// `recon` is non-null the dequantized reconstruction is written there
// (the EF leg).  Returns bytes written, -1 on bad args / short cap.
inline int64_t EncodeQblock(const float* x, uint32_t n, int bits,
                            uint32_t block, float* recon,
                            unsigned char* out, uint64_t cap) {
  if ((bits != 4 && bits != 8) || block == 0 || block > 0xFFFF) return -1;
  const uint64_t nblocks = (static_cast<uint64_t>(n) + block - 1) / block;
  const size_t qbytes = bits == 8 ? n : (static_cast<size_t>(n) + 1) / 2;
  const size_t need = 8 + static_cast<size_t>(nblocks) * 4 + qbytes;
  if (cap < need) return -1;
  out[0] = static_cast<unsigned char>(kQblock);
  std::memcpy(out + 1, &n, 4);
  out[5] = static_cast<unsigned char>(bits);
  uint16_t blk16 = static_cast<uint16_t>(block);
  std::memcpy(out + 6, &blk16, 2);
  unsigned char* sp = out + 8;
  unsigned char* qp = out + 8 + nblocks * 4;
  const int qmax = (1 << (bits - 1)) - 1;
  if (bits == 4) std::memset(qp, 0, qbytes);   // nibble ORs need zeros
  for (uint64_t b = 0; b < nblocks; ++b) {
    const uint32_t lo = static_cast<uint32_t>(b * block);
    const uint32_t hi = lo + block < n ? lo + block : n;
    float amax = 0.0f;
    for (uint32_t i = lo; i < hi; ++i) {
      float a = std::fabs(x[i]);
      if (a > amax) amax = a;
    }
    const float scale = amax > 0.0f
        ? amax / static_cast<float>(qmax) : 0.0f;
    std::memcpy(sp + b * 4, &scale, 4);
    for (uint32_t i = lo; i < hi; ++i) {
      int qi = 0;
      if (scale > 0.0f) {
        qi = static_cast<int>(std::lrintf(x[i] / scale));
        if (qi > qmax) qi = qmax;
        if (qi < -qmax) qi = -qmax;
      }
      if (bits == 8)
        reinterpret_cast<signed char*>(qp)[i] =
            static_cast<signed char>(qi);
      else
        qp[i >> 1] |= static_cast<unsigned char>(
            (qi & 0xF) << ((i & 1) * 4));
      if (recon) recon[i] = static_cast<float>(qi) * scale;
    }
  }
  return static_cast<int64_t>(need);
}

// Re-compress the merged f32 buffer with qblock — the bidirectional pull
// leg for a key whose codec table selected the quantized-block format.
// When `ef_err` is non-null, vanilla EF runs under the same law as the
// onebit leg: the caller already folded last round's error into `store`;
// here the requantization error store[i] - recon[i] is written back.
inline void CompressQblock(const std::vector<char>& store, int bits,
                           uint32_t block, std::vector<char>* out,
                           std::vector<float>* ef_err) {
  const size_t n = store.size() / 4;
  const float* x = reinterpret_cast<const float*>(store.data());
  const uint64_t nblocks =
      block ? (static_cast<uint64_t>(n) + block - 1) / block : 0;
  const size_t qbytes = bits == 8 ? n : (n + 1) / 2;
  out->assign(8 + static_cast<size_t>(nblocks) * 4 + qbytes, 0);
  if (ef_err) ef_err->resize(n);
  EncodeQblock(x, static_cast<uint32_t>(n), bits, block,
               ef_err ? ef_err->data() : nullptr,
               reinterpret_cast<unsigned char*>(out->data()),
               out->size());
  if (ef_err) {
    float* e = ef_err->data();
    for (size_t i = 0; i < n; ++i) e[i] = x[i] - e[i];
  }
}

// ---------------------------------------------------------------------------
// Worker-side dithering encoder (ctypes: bps_wire_encode_dithering).
// Bit-exact with the numpy reference in server/wire.py — same float32
// quantization arithmetic, same xorshift32 lane PRNG, same dense/elias
// bit layouts — so a C-encoded blob is indistinguishable from a
// numpy-encoded one (asserted by tests/test_ps_compression.py).  The
// numpy encode path is ~0.02 GB/s (dense) / ~0.002 GB/s (elias) per
// core; this loop is the reason the compressed wire stops being
// numpy-bound (round-4 review weak #4).
// ---------------------------------------------------------------------------

struct BitWriter {
  // Register-accumulated LSB-first-per-byte bit stream: bits collect in
  // `acc` and flush 8 bytes at a time (a per-bit RMW into memory costs
  // ~3 ns/bit in store-forwarding stalls — the difference between a
  // 0.03 and a 0.3 GB/s elias encoder).  The buffer needs 8 bytes of
  // slack past the final byte for the word flush.
  unsigned char* buf;
  uint64_t acc = 0;
  int nacc = 0;      // bits pending in acc (< 64)
  size_t nbytes = 0; // bytes flushed so far
  size_t pos = 0;    // total bits appended
  void Flush() {
    std::memcpy(buf + nbytes, &acc, 8);    // little-endian == LSB-first
    nbytes += 8;
    acc = 0;
    nacc = 0;
  }
  void Put(int bit) {
    acc |= static_cast<uint64_t>(bit) << nacc;
    ++pos;
    if (++nacc == 64) Flush();
  }
  // Emit `len` bits of `code`, MSB-of-code-first (matches
  // wire.py _emit_bitstream).  Appending MSB-first into an LSB-first
  // stream == appending the bit-reversed code as one chunk — ~8 table
  // ops per code instead of `len` shift/or round trips.
  void PutCode(uint64_t code, int len) {
    if (len == 0) return;
    uint64_t rev = RevBits(code, len);
    pos += static_cast<size_t>(len);
    acc |= rev << nacc;
    int spill = nacc + len - 64;
    if (spill >= 0) {
      int taken = len - spill;
      nacc = 64;
      Flush();
      if (spill > 0)
        acc = (taken >= 64) ? 0 : rev >> taken;
      nacc = spill;
    } else {
      nacc += len;
    }
  }
  void Finish() {   // flush the partial word (zero-padded final byte)
    int left = nacc;
    while (left > 0) {
      buf[nbytes++] = static_cast<unsigned char>(acc & 0xFF);
      acc >>= 8;
      left -= 8;
    }
    nacc = 0;
  }
};

inline int BitLen(uint64_t v) {
  int l = 0;
  while (v) { ++l; v >>= 1; }
  return l;
}

inline void PutElias(BitWriter* w, uint64_t v) {
  // Elias-delta: LL-1 zeros, L in LL bits (MSB first), v's low L-1 bits.
  int L = BitLen(v);
  int LL = BitLen(static_cast<uint64_t>(L));
  int len = 2 * LL + L - 2;
  uint64_t low_mask = (L > 1) ? ((1ULL << (L - 1)) - 1) : 0;
  uint64_t code = (static_cast<uint64_t>(L) << (L - 1)) | (v & low_mask);
  w->PutCode(code, len);
}

// Encode f32 x[n] as a dithering wire blob into out[cap].  `rng` is the
// n-lane xorshift32 state (updated in place, same update as wire.py
// _xorshift32); `recon`, when non-null, receives the dequantized
// reconstruction (the worker-side EF term).  `norm` is computed by the
// caller (numpy's pairwise float32 sum is the parity reference for l2).
// Returns bytes written, or -1 when cap is too small / s invalid.
inline int64_t EncodeDithering(const float* x, uint32_t n, uint32_t s,
                               int natural, int elias, float norm,
                               uint32_t* rng, float* recon,
                               unsigned char* out, uint64_t cap) {
  if (s == 0 || s > 255) return -1;
  // Quantization levels, float32-identical to wire.py _levels().
  float levels[257];
  if (natural) {
    levels[0] = 0.0f;
    for (uint32_t i = 0; i < s; ++i)
      levels[i + 1] = std::pow(2.0f, static_cast<float>(
          static_cast<int>(i) - static_cast<int>(s) + 1));
  } else {
    for (uint32_t i = 0; i <= s; ++i)
      levels[i] = static_cast<float>(i) / static_cast<float>(s);
  }
  const float fnorm = norm;
  const uint64_t head = 1 + 4 + 1 + 1 + 4;  // comp|n|flags|s|norm
  const int b = BitLen(s);
  uint64_t need_dense = head + (static_cast<uint64_t>(n) * b + 7) / 8
      + (n + 7) / 8;
  // Dense writes RMW into zeroed bytes; elias flushes whole words (and
  // needs 8 bytes of slack past the stream for the word flush).
  if (elias) {
    if (cap < head + 4 + 16) return -1;
    std::memset(out, 0, head + 4);
  } else {
    if (cap < need_dense) return -1;
    std::memset(out, 0, need_dense);
  }
  out[0] = static_cast<unsigned char>(kDithering);
  std::memcpy(out + 1, &n, 4);
  out[5] = static_cast<unsigned char>((natural ? 1 : 0) | (elias ? 2 : 0));
  out[6] = static_cast<unsigned char>(s);
  std::memcpy(out + 7, &fnorm, 4);

  const uint64_t lvlbytes = (static_cast<uint64_t>(n) * b + 7) / 8;
  unsigned char* signbytes = out + head + lvlbytes;
  BitWriter ew{out + head + 4};          // elias: stream after u32 nbits
  int64_t prev = -1;
  const int si = static_cast<int>(s);
  for (uint32_t i = 0; i < n; ++i) {
    float mag = std::fabs(x[i]) / fnorm;
    // j = searchsorted(levels, mag, right) - 1, clipped to [0, s-1].
    int j;
    if (!natural) {
      // Linear levels are i/s: start from floor(mag*s) and fix up the
      // float-rounding edge (at most one step each way) — ~5x faster
      // than the binary search and bit-identical to it.
      if (!(mag == mag)) {
        j = si - 1;               // NaN sorts past every level in numpy
      } else if (mag >= 1.0f) {
        j = si - 1;               // levels[s] = 1.0 <= mag, then clipped
      } else {
        j = static_cast<int>(mag * static_cast<float>(si));
        if (j > si - 1) j = si - 1;
        while (j < si - 1 && levels[j + 1] <= mag) ++j;
        while (j > 0 && levels[j] > mag) --j;
      }
    } else if (!(mag == mag)) {
      j = si - 1;   // NaN sorts past every level in numpy searchsorted
    } else {
      uint32_t lo_i = 0, hi_i = s + 1;
      while (lo_i < hi_i) {               // first idx with levels[idx] > mag
        uint32_t mid = (lo_i + hi_i) / 2;
        if (levels[mid] <= mag) lo_i = mid + 1; else hi_i = mid;
      }
      j = static_cast<int>(lo_i) - 1;
      if (j < 0) j = 0;
      if (j > si - 1) j = si - 1;
    }
    float lo = levels[j], hi = levels[j + 1];
    float denom = hi - lo;
    if (denom < 1e-30f) denom = 1e-30f;
    float p_up = (hi > lo) ? (mag - lo) / denom : 0.0f;
    uint32_t r = rng[i];
    r ^= r << 13; r ^= r >> 17; r ^= r << 5;
    rng[i] = r;
    float u = static_cast<float>(r >> 8) / static_cast<float>(1 << 24);
    uint32_t level = static_cast<uint32_t>(j) + (u < p_up ? 1u : 0u);
    int sign = x[i] < 0.0f ? 1 : 0;
    if (recon) {
      float m2;
      if (natural)
        m2 = level == 0 ? 0.0f
             : std::pow(2.0f, static_cast<float>(
                   static_cast<int>(level) - static_cast<int>(s)));
      else
        m2 = static_cast<float>(level) / static_cast<float>(s);
      recon[i] = ((1.0f - 2.0f * static_cast<float>(sign)) * m2) * fnorm;
    }
    if (elias) {
      if (level != 0) {
        // Worst case per nonzero ~67 bits; stop before overrunning cap
        // (the 8-byte slack for the word flush included).
        if (head + 4 + ew.nbytes + 32 > cap) return -1;
        uint64_t gap = static_cast<uint64_t>(
            static_cast<int64_t>(i) - prev);
        prev = static_cast<int64_t>(i);
        PutElias(&ew, gap);
        ew.Put(sign);
        PutElias(&ew, level);
      }
    } else {
      // levels ride LSB-first within the stream: bit t of the level at
      // stream position i*b + t (matches _pack_levels).  b <= 8, so a
      // level spans at most one byte boundary: one windowed RMW.
      uint64_t pos = static_cast<uint64_t>(i) * b;
      unsigned w = level << (pos & 7);
      out[head + (pos >> 3)] |= static_cast<unsigned char>(w & 0xFF);
      if (w >> 8)
        out[head + (pos >> 3) + 1] |= static_cast<unsigned char>(w >> 8);
      if (sign)
        signbytes[i >> 3] |= static_cast<unsigned char>(1u << (i & 7));
    }
  }
  if (elias) {
    ew.Finish();
    uint32_t nbits = static_cast<uint32_t>(ew.pos);
    std::memcpy(out + head, &nbits, 4);
    return static_cast<int64_t>(head + 4 + (nbits + 7) / 8);
  }
  return static_cast<int64_t>(need_dense);
}

}  // namespace codec

// ---------------------------------------------------------------------------
// Server-side span tracer (CMD_TRACE) — the server half of the distributed
// timeline (worker half: core.cc g_tracer; reference: the per-stage server
// profiling the reference exposes via BYTEPS_SERVER_DEBUG, made structured).
// Engine threads record spans for traced frames only (header kFlagTraced,
// i.e. inside the worker's BYTEPS_TRACE_START/END_STEP window) into a
// bounded ring; the reader thread drains it as JSON on CMD_TRACE.  All
// timestamps are this host's steady_clock µs — the worker aligns them onto
// its own clock via CMD_PING offset estimation (client.py
// estimate_clock_offset), so cross-host spans land on one timeline.
// ---------------------------------------------------------------------------
inline int64_t NowUs() {
  return std::chrono::duration_cast<std::chrono::microseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

// ---------------------------------------------------------------------------
// Consistent-hash ring — the server half of the ONE placement law shared
// with the workers (common/ring.py; parity asserted by
// tests/test_server_elastic.py through bps_ring_owner).  A key is owned
// by the server whose first virtual-node point is at-or-after the key's
// point on a 64-bit ring (wrapping).  Removing a server moves only ITS
// keys; adding one moves ~1/N of the keys, all TO the joiner — which is
// what makes state handoff a one-directional stream.
// ---------------------------------------------------------------------------
namespace ring {

inline uint64_t Mix64(uint64_t x) {
  // splitmix64 — bit-identical to common/ring.py splitmix64().
  x += 0x9E3779B97F4A7C15ULL;
  x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ULL;
  x = (x ^ (x >> 27)) * 0x94D049BB133111EBULL;
  return x ^ (x >> 31);
}

inline uint64_t VnodePoint(uint32_t id, uint32_t v) {
  return Mix64(((static_cast<uint64_t>(id) + 1) << 32) | v);
}

inline uint64_t KeyPoint(uint64_t key) { return Mix64(key); }

// Owner of `key` among sorted (point, id) rows: first point >= the key's
// point, wrapping to the smallest.
inline uint32_t Owner(uint64_t key,
                      const std::vector<std::pair<uint64_t, uint32_t>>&
                          points) {
  uint64_t kp = KeyPoint(key);
  auto it = std::lower_bound(points.begin(), points.end(),
                             std::make_pair(kp, uint32_t{0}));
  if (it == points.end()) it = points.begin();
  return it->second;
}

}  // namespace ring

// ---------------------------------------------------------------------------
// Value-domain consistency auditor (BYTEPS_TPU_AUDIT=1) — the cheap
// order-independent digest of a published round's bytes.  Per 4 KiB chunk
// a standard CRC-32 (the zlib polynomial, so the worker side can use
// Python's C-accelerated zlib.crc32), summed mod 2^32 across chunks:
// chunkwise so it can be computed incrementally/in parallel and so a
// worker can digest a streamed receive without buffering, sum-combined per
// the ISSUE's order-independent shape.  Detects single-bit wire/memory
// corruption, a divergent published sum, and (via the round id carried
// next to it) failover-lost rounds.  Bit-identical to the worker's
// client.py audit_digest — parity asserted through bps_audit_digest.
// ---------------------------------------------------------------------------
namespace audit {

// Slice-by-8 tables: a byte-at-a-time CRC runs ~0.3 GB/s, which would
// put ~10 ms of digest on every 4 MB publish — measurably widening the
// round.  Eight derived tables let the loop fold 8 bytes per iteration
// (~2-3 GB/s), keeping the armed publish cost near a single memory
// pass.  Built inside a function-local static's constructor: C++11
// magic statics make the one-time build race-free when several engine
// threads publish their first armed round concurrently (a DIY
// flag-guarded build would be a TSAN-visible data race even though the
// values are idempotent).
struct Crc32TableSet {
  uint32_t t[8][256];
  Crc32TableSet() {
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k)
        c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : (c >> 1);
      t[0][i] = c;
    }
    for (int d = 1; d < 8; ++d)
      for (uint32_t i = 0; i < 256; ++i)
        t[d][i] = (t[d - 1][i] >> 8) ^ t[0][t[d - 1][i] & 0xFF];
  }
};

inline const uint32_t (*Crc32Tables())[256] {
  static const Crc32TableSet tables;
  return tables.t;
}

inline uint32_t Crc32(const char* p, size_t n) {
  const uint32_t (*t)[256] = Crc32Tables();
  const unsigned char* u = reinterpret_cast<const unsigned char*>(p);
  uint32_t c = 0xFFFFFFFFu;
  // 8-byte folds assume little-endian lane order (every deployment
  // target); the tail loop is the bitwise-identical reference.
  while (n >= 8) {
    uint32_t lo, hi;
    std::memcpy(&lo, u, 4);
    std::memcpy(&hi, u + 4, 4);
    lo ^= c;
    c = t[7][lo & 0xFF] ^ t[6][(lo >> 8) & 0xFF]
        ^ t[5][(lo >> 16) & 0xFF] ^ t[4][lo >> 24]
        ^ t[3][hi & 0xFF] ^ t[2][(hi >> 8) & 0xFF]
        ^ t[1][(hi >> 16) & 0xFF] ^ t[0][hi >> 24];
    u += 8;
    n -= 8;
  }
  while (n--) c = t[0][(c ^ *u++) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

// 64 KiB chunks: still fine-grained enough to localize a corruption to
// a chunk when debugging by hand, while keeping the worker's Python
// fallback (one zlib.crc32 call per chunk) at full C speed — 4 KiB
// chunks cost a Python-level loop iteration per 4 KiB, halving it.
enum : size_t { kChunk = 65536 };

inline uint32_t Digest(const char* p, size_t n) {
  uint32_t sum = 0;
  for (size_t off = 0; off < n; off += kChunk)
    sum += Crc32(p + off, n - off < kChunk ? n - off : kChunk);
  return sum;
}

}  // namespace audit

struct TraceSpan {
  const char* stage = "";  // static strings only ("RECV", "SUM", ...)
  uint64_t key = 0;
  uint64_t round = 0;
  uint32_t worker = 0;
  int64_t ts_us = 0;
  int64_t dur_us = 0;
  uint64_t bytes = 0;
};

class ServerTracer {
 public:
  ServerTracer() {
    // Ring capacity (spans): BYTEPS_SERVER_TRACE_EVENTS, strict-parsed
    // like BYTEPS_SERVER_MAX_MSG_BYTES.  65536 spans ≈ 5 MB of JSON and
    // thousands of traced rounds between fetches; overflow drops the
    // OLDEST spans and reports the count so the client can warn.
    const char* cap = std::getenv("BYTEPS_SERVER_TRACE_EVENTS");
    if (cap && cap[0]) {
      char* end = nullptr;
      uint64_t v = std::strtoull(cap, &end, 10);
      if (end && *end == '\0' && v > 0) cap_ = static_cast<size_t>(v);
    }
    ring_.resize(cap_);
  }

  void Record(const char* stage, uint64_t key, uint64_t round,
              uint32_t worker, int64_t ts_us, int64_t dur_us,
              uint64_t bytes) {
    std::lock_guard<std::mutex> lk(mu_);
    ring_[head_] = TraceSpan{stage, key, round, worker, ts_us, dur_us,
                             bytes};
    head_ = (head_ + 1) % cap_;
    if (count_ < cap_) ++count_;
    else ++dropped_;
  }

  // Fetch-and-clear: each span is returned to exactly one fetcher (in a
  // multi-worker run the fetching workers partition the stream — the
  // offline analyzer merges files, tools/trace_analyze.py).  The ring is
  // SWAPPED out under the mutex (O(1) + one pre-built allocation) and
  // serialized outside it: formatting up to 65536 spans takes
  // milliseconds, and holding mu_ for that would stall every engine
  // thread's Record() mid-merge — an observability fetch must never
  // inject a cross-engine pause into live rounds.
  std::string DrainJson() {
    std::vector<TraceSpan> taken(cap_);   // allocated outside the lock
    size_t head, count;
    uint64_t dropped;
    {
      std::lock_guard<std::mutex> lk(mu_);
      std::swap(ring_, taken);
      head = head_;
      count = count_;
      dropped = dropped_;
      head_ = count_ = 0;
      dropped_ = 0;
    }
    std::string js;
    js.reserve(96 + count * 112);
    char buf[224];
    std::snprintf(buf, sizeof(buf),
                  "{\"now_us\":%lld,\"dropped\":%llu,\"spans\":[",
                  static_cast<long long>(NowUs()),
                  static_cast<unsigned long long>(dropped));
    js += buf;
    size_t start = (head + cap_ - count) % cap_;
    for (size_t i = 0; i < count; ++i) {
      const TraceSpan& s = taken[(start + i) % cap_];
      std::snprintf(buf, sizeof(buf),
                    "%s{\"st\":\"%s\",\"k\":%llu,\"r\":%llu,\"w\":%u,"
                    "\"ts\":%lld,\"d\":%lld,\"b\":%llu}",
                    i ? "," : "", s.stage,
                    static_cast<unsigned long long>(s.key),
                    static_cast<unsigned long long>(s.round), s.worker,
                    static_cast<long long>(s.ts_us),
                    static_cast<long long>(s.dur_us),
                    static_cast<unsigned long long>(s.bytes));
      js += buf;
    }
    js += "]}";
    return js;
  }

 private:
  std::mutex mu_;
  std::vector<TraceSpan> ring_;
  size_t cap_ = 65536;
  size_t head_ = 0, count_ = 0;
  uint64_t dropped_ = 0;
};

#pragma pack(push, 1)
struct ReqHeader {
  uint8_t cmd;
  uint8_t dtype;   // 0 = f32 (summed); 1 = raw bytes (last-write-wins);
                   // 2 = compressed (decompress-sum, recompress on pull)
  uint16_t flags;
  uint32_t req_id;
  uint32_t worker_id;
  uint64_t key;
  uint64_t len;
};
struct RespHeader {
  uint8_t status;
  uint32_t req_id;
  uint64_t key;
  uint64_t len;
};
// 24-byte audit trailer appended to the payload of an audited pull
// response (request dtype == kAuditPullMark on an audit-armed server):
// the digest the server recorded when it PUBLISHED the buffer it is now
// serving, plus the round id, the membership epoch at publish, and the
// contributor count.  n == 0 means "no digest recorded" (pre-first
// publish, or state that migrated in without its audit history) — the
// client skips verification for that pull instead of flagging it.
struct AuditTrailer {
  uint32_t digest;
  uint64_t round;
  uint64_t epoch;
  uint32_t n;
};
#pragma pack(pop)

struct Conn {
  int fd = -1;
  std::mutex write_mu;
  // Outstanding holders that may still Respond on this fd after the
  // reader exits: queued engine tasks, deferred pulls, barrier waiters.
  // Each holder AddRef/ReleaseRef's; once the reader has exited AND the
  // count drains to zero the fd is closed (advisor r4: a one-way
  // `referenced` bool meant one valid engine-bound frame pinned the fd
  // until server shutdown, so the connect-and-send-one-frame fd
  // exhaustion was still reachable).
  std::atomic<int> refs{0};
  std::atomic<bool> reader_done{false};
  // Per-connection receive-buffer freelist: payload buffers cycle
  // reader -> engine -> back here instead of a fresh (value-initialized!)
  // vector per frame — `std::vector<char> payload(h.len)` was a hidden
  // 4MB memset per partition per round on top of the malloc churn.
  // Bounded small: steady-state one worker conn has ~engine-queue-depth
  // buffers in flight.
  std::mutex pool_mu;
  std::vector<std::vector<char>> bufpool;
};

struct PendingPull {
  Conn* conn;
  uint32_t req_id = 0;
  uint64_t key;
  uint16_t want_round = 0;  // raw round flags the worker sent (traced
                            // frames carry kFlagTraced + round mod 2^15,
                            // untraced the round mod 2^16 — RoundMatch)
  uint32_t worker = 0;      // for the PULL_SEND trace span
  bool traced = false;      // record a span when the pull finally serves
  bool audited = false;     // append the AuditTrailer when it serves
  bool ungated = false;     // a kSparseRead parked ONLY by the
                            // replication gate (ReplBlocked): it ignores
                            // the round match and serves as soon as the
                            // successor's ack lands
  // Row-sparse pulls (dtype kSparseRows) park their request payload
  // (SparseHdr + index stream) here; empty for dense pulls.  Served by
  // FlushPulls via RespondSparse when the wanted round publishes.
  std::vector<char> sparse;
};

// Per-key merge state — the reference's BytePSArray + update buffers
// (reference: server.h "UpdateBuf", server.cc:48-84).
struct KeyState {
  std::vector<char> store;     // in-progress merge buffer (f32 elements)
  std::vector<char> out;       // last completed round (served to pulls) —
                               // the reference's store_/update_buf split
                               // (reference: server.cc:48-84) that keeps a
                               // straggler's round-r pull valid while
                               // round r+1 is already merging
  std::set<uint32_t> seen;     // worker ids seen this round (dedup,
                               // reference: server.cc:150-177 seen_sender)
  // The OPEN round's contributor set under elastic membership.  EMPTY in
  // a fixed-membership run (epoch 0): round completion then falls back to
  // the historical seen.size() >= num_workers_ count, so a job that never
  // resizes behaves (and talks) exactly as before.  Once the epoch has
  // ever advanced, every round's first push snapshots the live worker set
  // here, and the round publishes only when ALL of them have contributed
  // — membership changes land between rounds, never inside one.  A
  // transition's fan-out task pins still-open epoch-0 rounds to the
  // pre-transition set and erases departed workers (the re-finalize leg).
  std::set<uint32_t> round_members;
  uint64_t completed_round = 0;
  uint8_t dtype = 0;
  std::string kwargs;          // compressor registration (INIT payload)
  bool bidirectional = false;  // recompress merged buffer on the pull leg
  bool onebit_scaled = true;
  bool round_compressed = false;  // any push this round arrived compressed
  bool server_ef = false;      // vanilla error feedback on the recompress
                               // leg — carried across rounds (reference:
                               // the server registry layers EF too,
                               // skipping only momentum,
                               // compressor_registry.cc:39-56)
  std::vector<float> ef_err;   // requantization error, one slot per elem
  std::vector<PendingPull> pending;
  // Traced merges of the OPEN round: (worker, merge-complete ts).  On
  // publish each entry becomes a MERGE_WAIT span — the time that worker's
  // contribution sat waiting for the round's remaining workers, i.e. the
  // straggler signal.  Only traced pushes append, so an untraced run
  // never allocates here.  Cleared wherever `seen` resets.
  std::vector<std::pair<uint32_t, int64_t>> merge_ts;
  std::atomic<uint64_t> push_count{0};  // total pushes (schedule priority);
                                        // atomic: written by engine, read
                                        // by reader threads
  // --- scatter-receive state (reader-visible) ---------------------------
  // declared_len mirrors the store size the engine last established
  // (INIT / size-change reset) so a READER thread can decide — without
  // touching engine-owned state — whether an incoming raw-f32 push can
  // be received straight into this key's scatter buffer.
  std::atomic<uint64_t> declared_len{0};
  // One frame at a time may hold the scatter lease (acquire via
  // exchange); the holder's reader fills scatter_buf off the socket, the
  // engine consumes it when the task runs (adopting it into the store by
  // swap on the round's first push, summing from it otherwise) and
  // releases the lease.  Losers of the CAS take the buffered path — the
  // scatter is an allocation/copy optimization, never a semantic change.
  std::atomic<bool> scatter_leased{false};
  std::vector<char> scatter_buf;
  // Live state marker for the elastic ring: set by INIT/push/migrate-in,
  // cleared by migrate-out.  Drives the keys_owned gauge and tells the
  // kMoved path whether there is state to hand over before redirecting.
  // Atomic because the reader-thread stats path counts it while engines
  // flip it.
  std::atomic<bool> active{false};
  // Chain replication (CMD_REPL): the newest completed_round the ring
  // successor has ACKED holding a replica of.  The zero-loss pull gate
  // (ReplBlocked) parks pulls while completed_round runs ahead of this
  // by more than the lag window, so no worker can consume a round that
  // would be lost if this server died right now.  Atomic: written by
  // the replication thread on ack, read by the key's engine.
  std::atomic<uint64_t> repl_acked_round{0};
  // --- audit state (engine-owned, like the round state) -----------------
  // Digest of the LAST published `out` buffer + the round/epoch/
  // contributor-count recorded with it — what an audited pull's trailer
  // carries.  Written only in PublishRound when BYTEPS_TPU_AUDIT=1;
  // audit_n == 0 until the first armed publish (clients skip those).
  // NOT part of the CMD_MIGRATE wire format on purpose: a migrated key's
  // new owner starts with an empty digest (n=0 trailers) and re-records
  // at its next publish, so mixed-version servers stay compatible.
  uint64_t audit_round = 0;
  uint32_t audit_digest = 0;
  uint64_t audit_epoch = 0;
  uint32_t audit_n = 0;
  // --- per-key codec table (engine-owned; CMD_CODEC) --------------------
  // Epoch-versioned wire-compressor renegotiation: `codec_epoch` is the
  // newest accepted proposal (0 = launch config — INIT kwargs govern and
  // nothing below is ever consulted, keeping the pre-codec wire
  // byte-identical); while `codec_pending`, `codec_next` holds the
  // proposed kwargs ("" = raw) that take effect at the FIRST round
  // boundary with completed_round >= codec_effective
  // (ApplyPendingCodec).  Once the epoch has advanced, every push's wire
  // format is checked against the active codec and mismatches draw
  // kCodecStale — no round ever mixes formats.  Rides CMD_MIGRATE so a
  // migrated key keeps its *current* codec epoch, not its launch config.
  uint32_t codec_epoch = 0;
  uint32_t codec_applied_epoch = 0;
  bool codec_pending = false;
  uint64_t codec_effective = 0;
  std::string codec_next;
  // A switch away from a server-EF codec must never silently drop the
  // accumulated requantization error: this flag folds ef_err into the
  // next published sum exactly once (PublishRound), then clears it.
  bool ef_fold_pending = false;
  // Bidirectional recompress codec + qblock params (from kwargs).
  uint8_t pull_comp = 1;        // codec::kOnebit
  uint8_t qblock_bits = 8;
  uint16_t qblock_block = 256;
  // --- server-resident optimizer plane (CMD_OPT; engine-owned) ----------
  // Epoch-versioned like the codec table above: `opt_epoch` 0 = the
  // plane is unarmed and NOTHING below is consulted — an undeclared run
  // publishes sums and stays wire byte-identical.  While `opt_pending`,
  // `opt_next` holds the proposed kwargs ("" = off) that take effect at
  // the first round boundary with completed_round >= opt_effective, so
  // no round ever mixes update modes.  Once a mode is ACTIVE, every
  // publish runs merge -> optimizer step -> publish *parameters*
  // (OptUpdateStage): the optimizer consumes exactly the bytes a
  // sum-mode pull would have served (codec/EF law untouched), updates
  // the server-owned slots below, and replaces `out` with the updated
  // params.  param_version increments exactly once per update — the
  // exactly-one-update proof replays and migrations are audited against.
  uint32_t opt_epoch = 0;
  uint32_t opt_applied_epoch = 0;
  bool opt_pending = false;
  uint64_t opt_effective = 0;
  std::string opt_next;         // pending kwargs
  std::string opt_kwargs;       // active kwargs ("" = off)
  uint8_t opt_kind = 0;         // 0 off, 1 sgd, 2 momentum, 3 adam,
                                // 4 adagrad (opt_v = sum-of-squares)
  // Hyperparams kept as the DOUBLES the kwargs decimals parse to (the
  // same f64 the worker-local optax baseline holds); every update-stage
  // constant derives from them with optax's exact rounding, e.g.
  // (float)(1.0 - b1) — f32-parity depends on this.
  double opt_lr = 0.01, opt_mu = 0.9, opt_b1 = 0.9, opt_b2 = 0.999,
         opt_eps = 1e-8, opt_gscale = 1.0, opt_acc0 = 0.1;
  std::vector<float> params;    // the authoritative weights
  std::vector<float> opt_m;     // momentum trace / Adam first moment
  std::vector<float> opt_v;     // Adam second moment
  uint64_t opt_step = 0;        // optimizer step count (Adam bias corr,
                                // mirrors optax safe_int32_increment)
  uint64_t param_version = 0;   // ++ per published optimizer update
  uint64_t opt_slot_acc = 0;    // bytes last accounted to opt_slot_bytes_
  bool opt_warned = false;      // one unseeded-params warning per key
  // Update-stage gradient scratch, reused round to round (a fresh
  // zero-filled vector per publish would put an alloc + full-buffer
  // memset on the engine's critical path).  Transient — never rides
  // CMD_MIGRATE.
  std::vector<float> opt_scratch;

  // --- row-sparse embedding plane (dtype kSparseRows) -------------------
  // A key becomes an embedding key at INIT time via kwargs
  // `embed_rows=N,embed_width=D` with declared length 0: the dense store
  // stays empty and all round state lives row-wise in the maps below.
  // The dense and sparse planes are mutually exclusive per key.
  uint64_t embed_rows = 0;   // declared table rows (0 = not an embed key)
  uint32_t embed_width = 0;  // f32 elements per row
  // Open-round merge: row -> accumulated gradient row.  First touch of a
  // row COPIES the pushed payload (the dense plane's COPY_FIRST law —
  // zero-init plus += would turn a pushed -0.0 into +0.0 and break
  // dense/sparse bit-identity); later touches element-wise += in
  // arrival order.
  std::unordered_map<uint64_t, std::vector<float>> embed_merge;
  // Published round: swapped in from embed_merge at publish.  What
  // unarmed round-gated pulls serve; rows absent here read as zeros —
  // sum semantics, exactly what a dense pull over an untouched slice
  // yields.  When the key is armed (opt_kind != 0) pulls serve `params`
  // rows instead and this map only tracks which rows the round touched.
  std::unordered_map<uint64_t, std::vector<float>> embed_out;
  // Per-row update counts for lazy bias correction (Adam) — only rows a
  // publish actually touched step, mirroring a worker-local optax
  // baseline that masks untouched rows out of the update.  Sized
  // embed_rows lazily when the key arms; params/opt_m/opt_v above are
  // reused at embed_rows*embed_width.
  std::vector<uint32_t> embed_row_step;
};

struct Task {
  uint8_t cmd;
  uint8_t dtype;
  uint16_t flags;
  uint32_t req_id;
  uint32_t worker_id;
  uint64_t key;
  std::vector<char> payload;
  Conn* conn;
  uint64_t priority;  // higher = sooner when scheduling enabled
  uint64_t seq;       // FIFO tiebreak
  int64_t recv_us = 0;  // frame-read timestamp, set only for traced
                        // frames: engine-start minus this is the RECV
                        // span (server-side queue wait)
  bool scattered = false;  // payload was scatter-received into the key's
                           // scatter_buf (payload itself is empty); the
                           // engine owns releasing the scatter lease
};

struct TaskCmp {
  bool operator()(const Task& a, const Task& b) const {
    if (a.priority != b.priority) return a.priority < b.priority;
    return a.seq > b.seq;  // earlier first
  }
};

// Per-engine priority queue (reference: queue.h:31-105).
class EngineQueue {
 public:
  void Push(Task&& t) {
    std::lock_guard<std::mutex> lk(mu_);
    q_.push(std::move(t));
    cv_.notify_one();
  }
  bool Pop(Task* out) {
    std::unique_lock<std::mutex> lk(mu_);
    cv_.wait(lk, [&] { return !q_.empty() || stopped_; });
    if (q_.empty()) return false;
    // priority_queue has no non-const top-move; const_cast is the standard
    // workaround for move-only payloads.
    *out = std::move(const_cast<Task&>(q_.top()));
    q_.pop();
    return true;
  }
  void Stop() {
    std::lock_guard<std::mutex> lk(mu_);
    stopped_ = true;
    cv_.notify_all();
  }

 private:
  std::priority_queue<Task, std::vector<Task>, TaskCmp> q_;
  std::mutex mu_;
  std::condition_variable cv_;
  bool stopped_ = false;
};

class Server {
 public:
  Server(int port, int num_workers, int engine_threads, bool schedule,
         bool async_mode)
      : port_(port), num_workers_(num_workers),
        engine_threads_(engine_threads < 1 ? 1 : engine_threads),
        schedule_(schedule), async_(async_mode),
        queues_(engine_threads_), engine_load_(engine_threads_, 0) {
#if defined(__GLIBC__)
    // Partition payloads (4MB default) sit above glibc's default mmap
    // threshold, so the reader's per-push buffer would be a fresh
    // mmap/munmap each time — page faults + TLB shootdowns on every
    // partition of every round.  Raise the threshold so those buffers
    // recycle through the heap (the zero-copy discipline the reference
    // gets from ps-lite's pinned SArray pools).
    mallopt(M_MMAP_THRESHOLD, 64 * 1024 * 1024);
    mallopt(M_TRIM_THRESHOLD, 128 * 1024 * 1024);
#endif
    // Server value tracing (reference: BYTEPS_SERVER_DEBUG(_KEY),
    // server.cc:124-201): log each push merge and round publish with the
    // f32 sum of the buffer, optionally filtered to one key.
    const char* dbg = std::getenv("BYTEPS_SERVER_DEBUG");
    debug_ = dbg && dbg[0] && !(dbg[0] == '0' && dbg[1] == '\0');
    const char* dk = std::getenv("BYTEPS_SERVER_DEBUG_KEY");
    debug_key_ = dk && dk[0] ? std::strtoull(dk, nullptr, 10) : ~0ULL;
    // Frame-size cap: h.len comes off the wire, so a corrupted client (or
    // a stray non-protocol connection) could otherwise drive a multi-GB
    // vector allocation -> bad_alloc -> the whole PS tier dies.  Partition
    // payloads are bounded by BYTEPS_PARTITION_BYTES (4MB default), so
    // 1GB default headroom is generous; oversize frames drop the one
    // connection, never the server.
    const char* mx = std::getenv("BYTEPS_SERVER_MAX_MSG_BYTES");
    if (mx && mx[0]) {
      // Strict parse: a human-style value ("4MB", "1e9") would otherwise
      // silently yield a tiny cap and the server would drop every
      // connection while looking healthy.
      char* end = nullptr;
      uint64_t v = std::strtoull(mx, &end, 10);
      if (end && *end == '\0' && v > 0) {
        max_msg_ = v;
      } else {
        std::fprintf(stderr,
                     "[byteps server] ignoring invalid "
                     "BYTEPS_SERVER_MAX_MSG_BYTES=%s (want a positive "
                     "integer byte count); using %llu\n",
                     mx, static_cast<unsigned long long>(max_msg_));
      }
    }
    // Colocated-server UDS fast path (BYTEPS_TPU_SERVER_UDS): also listen
    // on AF_UNIX at "<base>.<port>" — same framing, bit-identical
    // protocol, lower per-frame cost than loopback TCP.  The ".<port>"
    // suffix keys the path per server so one env var covers a multi-
    // server host (client.py _dial derives the same name).
    const char* uds = std::getenv("BYTEPS_TPU_SERVER_UDS");
    if (uds && uds[0]) uds_base_ = uds;
    // Socket buffer tuning (BYTEPS_TPU_SOCK_BUF_KB): SO_SNDBUF/SO_RCVBUF
    // on every accepted connection; 0 = kernel default (auto-tuning).
    // Strict-parse like max_msg_.
    const char* sb = std::getenv("BYTEPS_TPU_SOCK_BUF_KB");
    if (sb && sb[0]) {
      char* end = nullptr;
      uint64_t v = std::strtoull(sb, &end, 10);
      if (end && *end == '\0')
        sock_buf_bytes_ = static_cast<int>(v * 1024);
      else
        std::fprintf(stderr,
                     "[byteps server] ignoring invalid "
                     "BYTEPS_TPU_SOCK_BUF_KB=%s (want a KiB count)\n", sb);
    }
    // Elastic membership: the launch-time worker set is epoch 0 — dense
    // ids 0..num_workers-1, the DMLC_WORKER_ID convention — each with a
    // lease refreshed by any frame it sends (traffic or CMD_PING).
    // BYTEPS_TPU_EVICT_TIMEOUT_S > 0 arms the lease scanner: a worker
    // silent for that long is evicted at an epoch boundary and open
    // rounds re-finalize against the survivors.  0 (default) keeps the
    // historical semantics — a dead worker wedges rounds until the
    // worker-side stall watchdog/barrier timeout fails them loudly.
    const char* ev = std::getenv("BYTEPS_TPU_EVICT_TIMEOUT_S");
    if (ev && ev[0]) {
      char* end = nullptr;
      double v = std::strtod(ev, &end);
      if (end && *end == '\0' && v >= 0.0)
        evict_timeout_s_ = v;
      else
        std::fprintf(stderr,
                     "[byteps server] ignoring invalid "
                     "BYTEPS_TPU_EVICT_TIMEOUT_S=%s (want seconds)\n", ev);
    }
    const int64_t now = NowUs();
    for (int i = 0; i < num_workers_; ++i)
      members_[static_cast<uint32_t>(i)] = MemberRec{now, true};
    // Hierarchical reduction (BYTEPS_TPU_SLICE_SIZE, parallel/
    // hierarchy.py): workers are grouped into slices of this many
    // contiguous ids, only one leader per slice pushes/pulls, and
    // RoundComplete counts SLICES covered, not chips — a slice whose
    // every member departed stops being expected through the same
    // epoch/round_members machinery elastic membership already uses.
    // 1 (default) keeps the historical per-worker completion exactly.
    const char* ss = std::getenv("BYTEPS_TPU_SLICE_SIZE");
    if (ss && ss[0]) {
      char* end = nullptr;
      uint64_t v = std::strtoull(ss, &end, 10);
      if (end && *end == '\0' && v >= 1)
        slice_size_ = static_cast<int>(v);
      else
        std::fprintf(stderr,
                     "[byteps server] ignoring invalid "
                     "BYTEPS_TPU_SLICE_SIZE=%s (want >= 1)\n", ss);
    }
    // Elastic PS tier (consistent-hash ring).  BYTEPS_TPU_RING=1 arms
    // ring placement + ownership enforcement; BYTEPS_TPU_RING_JOIN=1
    // additionally makes this a JOINING server (it announces itself to
    // the launch peers at startup and the ring re-shards ~1/N of the
    // keys onto it).  Unarmed (default), no ring state exists, status
    // kMoved is never emitted, and the wire is byte-identical to the
    // pre-ring server.
    auto truthy = [](const char* v) {
      return v && v[0] && !(v[0] == '0' && v[1] == '\0');
    };
    // Value-domain consistency auditor (BYTEPS_TPU_AUDIT=1): record a
    // chunked-CRC digest of every published round (PublishRound), serve
    // the last-K window over CMD_AUDIT, and append the trailer to pulls
    // that ask for it (dtype kAuditPullMark).  Unarmed (default): no
    // digest is ever computed, no trailer ever appended, CMD_AUDIT
    // answers {"armed":0} — the wire is byte-identical to pre-audit.
    audit_armed_ = truthy(std::getenv("BYTEPS_TPU_AUDIT"));
    const char* aw = std::getenv("BYTEPS_TPU_AUDIT_WINDOW");
    if (aw && aw[0]) {
      char* end = nullptr;
      uint64_t v = std::strtoull(aw, &end, 10);
      if (end && *end == '\0' && v > 0 && v <= 4096)
        audit_window_ = static_cast<int>(v);
      else
        std::fprintf(stderr,
                     "[byteps server] ignoring invalid "
                     "BYTEPS_TPU_AUDIT_WINDOW=%s (want 1..4096)\n", aw);
    }
    // Test-only single-bit fault injection ("key:round:bit"): the FIRST
    // audited pull serving that key+round gets one bit of its payload
    // flipped (in a copy — the store is never corrupted), simulating
    // wire/memory corruption downstream of the publish.  The digest in
    // the trailer is the honest pre-corruption one, so the client's
    // re-digest must flag the mismatch — the end-to-end detection test.
    const char* af = std::getenv("BYTEPS_TPU_AUDIT_FAULT");
    if (af && af[0]) {
      unsigned long long k = 0, r = 0, b = 0;
      if (std::sscanf(af, "%llu:%llu:%llu", &k, &r, &b) == 3) {
        fault_armed_ = true;
        fault_key_ = k;
        fault_round_ = r;
        fault_bit_ = b;
      } else {
        std::fprintf(stderr,
                     "[byteps server] ignoring invalid "
                     "BYTEPS_TPU_AUDIT_FAULT=%s (want key:round:bit)\n",
                     af);
      }
    }
    ring_join_ = truthy(std::getenv("BYTEPS_TPU_RING_JOIN"));
    ring_armed_ = ring_join_ || truthy(std::getenv("BYTEPS_TPU_RING"));
    // Chain replication (BYTEPS_TPU_REPL=1): every publish streams the
    // key's serialized state to its ring successor, and the zero-loss
    // gate parks pulls until the successor acks within
    // BYTEPS_TPU_REPL_LAG rounds (default 0: a round is pullable only
    // once it can survive this server's death).  Unarmed (default): no
    // replication thread, no peer traffic, no gate — wire and timing
    // byte-identical to the pre-replication server.
    repl_armed_ = truthy(std::getenv("BYTEPS_TPU_REPL"));
    const char* rlag = std::getenv("BYTEPS_TPU_REPL_LAG");
    if (rlag && rlag[0]) {
      char* end = nullptr;
      uint64_t v = std::strtoull(rlag, &end, 10);
      if (end && *end == '\0')
        repl_lag_window_ = v;
      else
        std::fprintf(stderr,
                     "[byteps server] ignoring invalid "
                     "BYTEPS_TPU_REPL_LAG=%s (want a round count)\n",
                     rlag);
    }
    // Fleet observability plane (BYTEPS_TPU_FLEET=1): retain a bounded
    // per-worker ring of published window summaries (CMD_WINDOW) and
    // serve the merged view (CMD_FLEET).  Unarmed (default): no ring
    // exists, both commands answer their downgrade shapes, the migrate
    // blob carries no fleet trailer — wire byte-identical to pre-fleet.
    fleet_armed_ = truthy(std::getenv("BYTEPS_TPU_FLEET"));
    const char* fwn = std::getenv("BYTEPS_TPU_FLEET_WINDOWS");
    if (fwn && fwn[0]) {
      char* end = nullptr;
      uint64_t v = std::strtoull(fwn, &end, 10);
      if (end && *end == '\0' && v > 0 && v <= 4096)
        fleet_windows_ = static_cast<int>(v);
      else
        std::fprintf(stderr,
                     "[byteps server] ignoring invalid "
                     "BYTEPS_TPU_FLEET_WINDOWS=%s (want 1..4096)\n", fwn);
    }
    const char* sid = std::getenv("DMLC_SERVER_ID");
    if (sid && sid[0])
      my_server_id_ = static_cast<uint32_t>(std::strtoul(sid, nullptr, 10));
    const char* vn = std::getenv("BYTEPS_TPU_RING_VNODES");
    if (vn && vn[0]) {
      char* end = nullptr;
      uint64_t v = std::strtoull(vn, &end, 10);
      if (end && *end == '\0' && v > 0 && v <= 4096)
        ring_vnodes_ = static_cast<int>(v);
      else
        std::fprintf(stderr,
                     "[byteps server] ignoring invalid "
                     "BYTEPS_TPU_RING_VNODES=%s (want 1..4096)\n", vn);
    }
    if (ring_armed_) {
      // Peer address book: BYTEPS_TPU_RING_PEERS="host:port,host:port"
      // (index = server id), else the single-host convention the workers
      // use — 127.0.0.1:(DMLC_PS_ROOT_PORT + 1 + id) for the
      // DMLC_NUM_SERVER launch servers.  First-seen addresses are
      // sticky: a worker-proposed RING_SET can never redirect
      // server-to-server migrations through a worker-side chaos proxy.
      const char* root = std::getenv("DMLC_PS_ROOT_PORT");
      int root_port = root && root[0] ? std::atoi(root) : 9000;
      const char* ns = std::getenv("DMLC_NUM_SERVER");
      int num_server = ns && ns[0] ? std::atoi(ns) : 1;
      const char* peers = std::getenv("BYTEPS_TPU_RING_PEERS");
      if (peers && peers[0]) {
        std::string s(peers);
        size_t pos = 0;
        uint32_t id = 0;
        while (pos <= s.size()) {
          size_t comma = s.find(',', pos);
          std::string one = s.substr(
              pos, comma == std::string::npos ? std::string::npos
                                              : comma - pos);
          size_t colon = one.rfind(':');
          if (colon != std::string::npos)
            peer_book_[id++] = {one.substr(0, colon),
                                std::atoi(one.c_str() + colon + 1)};
          if (comma == std::string::npos) break;
          pos = comma + 1;
        }
      } else {
        for (int i = 0; i < num_server; ++i)
          peer_book_[static_cast<uint32_t>(i)] =
              {"127.0.0.1", root_port + 1 + i};
      }
      // Advertised address for migrations TO this server (the joiner
      // announces it in its RING_SET).
      advertise_host_ = "127.0.0.1";
      advertise_port_ = port_;
      const char* adv = std::getenv("BYTEPS_TPU_RING_ADVERTISE");
      if (adv && adv[0]) {
        std::string a(adv);
        size_t colon = a.rfind(':');
        if (colon != std::string::npos) {
          advertise_host_ = a.substr(0, colon);
          advertise_port_ = std::atoi(a.c_str() + colon + 1);
        }
      }
      if (!ring_join_) {
        // Launch ring, epoch 0: the DMLC_NUM_SERVER launch set.  The
        // epoch mirror stays 0, so ownership is NOT enforced yet —
        // workers armed with the same law already place by this ring,
        // and enforcement only matters once a transition can strand a
        // frame on a stale owner.
        for (auto& kv : peer_book_)
          ring_members_.push_back(
              RingServer{kv.first, kv.second.first, kv.second.second});
        RebuildRingPointsLocked();
      }
    }
  }

  int Run() {
    listen_fd_ = socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd_ < 0) return 1;
    int one = 1;
    setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = htons(static_cast<uint16_t>(port_));
    if (bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr),
             sizeof(addr)) != 0)
      return 2;
    if (listen(listen_fd_, 64) != 0) return 3;

    for (int i = 0; i < engine_threads_; ++i)
      engines_.emplace_back(&Server::EngineLoop, this, i);

    // Lease scanner (elastic eviction), armed only by the env knob — a
    // fixed-membership server runs zero extra threads.
    std::thread lease_thread;
    if (evict_timeout_s_ > 0.0)
      lease_thread = std::thread(&Server::LeaseLoop, this);

    // Optional AF_UNIX listener for colocated workers (see ctor): its
    // acceptor runs on a side thread feeding the same ReaderLoop — a UDS
    // conn is indistinguishable from a TCP one past accept().
    std::thread uds_acceptor;
    if (!uds_base_.empty()) {
      uds_path_ = uds_base_ + "." + std::to_string(port_);
      sockaddr_un ua{};
      if (uds_path_.size() < sizeof(ua.sun_path)) {
        uds_listen_fd_ = socket(AF_UNIX, SOCK_STREAM, 0);
        if (uds_listen_fd_ >= 0) {
          ua.sun_family = AF_UNIX;
          std::strncpy(ua.sun_path, uds_path_.c_str(),
                       sizeof(ua.sun_path) - 1);
          ::unlink(uds_path_.c_str());   // stale file from a dead server
          if (bind(uds_listen_fd_, reinterpret_cast<sockaddr*>(&ua),
                   sizeof(ua)) == 0 &&
              listen(uds_listen_fd_, 64) == 0) {
            uds_acceptor = std::thread(
                &Server::AcceptLoop, this, uds_listen_fd_, false);
          } else {
            std::fprintf(stderr,
                         "[byteps server] UDS listen at %s failed "
                         "(errno=%d); serving TCP only\n",
                         uds_path_.c_str(), errno);
            close(uds_listen_fd_);
            uds_listen_fd_ = -1;
          }
        }
      } else {
        std::fprintf(stderr,
                     "[byteps server] BYTEPS_TPU_SERVER_UDS path too long "
                     "(%zu chars); serving TCP only\n", uds_path_.size());
      }
    }

    // Joining server: announce once the listeners are up, so migrations
    // streaming back land on a live acceptor.
    std::thread join_thread;
    if (ring_join_) join_thread = std::thread(&Server::JoinLoop, this);

    // Chain-replication sender (BYTEPS_TPU_REPL): drains the per-key
    // newest-blob queue to each key's ring successor off the publish
    // critical path.  Unarmed runs start zero extra threads.
    std::thread repl_thread;
    if (repl_armed_) repl_thread = std::thread(&Server::ReplLoop, this);

    AcceptLoop(listen_fd_, true);
    if (join_thread.joinable()) join_thread.join();
    if (repl_thread.joinable()) {
      // Joined BEFORE the engine queues stop: the replication thread
      // fans kReplFlushTask into them on every ack.
      { std::lock_guard<std::mutex> lk(repl_mu_); }
      repl_cv_.notify_all();
      repl_thread.join();
    }
    if (lease_thread.joinable()) lease_thread.join();
    if (uds_acceptor.joinable()) uds_acceptor.join();
    if (uds_listen_fd_ >= 0) {
      close(uds_listen_fd_);
      ::unlink(uds_path_.c_str());
    }
    for (auto& q : queues_) q.Stop();
    for (auto& t : engines_) t.join();
    {
      // Readers may be blocked in recv() on idle-but-open worker sockets;
      // a half-close unblocks them so the active count can drain.
      std::lock_guard<std::mutex> lk(conns_mu_);
      for (auto* c : conns_)
        if (c->fd >= 0) ::shutdown(c->fd, SHUT_RDWR);
    }
    {
      std::unique_lock<std::mutex> lk(readers_mu_);
      readers_cv_.wait(lk, [&] { return active_readers_ == 0; });
    }
    {
      std::lock_guard<std::mutex> lk(conns_mu_);
      for (auto* c : conns_) {
        if (c->fd >= 0) close(c->fd);
        delete c;
      }
      conns_.clear();
    }
    {
      std::lock_guard<std::mutex> lk(peer_mu_);
      for (auto& kv : peer_fds_) close(kv.second);
      peer_fds_.clear();
    }
    close(listen_fd_);
    return 0;
  }

 private:
  // Accept loop shared by the TCP and UDS listeners: accept, tune, hand
  // the conn to a detached counted reader.  `is_tcp` gates TCP_NODELAY
  // (meaningless on AF_UNIX).
  void AcceptLoop(int lfd, bool is_tcp) {
    int one = 1;
    while (!shutdown_.load()) {
      int fd = accept(lfd, nullptr, nullptr);
      if (fd < 0) {
        // Transient accept failures (fd pressure, aborted handshakes,
        // signals) must not tear down the tier — existing sessions keep
        // training and new connections retry.  Anything else (EBADF from
        // the shutdown path closing the listener) ends the loop.
        if (errno == EINTR || errno == ECONNABORTED || errno == EMFILE ||
            errno == ENFILE || errno == ENOBUFS || errno == ENOMEM) {
          std::this_thread::sleep_for(std::chrono::milliseconds(20));
          continue;
        }
        break;
      }
      if (is_tcp)
        setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      if (sock_buf_bytes_ > 0) {
        // Best-effort: the kernel clamps (and doubles) as it pleases.
        setsockopt(fd, SOL_SOCKET, SO_SNDBUF, &sock_buf_bytes_,
                   sizeof(sock_buf_bytes_));
        setsockopt(fd, SOL_SOCKET, SO_RCVBUF, &sock_buf_bytes_,
                   sizeof(sock_buf_bytes_));
      }
      auto* conn = new Conn();
      conn->fd = fd;
      {
        std::lock_guard<std::mutex> lk(conns_mu_);
        conns_.push_back(conn);
      }
      // Detached, counted: a joinable-but-terminated thread retains its
      // stack until join, so tracking readers in a vector let a rogue
      // connect loop accumulate a zombie stack per attempt (advisor r4).
      // Shutdown synchronizes on the active count instead of join().
      {
        std::lock_guard<std::mutex> lk(readers_mu_);
        ++active_readers_;
      }
      std::thread(&Server::ReaderLoop, this, conn).detach();
    }
  }

  static bool ReadFull(int fd, void* buf, size_t n) {
    char* p = static_cast<char*>(buf);
    while (n > 0) {
      ssize_t r = recv(fd, p, n, 0);
      if (r <= 0) return false;
      p += r;
      n -= static_cast<size_t>(r);
    }
    return true;
  }

  static bool WriteFull(int fd, const void* buf, size_t n) {
    const char* p = static_cast<const char*>(buf);
    while (n > 0) {
      ssize_t r = send(fd, p, n, MSG_NOSIGNAL);
      if (r <= 0) return false;
      p += r;
      n -= static_cast<size_t>(r);
    }
    return true;
  }

  void Respond(Conn* c, uint8_t status, uint32_t req_id, uint64_t key,
               const char* data, uint64_t len) {
    RespondT(c, status, req_id, key, data, len, nullptr, 0);
  }

  // Respond with an optional trailer gathered after the payload (the
  // audited-pull path: payload + 24-byte AuditTrailer ride the one
  // response frame, h.len covering both, with no payload-sized copy).
  void RespondT(Conn* c, uint8_t status, uint32_t req_id, uint64_t key,
                const char* data, uint64_t len, const void* trailer,
                uint64_t tlen) {
    // Member (not static) for the wire-bytes-out stat: counted at frame
    // build time — close enough for an operator-facing gauge, and the
    // alternative (counting the sendmsg return) would misreport dropped
    // peers anyway.
    bytes_out_.fetch_add(sizeof(RespHeader) + len + tlen,
                         std::memory_order_relaxed);
    std::lock_guard<std::mutex> lk(c->write_mu);
    RespHeader h{status, req_id, key, len + tlen};
    // One sendmsg for header+payload(+trailer): separate send() calls
    // under TCP_NODELAY put the 21-byte header on the wire as its own
    // packet (extra syscall + packet + reader wakeup per response on the
    // pull-heavy path).
    iovec iov[3] = {{&h, sizeof(h)}, {nullptr, 0}, {nullptr, 0}};
    int iovcnt = 1;
    if (len)
      iov[iovcnt++] = {const_cast<char*>(data), static_cast<size_t>(len)};
    if (tlen)
      iov[iovcnt++] = {const_cast<void*>(trailer),
                       static_cast<size_t>(tlen)};
    msghdr msg{};
    msg.msg_iov = iov;
    msg.msg_iovlen = iovcnt;
    while (true) {
      ssize_t r = sendmsg(c->fd, &msg, MSG_NOSIGNAL);
      if (r < 0 && errno == EINTR) continue;  // signal mid-frame: resume,
                                              // or the stream desyncs
      if (r <= 0) return;   // peer gone: reader/engine paths tolerate
      size_t done = static_cast<size_t>(r);
      while (msg.msg_iovlen > 0 && done >= msg.msg_iov[0].iov_len) {
        done -= msg.msg_iov[0].iov_len;
        ++msg.msg_iov;
        --msg.msg_iovlen;
      }
      if (msg.msg_iovlen == 0) return;
      msg.msg_iov[0].iov_base =
          static_cast<char*>(msg.msg_iov[0].iov_base) + done;
      msg.msg_iov[0].iov_len -= done;
    }
  }

  // --- conn reference counting (fd lifetime) -------------------------
  // A holder is anything that may Respond on the conn after its reader
  // exits.  Take the ref BEFORE handing the conn to the holder; release
  // AFTER the holder's last write.  The fd closes when the reader has
  // exited and the count drains to zero — no holder remains, so a
  // recycled fd number can never be misdirected.
  static void AddRef(Conn* c) {
    c->refs.fetch_add(1, std::memory_order_relaxed);
  }
  void ReleaseRef(Conn* c) {
    if (c->refs.fetch_sub(1, std::memory_order_acq_rel) == 1 &&
        c->reader_done.load(std::memory_order_acquire))
      MaybeCloseFd(c);
  }
  void MaybeCloseFd(Conn* c) {
    std::lock_guard<std::mutex> lk(conns_mu_);
    if (c->fd >= 0 && c->reader_done.load(std::memory_order_acquire) &&
        c->refs.load(std::memory_order_acquire) == 0) {
      ::close(c->fd);
      c->fd = -1;
    }
  }

  // Key -> engine by least accumulated load (reference: server.h:149-173).
  int EngineFor(uint64_t key, uint64_t bytes) {
    std::lock_guard<std::mutex> lk(assign_mu_);
    auto it = key_engine_.find(key);
    if (it != key_engine_.end()) return it->second;
    int best = 0;
    for (int i = 1; i < engine_threads_; ++i)
      if (engine_load_[i] < engine_load_[best]) best = i;
    engine_load_[best] += bytes;
    key_engine_[key] = best;
    return best;
  }

  // --- CMD_STATS telemetry -------------------------------------------
  // Engine threads fold per-key / per-worker deltas in under stats_mu_
  // (a few int stores per push — noise next to the 4MB f32 merge the
  // same task just did); the reader thread serializes the whole table
  // to JSON under the same mutex.  Kept separate from KeyState on
  // purpose: KeyState is engine-owned and reading it from a reader
  // thread would race the merge loop.
  struct KeyStat {
    uint64_t pushes = 0;          // frames accepted (incl. dups/stale acks)
    uint64_t merges = 0;          // frames actually merged into a round
    uint64_t completed_round = 0; // rounds published
    uint64_t round_pushes = 0;    // workers merged into the OPEN round —
                                  // pending-push depth = num_workers minus
                                  // this (how many pushes the round still
                                  // waits on)
    uint64_t pending_pulls = 0;   // pulls parked for an unpublished round
    uint64_t bytes = 0;           // wire payload bytes pushed
    uint64_t param_version = 0;   // server-opt: published update count
    uint8_t opt_mode = 0;         // server-opt: active optimizer (0=off)
  };
  struct WorkerStat {
    uint64_t pushes = 0;  // accepted merges from this worker
    uint64_t round = 0;   // round position: sync = the round index this
                          // worker is pushing INTO + 1 (so equal workers
                          // report equal numbers); async = push count
  };

  void StatPush(uint64_t key, uint32_t worker, uint64_t wire_bytes,
                bool merged, uint64_t round_pos, uint64_t round_pushes = 0) {
    std::lock_guard<std::mutex> lk(stats_mu_);
    KeyStat& ks = key_stats_[key];
    ks.pushes++;
    if (merged) {
      ks.merges++;
      ks.bytes += wire_bytes;
      ks.round_pushes = round_pushes;
      WorkerStat& ws = worker_stats_[worker];
      ws.pushes++;
      // round_pos = 0 means "no sync round" (async / seed): a worker's
      // progress signal degrades to its accepted-push count there.
      uint64_t rp = round_pos ? round_pos : ws.pushes;
      if (rp > ws.round) ws.round = rp;
    }
  }

  void StatPublish(uint64_t key, uint64_t completed_round) {
    std::lock_guard<std::mutex> lk(stats_mu_);
    KeyStat& ks = key_stats_[key];
    ks.completed_round = completed_round;
    ks.round_pushes = 0;   // fresh round: no one has pushed into it yet
  }

  void StatOpt(uint64_t key, uint64_t param_version, uint8_t opt_mode) {
    std::lock_guard<std::mutex> lk(stats_mu_);
    KeyStat& ks = key_stats_[key];
    ks.param_version = param_version;
    ks.opt_mode = opt_mode;
  }

  void StatPendingPulls(uint64_t key, int64_t delta) {
    std::lock_guard<std::mutex> lk(stats_mu_);
    uint64_t& p = key_stats_[key].pending_pulls;
    p = (delta < 0 && p < static_cast<uint64_t>(-delta))
            ? 0 : p + delta;
  }

  std::string StatsJson() {
    // Worst-case row: the header now carries ~30 numeric fields at up
    // to 20 digits + ~450 chars of labels — keep comfortable headroom
    // (snprintf truncation would silently corrupt the JSON).
    char buf[2048];
    std::string js;
    js.reserve(4096);
    const uint64_t keys_owned = ring_armed_ ? KeysOwned() : 0;
    // Chain-replication gauges: replicas parked for OTHER servers'
    // keys, and the owner-side lag (newest published round minus the
    // successor's acked round, max over keys) — what the doctor's
    // replication_lag rule and bps_repl_lag_rounds watch.
    uint64_t replicas_held = 0, repl_lag = 0;
    if (repl_armed_) {
      std::lock_guard<std::mutex> lk(repl_mu_);
      replicas_held = replicas_.size();
      for (auto& kv : repl_pub_) {
        auto it = repl_ack_.find(kv.first);
        const uint64_t acked = it == repl_ack_.end() ? 0 : it->second;
        if (kv.second > acked && kv.second - acked > repl_lag)
          repl_lag = kv.second - acked;
      }
    }
    // Fleet-plane gauges: worker rings held and total window blobs
    // parked — what bps_top's fleet panel and the elastic-edge tests
    // watch to confirm publishes landed and eviction expired a ring.
    uint64_t fleet_workers = 0, fleet_held = 0;
    if (fleet_armed_) {
      std::lock_guard<std::mutex> lk(fleet_mu_);
      fleet_workers = fleet_rings_.size();
      for (auto& kv : fleet_rings_) fleet_held += kv.second.size();
    }
    std::snprintf(buf, sizeof(buf),
                  "{\"bytes_in\":%llu,\"bytes_out\":%llu,\"async\":%d,"
                  "\"num_workers\":%d,\"scatter_frames\":%llu,"
                  "\"epoch\":%llu,\"deferred_joins\":%llu,"
                  "\"server_id\":%u,\"ring_armed\":%d,\"ring_epoch\":%llu,"
                  "\"draining\":%d,\"keys_owned\":%llu,"
                  "\"migrations_in\":%llu,\"migrations_out\":%llu,"
                  "\"moved_frames\":%llu,\"codec_sets\":%llu,"
                  "\"codec_stale_frames\":%llu,\"opt_sets\":%llu,"
                  "\"opt_updates\":%llu,\"opt_slot_bytes\":%llu,"
                  "\"knob_epoch\":%llu,\"knob_sets\":%llu,"
                  "\"knob_stale_frames\":%llu,"
                  "\"embed_rows_served\":%llu,"
                  "\"embed_table_bytes\":%llu,"
                  "\"repl_armed\":%d,\"repl_rounds_out\":%llu,"
                  "\"repl_bytes_out\":%llu,\"repl_rounds_in\":%llu,"
                  "\"repl_bytes_in\":%llu,\"repl_replicas_held\":%llu,"
                  "\"repl_promotions\":%llu,\"repl_lag_rounds\":%llu,"
                  "\"fleet_armed\":%d,\"fleet_workers\":%llu,"
                  "\"fleet_windows_held\":%llu,\"fleet_publishes\":%llu,"
                  "\"slice_size\":%d,\"keys\":{",
                  static_cast<unsigned long long>(
                      bytes_in_.load(std::memory_order_relaxed)),
                  static_cast<unsigned long long>(
                      bytes_out_.load(std::memory_order_relaxed)),
                  async_ ? 1 : 0, num_workers_,
                  static_cast<unsigned long long>(
                      scatter_frames_.load(std::memory_order_relaxed)),
                  static_cast<unsigned long long>(
                      epoch_atomic_.load(std::memory_order_acquire)),
                  static_cast<unsigned long long>(
                      deferred_joins_.load(std::memory_order_relaxed)),
                  my_server_id_, ring_armed_ ? 1 : 0,
                  static_cast<unsigned long long>(
                      ring_epoch_atomic_.load(std::memory_order_acquire)),
                  draining_ ? 1 : 0,
                  static_cast<unsigned long long>(keys_owned),
                  static_cast<unsigned long long>(
                      migrations_in_.load(std::memory_order_relaxed)),
                  static_cast<unsigned long long>(
                      migrations_out_.load(std::memory_order_relaxed)),
                  static_cast<unsigned long long>(
                      moved_frames_.load(std::memory_order_relaxed)),
                  static_cast<unsigned long long>(
                      codec_sets_.load(std::memory_order_relaxed)),
                  static_cast<unsigned long long>(
                      codec_stale_.load(std::memory_order_relaxed)),
                  static_cast<unsigned long long>(
                      opt_sets_.load(std::memory_order_relaxed)),
                  static_cast<unsigned long long>(
                      opt_updates_.load(std::memory_order_relaxed)),
                  static_cast<unsigned long long>(
                      opt_slot_bytes_.load(std::memory_order_relaxed)),
                  static_cast<unsigned long long>(
                      knob_epoch_atomic_.load(std::memory_order_acquire)),
                  static_cast<unsigned long long>(
                      knob_sets_.load(std::memory_order_relaxed)),
                  static_cast<unsigned long long>(
                      knob_stale_.load(std::memory_order_relaxed)),
                  static_cast<unsigned long long>(
                      embed_rows_served_.load(std::memory_order_relaxed)),
                  static_cast<unsigned long long>(
                      embed_table_bytes_.load(std::memory_order_relaxed)),
                  repl_armed_ ? 1 : 0,
                  static_cast<unsigned long long>(
                      repl_rounds_out_.load(std::memory_order_relaxed)),
                  static_cast<unsigned long long>(
                      repl_bytes_out_.load(std::memory_order_relaxed)),
                  static_cast<unsigned long long>(
                      repl_rounds_in_.load(std::memory_order_relaxed)),
                  static_cast<unsigned long long>(
                      repl_bytes_in_.load(std::memory_order_relaxed)),
                  static_cast<unsigned long long>(replicas_held),
                  static_cast<unsigned long long>(
                      repl_promotions_.load(std::memory_order_relaxed)),
                  static_cast<unsigned long long>(repl_lag),
                  fleet_armed_ ? 1 : 0,
                  static_cast<unsigned long long>(fleet_workers),
                  static_cast<unsigned long long>(fleet_held),
                  static_cast<unsigned long long>(
                      fleet_publishes_.load(std::memory_order_relaxed)),
                  slice_size_);
    js += buf;
    std::lock_guard<std::mutex> lk(stats_mu_);
    bool first = true;
    for (auto& kv : key_stats_) {
      std::snprintf(buf, sizeof(buf),
                    "%s\"%llu\":{\"pushes\":%llu,\"merges\":%llu,"
                    "\"completed_round\":%llu,\"round_pushes\":%llu,"
                    "\"pending_pulls\":%llu,\"bytes\":%llu,"
                    "\"param_version\":%llu,\"opt_mode\":%u}",
                    first ? "" : ",",
                    static_cast<unsigned long long>(kv.first),
                    static_cast<unsigned long long>(kv.second.pushes),
                    static_cast<unsigned long long>(kv.second.merges),
                    static_cast<unsigned long long>(
                        kv.second.completed_round),
                    static_cast<unsigned long long>(
                        kv.second.round_pushes),
                    static_cast<unsigned long long>(
                        kv.second.pending_pulls),
                    static_cast<unsigned long long>(kv.second.bytes),
                    static_cast<unsigned long long>(
                        kv.second.param_version),
                    static_cast<unsigned>(kv.second.opt_mode));
      js += buf;
      first = false;
    }
    js += "},\"workers\":{";
    first = true;
    for (auto& kv : worker_stats_) {
      std::snprintf(buf, sizeof(buf),
                    "%s\"%u\":{\"pushes\":%llu,\"round\":%llu}",
                    first ? "" : ",", kv.first,
                    static_cast<unsigned long long>(kv.second.pushes),
                    static_cast<unsigned long long>(kv.second.round));
      js += buf;
      first = false;
    }
    // Membership view (epoch-versioned worker set + lease ages) so one
    // CMD_STATS poll carries the whole liveness story.  member_mu_ nests
    // inside stats_mu_ here and nowhere takes them in the other order.
    js += "},\"members\":{";
    {
      const int64_t now = NowUs();
      std::lock_guard<std::mutex> mlk(member_mu_);
      first = true;
      for (auto& kv : members_) {
        std::snprintf(buf, sizeof(buf),
                      "%s\"%u\":{\"alive\":%d,\"age_ms\":%lld}",
                      first ? "" : ",", kv.first,
                      kv.second.alive ? 1 : 0,
                      static_cast<long long>(
                          (now - kv.second.last_seen_us) / 1000));
        js += buf;
        first = false;
      }
    }
    js += "}}";
    return js;
  }

  // --- CMD_AUDIT: publish-digest window ------------------------------
  // The last-K (round, digest, epoch, contributors) records per key,
  // appended by PublishRound under audit_mu_ (a handful of ints + the
  // contributor ids per publish — noise next to the digest pass itself),
  // serialized by the reader thread here.  Shape:
  //   {"armed":1,"window":K,"epoch":E,"ring_epoch":R,
  //    "keys":{"<key>":[{"r":round,"d":digest,"e":epoch,"w":[ids]},...]}}
  std::string AuditJson() {
    char buf[256];
    std::string js;
    js.reserve(2048);
    std::snprintf(buf, sizeof(buf),
                  "{\"armed\":%d,\"window\":%d,\"epoch\":%llu,"
                  "\"ring_epoch\":%llu,\"keys\":{",
                  audit_armed_ ? 1 : 0, audit_window_,
                  static_cast<unsigned long long>(
                      epoch_atomic_.load(std::memory_order_acquire)),
                  static_cast<unsigned long long>(
                      ring_epoch_atomic_.load(std::memory_order_acquire)));
    js += buf;
    std::lock_guard<std::mutex> lk(audit_mu_);
    bool first_key = true;
    for (auto& kv : audit_log_) {
      std::snprintf(buf, sizeof(buf), "%s\"%llu\":[",
                    first_key ? "" : ",",
                    static_cast<unsigned long long>(kv.first));
      js += buf;
      first_key = false;
      bool first_rec = true;
      for (auto& rec : kv.second) {
        std::snprintf(buf, sizeof(buf),
                      "%s{\"r\":%llu,\"d\":%llu,\"e\":%llu,\"w\":[",
                      first_rec ? "" : ",",
                      static_cast<unsigned long long>(rec.round),
                      static_cast<unsigned long long>(rec.digest),
                      static_cast<unsigned long long>(rec.epoch));
        js += buf;
        first_rec = false;
        bool first_w = true;
        for (uint32_t w : rec.who) {
          std::snprintf(buf, sizeof(buf), "%s%u", first_w ? "" : ",", w);
          js += buf;
          first_w = false;
        }
        js += "]}";
      }
      js += "]";
    }
    js += "}}";
    return js;
  }

  // Merged fleet view (CMD_FLEET): per-worker rings as JSON arrays of
  // the raw worker-published window summaries, ordered by window index.
  // The server splices blobs verbatim — it never parses worker JSON —
  // so a malformed publish can corrupt only its own row, which the
  // Python merge side skips (the same trust boundary as CMD_STATS keys).
  std::string FleetJson() {
    if (!fleet_armed_) return "{\"armed\":0}";
    char buf[128];
    std::string js;
    js.reserve(4096);
    std::snprintf(buf, sizeof(buf),
                  "{\"armed\":1,\"cap\":%d,\"server_id\":%u,"
                  "\"workers\":{", fleet_windows_, my_server_id_);
    js += buf;
    std::lock_guard<std::mutex> lk(fleet_mu_);
    bool first_w = true;
    for (auto& kv : fleet_rings_) {
      std::snprintf(buf, sizeof(buf), "%s\"%u\":[",
                    first_w ? "" : ",", kv.first);
      js += buf;
      first_w = false;
      bool first_e = true;
      for (auto& e : kv.second) {
        if (!first_e) js += ",";
        js += e.second;
        first_e = false;
      }
      js += "]";
    }
    js += "}}";
    return js;
  }

  // --- elastic membership --------------------------------------------
  // The worker set is epoch-versioned: every join (HELLO from a non-live
  // id), graceful leave (CMD_LEAVE) and lease eviction bumps `epoch_` and
  // fans a snapshot task out to every engine (per-key round state is
  // engine-owned).  Fixed-membership runs never transition, epoch stays
  // 0, and every data-path check short-circuits on the atomic mirror —
  // the wire and the merge math are untouched.
  struct MemberRec {
    int64_t last_seen_us = 0;
    bool alive = false;
  };

  // Lease refresh: any frame from a live member renews it.  Non-members
  // are ignored — only HELLO admits (a stray frame from a rogue id must
  // not silently grow the world).
  void TouchWorker(uint32_t worker) {
    // Fixed-mode fast path: with eviction unarmed and the epoch never
    // advanced, nothing consumes leases — skip the clock read and the
    // lock so the per-frame hot path is exactly as cheap as before this
    // feature (CMD_STATS ages then read as time-since-launch, which is
    // documented and has no liveness consumer at epoch 0).
    if (evict_timeout_s_ <= 0.0 &&
        epoch_atomic_.load(std::memory_order_relaxed) == 0)
      return;
    std::lock_guard<std::mutex> lk(member_mu_);
    auto it = members_.find(worker);
    if (it != members_.end() && it->second.alive)
      it->second.last_seen_us = NowUs();
  }

  // HELLO admission: a non-live id joins the membership at the next
  // epoch boundary (each key's next round snapshots the new set).  A
  // live member's HELLO — every fixed-mode session start, and every
  // reconnect handshake — is a lease touch, nothing more.
  void AdmitWorker(uint32_t worker) {
    std::vector<uint32_t> old_live, removed;
    {
      std::lock_guard<std::mutex> lk(member_mu_);
      MemberRec& m = members_[worker];
      m.last_seen_us = NowUs();
      if (m.alive) return;
      for (auto& kv : members_)
        if (kv.second.alive) old_live.push_back(kv.first);
      m.alive = true;
      ++epoch_;
      epoch_atomic_.store(epoch_, std::memory_order_release);
      std::fprintf(stderr,
                   "[byteps server] worker %u joined; membership epoch %llu"
                   " (%zu live)\n", worker,
                   static_cast<unsigned long long>(epoch_),
                   old_live.size() + 1);
    }
    FanOutMembership(old_live, removed, /*refinalize=*/false);
    RecheckBarriers();
  }

  // Leave/evict: remove a live member at an epoch boundary and
  // re-finalize open rounds against the survivors.  The last live worker
  // is never removed — evicting the whole world helps no one, and a
  // paused single-worker job must stay resumable.
  void RemoveWorker(uint32_t worker, const char* why) {
    std::vector<uint32_t> old_live, removed;
    {
      std::lock_guard<std::mutex> lk(member_mu_);
      auto it = members_.find(worker);
      if (it == members_.end() || !it->second.alive) return;
      int live = 0;
      for (auto& kv : members_)
        if (kv.second.alive) {
          ++live;
          old_live.push_back(kv.first);
        }
      if (live <= 1) {
        std::fprintf(stderr,
                     "[byteps server] not removing worker %u (%s): it is "
                     "the last live member\n", worker, why);
        return;
      }
      it->second.alive = false;
      removed.push_back(worker);
      ++epoch_;
      epoch_atomic_.store(epoch_, std::memory_order_release);
      std::fprintf(stderr,
                   "[byteps server] worker %u removed (%s); membership "
                   "epoch %llu (%d live)\n", worker, why,
                   static_cast<unsigned long long>(epoch_), live - 1);
    }
    FanOutMembership(old_live, removed, /*refinalize=*/true);
    RecheckBarriers();
    // Expire the evicted worker's fleet ring: a departed worker must
    // drop out of the merged CMD_FLEET view (its stale windows would
    // otherwise pin fleet rules on a ghost forever).  fleet_mu_ is a
    // leaf lock — never taken while holding member_mu_.
    if (fleet_armed_ && !removed.empty()) {
      std::lock_guard<std::mutex> lk(fleet_mu_);
      for (uint32_t w : removed) fleet_rings_.erase(w);
    }
  }

  int LiveCount() {
    std::lock_guard<std::mutex> lk(member_mu_);
    int n = 0;
    for (auto& kv : members_)
      if (kv.second.alive) ++n;
    return n;
  }

  std::vector<uint32_t> LiveWorkers() {
    std::lock_guard<std::mutex> lk(member_mu_);
    std::vector<uint32_t> out;
    for (auto& kv : members_)
      if (kv.second.alive) out.push_back(kv.first);
    return out;
  }

  // Identity-based barrier completion: a generation releases when every
  // LIVE worker has arrived.  Arrival COUNT is not enough under
  // elasticity — an evicted worker's stale arrival would otherwise fill
  // the shrunken bar and release the group while a live worker is still
  // on its way, stranding it in a fresh group forever.
  static bool BarrierGroupComplete(const std::vector<PendingPull>& group,
                                   const std::vector<uint32_t>& live) {
    std::set<uint32_t> arrived;
    for (const auto& w : group) arrived.insert(w.worker);
    for (uint32_t w : live)
      if (!arrived.count(w)) return false;
    return true;
  }

  // Snapshot the live set into a key's round_members — the per-round
  // epoch boundary.  Called at each round's first push once the epoch
  // has ever advanced (epoch 0 keeps the legacy count-based completion).
  void AdoptRoundMembers(KeyState& ks) {
    std::lock_guard<std::mutex> lk(member_mu_);
    ks.round_members.clear();
    for (auto& kv : members_)
      if (kv.second.alive) ks.round_members.insert(kv.first);
  }

  // One transition task per engine, payload self-contained:
  //   u8 refinalize | u32 n_old | u32 old_ids[] | u32 n_rm | u32 rm_ids[]
  // old_ids = the live set BEFORE the transition (pins still-open
  // epoch-0 rounds to the set they opened under); rm_ids = departures to
  // erase from every open round's contributor set.
  void FanOutMembership(const std::vector<uint32_t>& old_live,
                        const std::vector<uint32_t>& removed,
                        bool refinalize) {
    std::vector<char> payload(1 + 4 + old_live.size() * 4 +
                              4 + removed.size() * 4);
    char* p = payload.data();
    p[0] = refinalize ? 1 : 0;
    uint32_t n = static_cast<uint32_t>(old_live.size());
    std::memcpy(p + 1, &n, 4);
    std::memcpy(p + 5, old_live.data(), old_live.size() * 4);
    uint32_t m = static_cast<uint32_t>(removed.size());
    std::memcpy(p + 5 + old_live.size() * 4, &m, 4);
    std::memcpy(p + 9 + old_live.size() * 4, removed.data(),
                removed.size() * 4);
    for (int i = 0; i < engine_threads_; ++i) {
      Task t;
      t.cmd = kMembershipTask;
      t.dtype = 0;
      t.flags = 0;
      t.req_id = 0;
      t.worker_id = 0;
      t.key = 0;
      t.payload = payload;   // copy per engine
      t.conn = nullptr;
      t.seq = seq_.fetch_add(1);
      t.priority = UINT64_MAX;   // jump queued pushes, like kLrScale
      queues_[i].Push(std::move(t));
    }
  }

  // A shrink can complete a barrier the departed worker would never
  // reach; a grow raises the bar for groups still filling.  Like
  // HandleBarrier, the live set is read inside barrier_mu_ so the check
  // and the release are atomic against further transitions.
  void RecheckBarriers() {
    std::vector<PendingPull> to_release;
    {
      std::lock_guard<std::mutex> lk(barrier_mu_);
      const std::vector<uint32_t> live = LiveWorkers();
      for (auto it = barrier_waiters_.begin();
           it != barrier_waiters_.end();) {
        if (BarrierGroupComplete(it->second, live)) {
          for (auto& w : it->second) to_release.push_back(w);
          released_gens_.insert(it->first);
          it = barrier_waiters_.erase(it);
        } else {
          ++it;
        }
      }
    }
    for (auto& w : to_release) {
      Respond(w.conn, kOk, w.req_id, w.key, nullptr, 0);
      ReleaseRef(w.conn);
    }
  }

  // CMD_MEMBERS JSON: epoch, per-worker alive + last-seen age, and which
  // ids have arrived at each pending barrier generation (the "who is the
  // barrier waiting on" half of the diagnostic).
  std::string MembersJson() {
    char buf[160];
    std::string js;
    js.reserve(512);
    const int64_t now = NowUs();
    {
      std::lock_guard<std::mutex> lk(member_mu_);
      std::snprintf(buf, sizeof(buf),
                    "{\"epoch\":%llu,\"members\":{",
                    static_cast<unsigned long long>(epoch_));
      js += buf;
      bool first = true;
      for (auto& kv : members_) {
        std::snprintf(buf, sizeof(buf),
                      "%s\"%u\":{\"alive\":%d,\"age_ms\":%lld}",
                      first ? "" : ",", kv.first,
                      kv.second.alive ? 1 : 0,
                      static_cast<long long>(
                          (now - kv.second.last_seen_us) / 1000));
        js += buf;
        first = false;
      }
    }
    js += "},\"barrier\":{";
    {
      std::lock_guard<std::mutex> lk(barrier_mu_);
      bool first = true;
      for (auto& kv : barrier_waiters_) {
        std::snprintf(buf, sizeof(buf), "%s\"%llu\":[",
                      first ? "" : ",",
                      static_cast<unsigned long long>(kv.first));
        js += buf;
        for (size_t i = 0; i < kv.second.size(); ++i) {
          std::snprintf(buf, sizeof(buf), "%s%u", i ? "," : "",
                        kv.second[i].worker);
          js += buf;
        }
        js += "]";
        first = false;
      }
    }
    js += "}}";
    return js;
  }

  // Lease scanner (armed only when BYTEPS_TPU_EVICT_TIMEOUT_S > 0): a
  // live member silent past the timeout is evicted.  Workers keep the
  // lease warm with data traffic, or — when idle — the client-side
  // heartbeat PING the same knob arms (client.py _lease_loop).
  void LeaseLoop() {
    const int64_t timeout_us =
        static_cast<int64_t>(evict_timeout_s_ * 1e6);
    const int64_t scan_us =
        std::max<int64_t>(20000, std::min<int64_t>(timeout_us / 4,
                                                   1000000));
    while (!shutdown_.load()) {
      std::this_thread::sleep_for(std::chrono::microseconds(scan_us));
      const int64_t now = NowUs();
      std::vector<std::pair<int64_t, uint32_t>> expired;  // (last_seen, id)
      {
        std::lock_guard<std::mutex> lk(member_mu_);
        for (auto& kv : members_)
          if (kv.second.alive &&
              now - kv.second.last_seen_us > timeout_us)
            expired.emplace_back(kv.second.last_seen_us, kv.first);
      }
      // Most-stale first: when several leases lapse in one scan (e.g. a
      // heartbeat hiccup), the worker silent the LONGEST is the dead one
      // — and the last-live guard then protects the rest.
      std::sort(expired.begin(), expired.end());
      for (auto& e : expired)
        RemoveWorker(e.second, "lease expired");  // last-live guard inside
    }
  }

  // --- elastic PS ring ------------------------------------------------
  // The server tier's own membership: an epoch-versioned consistent-hash
  // ring (see the `ring` namespace for the shared law).  Transitions are
  // CMD_RING_SET/CMD_DRAIN writes carrying the full next-epoch table;
  // applied tables fan a reshard task per engine so owned-but-no-longer-
  // mine keys stream their state to the new owner (CMD_MIGRATE) before
  // any redirect is issued — state-before-redirect is what makes drain
  // and scale-up exact.  ring_epoch_atomic_ mirrors the epoch for the
  // lock-free fixed-mode short-circuit on the data path.
  struct RingServer {
    uint32_t id;
    std::string host;
    int port;
  };

  void RebuildRingPointsLocked() {
    auto pts = std::make_shared<
        std::vector<std::pair<uint64_t, uint32_t>>>();
    for (auto& m : ring_members_)
      for (int v = 0; v < ring_vnodes_; ++v)
        pts->emplace_back(
            ring::VnodePoint(m.id, static_cast<uint32_t>(v)), m.id);
    std::sort(pts->begin(), pts->end());
    // Published via atomic shared_ptr so the PER-FRAME ownership check
    // never takes ring_mu_: after the first transition every
    // INIT/PUSH/PULL consults the table, and serializing all engines
    // through one mutex for the rest of the run would undo the epoch-0
    // fast path's whole point.
    std::shared_ptr<const std::vector<std::pair<uint64_t, uint32_t>>>
        cpts = std::move(pts);
    std::atomic_store_explicit(&ring_points_, std::move(cpts),
                               std::memory_order_release);
    // Successor table for chain replication: the same point set MINUS
    // this server's own vnodes, so Owner(key, repl_points) is the next
    // distinct server clockwise of the key — exactly who inherits the
    // key if this owner dies.  Published the same lock-free way; empty
    // on a single-member ring (ReplEnqueue then self-acks).
    auto rpts = std::make_shared<
        std::vector<std::pair<uint64_t, uint32_t>>>();
    for (auto& m : ring_members_) {
      if (m.id == my_server_id_) continue;
      for (int v = 0; v < ring_vnodes_; ++v)
        rpts->emplace_back(
            ring::VnodePoint(m.id, static_cast<uint32_t>(v)), m.id);
    }
    std::sort(rpts->begin(), rpts->end());
    std::shared_ptr<const std::vector<std::pair<uint64_t, uint32_t>>>
        crpts = std::move(rpts);
    std::atomic_store_explicit(&repl_points_, std::move(crpts),
                               std::memory_order_release);
  }

  std::shared_ptr<const std::vector<std::pair<uint64_t, uint32_t>>>
  RingPoints() {
    return std::atomic_load_explicit(&ring_points_,
                                     std::memory_order_acquire);
  }

  std::shared_ptr<const std::vector<std::pair<uint64_t, uint32_t>>>
  ReplPoints() {
    return std::atomic_load_explicit(&repl_points_,
                                     std::memory_order_acquire);
  }

  // True when this server must NOT process frames for `key` (the ring
  // has advanced and another server owns it — or this server is
  // draining, in which case it is no longer a member at all).  The data
  // path pays one atomic load until the first transition, and a
  // lock-free point-table read plus one binary search after it.
  bool RingMisplaced(uint64_t key) {
    if (!ring_armed_) return false;
    if (ring_epoch_atomic_.load(std::memory_order_acquire) == 0)
      return false;
    auto pts = RingPoints();
    if (!pts || pts->empty()) return false;
    return ring::Owner(key, *pts) != my_server_id_;
  }

  uint64_t KeysOwned() {
    std::lock_guard<std::mutex> lk(store_mu_);
    uint64_t n = 0;
    for (auto& kv : store_)
      if (kv.second.active.load(std::memory_order_relaxed)) ++n;
    return n;
  }

  // Ring table as JSON (CMD_RING response and every kMoved payload).
  // `include_owned=false` skips the full-store KeysOwned() scan — the
  // kMoved path emits this per redirected frame, and clients never read
  // keys_owned from a MOVED payload (only CMD_RING polls do).
  std::string RingJson(bool include_owned = true) {
    const uint64_t owned = include_owned ? KeysOwned() : 0;
    char buf[512];                        // store_mu_ released before
    //                                       ring_mu_ — never nested.
    // 512 covers the worst-case row (a 255-byte host + labels) and the
    // worst-case header; snprintf truncation would silently corrupt the
    // JSON every worker redirect depends on.
    std::string js;
    js.reserve(256);
    std::lock_guard<std::mutex> lk(ring_mu_);
    std::snprintf(buf, sizeof(buf),
                  "{\"epoch\":%llu,\"vnodes\":%d,\"armed\":%d,"
                  "\"draining\":%d,\"server_id\":%u,\"keys_owned\":%llu,"
                  "\"migrations_in\":%llu,\"migrations_out\":%llu,"
                  "\"servers\":[",
                  static_cast<unsigned long long>(ring_epoch_),
                  ring_vnodes_, ring_armed_ ? 1 : 0, draining_ ? 1 : 0,
                  my_server_id_, static_cast<unsigned long long>(owned),
                  static_cast<unsigned long long>(
                      migrations_in_.load(std::memory_order_relaxed)),
                  static_cast<unsigned long long>(
                      migrations_out_.load(std::memory_order_relaxed)));
    js += buf;
    bool first = true;
    for (auto& m : ring_members_) {
      std::snprintf(buf, sizeof(buf),
                    "%s{\"id\":%u,\"host\":\"%s\",\"port\":%d}",
                    first ? "" : ",", m.id, m.host.c_str(), m.port);
      js += buf;
      first = false;
    }
    js += "]}";
    return js;
  }

  // Binary ring table (the CMD_RING_SET payload format,
  // common/ring.py RingTable.to_wire): u64 epoch | u32 vnodes | u32 n |
  // n x (u32 id | u16 port | u8 host_len | host).  Shared by the
  // joiner's peer read (CMD_RING flags bit0) and the write parse.
  std::string RingWire() {
    std::lock_guard<std::mutex> lk(ring_mu_);
    std::string out;
    char hdr[16];
    uint64_t ep = ring_epoch_;
    uint32_t vn = static_cast<uint32_t>(ring_vnodes_);
    uint32_t n = static_cast<uint32_t>(ring_members_.size());
    std::memcpy(hdr, &ep, 8);
    std::memcpy(hdr + 8, &vn, 4);
    std::memcpy(hdr + 12, &n, 4);
    out.append(hdr, 16);
    for (auto& m : ring_members_) {
      char row[7];
      uint16_t p16 = static_cast<uint16_t>(m.port);
      uint8_t hl = static_cast<uint8_t>(
          std::min<size_t>(m.host.size(), 255));
      std::memcpy(row, &m.id, 4);
      std::memcpy(row + 4, &p16, 2);
      row[6] = static_cast<char>(hl);
      out.append(row, 7);
      out.append(m.host.data(), hl);
    }
    return out;
  }

  bool ParseRingWire(const std::vector<char>& p, uint64_t* epoch,
                     uint32_t* vnodes, std::vector<RingServer>* out) {
    if (p.size() < 16) return false;
    uint32_t n = 0;
    std::memcpy(epoch, p.data(), 8);
    std::memcpy(vnodes, p.data() + 8, 4);
    std::memcpy(&n, p.data() + 12, 4);
    if (n == 0 || n > 4096 || *vnodes == 0 || *vnodes > 4096) return false;
    size_t pos = 16;
    for (uint32_t i = 0; i < n; ++i) {
      if (pos + 7 > p.size()) return false;
      RingServer s;
      uint16_t p16 = 0;
      std::memcpy(&s.id, p.data() + pos, 4);
      std::memcpy(&p16, p.data() + pos + 4, 2);
      uint8_t hl = static_cast<uint8_t>(p[pos + 6]);
      pos += 7;
      if (pos + hl > p.size()) return false;
      s.host.assign(p.data() + pos, hl);
      s.port = p16;
      pos += hl;
      out->push_back(std::move(s));
    }
    return true;
  }

  // Apply a proposed ring table.  Only a NEWER epoch lands (racing
  // proposers of the same transition are idempotent; a stale proposer
  // reads the authoritative table back from the response).  Known
  // server ids keep their first-seen (peer-book) address — proposals
  // travel through workers, whose dial addresses may be test proxies —
  // and unknown ids (the joiner) are adopted into the book.  Applying
  // fans a reshard task to every engine.
  bool ApplyRing(uint64_t epoch, uint32_t vnodes,
                 std::vector<RingServer> servers, bool make_draining) {
    {
      std::lock_guard<std::mutex> lk(ring_mu_);
      if (epoch <= ring_epoch_) return false;
      for (auto& s : servers) {
        auto it = peer_book_.find(s.id);
        if (it != peer_book_.end()) {
          s.host = it->second.first;
          s.port = it->second.second;
        } else {
          peer_book_[s.id] = {s.host, s.port};
        }
      }
      ring_epoch_ = epoch;
      ring_vnodes_ = static_cast<int>(vnodes);
      ring_members_ = std::move(servers);
      if (make_draining) draining_.store(true, std::memory_order_relaxed);
      RebuildRingPointsLocked();
      if (repl_armed_) ReplSweepLocked();
      ring_epoch_atomic_.store(ring_epoch_, std::memory_order_release);
      bool member = false;
      for (auto& m : ring_members_)
        if (m.id == my_server_id_) member = true;
      std::fprintf(stderr,
                   "[byteps server] ring epoch %llu applied: %zu member(s)"
                   "%s%s\n",
                   static_cast<unsigned long long>(ring_epoch_),
                   ring_members_.size(),
                   member ? "" : " (this server excluded)",
                   draining_.load() ? " [draining]" : "");
    }
    // Reshard fan-out: each engine migrates ITS keys that now belong to
    // another live server — max priority so the handoff jumps queued
    // pushes (which would be kMoved-redirected anyway).
    for (int i = 0; i < engine_threads_; ++i) {
      Task t;
      t.cmd = kRingTask;
      t.dtype = 0;
      t.flags = 0;
      t.req_id = 0;
      t.worker_id = 0;
      t.key = 0;
      t.conn = nullptr;
      t.seq = seq_.fetch_add(1);
      t.priority = UINT64_MAX;
      queues_[i].Push(std::move(t));
    }
    return true;
  }

  // --- server->server peer transport (migrations) ---------------------
  // One cached blocking connection per peer, serialized by peer_mu_ —
  // migrations are rare (ring transitions only) and strictly ordered,
  // so a single in-flight request at a time is plenty and keeps the
  // path free of multiplexing machinery.
  int DialPeer(const std::string& host, int port) {
    int fd = socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return -1;
    timeval tv{30, 0};
    setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
    setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (inet_pton(AF_INET, host.c_str(), &addr.sin_addr) != 1) {
      close(fd);
      return -1;
    }
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    if (connect(fd, reinterpret_cast<sockaddr*>(&addr),
                sizeof(addr)) != 0) {
      close(fd);
      return -1;
    }
    return fd;
  }

  // Blocking request/response to a peer server.  worker_id 0xFFFFFFFF:
  // never a member id, so peer traffic cannot refresh worker leases.
  // `resp` (optional) receives the response payload.  One retry on a
  // stale cached fd (peer restarted between migrations).
  bool PeerRequest(uint32_t id, const std::string& host, int port,
                   uint8_t cmd, uint16_t flags, uint64_t key,
                   const char* payload, uint64_t len,
                   std::vector<char>* resp = nullptr) {
    std::lock_guard<std::mutex> lk(peer_mu_);
    // Negative cache: a peer that just failed (dead joiner, partition)
    // is not re-dialed for 2s — without this, EVERY misplaced frame for
    // its keys would block its engine thread in connect() for up to the
    // socket timeout, head-of-line-stalling healthy keys on the same
    // engine.  Callers treat the fast false as "migration failed" and
    // answer kError (exact-or-loud).
    {
      auto it = peer_down_until_us_.find(id);
      if (it != peer_down_until_us_.end()) {
        if (NowUs() < it->second) return false;
        peer_down_until_us_.erase(it);
      }
    }
    for (int attempt = 0; attempt < 2; ++attempt) {
      int fd = -1;
      auto it = peer_fds_.find(id);
      if (it != peer_fds_.end()) fd = it->second;
      bool fresh = fd < 0;
      if (fd < 0) {
        fd = DialPeer(host, port);
        if (fd < 0) {
          peer_down_until_us_[id] = NowUs() + 2000000;
          return false;
        }
        peer_fds_[id] = fd;
      }
      ReqHeader h{cmd, 0, flags, 0, 0xFFFFFFFFu, key, len};
      bool ok = WriteFull(fd, &h, sizeof(h)) &&
                (len == 0 || WriteFull(fd, payload, len));
      RespHeader rh{};
      ok = ok && ReadFull(fd, &rh, sizeof(rh));
      if (ok && rh.len > 0) {
        if (rh.len > max_msg_) ok = false;
        else {
          std::vector<char> body(rh.len);
          ok = ReadFull(fd, body.data(), rh.len);
          if (ok && resp) *resp = std::move(body);
        }
      }
      if (ok) return rh.status == kOk;
      close(fd);
      peer_fds_.erase(id);
      if (fresh) {               // a brand-new dial failing won't heal
        peer_down_until_us_[id] = NowUs() + 2000000;
        return false;
      }
    }
    peer_down_until_us_[id] = NowUs() + 2000000;
    return false;
  }

  // Serialize one key's full merge state for CMD_MIGRATE.  Runs on the
  // key's engine thread, so every field is stable.
  std::vector<char> SerializeKeyState(const KeyState& ks,
                                      bool with_fleet = false) {
    std::vector<char> out;
    auto put = [&](const void* p, size_t n) {
      out.insert(out.end(), static_cast<const char*>(p),
                 static_cast<const char*>(p) + n);
    };
    uint64_t completed = ks.completed_round;
    uint64_t declared = ks.declared_len.load(std::memory_order_relaxed);
    uint64_t pushes = ks.push_count.load(std::memory_order_relaxed);
    uint8_t dtype = ks.dtype;
    uint8_t flags = (ks.bidirectional ? 1 : 0) |
                    (ks.onebit_scaled ? 2 : 0) | (ks.server_ef ? 4 : 0) |
                    (ks.round_compressed ? 8 : 0);
    put(&completed, 8);
    put(&declared, 8);
    put(&pushes, 8);
    put(&dtype, 1);
    put(&flags, 1);
    uint32_t klen = static_cast<uint32_t>(ks.kwargs.size());
    put(&klen, 4);
    put(ks.kwargs.data(), klen);
    uint64_t n = ks.store.size();
    put(&n, 8);
    put(ks.store.data(), n);
    n = ks.out.size();
    put(&n, 8);
    put(ks.out.data(), n);
    n = ks.ef_err.size();
    put(&n, 8);
    put(ks.ef_err.data(), n * 4);
    uint32_t cnt = static_cast<uint32_t>(ks.seen.size());
    put(&cnt, 4);
    for (uint32_t w : ks.seen) put(&w, 4);
    cnt = static_cast<uint32_t>(ks.round_members.size());
    put(&cnt, 4);
    for (uint32_t w : ks.round_members) put(&w, 4);
    // Codec-table trailer (appended so pre-codec receivers, which parse
    // positionally and ignore trailing bytes, stay compatible): a
    // migrated key must carry its CURRENT codec epoch — active kwargs
    // already rode above; this adds the epoch/pending half so a
    // renegotiated key keeps renegotiating where it lands instead of
    // snapping back to its launch config.
    put(&ks.codec_epoch, 4);
    put(&ks.codec_applied_epoch, 4);
    uint8_t pend = ks.codec_pending ? 1 : 0;
    put(&pend, 1);
    put(&ks.codec_effective, 8);
    uint32_t nklen = static_cast<uint32_t>(ks.codec_next.size());
    put(&nklen, 4);
    put(ks.codec_next.data(), nklen);
    uint8_t fold = ks.ef_fold_pending ? 1 : 0;
    put(&fold, 1);
    // Optimizer-plane trailer (appended AFTER the codec trailer, same
    // version-tolerance law: pre-subsystem receivers parse positionally
    // and ignore trailing bytes; pre-subsystem SENDERS simply omit it
    // and the receiver's remaining()-based parse leaves every opt field
    // at its reset default).  A migrated key's new owner continues the
    // exact optimizer trajectory: table epoch, hyperparams, params and
    // m/v slots, step count, and param_version all ride along —
    // byte-equal, which the chaos tests assert through slots_crc.
    put(&ks.opt_epoch, 4);
    put(&ks.opt_applied_epoch, 4);
    uint8_t opend = ks.opt_pending ? 1 : 0;
    put(&opend, 1);
    put(&ks.opt_effective, 8);
    uint32_t oklen = static_cast<uint32_t>(ks.opt_kwargs.size());
    put(&oklen, 4);
    put(ks.opt_kwargs.data(), oklen);
    uint32_t onlen = static_cast<uint32_t>(ks.opt_next.size());
    put(&onlen, 4);
    put(ks.opt_next.data(), onlen);
    put(&ks.param_version, 8);
    put(&ks.opt_step, 8);
    uint64_t fn = ks.params.size();
    put(&fn, 8);
    put(ks.params.data(), fn * 4);
    fn = ks.opt_m.size();
    put(&fn, 8);
    put(ks.opt_m.data(), fn * 4);
    fn = ks.opt_v.size();
    put(&fn, 8);
    put(ks.opt_v.data(), fn * 4);
    // Global knob-table trailer (the CMD_MIGRATE-adjacent seam of the
    // knob plane): the table is SERVER-global, but a ring drain hands
    // keys to a peer that may predate the switch — so every migrated
    // key carries the sender's table and the receiver adopts it IF
    // NEWER, idempotent across the N keys of a drain exactly like a
    // racing CMD_KNOB SET.  The acked map deliberately does NOT ride:
    // workers re-ack the new owner via the kKnobStale backstop (one
    // adopt-and-replay round trip, self-healing).  Absent from pre-knob
    // senders — the receiver's remaining()-based parse then leaves its
    // table untouched, version-tolerant like the codec/opt trailers.
    {
      std::lock_guard<std::mutex> lk(knob_mu_);
      put(&knob_epoch_, 4);
      put(&knob_applied_, 4);
      uint8_t kpend = knob_pending_ ? 1 : 0;
      put(&kpend, 1);
      put(&knob_effective_, 8);
      uint32_t kl = static_cast<uint32_t>(knob_kwargs_.size());
      put(&kl, 4);
      put(knob_kwargs_.data(), kl);
      kl = static_cast<uint32_t>(knob_next_.size());
      put(&kl, 4);
      put(knob_next_.data(), kl);
    }
    // Row-sparse embedding trailer (appended AFTER the knob trailer,
    // same version-tolerance law: absent from pre-sparse senders, and a
    // pre-sparse receiver's positional parse ignores it).  Carries the
    // declared table shape, the PUBLISHED round's rows, the OPEN
    // round's partial merge, and the per-row step counts — params/m/v
    // already rode the optimizer trailer above, so a drained embedding
    // key's new owner continues the exact row-wise trajectory.
    {
      put(&ks.embed_rows, 8);
      put(&ks.embed_width, 4);
      auto put_rows =
          [&](const std::unordered_map<uint64_t, std::vector<float>>& m) {
            uint64_t cnt = 0;
            for (auto& kv : m)
              if (kv.second.size() == ks.embed_width) ++cnt;
            put(&cnt, 8);
            for (auto& kv : m)
              if (kv.second.size() == ks.embed_width) {
                put(&kv.first, 8);
                put(kv.second.data(), kv.second.size() * 4);
              }
          };
      put_rows(ks.embed_out);
      put_rows(ks.embed_merge);
      uint64_t nz = 0;
      for (uint32_t s : ks.embed_row_step)
        if (s) ++nz;
      put(&nz, 8);
      for (uint64_t r = 0; r < ks.embed_row_step.size(); ++r)
        if (ks.embed_row_step[r]) {
          put(&r, 8);
          put(&ks.embed_row_step[r], 4);
        }
    }
    // Fleet-ring trailer (appended AFTER the embed trailer, same
    // version-tolerance law).  MIGRATE blobs only (with_fleet is false
    // on the per-publish replication path — rings are server-global, so
    // re-serializing them per publish would tax every round for state
    // one drain-time copy preserves).  Written only when fleet-armed:
    // an unarmed server's blob stays byte-identical to pre-fleet, which
    // the elastic byte-equality tests pin.  Like the knob trailer this
    // is GLOBAL state riding a per-key blob; the receiver adopts each
    // (worker, window) only-if-absent, so a drain's N key blobs install
    // idempotently.
    if (fleet_armed_ && with_fleet) {
      std::lock_guard<std::mutex> lk(fleet_mu_);
      uint32_t nw = static_cast<uint32_t>(fleet_rings_.size());
      put(&nw, 4);
      for (auto& kv : fleet_rings_) {
        put(&kv.first, 4);
        uint32_t nwin = static_cast<uint32_t>(kv.second.size());
        put(&nwin, 4);
        for (auto& e : kv.second) {
          put(&e.first, 8);
          uint32_t bl = static_cast<uint32_t>(e.second.size());
          put(&bl, 4);
          put(e.second.data(), bl);
        }
      }
    }
    return out;
  }

  // Stream one key's state to its new ring owner and retire it locally.
  // Engine thread (owns the key).  Returns false — state kept — when the
  // new owner is unreachable; the caller then answers kError instead of
  // kMoved, so a worker can never be redirected AHEAD of the state (the
  // exactness contract: state-before-redirect).
  bool MigrateKeyOut(uint64_t key, KeyState& ks) {
    uint32_t owner = 0;
    std::string host;
    int port = 0;
    {
      std::lock_guard<std::mutex> lk(ring_mu_);
      auto pts = RingPoints();
      if (!pts || pts->empty()) return false;
      owner = ring::Owner(key, *pts);
      if (owner == my_server_id_) return true;   // raced a newer ring
      for (auto& m : ring_members_)
        if (m.id == owner) {
          host = m.host;
          port = m.port;
        }
    }
    if (host.empty()) return false;
    std::vector<char> blob = SerializeKeyState(ks, /*with_fleet=*/true);
    if (!PeerRequest(owner, host, port, kMigrate, 0, key, blob.data(),
                     blob.size())) {
      std::fprintf(stderr,
                   "[byteps server] migration of key %llu to server %u "
                   "(%s:%d) failed; state kept\n",
                   static_cast<unsigned long long>(key), owner,
                   host.c_str(), port);
      return false;
    }
    migrations_out_.fetch_add(1, std::memory_order_relaxed);
    // Waiting pulls re-route to the new owner (which now holds `out`).
    if (!ks.pending.empty()) {
      std::string js = RingJson(/*include_owned=*/false);
      int64_t flushed = 0;
      for (auto& p : ks.pending) {
        Respond(p.conn, kMoved, p.req_id, key, js.data(), js.size());
        ReleaseRef(p.conn);
        ++flushed;
      }
      ks.pending.clear();
      StatPendingPulls(key, -flushed);
    }
    // Retire: the KeyState object stays (readers may hold pointers into
    // the store_ map — entries are never erased, same as the rest of the
    // server) but all payload memory is released and the scatter door
    // closed.  declared_len 0 first, so no new scatter lease can start;
    // an ALREADY-queued scattered task still holds the lease, in which
    // case the buffer is left for its (kMoved-bound) task to release.
    ks.declared_len.store(0, std::memory_order_release);
    if (!ks.scatter_leased.exchange(true, std::memory_order_acquire)) {
      ks.scatter_buf.clear();
      ks.scatter_buf.shrink_to_fit();
      ks.scatter_leased.store(false, std::memory_order_release);
    }
    ks.store.clear();
    ks.store.shrink_to_fit();
    ks.out.clear();
    ks.out.shrink_to_fit();
    ks.seen.clear();
    ks.round_members.clear();
    ks.merge_ts.clear();
    ks.ef_err.clear();
    ks.ef_err.shrink_to_fit();
    ks.kwargs.clear();
    ks.round_compressed = false;
    // Codec table rode the migration blob; the retired copy resets so a
    // later ownership return re-seeds from INIT/CMD_CODEC, not a stale
    // epoch.
    ks.codec_epoch = 0;
    ks.codec_applied_epoch = 0;
    ks.codec_pending = false;
    ks.codec_effective = 0;
    ks.codec_next.clear();
    ks.ef_fold_pending = false;
    ks.pull_comp = codec::kOnebit;
    ks.qblock_bits = 8;
    ks.qblock_block = 256;
    // Optimizer plane rode the migration blob (table, params, slots,
    // param_version); the retired copy resets like the codec table so a
    // later ownership return re-seeds from CMD_OPT, never a stale epoch
    // — and releases the slot memory it was accounting.
    ks.opt_epoch = 0;
    ks.opt_applied_epoch = 0;
    ks.opt_pending = false;
    ks.opt_effective = 0;
    ks.opt_next.clear();
    ks.opt_kwargs.clear();
    ks.opt_kind = 0;
    ks.params.clear();
    ks.params.shrink_to_fit();
    ks.opt_m.clear();
    ks.opt_m.shrink_to_fit();
    ks.opt_v.clear();
    ks.opt_v.shrink_to_fit();
    ks.opt_scratch.clear();
    ks.opt_scratch.shrink_to_fit();
    ks.opt_step = 0;
    ks.param_version = 0;
    ks.opt_warned = false;
    // Embedding plane rode the trailer; retire it like the rest and
    // release the declared-footprint gauge bytes.
    embed_table_bytes_.fetch_add(
        0 - ks.embed_rows * ks.embed_width * 4, std::memory_order_relaxed);
    ks.embed_rows = 0;
    ks.embed_width = 0;
    ks.embed_merge.clear();
    ks.embed_out.clear();
    ks.embed_row_step.clear();
    ks.embed_row_step.shrink_to_fit();
    OptSlotAccount(ks);
    StatOpt(key, 0, 0);
    // Chain-replication bookkeeping leaves with the key: the new owner
    // replicates to ITS successor from its next publish, and a stale
    // pending blob from here must never resurrect the old trajectory.
    if (repl_armed_) {
      std::lock_guard<std::mutex> lk(repl_mu_);
      repl_pending_.erase(key);
      repl_pub_.erase(key);
      repl_ack_.erase(key);
    }
    ks.repl_acked_round.store(0, std::memory_order_relaxed);
    ks.active.store(false, std::memory_order_relaxed);
    // Drop the migrated key's digest window too: the new owner records
    // fresh digests from its next publish, and a stale window here
    // would make two servers answer CMD_AUDIT for the same key (the
    // worker-side merge handles overlap, but the ex-owner's rows would
    // go stale-forever, shadowing nothing useful).
    if (audit_armed_) {
      std::lock_guard<std::mutex> alk(audit_mu_);
      audit_log_.erase(key);
      ks.audit_round = 0;
      ks.audit_digest = 0;
      ks.audit_epoch = 0;
      ks.audit_n = 0;
    }
    return true;
  }

  // The one kMoved answer: hand state over first (if any), then redirect
  // with the current ring so the client re-plans without another RTT.
  void RespondMoved(Task& t, KeyState* ks) {
    moved_frames_.fetch_add(1, std::memory_order_relaxed);
    if (ks != nullptr && ks->active.load(std::memory_order_relaxed)) {
      if (!MigrateKeyOut(t.key, *ks)) {
        Respond(t.conn, kError, t.req_id, t.key, nullptr, 0);
        return;
      }
    }
    std::string js = RingJson(/*include_owned=*/false);
    Respond(t.conn, kMoved, t.req_id, t.key, js.data(), js.size());
  }

  // Reshard (kRingTask, engine side): migrate every key this engine owns
  // whose new ring owner is another server — proactively, so pull-side
  // state (published rounds, EF errors) reaches the new owner without
  // waiting for worker traffic to bounce off a kMoved.
  void HandleReshard(int idx) {
    if (!ring_armed_) return;
    std::vector<uint64_t> keys;
    {
      std::lock_guard<std::mutex> lk(assign_mu_);
      for (auto& kv : key_engine_)
        if (kv.second == idx) keys.push_back(kv.first);
    }
    for (uint64_t key : keys) {
      if (!RingMisplaced(key)) continue;
      KeyState* ks = FindState(key);
      if (ks != nullptr && ks->active.load(std::memory_order_relaxed))
        MigrateKeyOut(key, *ks);   // failure logged inside; state kept —
      //                              the next frame retries via kMoved
    }
  }

  // Parse a serialized key-state blob (SerializeKeyState's format) and
  // install it into `ks` — the shared install leg of CMD_MIGRATE and
  // the CMD_REPL failover adoption (MaybeAdoptReplica).  Returns false
  // with `ks` untouched when the mandatory header/buffer section is
  // malformed, so a corrupt blob is discarded WHOLE, never
  // half-installed; the version-tolerant trailers (codec/opt/knob/
  // embed) keep their reset defaults when absent, exactly as a
  // pre-subsystem sender's blob always behaved.  Engine thread.
  bool InstallKeyStateBlob(uint64_t key, KeyState& ks,
                           const std::vector<char>& p) {
    size_t pos = 0;
    auto take = [&](void* dst, size_t n) {
      if (pos + n > p.size()) return false;
      std::memcpy(dst, p.data() + pos, n);
      pos += n;
      return true;
    };
    // Overflow-safe bounds: every length is compared against the bytes
    // REMAINING (p.size() - pos), never via `pos + n` — the length
    // fields come off the wire, and a crafted store_n near 2^64 (or an
    // ef_n whose *4 wraps) would otherwise pass a wrapped addition and
    // drive an out-of-bounds read or an uncaught engine bad_alloc.
    auto remaining = [&]() -> uint64_t { return p.size() - pos; };
    uint64_t completed = 0, declared = 0, pushes = 0;
    uint8_t dtype = 0, flags = 0;
    uint32_t klen = 0;
    if (!take(&completed, 8) || !take(&declared, 8) ||
        !take(&pushes, 8) || !take(&dtype, 1) || !take(&flags, 1) ||
        !take(&klen, 4) || klen > remaining()) {
      return false;
    }
    std::string kwargs(p.data() + pos, klen);
    pos += klen;
    uint64_t store_n = 0, out_n = 0, ef_n = 0;
    if (!take(&store_n, 8) || store_n > remaining()) {
      return false;
    }
    size_t store_at = pos;
    pos += static_cast<size_t>(store_n);
    if (!take(&out_n, 8) || out_n > remaining()) {
      return false;
    }
    size_t out_at = pos;
    pos += static_cast<size_t>(out_n);
    if (!take(&ef_n, 8) || ef_n > remaining() / 4) {
      return false;
    }
    size_t ef_at = pos;
    pos += static_cast<size_t>(ef_n) * 4;
    uint32_t n_seen = 0;
    if (!take(&n_seen, 4) || n_seen > remaining() / 4) {
      return false;
    }
    size_t seen_at = pos;
    pos += static_cast<size_t>(n_seen) * 4;
    uint32_t n_members = 0;
    if (!take(&n_members, 4) || n_members > remaining() / 4) {
      return false;
    }
    size_t members_at = pos;
    ks.completed_round = completed;
    ks.dtype = dtype;
    ks.kwargs = std::move(kwargs);
    ks.bidirectional = (flags & 1) != 0;
    ks.onebit_scaled = (flags & 2) != 0;
    ks.server_ef = (flags & 4) != 0;
    ks.round_compressed = (flags & 8) != 0;
    ks.store.assign(p.data() + store_at, p.data() + store_at + store_n);
    ks.out.assign(p.data() + out_at, p.data() + out_at + out_n);
    ks.ef_err.resize(ef_n);
    if (ef_n)
      std::memcpy(ks.ef_err.data(), p.data() + ef_at,
                  static_cast<size_t>(ef_n) * 4);
    ks.seen.clear();
    for (uint32_t i = 0; i < n_seen; ++i) {
      uint32_t w = 0;
      std::memcpy(&w, p.data() + seen_at + i * 4ull, 4);
      ks.seen.insert(w);
    }
    ks.round_members.clear();
    for (uint32_t i = 0; i < n_members; ++i) {
      uint32_t w = 0;
      std::memcpy(&w, p.data() + members_at + i * 4ull, 4);
      ks.round_members.insert(w);
    }
    pos = members_at + static_cast<size_t>(n_members) * 4;
    // Codec-table trailer (absent from pre-codec senders: every field
    // then keeps its reset default and the key behaves exactly as a
    // launch-config key — version-tolerant by the remaining()-based
    // parse).  Re-derive the kwargs-dependent flags through the ONE
    // parse (ApplyCodecKwargs) so pull_comp/qblock params can never
    // drift from the kwargs that rode the legacy fields above; the
    // explicit flag bits above still win for bidirectional/scaled/EF
    // (they are what the old owner actually ran).
    ks.codec_epoch = 0;
    ks.codec_applied_epoch = 0;
    ks.codec_pending = false;
    ks.codec_effective = 0;
    ks.codec_next.clear();
    ks.ef_fold_pending = false;
    ks.pull_comp = codec::kOnebit;
    ks.qblock_bits = 8;
    ks.qblock_block = 256;
    {
      const std::string kw_now = ks.kwargs;
      ApplyCodecKwargs(ks, kw_now);
      ks.bidirectional = (flags & 1) != 0;
      ks.onebit_scaled = (flags & 2) != 0;
      ks.server_ef = (flags & 4) != 0;
      ks.ef_fold_pending = false;   // trailer (or default) decides below
    }
    uint32_t cep = 0, caep = 0, nklen = 0;
    uint8_t pend = 0, fold = 0;
    uint64_t ceff = 0;
    if (take(&cep, 4) && take(&caep, 4) && take(&pend, 1) &&
        take(&ceff, 8) && take(&nklen, 4) && nklen <= remaining()) {
      ks.codec_epoch = cep;
      ks.codec_applied_epoch = caep;
      ks.codec_pending = pend != 0;
      ks.codec_effective = ceff;
      ks.codec_next.assign(p.data() + pos, nklen);
      pos += nklen;
      if (take(&fold, 1)) ks.ef_fold_pending = fold != 0;
    }
    // Optimizer-plane trailer (absent from pre-subsystem senders: the
    // reset defaults below then hold and the key behaves exactly as a
    // sum-only key — version-tolerant by the same remaining()-based
    // parse as the codec trailer above).
    ks.opt_epoch = 0;
    ks.opt_applied_epoch = 0;
    ks.opt_pending = false;
    ks.opt_effective = 0;
    ks.opt_next.clear();
    ks.opt_kwargs.clear();
    ks.opt_kind = 0;
    ks.params.clear();
    ks.opt_m.clear();
    ks.opt_v.clear();
    ks.opt_step = 0;
    ks.param_version = 0;
    ks.opt_warned = false;
    {
      uint32_t oep = 0, oaep = 0, oklen = 0;
      uint8_t opend = 0;
      uint64_t oeff = 0;
      if (take(&oep, 4) && take(&oaep, 4) && take(&opend, 1) &&
          take(&oeff, 8) && take(&oklen, 4) && oklen <= remaining()) {
        std::string okw(p.data() + pos, oklen);
        pos += oklen;
        uint32_t onlen = 0;
        uint64_t pv = 0, ostep = 0, pn = 0, mn = 0, vn = 0;
        if (take(&onlen, 4) && onlen <= remaining()) {
          std::string onext(p.data() + pos, onlen);
          pos += onlen;
          if (take(&pv, 8) && take(&ostep, 8) &&
              take(&pn, 8) && pn <= remaining() / 4) {
            size_t pn_at = pos;
            pos += static_cast<size_t>(pn) * 4;
            if (take(&mn, 8) && mn <= remaining() / 4) {
              size_t mn_at = pos;
              pos += static_cast<size_t>(mn) * 4;
              if (take(&vn, 8) && vn <= remaining() / 4) {
                ks.opt_epoch = oep;
                ks.opt_applied_epoch = oaep;
                ks.opt_pending = opend != 0;
                ks.opt_effective = oeff;
                ks.opt_next = std::move(onext);
                ApplyOptKwargs(ks, okw);   // sets kind + hyperparams
                ks.param_version = pv;
                ks.opt_step = ostep;
                ks.params.resize(pn);
                if (pn)
                  std::memcpy(ks.params.data(), p.data() + pn_at,
                              static_cast<size_t>(pn) * 4);
                ks.opt_m.resize(mn);
                if (mn)
                  std::memcpy(ks.opt_m.data(), p.data() + mn_at,
                              static_cast<size_t>(mn) * 4);
                ks.opt_v.resize(vn);
                if (vn)
                  std::memcpy(ks.opt_v.data(), p.data() + pos,
                              static_cast<size_t>(vn) * 4);
                pos += static_cast<size_t>(vn) * 4;
              }
            }
          }
        }
      }
    }
    // Global knob-table trailer (absent from pre-knob senders: the
    // remaining()-based parse then leaves the local table untouched).
    // Adopted IF NEWER under the same idempotency law as a racing
    // CMD_KNOB SET, so the N per-key migrations of a drain converge on
    // the sender's table and a post-switch drain CARRIES the knob epoch
    // to the surviving owner.  The acked map intentionally resets:
    // workers re-introduce themselves via the kKnobStale backstop.
    {
      uint32_t kep = 0, kaep = 0, kwl = 0, knl = 0;
      uint8_t kpend = 0;
      uint64_t keff = 0;
      if (take(&kep, 4) && take(&kaep, 4) && take(&kpend, 1) &&
          take(&keff, 8) && take(&kwl, 4) && kwl <= remaining()) {
        std::string kkw(p.data() + pos, kwl);
        pos += kwl;
        if (take(&knl, 4) && knl <= remaining()) {
          std::string knext(p.data() + pos, knl);
          pos += knl;
          std::lock_guard<std::mutex> lk(knob_mu_);
          if (kep > knob_epoch_) {
            knob_epoch_ = kep;
            knob_applied_ = kaep;
            knob_pending_ = kpend != 0;
            knob_effective_ = keff;
            knob_kwargs_ = std::move(kkw);
            knob_next_ = std::move(knext);
            knob_epoch_atomic_.store(kep, std::memory_order_release);
          }
        }
      }
    }
    // Row-sparse embedding trailer (absent from pre-sparse senders: the
    // reset defaults below then hold and the key stays dense —
    // version-tolerant by the same remaining()-based parse).  The shape
    // is bounded like every other wire length: total table elements
    // must fit the migration frame cap, so a crafted header can never
    // drive a giant allocation.
    embed_table_bytes_.fetch_add(
        0 - ks.embed_rows * ks.embed_width * 4, std::memory_order_relaxed);
    ks.embed_rows = 0;
    ks.embed_width = 0;
    ks.embed_merge.clear();
    ks.embed_out.clear();
    ks.embed_row_step.clear();
    {
      uint64_t er = 0;
      uint32_t ew = 0;
      if (take(&er, 8) && take(&ew, 4)) {
        auto take_rows =
            [&](std::unordered_map<uint64_t, std::vector<float>>* m) {
              uint64_t cnt = 0;
              if (!take(&cnt, 8)) return false;
              const uint64_t rb = 8ull + static_cast<uint64_t>(ew) * 4;
              if (cnt > remaining() / rb) return false;
              for (uint64_t i = 0; i < cnt; ++i) {
                uint64_t row = 0;
                if (!take(&row, 8)) return false;
                std::vector<float> v(ew);
                if (!take(v.data(), static_cast<size_t>(ew) * 4))
                  return false;
                (*m)[row] = std::move(v);
              }
              return true;
            };
        std::unordered_map<uint64_t, std::vector<float>> eo, em;
        uint64_t nz = 0;
        // The sender writes the (empty) rows/step sections even for a
        // dense key, so they must be CONSUMED even when er/ew say
        // "no table" — short-circuiting on the shape here would leave
        // the cursor 24 bytes behind and misalign every trailer that
        // follows (the fleet rings would silently parse as absent).
        bool eok = (ew == 0 || er <= (max_msg_ / 4) / ew) &&
                   take_rows(&eo) && take_rows(&em) && take(&nz, 8) &&
                   nz <= remaining() / 12;
        if (eok) {
          std::vector<uint32_t> steps(static_cast<size_t>(er), 0);
          for (uint64_t i = 0; i < nz && eok; ++i) {
            uint64_t row = 0;
            uint32_t s = 0;
            eok = take(&row, 8) && take(&s, 4) && row < er;
            if (eok) steps[static_cast<size_t>(row)] = s;
          }
          if (eok && er != 0 && ew != 0) {
            ks.embed_rows = er;
            ks.embed_width = ew;
            ks.embed_out = std::move(eo);
            ks.embed_merge = std::move(em);
            ks.embed_row_step = std::move(steps);
            embed_table_bytes_.fetch_add(er * ew * 4,
                                         std::memory_order_relaxed);
          }
        }
      }
    }
    // Fleet-ring trailer: global state riding a per-key blob (the knob
    // law).  Adopt each (worker, window) ONLY-IF-ABSENT — a drain sends
    // one copy per migrated key and the install must be idempotent —
    // then trim to this server's cap.  Absent from pre-fleet and
    // unarmed senders (and from repl blobs): the first take() fails on
    // an exhausted buffer and the rings stay untouched.  Every length
    // is bounds-checked against remaining() before use; a '{' sniff
    // rejects blobs that can't be a published summary.
    if (fleet_armed_) {
      uint32_t fnw = 0;
      if (take(&fnw, 4) && fnw <= 4096) {
        std::lock_guard<std::mutex> lk(fleet_mu_);
        bool fok = true;
        for (uint32_t i = 0; i < fnw && fok; ++i) {
          uint32_t wid = 0, nwin = 0;
          fok = take(&wid, 4) && take(&nwin, 4) && nwin <= 4096;
          for (uint32_t j = 0; j < nwin && fok; ++j) {
            uint64_t widx = 0;
            uint32_t bl = 0;
            fok = take(&widx, 8) && take(&bl, 4) && bl <= remaining();
            if (!fok) break;
            const char* blob = p.data() + pos;
            pos += bl;
            if (bl == 0 || blob[0] != '{') continue;
            auto& ring = fleet_rings_[wid];
            bool have = false;
            for (auto& e : ring)
              if (e.first == widx) {
                have = true;
                break;
              }
            if (!have) {
              auto it = ring.begin();
              while (it != ring.end() && it->first < widx) ++it;
              ring.insert(it, {widx, std::string(blob, bl)});
              while (static_cast<int>(ring.size()) > fleet_windows_)
                ring.pop_front();
            }
          }
        }
      }
    }
    OptSlotAccount(ks);
    StatOpt(key, ks.param_version, ks.opt_kind);
    ks.merge_ts.clear();
    ks.push_count.store(pushes, std::memory_order_relaxed);
    ks.declared_len.store(declared, std::memory_order_release);
    ks.active.store(true, std::memory_order_relaxed);
    return true;
  }

  // Install a migrated key (CMD_MIGRATE, engine side).
  void HandleMigrate(Task& t) {
    KeyState& ks = StateFor(t.key);
    if (ks.active.load(std::memory_order_relaxed) &&
        ks.push_count.load(std::memory_order_relaxed) > 0) {
      // The local key already carries LIVE pushes: either workers
      // rebased onto this server before a straggling migration landed
      // (local rounds are ahead), or a worker that adopted the new ring
      // early fresh-INITed and pushed here while the old owner's
      // reshard stream was still in flight (local round 0, migrated
      // round r).  Installing over either would silently destroy
      // merged gradients and desync round counters across the fleet —
      // refuse loudly instead: the sender keeps its copy, its next
      // frame answers kError, and the job fails EXACT-OR-LOUD rather
      // than diverging.
      uint64_t completed = 0;
      if (t.payload.size() >= 8)
        std::memcpy(&completed, t.payload.data(), 8);
      std::fprintf(stderr,
                   "[byteps server] refusing migration of key %llu: local "
                   "state has live pushes at round %llu (migrated round "
                   "%llu)\n",
                   static_cast<unsigned long long>(t.key),
                   static_cast<unsigned long long>(ks.completed_round),
                   static_cast<unsigned long long>(completed));
      Respond(t.conn, kError, t.req_id, t.key, nullptr, 0);
      return;
    }
    if (!InstallKeyStateBlob(t.key, ks, t.payload)) {
      Respond(t.conn, kError, t.req_id, t.key, nullptr, 0);
      return;
    }
    // A chain replica parked here for this key is superseded by the
    // richer migration blob (it carries the OPEN round too) — drop it,
    // and re-replicate the adopted state to THIS server's successor so
    // the drain handoff is never the one unprotected copy.
    if (repl_armed_) {
      {
        std::lock_guard<std::mutex> lk(repl_mu_);
        replicas_.erase(t.key);
      }
      ReplEnqueue(ks, t.key);
    }
    migrations_in_.fetch_add(1, std::memory_order_relaxed);
    StatPublish(t.key, ks.completed_round);
    Respond(t.conn, kOk, t.req_id, t.key, nullptr, 0);
    // A pull parked here BEFORE the migration landed (a worker that
    // adopted the new ring early) may be satisfiable by the migrated
    // published round — serve it now, not at some unrelated later
    // publish.
    FlushPulls(ks, t.key);
  }

  // --- chain replication (CMD_REPL) -----------------------------------
  // Zero-loss failover: after every publish the owner hands the key's
  // serialized state to ReplLoop, which streams it to the key's ring
  // successor; pulls for the new round park (ReplBlocked) until the
  // successor's ack proves a second copy exists, so a SIGKILLed owner
  // can never take an already-consumed round with it.  On failover the
  // fresh owner adopts the replica (MaybeAdoptReplica) instead of
  // rebasing workers to round 0 — zero lost rounds, zero optimizer
  // resets, with slots_crc + the audit digest as the proof surface.

  // True while the key's newest published round has not been acked by
  // the ring successor within the lag window — the pull gate.  Engine
  // thread (completed_round is engine-owned); unarmed runs answer
  // false on one boolean test.
  bool ReplBlocked(const KeyState& ks) {
    if (!repl_armed_) return false;
    return ks.completed_round >
           ks.repl_acked_round.load(std::memory_order_acquire) +
               repl_lag_window_;
  }

  // Hand the just-published (or just-installed) state to the
  // replication thread: newest blob per key wins, so a slow successor
  // coalesces rounds instead of queueing them.  Engine thread — the
  // serialize runs while the key's state is stable, and the peer I/O
  // never sits on the publish critical path.
  void ReplEnqueue(KeyState& ks, uint64_t key) {
    if (!repl_armed_) return;
    auto rpts = ReplPoints();
    if (!ring_armed_ || draining_.load(std::memory_order_relaxed) ||
        !rpts || rpts->empty()) {
      // No successor to wait for (single-member ring, ring unarmed, or
      // this server is draining — its keys are leaving anyway): the
      // gate must never park pulls forever.
      ks.repl_acked_round.store(ks.completed_round,
                                std::memory_order_release);
      return;
    }
    std::vector<char> blob = SerializeKeyState(ks);
    {
      std::lock_guard<std::mutex> lk(repl_mu_);
      repl_pending_[key] = std::move(blob);
      repl_pub_[key] = ks.completed_round;
    }
    repl_cv_.notify_one();
  }

  // Ack bookkeeping shared by the success and no-successor legs: lift
  // the key's acked round (only-if-newer — acks can arrive out of
  // order around a coalesced re-send), then wake the key's engine so
  // the gated pulls flush on the thread that owns the round state.
  void ReplAck(uint64_t key, uint64_t round) {
    KeyState* ks = FindState(key);
    if (ks != nullptr) {
      uint64_t prev = ks->repl_acked_round.load(std::memory_order_relaxed);
      while (prev < round &&
             !ks->repl_acked_round.compare_exchange_weak(
                 prev, round, std::memory_order_release,
                 std::memory_order_relaxed)) {
      }
    }
    {
      std::lock_guard<std::mutex> lk(repl_mu_);
      auto& acked = repl_ack_[key];
      if (round > acked) acked = round;
    }
    Task t;
    t.cmd = kReplFlushTask;
    t.dtype = 0;
    t.flags = 0;
    t.req_id = 0;
    t.worker_id = 0;
    t.key = key;
    t.conn = nullptr;
    t.seq = seq_.fetch_add(1);
    t.priority = UINT64_MAX;
    queues_[EngineFor(key, 0)].Push(std::move(t));
  }

  // Replication sender thread (Run starts it only when armed): drains
  // the newest-blob queue to each key's ring successor.  A failed send
  // re-queues the blob and backs off — PeerRequest's 2s negative cache
  // makes the retry a fast false while the successor is down, and a
  // ring transition re-homes the key's successor via ReplPoints.
  void ReplLoop() {
    for (;;) {
      uint64_t key = 0;
      std::vector<char> blob;
      {
        std::unique_lock<std::mutex> lk(repl_mu_);
        repl_cv_.wait(lk, [&] {
          return shutdown_.load() || !repl_pending_.empty();
        });
        if (shutdown_.load()) return;
        auto it = repl_pending_.begin();
        key = it->first;
        blob = std::move(it->second);
        repl_pending_.erase(it);
      }
      uint64_t round = 0;
      if (blob.size() >= 8) std::memcpy(&round, blob.data(), 8);
      uint32_t target = 0;
      std::string host;
      int port = 0;
      {
        auto rpts = ReplPoints();
        if (rpts && !rpts->empty()) {
          target = ring::Owner(key, *rpts);
          std::lock_guard<std::mutex> lk(ring_mu_);
          auto it = peer_book_.find(target);
          if (it != peer_book_.end()) {
            host = it->second.first;
            port = it->second.second;
          }
        }
      }
      if (host.empty()) {
        // Successor vanished mid-flight (scale-down to one server):
        // nothing to replicate to — self-ack so the gate opens.
        ReplAck(key, round);
        continue;
      }
      if (PeerRequest(target, host, port, kRepl, 0, key, blob.data(),
                      blob.size())) {
        repl_rounds_out_.fetch_add(1, std::memory_order_relaxed);
        repl_bytes_out_.fetch_add(blob.size(), std::memory_order_relaxed);
        ReplAck(key, round);
      } else {
        {
          std::lock_guard<std::mutex> lk(repl_mu_);
          // Newest wins: only re-queue when no fresher publish landed.
          if (repl_pending_.find(key) == repl_pending_.end())
            repl_pending_[key] = std::move(blob);
        }
        // Throttle the retry loop; the negative cache already makes
        // each failed attempt cheap.
        std::this_thread::sleep_for(std::chrono::milliseconds(100));
        if (shutdown_.load()) return;
      }
    }
  }

  // Failover adoption: the FIRST frame touching a key this server now
  // owns but holds no live state for consumes the parked chain replica
  // — the fresh owner resumes from the replicated published round +
  // optimizer slots instead of rebasing workers to round 0.  Engine
  // thread.  A malformed replica is discarded whole and the legacy
  // rebase path takes over (adopt-whole-or-discard).  Gated on an
  // advanced ring epoch: at epoch 0 ownership is not enforced and a
  // misrouted frame must not install a replica under a live owner.
  void MaybeAdoptReplica(uint64_t key, KeyState& ks) {
    if (!repl_armed_) return;
    if (ks.active.load(std::memory_order_relaxed) ||
        ks.push_count.load(std::memory_order_relaxed) != 0)
      return;
    if (ring_epoch_atomic_.load(std::memory_order_acquire) == 0 ||
        RingMisplaced(key))
      return;
    std::vector<char> blob;
    {
      std::lock_guard<std::mutex> lk(repl_mu_);
      auto it = replicas_.find(key);
      if (it == replicas_.end()) return;
      blob = std::move(it->second.second);
      replicas_.erase(it);
    }
    if (!InstallKeyStateBlob(key, ks, blob)) {
      std::fprintf(stderr,
                   "[byteps server] discarding malformed replica for key "
                   "%llu (%zu bytes)\n",
                   static_cast<unsigned long long>(key), blob.size());
      return;
    }
    repl_promotions_.fetch_add(1, std::memory_order_relaxed);
    std::fprintf(stderr,
                 "[byteps server] adopted replica for key %llu at round "
                 "%llu (param_version %llu)\n",
                 static_cast<unsigned long long>(key),
                 static_cast<unsigned long long>(ks.completed_round),
                 static_cast<unsigned long long>(ks.param_version));
    StatPublish(key, ks.completed_round);
    // Re-protect immediately: the adopted round is the only copy until
    // THIS server's successor acks it (the gate stays closed exactly
    // that long), so a second failure still loses nothing.
    ReplEnqueue(ks, key);
    FlushPulls(ks, key);
  }

  // Replica GC on a ring transition (under ring_mu_): keep a parked
  // replica only while this server is the key's owner (a promotion
  // candidate) or its current successor; anything else — e.g. a
  // scale-up moved the successor role — is dropped, and the live owner
  // re-protects at its next publish.
  void ReplSweepLocked() {
    auto pts = RingPoints();
    if (!pts || pts->empty()) return;
    std::lock_guard<std::mutex> lk(repl_mu_);
    for (auto it = replicas_.begin(); it != replicas_.end();) {
      const uint64_t key = it->first;
      const uint32_t owner = ring::Owner(key, *pts);
      bool keep = owner == my_server_id_;
      if (!keep) {
        std::vector<std::pair<uint64_t, uint32_t>> minus;
        minus.reserve(pts->size());
        for (auto& pt : *pts)
          if (pt.second != owner) minus.push_back(pt);
        keep = !minus.empty() &&
               ring::Owner(key, minus) == my_server_id_;
      }
      if (keep)
        ++it;
      else
        it = replicas_.erase(it);
    }
  }

  // Joining server: read the current ring from a launch peer (binary
  // CMD_RING), compose next-epoch = current + self, apply locally (so
  // migrations streaming in are accepted), then announce to every
  // member.  Runs on its own thread once the listeners are up.
  void JoinLoop() {
    // Snapshot the launch peer book under ring_mu_: ApplyRing mutates
    // peer_book_ from reader threads (a concurrent worker proposal),
    // and an unlocked map iteration racing that insert is UB.
    std::map<uint32_t, std::pair<std::string, int>> launch_peers;
    {
      std::lock_guard<std::mutex> lk(ring_mu_);
      launch_peers = peer_book_;
    }
    std::vector<char> bin;
    bool got = false;
    for (int attempt = 0; attempt < 120 && !shutdown_.load(); ++attempt) {
      for (auto& kv : launch_peers) {
        if (kv.first == my_server_id_) continue;
        if (PeerRequest(kv.first, kv.second.first, kv.second.second,
                        kRing, /*flags=*/1, 0, nullptr, 0, &bin)) {
          got = true;
          break;
        }
      }
      if (got) break;
      std::this_thread::sleep_for(std::chrono::milliseconds(500));
    }
    if (!got) {
      std::fprintf(stderr,
                   "[byteps server] ring join failed: no peer answered "
                   "CMD_RING; serving without joining\n");
      return;
    }
    // Compose-announce-CONFIRM, retried: peers reject a RING_SET whose
    // epoch collides with a concurrent transition (e.g. a worker
    // failover proposal that claimed the same epoch+1) yet still answer
    // kOk with their authoritative table — so membership must be
    // verified by re-reading the ring, never assumed from the acks.
    for (int round = 0; round < 5 && !shutdown_.load(); ++round) {
      uint64_t epoch = 0;
      uint32_t vnodes = static_cast<uint32_t>(ring_vnodes_);
      std::vector<RingServer> servers;
      if (!ParseRingWire(bin, &epoch, &vnodes, &servers)) {
        std::fprintf(stderr,
                     "[byteps server] ring join failed: unparseable peer "
                     "ring; serving without joining\n");
        return;
      }
      bool already_member = false;
      for (auto& s : servers)
        if (s.id == my_server_id_) already_member = true;
      if (already_member) {
        ApplyRing(epoch, vnodes, servers, /*make_draining=*/false);
        std::fprintf(stderr,
                     "[byteps server] joined the ring as server %u "
                     "(epoch %llu)\n", my_server_id_,
                     static_cast<unsigned long long>(epoch));
        return;
      }
      std::vector<RingServer> next;
      for (auto& s : servers) next.push_back(s);
      next.push_back(
          RingServer{my_server_id_, advertise_host_, advertise_port_});
      ApplyRing(epoch + 1, vnodes, next, /*make_draining=*/false);
      std::string wire = RingWire();
      for (auto& s : next) {
        if (s.id == my_server_id_) continue;
        auto it = launch_peers.find(s.id);
        auto addr = it != launch_peers.end()
                        ? it->second : std::make_pair(s.host, s.port);
        if (!PeerRequest(s.id, addr.first, addr.second, kRingSet, 0, 0,
                         wire.data(), wire.size()))
          std::fprintf(stderr,
                       "[byteps server] ring join announce to server %u "
                       "failed (it will learn via a worker proposal)\n",
                       s.id);
      }
      // Confirm against a peer's view; on a collision, re-compose from
      // the fresher table next round.
      std::this_thread::sleep_for(std::chrono::milliseconds(200));
      got = false;
      for (auto& kv : launch_peers) {
        if (kv.first == my_server_id_) continue;
        if (PeerRequest(kv.first, kv.second.first, kv.second.second,
                        kRing, /*flags=*/1, 0, nullptr, 0, &bin)) {
          got = true;
          break;
        }
      }
      if (!got) {
        std::fprintf(stderr,
                     "[byteps server] ring join: peers unreachable after "
                     "announce; assuming epoch %llu stands\n",
                     static_cast<unsigned long long>(
                         ring_epoch_atomic_.load(
                             std::memory_order_acquire)));
        return;
      }
    }
    std::fprintf(stderr,
                 "[byteps server] ring join did not converge after 5 "
                 "rounds; serving with the last announced table\n");
  }

  void ReaderLoop(Conn* conn) {
    ReaderBody(conn);
    // Reader exit (peer hung up, we rejected an oversize frame, or a
    // shutdown command): half-close so the peer sees EOF immediately
    // instead of a silently dead socket.  Engine responses racing on
    // this conn fail with EPIPE, which Respond already tolerates
    // (crashed-worker path).  The fd itself closes as soon as the last
    // outstanding holder (queued task / deferred pull / barrier waiter)
    // releases — immediately, for the rejected-rogue-frame case.
    {
      std::lock_guard<std::mutex> lk(conns_mu_);
      if (conn->fd >= 0) ::shutdown(conn->fd, SHUT_RDWR);
    }
    conn->reader_done.store(true, std::memory_order_release);
    {
      // Drop the conn's recycled receive buffers: the Conn object itself
      // lives until server shutdown (conns_ is never pruned), so a
      // reconnect-churning fleet would otherwise pin ~4 payload-sized
      // buffers per dead connection forever.
      std::lock_guard<std::mutex> lk(conn->pool_mu);
      conn->bufpool.clear();
      conn->bufpool.shrink_to_fit();
    }
    MaybeCloseFd(conn);
    {
      // notify while HOLDING the mutex: with a notify after release,
      // another reader's notify can wake Run()'s predicated wait first,
      // the Server (stack-allocated in bps_ps_server_run) is destroyed,
      // and this thread's pending notify_all() touches a freed cv.
      std::lock_guard<std::mutex> lk(readers_mu_);
      --active_readers_;
      readers_cv_.notify_all();
    }
  }

  // Pop a recycled receive buffer off the conn's freelist (resize only
  // value-initializes GROWTH, and partition payloads are uniform, so the
  // steady state is a no-op resize) / return one after the engine is done
  // with it.  The conn outlives every holder (deleted only at server
  // shutdown), so the engine-side return can't use-after-free.
  static std::vector<char> PopBuf(Conn* c, size_t n) {
    std::vector<char> b;
    if (n >= 4096) {   // PushBuf's retention floor: a control frame must
      //                  not evict (and then destroy) a pooled 4MB data
      //                  buffer it will never refill
      std::lock_guard<std::mutex> lk(c->pool_mu);
      if (!c->bufpool.empty()) {
        b = std::move(c->bufpool.back());
        c->bufpool.pop_back();
      }
    }
    b.resize(n);
    return b;
  }
  static void PushBuf(Conn* c, std::vector<char>&& b) {
    if (b.capacity() < 4096) return;   // tiny frames: not worth pooling
    // A dead reader never pops again — returning a buffer after its
    // exit-time pool purge would re-pin payload memory on a Conn that
    // lives (unpooled) until server shutdown.
    if (c->reader_done.load(std::memory_order_acquire)) return;
    std::lock_guard<std::mutex> lk(c->pool_mu);
    if (c->bufpool.size() < 4) c->bufpool.push_back(std::move(b));
  }

  KeyState* FindState(uint64_t key) {
    std::lock_guard<std::mutex> lk(store_mu_);
    auto it = store_.find(key);
    return it == store_.end() ? nullptr : &it->second;
  }

  void ReaderBody(Conn* conn) {
    ReqHeader h;
    while (!shutdown_.load()) {
      if (!ReadFull(conn->fd, &h, sizeof(h))) break;
      if (h.len > max_msg_) break;  // corrupt/hostile frame: drop the conn
      // Scatter receive: a sync raw-f32 push for an already-declared key
      // (reader-visible via the declared_len mirror) whose scatter lease
      // is free reads its payload straight off the socket into the key's
      // persistent scatter buffer — no per-push allocation, no memset,
      // and on the round's first push the engine ADOPTS the buffer into
      // the merge store by swap (HandlePush), so the payload's bytes are
      // written exactly once end to end.  Lease losers / undeclared keys
      // / compressed frames take the pooled buffered path below, with
      // identical merge semantics (regression-tested).
      bool scattered = false;
      const uint64_t key = h.key;   // aligned copy (h is packed)
      std::vector<char> payload;
      if (h.cmd == kPush && h.dtype == kF32 && !async_ && h.len > 0) {
        KeyState* ks = FindState(key);
        if (ks &&
            ks->declared_len.load(std::memory_order_acquire) == h.len &&
            !ks->scatter_leased.exchange(true,
                                         std::memory_order_acquire)) {
          if (ks->scatter_buf.size() != h.len)
            ks->scatter_buf.resize(h.len);
          if (!ReadFull(conn->fd, ks->scatter_buf.data(), h.len)) {
            // Conn died mid-payload: the lease must not leak.  The
            // half-filled scatter_buf is harmless — the next holder
            // overwrites it entirely before the engine ever reads it.
            ks->scatter_leased.store(false, std::memory_order_release);
            break;
          }
          scattered = true;
          scatter_frames_.fetch_add(1, std::memory_order_relaxed);
        }
      }
      if (!scattered) {
        payload = PopBuf(conn, h.len);
        if (h.len && !ReadFull(conn->fd, payload.data(), h.len)) break;
      }
      bytes_in_.fetch_add(sizeof(h) + h.len, std::memory_order_relaxed);
      // Lease refresh: any frame from a live member renews its lease
      // (the "refreshed by traffic/CMD_PING" contract) — one uncontended
      // lock per frame, noise next to the per-frame EngineFor lookup.
      TouchWorker(h.worker_id);
      switch (h.cmd) {
        case kHello: {
          // HELLO advertises server mode: u8 async | u8 schedule.  Lets
          // clients fail fast on mode mismatches (e.g. weight-delta async
          // training against a sync server would silently train on deltas).
          // It is also the elastic join/rejoin door: a HELLO from an id
          // that is not currently live admits it at the next epoch
          // boundary (a live member's HELLO — every fixed-mode session
          // start — changes nothing, keeping the fixed wire identical).
          // flags bit 0 = OBSERVER: a pull-only session introducing
          // itself without joining the worker set — it must never be
          // admitted into elastic membership (it would stall every
          // round it never pushes into).  TouchWorker already ignores
          // non-members, so an observer stays invisible to rounds in
          // both fixed and elastic modes.
          if (!(h.flags & 1)) AdmitWorker(h.worker_id);
          char mode[2] = {static_cast<char>(async_ ? 1 : 0),
                          static_cast<char>(schedule_ ? 1 : 0)};
          Respond(conn, kOk, h.req_id, h.key, mode, 2);
          break;
        }
        case kLeave:
          // Graceful departure: the client drained its in-flight rounds
          // first (client.py leave()), so open rounds either already
          // carry its push or re-finalize without it.
          RemoveWorker(h.worker_id, "graceful leave");
          Respond(conn, kOk, h.req_id, h.key, nullptr, 0);
          break;
        case kMembers: {
          std::string js = MembersJson();
          Respond(conn, kOk, h.req_id, h.key, js.data(), js.size());
          break;
        }
        case kRing: {
          // Ring read: JSON for workers, binary (flags bit0) for a
          // joining server's C++-side parse.  Reader thread so the ring
          // can still be read past a wedged engine — the failover path
          // depends on it.
          if (h.flags & 1) {
            std::string b = RingWire();
            Respond(conn, kOk, h.req_id, h.key, b.data(), b.size());
          } else {
            std::string js = RingJson();
            Respond(conn, kOk, h.req_id, h.key, js.data(), js.size());
          }
          break;
        }
        case kRingSet:
        case kDrain: {
          // Ring write / graceful drain.  Both carry a full binary
          // next-epoch table; drain additionally marks this server
          // draining (its member set excludes it, so every owned key
          // migrates out and subsequent frames are kMoved-redirected).
          uint64_t ep = 0;
          uint32_t vn = 0;
          std::vector<RingServer> srvs;
          if (!ring_armed_ ||
              !ParseRingWire(payload, &ep, &vn, &srvs)) {
            Respond(conn, kError, h.req_id, h.key, nullptr, 0);
            break;
          }
          ApplyRing(ep, vn, std::move(srvs), h.cmd == kDrain);
          std::string js = RingJson();
          Respond(conn, kOk, h.req_id, h.key, js.data(), js.size());
          break;
        }
        case kPing:
          if (h.flags & kFlagTraced) {
            // Traced ping: answer with this host's monotonic clock so
            // the worker can estimate the cross-host offset (NTP-style
            // midpoint, client.py estimate_clock_offset).  Untraced
            // pings keep the historical empty response byte-for-byte.
            int64_t now = NowUs();
            Respond(conn, kOk, h.req_id, h.key,
                    reinterpret_cast<const char*>(&now), sizeof(now));
          } else {
            Respond(conn, kOk, h.req_id, h.key, nullptr, 0);
          }
          break;
        case kTrace: {
          // Reader-thread drain, like kStats: a trace fetch must answer
          // even when an engine is wedged mid-round — that wedge is
          // exactly what the spans exist to diagnose.
          std::string js = tracer_.DrainJson();
          Respond(conn, kOk, h.req_id, h.key, js.data(), js.size());
          break;
        }
        case kStats: {
          // Reader-thread stats snapshot: never queues behind a busy (or
          // wedged) engine, so an operator can still scrape a server
          // that stopped making round progress — the exact situation
          // stats exist for.
          std::string js = StatsJson();
          Respond(conn, kOk, h.req_id, h.key, js.data(), js.size());
          break;
        }
        case kKnob:
          // Reader-thread knob plane, like kStats: the table is global
          // control-plane state and a SET/GET must answer even when an
          // engine is wedged mid-round.
          HandleKnobFrame(conn, h.req_id, key, h.flags, h.worker_id,
                          payload);
          break;
        case kRepl: {
          // Chain-replica install (peer traffic): park the serialized
          // key-state blob only-if-newer — the first 8 bytes are the
          // sender's completed_round, and a replayed or reordered blob
          // can never regress the parked copy (the CMD_RING_SET
          // idempotency law).  NOTHING is installed here: the blob
          // waits, whole, for a failover to re-home the key
          // (MaybeAdoptReplica) — a torn transfer never reaches this
          // point at all because the frame header's length prefix makes
          // delivery all-or-nothing (adopt-whole-or-discard).  Reader
          // thread, like kStats: a replica must land even when this
          // server's engines are wedged mid-round.
          uint64_t r = 0;
          if (!repl_armed_ || payload.size() < 30) {
            Respond(conn, kError, h.req_id, h.key, nullptr, 0);
            break;
          }
          std::memcpy(&r, payload.data(), 8);
          {
            std::lock_guard<std::mutex> lk(repl_mu_);
            auto& slot = replicas_[key];
            if (slot.second.empty() || r > slot.first) {
              slot.first = r;
              slot.second = std::move(payload);
            }
          }
          repl_rounds_in_.fetch_add(1, std::memory_order_relaxed);
          repl_bytes_in_.fetch_add(h.len, std::memory_order_relaxed);
          Respond(conn, kOk, h.req_id, h.key,
                  reinterpret_cast<const char*>(&r), 8);
          break;
        }
        case kAudit: {
          // Reader-thread digest-window read, same rationale as kStats:
          // the auditor's cross-check must answer even when an engine is
          // wedged mid-round — a silent wedge is one of the failure
          // modes it exists to name.  An unarmed server answers
          // {"armed":0} so a probing client downgrades instead of
          // sending audit markers nothing will honor.
          std::string js = AuditJson();
          Respond(conn, kOk, h.req_id, h.key, js.data(), js.size());
          break;
        }
        case kWindow: {
          // Fleet window publish: park the worker's JSON summary in its
          // bounded ring, keyed by window index (the frame's key field).
          // Reader thread, like kStats/kRepl — a publish is control-
          // plane state and must land even when every engine is wedged.
          // Re-publishing a held index replaces in place (idempotent
          // retries); a fresh index appends in order and the ring trims
          // from the oldest end.  The blob is stored verbatim, never
          // parsed — only a shape sniff (leading '{') rejects garbage.
          if (!fleet_armed_ || payload.empty() || payload[0] != '{') {
            Respond(conn, kError, h.req_id, h.key, nullptr, 0);
            break;
          }
          {
            std::lock_guard<std::mutex> lk(fleet_mu_);
            auto& ring = fleet_rings_[h.worker_id];
            bool replaced = false;
            for (auto& e : ring)
              if (e.first == key) {
                e.second.assign(payload.begin(), payload.end());
                replaced = true;
                break;
              }
            if (!replaced) {
              auto it = ring.begin();
              while (it != ring.end() && it->first < key) ++it;
              ring.insert(it, {key, std::string(payload.begin(),
                                                payload.end())});
              while (static_cast<int>(ring.size()) > fleet_windows_)
                ring.pop_front();
            }
          }
          fleet_publishes_.fetch_add(1, std::memory_order_relaxed);
          Respond(conn, kOk, h.req_id, h.key, nullptr, 0);
          break;
        }
        case kFleet: {
          // Merged fleet view, and the client's bootstrap probe: an
          // unarmed server answers {"armed":0} (kOk) so a probing
          // client downgrades instead of publishing windows nothing
          // retains — the kAudit probe law.
          std::string js = FleetJson();
          Respond(conn, kOk, h.req_id, h.key, js.data(), js.size());
          break;
        }
        case kLrScale: {
          // Fan out to every engine: per-key state is engine-owned, so
          // each engine rescales the ef_err of the keys assigned to it.
          // Highest priority so (under scheduling) the rescale runs ahead
          // of queued pushes; callers apply LR changes between steps.
          for (int i = 0; i < engine_threads_; ++i) {
            Task t;
            t.cmd = h.cmd;
            t.dtype = 0;
            t.flags = 0;
            t.req_id = h.req_id;
            t.worker_id = h.worker_id;
            t.key = 0;
            t.payload = payload;  // copy per engine
            t.conn = nullptr;     // the reader already acks
            t.seq = seq_.fetch_add(1);
            t.priority = UINT64_MAX;
            queues_[i].Push(std::move(t));
          }
          Respond(conn, kOk, h.req_id, h.key, nullptr, 0);
          break;
        }
        case kBarrier:
          AddRef(conn);   // barrier waiters outlive the reader
          HandleBarrier(conn, h.req_id, h.key, h.worker_id);
          break;
        case kShutdown:
          Respond(conn, kOk, h.req_id, h.key, nullptr, 0);
          shutdown_.store(true);
          // Unblock accept() on both listeners.
          { int s = socket(AF_INET, SOCK_STREAM, 0);
            sockaddr_in a{};
            a.sin_family = AF_INET;
            a.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
            a.sin_port = htons(static_cast<uint16_t>(port_));
            connect(s, reinterpret_cast<sockaddr*>(&a), sizeof(a));
            close(s); }
          if (uds_listen_fd_ >= 0) {
            int s = socket(AF_UNIX, SOCK_STREAM, 0);
            sockaddr_un a{};
            a.sun_family = AF_UNIX;
            std::strncpy(a.sun_path, uds_path_.c_str(),
                         sizeof(a.sun_path) - 1);
            connect(s, reinterpret_cast<sockaddr*>(&a), sizeof(a));
            close(s);
          }
          return;
        default: {
          Task t;
          t.cmd = h.cmd;
          t.dtype = h.dtype;
          t.flags = h.flags;
          t.req_id = h.req_id;
          t.worker_id = h.worker_id;
          t.key = h.key;
          t.payload = std::move(payload);
          t.conn = conn;
          t.scattered = scattered;
          t.seq = seq_.fetch_add(1);
          t.priority = 0;
          // Clock read only for traced frames: the untraced hot path
          // stays exactly as cheap as before.
          t.recv_us = (h.flags & kFlagTraced) ? NowUs() : 0;
          // `key` is the loop's aligned copy of h.key: h is
          // #pragma pack(1), so binding unordered_map::operator[]'s
          // `const key_type&` directly to h.key is UB (misaligned 8-byte
          // reference — UBSan catches it under the 4x2 soak).
          int idx = EngineFor(key, h.len);
          if (schedule_) {
            std::lock_guard<std::mutex> lk(store_mu_);
            t.priority = store_[key].push_count.load(
                std::memory_order_relaxed);  // closest-to-done first
          }
          AddRef(conn);   // the queued task holds the conn
          queues_[idx].Push(std::move(t));
        }
      }
    }
  }

  void HandleBarrier(Conn* conn, uint32_t req_id, uint64_t gen,
                     uint32_t worker) {
    // Waiters are grouped by generation so overlapping barriers (or a late
    // worker from generation g arriving amid generation g+1 waiters) can
    // never release a mixed group early.  Release is IDENTITY-based:
    // every LIVE member must have arrived (== the historical
    // distinct-count bar for a fixed dense world, but immune to a dead
    // worker's stale arrival under-filling or over-filling the group).
    // The live set is read INSIDE barrier_mu_ (member_mu_ nests inside
    // it; nothing takes them in the other order while holding
    // member_mu_), so an admit/evict between the read and the insert
    // cannot release against a stale world.
    //
    // A RELEASED generation stays an open door: a worker arriving at a
    // generation that already released — the elastic-join case, a
    // replacement worker's init() hitting the gen-0 startup rendezvous
    // the incumbents passed long ago — is answered immediately instead
    // of waiting for arrivals that will never come.  Generations are
    // therefore one-shot (monotonically increasing per job), which is
    // how every caller already uses them.
    std::vector<PendingPull> to_release;
    bool already_released = false;
    {
      std::lock_guard<std::mutex> lk(barrier_mu_);
      if (released_gens_.count(gen)) {
        already_released = true;
      } else {
        auto& group = barrier_waiters_[gen];
        group.push_back({conn, req_id, gen, 0, worker});
        if (BarrierGroupComplete(group, LiveWorkers())) {
          to_release.swap(group);
          barrier_waiters_.erase(gen);
          released_gens_.insert(gen);
        }
      }
    }
    if (already_released) {
      Respond(conn, kOk, req_id, gen, nullptr, 0);
      ReleaseRef(conn);
      return;
    }
    for (auto& w : to_release) {
      Respond(w.conn, kOk, w.req_id, w.key, nullptr, 0);
      ReleaseRef(w.conn);
    }
  }

  void EngineLoop(int idx) {
    Task t;
    while (queues_[idx].Pop(&t)) {
      switch (t.cmd) {
        case kInit: HandleInit(t); break;
        case kPush: HandlePush(t); break;
        case kPull: HandlePull(t); break;
        case kLrScale: HandleLrScale(t, idx); break;
        case kMembershipTask:
          // Internal fan-outs carry no conn; a WIRE frame claiming this
          // cmd is a protocol violator (or a probing client) and gets
          // the unknown-command error — never a membership mutation.
          if (t.conn == nullptr) HandleMembership(t, idx);
          else Respond(t.conn, kError, t.req_id, t.key, nullptr, 0);
          break;
        case kRingTask:
          // Same wire-rejection rule as kMembershipTask.
          if (t.conn == nullptr) HandleReshard(idx);
          else Respond(t.conn, kError, t.req_id, t.key, nullptr, 0);
          break;
        case kReplFlushTask:
          // Successor ack landed (ReplAck): serve the pulls the
          // zero-loss gate parked.  Same wire-rejection rule as the
          // other internal tasks.
          if (t.conn == nullptr) {
            KeyState* ks = FindState(t.key);
            if (ks != nullptr) FlushPulls(*ks, t.key);
          } else {
            Respond(t.conn, kError, t.req_id, t.key, nullptr, 0);
          }
          break;
        case kMigrate: HandleMigrate(t); break;
        case kCodec: HandleCodec(t); break;
        case kOpt: HandleOpt(t); break;
        default: Respond(t.conn, kError, t.req_id, t.key, nullptr, 0);
      }
      // The task's hold ends here (a deferred pull took its OWN ref in
      // HandlePull before this release, so the count can't dip to zero
      // in between).  kLrScale tasks carry no conn.  The payload buffer
      // recycles back to the conn's freelist — for a COPY_FIRST push
      // this is the PREVIOUS round's store (HandlePush swaps rather than
      // moves), so the same few buffers cycle socket -> store -> socket.
      if (t.conn) {
        PushBuf(t.conn, std::move(t.payload));
        ReleaseRef(t.conn);
      }
      t.conn = nullptr;
    }
  }

  void HandleLrScale(Task& t, int idx) {
    if (t.payload.size() < 4) return;
    float scale = 1.0f;
    std::memcpy(&scale, t.payload.data(), 4);
    std::vector<uint64_t> keys;
    {
      std::lock_guard<std::mutex> lk(assign_mu_);
      for (auto& kv : key_engine_)
        if (kv.second == idx) keys.push_back(kv.first);
    }
    for (uint64_t k : keys) {
      KeyState& ks = StateFor(k);
      for (auto& e : ks.ef_err) e *= scale;
    }
  }

  KeyState& StateFor(uint64_t key) {
    std::lock_guard<std::mutex> lk(store_mu_);
    return store_[key];
  }

  // The one round-completion predicate.  Empty round_members = fixed
  // membership (epoch never advanced): the historical distinct-sender
  // count.  Otherwise the round publishes exactly when every member of
  // ITS contributor set has merged — departed workers were erased from
  // the set by the transition fan-out, so a survivor-complete round
  // re-finalizes instead of waiting on the dead.
  bool RoundComplete(const KeyState& ks) const {
    if (slice_size_ <= 1) {
      if (ks.round_members.empty())
        return static_cast<int>(ks.seen.size()) >= num_workers_;
      for (uint32_t w : ks.round_members)
        if (!ks.seen.count(w)) return false;
      return true;
    }
    // Hierarchical mode: completion counts SLICES, not chips.  The
    // expected set is the slices the round's contributor set spans
    // (round_members, or the dense launch world at epoch 0); a slice
    // is covered once ANY of its members merged — normally its leader,
    // or the follower that took leadership over mid-round.  A slice
    // whose members were all erased by a membership transition simply
    // stops being expected — "a slice leaving = that many chips
    // leaving", expressed through the same round_members machinery.
    std::set<uint32_t> want;
    if (ks.round_members.empty()) {
      for (int w = 0; w < num_workers_; ++w)
        want.insert(static_cast<uint32_t>(w) /
                    static_cast<uint32_t>(slice_size_));
    } else {
      for (uint32_t w : ks.round_members)
        want.insert(w / static_cast<uint32_t>(slice_size_));
    }
    for (uint32_t w : ks.seen)
      want.erase(w / static_cast<uint32_t>(slice_size_));
    return want.empty();
  }

  // Membership transition, engine side (see FanOutMembership for the
  // payload).  Runs on the thread that owns each key, so no lock beyond
  // the assignment map is needed.
  void HandleMembership(Task& t, int idx) {
    const char* p = t.payload.data();
    size_t left = t.payload.size();
    if (left < 5) return;
    const bool refinalize = p[0] != 0;
    uint32_t n_old = 0;
    std::memcpy(&n_old, p + 1, 4);
    if (left < 9 + static_cast<size_t>(n_old) * 4) return;
    std::set<uint32_t> old_live;
    for (uint32_t i = 0; i < n_old; ++i) {
      uint32_t w = 0;
      std::memcpy(&w, p + 5 + i * 4, 4);
      old_live.insert(w);
    }
    uint32_t n_rm = 0;
    std::memcpy(&n_rm, p + 5 + static_cast<size_t>(n_old) * 4, 4);
    if (left < 9 + (static_cast<size_t>(n_old) + n_rm) * 4) return;
    std::set<uint32_t> removed;
    for (uint32_t i = 0; i < n_rm; ++i) {
      uint32_t w = 0;
      std::memcpy(&w, p + 9 + (static_cast<size_t>(n_old) + i) * 4, 4);
      removed.insert(w);
    }
    if (async_) return;   // no rounds to pin or re-finalize
    std::vector<uint64_t> keys;
    {
      std::lock_guard<std::mutex> lk(assign_mu_);
      for (auto& kv : key_engine_)
        if (kv.second == idx) keys.push_back(kv.first);
    }
    for (uint64_t key : keys) {
      KeyState& ks = StateFor(key);
      // Pin a still-open epoch-0 round to the set it opened under: from
      // this transition on, a joiner must never be able to complete (or
      // pollute) a round that predates its admission.
      if (!ks.seen.empty() && ks.round_members.empty())
        ks.round_members = old_live;
      // Erase departures — the surviving members become the round's
      // whole requirement (the re-finalize contract).
      if (!ks.round_members.empty())
        for (uint32_t w : removed) ks.round_members.erase(w);
      if (!refinalize || ks.seen.empty()) continue;
      // Publish if the survivors are all in.  A round whose pinned set
      // emptied entirely (every contributor departed) publishes what was
      // merged: the departed workers DID contribute, and holding the
      // round open would wedge every joiner's first pull.
      if (ks.round_members.empty() || RoundComplete(ks))
        PublishRound(ks, key, t.worker_id);
    }
  }

  // -- per-key codec table (CMD_CODEC) ------------------------------------
  // Small "k=v,k=v" integer lookup (the kwargs strings are the same ones
  // the worker registry ships at INIT).
  static int KwInt(const std::string& kw, const char* name, int dflt) {
    std::string pat = std::string(name) + "=";
    size_t at = kw.find(pat);
    // Must start a pair ("bits=" must not match "qbits=").
    while (at != std::string::npos && at != 0 && kw[at - 1] != ',')
      at = kw.find(pat, at + 1);
    if (at == std::string::npos) return dflt;
    return std::atoi(kw.c_str() + at + pat.size());
  }

  // The wire comp id the active kwargs imply for pushes of this key —
  // what the format-enforcement check compares against (0 = raw).
  static uint8_t ExpectedComp(const std::string& kw) {
    if (kw.find("compressor=onebit") != std::string::npos)
      return codec::kOnebit;
    if (kw.find("compressor=topk") != std::string::npos)
      return codec::kTopk;
    if (kw.find("compressor=randomk") != std::string::npos)
      return codec::kRandomk;
    if (kw.find("compressor=dithering") != std::string::npos)
      return codec::kDithering;
    if (kw.find("compressor=qblock") != std::string::npos)
      return codec::kQblock;
    return codec::kNone;
  }

  // Install one kwargs string as a key's ACTIVE codec: the single parse
  // shared by INIT (epoch 0 only), ApplyPendingCodec, and migrate
  // install, so the derived flags can never drift between paths.  A
  // switch away from an in-use server-EF leg arms the publish-time
  // residual fold (ef_fold_pending) instead of dropping the error.
  void ApplyCodecKwargs(KeyState& ks, const std::string& kw) {
    const bool ef_was_live = ks.server_ef && ks.bidirectional;
    ks.kwargs = kw;
    const bool onebit = kw.find("compressor=onebit") != std::string::npos;
    const bool qblock = kw.find("compressor=qblock") != std::string::npos;
    ks.bidirectional = onebit || qblock;
    ks.pull_comp = qblock ? codec::kQblock : codec::kOnebit;
    ks.onebit_scaled =
        kw.find("onebit_scaling=0") == std::string::npos;
    ks.server_ef = kw.find("ef=vanilla") != std::string::npos;
    int bits = KwInt(kw, "bits", 8);
    ks.qblock_bits = (bits == 4) ? 4 : 8;
    int block = KwInt(kw, "block", 256);
    if (block < 1) block = 1;
    if (block > 0xFFFF) block = 0xFFFF;
    ks.qblock_block = static_cast<uint16_t>(block);
    if (ef_was_live && !(ks.server_ef && ks.bidirectional) &&
        !ks.ef_err.empty())
      ks.ef_fold_pending = true;
  }

  void ApplyPendingCodec(KeyState& ks) {
    if (!ks.codec_pending) return;
    ApplyCodecKwargs(ks, ks.codec_next);
    ks.codec_applied_epoch = ks.codec_epoch;
    ks.codec_pending = false;
    ks.codec_next.clear();
  }

  static void JsonEscapeInto(std::string* out, const std::string& s) {
    for (char c : s) {
      if (c == '"' || c == '\\') out->push_back('\\');
      if (static_cast<unsigned char>(c) < 0x20) { out->push_back('?');
                                                  continue; }
      out->push_back(c);
    }
  }

  // The authoritative codec doc for one key — the SET/GET response and
  // the kCodecStale payload.  `kwargs` is always the ACTIVE codec (what
  // the round currently merging requires); `kwargs_next`/`effective_
  // round` describe the pending switch while one is staged.
  std::string CodecJson(uint64_t key, const KeyState& ks) {
    std::string js = "{\"key\":" + std::to_string(key) +
        ",\"epoch\":" + std::to_string(ks.codec_epoch) +
        ",\"applied_epoch\":" + std::to_string(ks.codec_applied_epoch) +
        ",\"pending\":" + (ks.codec_pending ? "1" : "0") +
        ",\"effective_round\":" + std::to_string(ks.codec_effective) +
        ",\"completed_round\":" + std::to_string(ks.completed_round) +
        ",\"kwargs\":\"";
    JsonEscapeInto(&js, ks.kwargs);
    js += "\",\"kwargs_next\":\"";
    JsonEscapeInto(&js, ks.codec_next);
    js += "\"}";
    return js;
  }

  void RespondCodecStale(Task& t, KeyState& ks) {
    codec_stale_.fetch_add(1, std::memory_order_relaxed);
    std::string js = CodecJson(t.key, ks);
    Respond(t.conn, kCodecStale, t.req_id, t.key, js.data(), js.size());
  }

  void HandleCodec(Task& t) {
    // Ring gate first, like every per-key op: a codec entry written on a
    // non-owner would be lost to the fleet (the owner's table is the one
    // CMD_MIGRATE carries and pushes are checked against).
    if (RingMisplaced(t.key)) {
      RespondMoved(t, FindState(t.key));
      return;
    }
    KeyState& ks = StateFor(t.key);
    if (t.flags & 1) {   // SET: u32 epoch | u64 effective | u32 klen | kw
      if (t.payload.size() < 16) {
        Respond(t.conn, kError, t.req_id, t.key, nullptr, 0);
        return;
      }
      uint32_t epoch = 0, klen = 0;
      uint64_t eff = 0;
      std::memcpy(&epoch, t.payload.data(), 4);
      std::memcpy(&eff, t.payload.data() + 4, 8);
      std::memcpy(&klen, t.payload.data() + 12, 4);
      if (t.payload.size() < 16ull + klen) {
        Respond(t.conn, kError, t.req_id, t.key, nullptr, 0);
        return;
      }
      // Applied only if newer — racing proposers are idempotent, and a
      // losing proposer reads the winner's doc from the response.
      if (epoch > ks.codec_epoch) {
        ks.codec_epoch = epoch;
        ks.codec_next.assign(t.payload.data() + 16, klen);
        ks.codec_effective = eff;
        ks.codec_pending = true;
        codec_sets_.fetch_add(1, std::memory_order_relaxed);
        // Async mode has no rounds to hold the boundary for: the table
        // applies immediately (pushes are independent deltas anyway).
        if (async_) ApplyPendingCodec(ks);
      }
    }
    std::string js = CodecJson(t.key, ks);
    Respond(t.conn, kOk, t.req_id, t.key, js.data(), js.size());
  }

  // -- global knob plane (CMD_KNOB) ---------------------------------------
  // The authoritative knob doc — the SET/GET/ACK response and the
  // kKnobStale payload.  `kwargs` is always the ACTIVE table (what the
  // rounds currently merging were planned under); `kwargs_next` /
  // `effective_round` describe the staged switch while one is pending.
  // The acked map is included so a proposer can observe fleet adoption.
  std::string KnobJsonLocked() {
    std::string js = "{\"epoch\":" + std::to_string(knob_epoch_) +
        ",\"applied_epoch\":" + std::to_string(knob_applied_) +
        ",\"pending\":" + (knob_pending_ ? "1" : "0") +
        ",\"effective_round\":" + std::to_string(knob_effective_) +
        ",\"kwargs\":\"";
    JsonEscapeInto(&js, knob_kwargs_);
    js += "\",\"kwargs_next\":\"";
    JsonEscapeInto(&js, knob_next_);
    js += "\",\"acked\":{";
    bool first = true;
    for (auto& kv : knob_acked_) {
      js += (first ? "\"" : ",\"") + std::to_string(kv.first) + "\":" +
            std::to_string(kv.second);
      first = false;
    }
    js += "}}";
    return js;
  }

  // The server half of the boundary apply: flip the staged table to
  // ACTIVE once any key's completed_round reaches the effective round.
  // Observational only (the enforcement is the per-push acked check) —
  // but it keeps the doc's `kwargs` field truthful for GET/stale
  // replies.  Caller holds knob_mu_.
  void MaybeApplyKnobLocked(uint64_t completed_round) {
    if (knob_pending_ && completed_round >= knob_effective_) {
      knob_kwargs_ = knob_next_;
      knob_applied_ = knob_epoch_;
      knob_pending_ = false;
      knob_next_.clear();
    }
  }

  // Reader-thread CMD_KNOB handler (kStats rationale: global
  // control-plane state, must answer even when an engine is wedged).
  // flags bit0 = SET, bit1 = ACK, neither = GET; every path answers the
  // authoritative doc so racing proposers and pollers all converge.
  void HandleKnobFrame(Conn* conn, uint32_t req_id, uint64_t key,
                       uint16_t flags, uint32_t worker_id,
                       const std::vector<char>& payload) {
    std::unique_lock<std::mutex> lk(knob_mu_);
    if (flags & 1) {   // SET: u32 epoch | u64 effective | u32 klen | kw
      if (payload.size() < 16) {
        lk.unlock();
        Respond(conn, kError, req_id, key, nullptr, 0);
        return;
      }
      uint32_t epoch = 0, klen = 0;
      uint64_t eff = 0;
      std::memcpy(&epoch, payload.data(), 4);
      std::memcpy(&eff, payload.data() + 4, 8);
      std::memcpy(&klen, payload.data() + 12, 4);
      if (payload.size() < 16ull + klen) {
        lk.unlock();
        Respond(conn, kError, req_id, key, nullptr, 0);
        return;
      }
      // Applied only if newer — racing proposers are idempotent, and a
      // losing proposer reads the winner's doc from the response.
      if (epoch > knob_epoch_) {
        knob_epoch_ = epoch;
        knob_next_.assign(payload.data() + 16, klen);
        knob_effective_ = eff;
        knob_pending_ = true;
        knob_sets_.fetch_add(1, std::memory_order_relaxed);
        knob_epoch_atomic_.store(epoch, std::memory_order_release);
        // Async mode has no rounds to hold the boundary for: the table
        // applies immediately, exactly like the codec law's async arm.
        if (async_) MaybeApplyKnobLocked(eff);
        // The proposer adopted what it proposed — its SET doubles as
        // its ACK, so a 1-worker job never needs the stale backstop.
        uint32_t& acked = knob_acked_[worker_id];
        if (epoch > acked) acked = epoch;
      }
    } else if (flags & 2) {   // ACK: u32 epoch this worker has adopted
      if (payload.size() >= 4) {
        uint32_t epoch = 0;
        std::memcpy(&epoch, payload.data(), 4);
        uint32_t& acked = knob_acked_[worker_id];
        if (epoch > acked) acked = epoch;
      }
    }
    std::string js = KnobJsonLocked();
    lk.unlock();
    Respond(conn, kOk, req_id, key, js.data(), js.size());
  }

  // Engine-thread push-path backstop (called only once the fast atomic
  // gate saw a nonzero epoch): a current-round push from a worker that
  // has not acked the newest knob epoch, for a key already at/past the
  // switch boundary, is rejected with the doc — its staged work may ride
  // a stale fusion layout / pool size / lane set.  Returns true when the
  // push was answered (caller returns without mutating state).
  bool KnobStaleCheck(Task& t, KeyState& ks) {
    std::unique_lock<std::mutex> lk(knob_mu_);
    MaybeApplyKnobLocked(ks.completed_round);
    auto it = knob_acked_.find(t.worker_id);
    const uint32_t acked = it == knob_acked_.end() ? 0 : it->second;
    if (acked >= knob_epoch_ || ks.completed_round < knob_effective_)
      return false;
    knob_stale_.fetch_add(1, std::memory_order_relaxed);
    std::string js = KnobJsonLocked();
    lk.unlock();
    Respond(t.conn, kKnobStale, t.req_id, t.key, js.data(), js.size());
    return true;
  }

  // -- server-resident optimizer plane (CMD_OPT) --------------------------
  // "k=v" double lookup, the float sibling of KwInt: strtod yields the
  // SAME f64 the worker-local optax baseline holds for the hyperparam
  // (Python repr round-trips through strtod exactly), so every f32
  // constant the update stage derives matches optax's rounding.
  static double KwFloat(const std::string& kw, const char* name,
                        double dflt) {
    std::string pat = std::string(name) + "=";
    size_t at = kw.find(pat);
    while (at != std::string::npos && at != 0 && kw[at - 1] != ',')
      at = kw.find(pat, at + 1);
    if (at == std::string::npos) return dflt;
    return std::strtod(kw.c_str() + at + pat.size(), nullptr);
  }

  // f32 integer power by square-and-multiply, op-for-op identical to
  // jax.lax.integer_pow's unrolling — which is what the worker-local
  // optax baseline computes for the Adam bias correction `decay**count`
  // when the count is concrete (eager/disable_jit execution) — with f32
  // rounding at every multiply.  NOT std::pow: libm's powf and XLA's
  // traced pow both round differently, and the equivalence law is
  // bitwise.
  static float IntPowF32(float x, uint64_t y) {
    if (y == 0) return 1.0f;
    float acc = 0.0f;
    bool have = false;
    while (y > 0) {
      if (y & 1) {
        acc = have ? acc * x : x;
        have = true;
      }
      y >>= 1;
      if (y > 0) x = x * x;
    }
    return acc;
  }

  // Install one kwargs string as a key's ACTIVE optimizer ("" = off) —
  // the single parse shared by ApplyPendingOpt and migrate install, the
  // ApplyCodecKwargs discipline.
  void ApplyOptKwargs(KeyState& ks, const std::string& kw) {
    ks.opt_kwargs = kw;
    uint8_t kind = 0;
    if (kw.find("opt=sgd") != std::string::npos) kind = 1;
    else if (kw.find("opt=momentum") != std::string::npos) kind = 2;
    else if (kw.find("opt=adam") != std::string::npos) kind = 3;
    else if (kw.find("opt=adagrad") != std::string::npos) kind = 4;
    ks.opt_kind = kind;
    ks.opt_lr = KwFloat(kw, "lr", 0.01);
    ks.opt_mu = KwFloat(kw, "mu", 0.9);
    ks.opt_b1 = KwFloat(kw, "b1", 0.9);
    ks.opt_b2 = KwFloat(kw, "b2", 0.999);
    // optax.adagrad defaults eps=1e-7 and seeds the sum-of-squares
    // accumulator at initial_accumulator_value=0.1 (scale_by_rss);
    // the other optimizers keep their optax defaults.
    ks.opt_eps = KwFloat(kw, "eps", kind == 4 ? 1e-7 : 1e-8);
    ks.opt_acc0 = KwFloat(kw, "acc0", 0.1);
    ks.opt_gscale = KwFloat(kw, "gscale", 1.0);
  }

  void ApplyPendingOpt(KeyState& ks) {
    if (!ks.opt_pending) return;
    ApplyOptKwargs(ks, ks.opt_next);
    ks.opt_applied_epoch = ks.opt_epoch;
    ks.opt_pending = false;
    ks.opt_next.clear();
  }

  // Keep the server-level optimizer-slot-bytes gauge in step with this
  // key's params/m/v allocations (engine thread; the atomic absorbs the
  // signed delta through unsigned wraparound).
  void OptSlotAccount(KeyState& ks) {
    const uint64_t now =
        (ks.params.size() + ks.opt_m.size() + ks.opt_v.size()) * 4;
    opt_slot_bytes_.fetch_add(now - ks.opt_slot_acc,
                              std::memory_order_relaxed);
    ks.opt_slot_acc = now;
  }

  // The authoritative opt doc for one key — the CMD_OPT response.
  // slots_crc is the chunk-summed CRC over params|m|v (audit::Digest,
  // summed per buffer): the byte-equality proof surface the migration
  // chaos tests compare across an ownership handoff.  Computed only on
  // this control path, never on the data plane.
  std::string OptJson(uint64_t key, const KeyState& ks) {
    uint32_t crc = 0;
    if (!ks.params.empty())
      crc += audit::Digest(
          reinterpret_cast<const char*>(ks.params.data()),
          ks.params.size() * 4);
    if (!ks.opt_m.empty())
      crc += audit::Digest(
          reinterpret_cast<const char*>(ks.opt_m.data()),
          ks.opt_m.size() * 4);
    if (!ks.opt_v.empty())
      crc += audit::Digest(
          reinterpret_cast<const char*>(ks.opt_v.data()),
          ks.opt_v.size() * 4);
    std::string js = "{\"key\":" + std::to_string(key) +
        ",\"epoch\":" + std::to_string(ks.opt_epoch) +
        ",\"applied_epoch\":" + std::to_string(ks.opt_applied_epoch) +
        ",\"pending\":" + (ks.opt_pending ? "1" : "0") +
        ",\"effective_round\":" + std::to_string(ks.opt_effective) +
        ",\"completed_round\":" + std::to_string(ks.completed_round) +
        ",\"param_version\":" + std::to_string(ks.param_version) +
        ",\"opt_step\":" + std::to_string(ks.opt_step) +
        ",\"opt_mode\":" + std::to_string(ks.opt_kind) +
        ",\"params_n\":" + std::to_string(ks.params.size()) +
        ",\"slot_bytes\":" + std::to_string(
            (ks.params.size() + ks.opt_m.size() + ks.opt_v.size()) * 4) +
        ",\"slots_crc\":" + std::to_string(crc) +
        ",\"kwargs\":\"";
    JsonEscapeInto(&js, ks.opt_kwargs);
    js += "\",\"kwargs_next\":\"";
    JsonEscapeInto(&js, ks.opt_next);
    js += "\"}";
    return js;
  }

  void HandleOpt(Task& t) {
    // Ring gate first, like every per-key op: the owner's table/slots
    // are what CMD_MIGRATE carries and publishes run against.
    if (RingMisplaced(t.key)) {
      RespondMoved(t, FindState(t.key));
      return;
    }
    if (async_ && (t.flags & 3)) {
      // Async mode has no rounds: there is no merge boundary for a
      // server-side update stage to run at.  Writes fail loudly.
      Respond(t.conn, kError, t.req_id, t.key, nullptr, 0);
      return;
    }
    KeyState& ks = StateFor(t.key);
    // Failover: a client probing/reseeding the optimizer plane after a
    // server death must see the REPLICATED slots, not an empty key —
    // the adopted param_version/params_n are what lets it skip the
    // reseed entirely (zero optimizer resets).
    MaybeAdoptReplica(t.key, ks);
    if (t.flags & 2) {
      // PARAM SEED: raw f32 initial parameters, applied only while the
      // key holds none — idempotent across racing workers (they all
      // ship the same broadcast weights), and a no-op after a migration
      // installed the authoritative copy (a replayed seed can never
      // reset live training, the kSeed/INIT idempotency discipline).
      if (!t.payload.empty() && t.payload.size() % 4 == 0 &&
          ks.params.empty()) {
        const float* f = reinterpret_cast<const float*>(t.payload.data());
        ks.params.assign(f, f + t.payload.size() / 4);
        ks.active.store(true, std::memory_order_relaxed);
        OptSlotAccount(ks);
        opt_seeds_.fetch_add(1, std::memory_order_relaxed);
        StatOpt(t.key, ks.param_version, ks.opt_kind);
      }
    } else if (t.flags & 1) {
      // SET: u32 epoch | u64 effective_round | u32 klen | kwargs.
      if (t.payload.size() < 16) {
        Respond(t.conn, kError, t.req_id, t.key, nullptr, 0);
        return;
      }
      uint32_t epoch = 0, klen = 0;
      uint64_t eff = 0;
      std::memcpy(&epoch, t.payload.data(), 4);
      std::memcpy(&eff, t.payload.data() + 4, 8);
      std::memcpy(&klen, t.payload.data() + 12, 4);
      if (t.payload.size() < 16ull + klen) {
        Respond(t.conn, kError, t.req_id, t.key, nullptr, 0);
        return;
      }
      // Applied only if newer — the CMD_CODEC/CMD_RING_SET idempotency
      // law: racing proposers converge, a replayed declaration cannot
      // regress the table, and the losers adopt the winner's doc from
      // the response.
      if (epoch > ks.opt_epoch) {
        ks.opt_epoch = epoch;
        ks.opt_next.assign(t.payload.data() + 16, klen);
        ks.opt_effective = eff;
        ks.opt_pending = true;
        opt_sets_.fetch_add(1, std::memory_order_relaxed);
      }
    }
    std::string js = OptJson(t.key, ks);
    Respond(t.conn, kOk, t.req_id, t.key, js.data(), js.size());
  }

  // The update stage: merge -> optimizer step -> publish *parameters*.
  // Runs inside PublishRound AFTER the codec/EF publish leg produced
  // `out`, and consumes EXACTLY the bytes a sum-mode pull would have
  // served — the decode of the recompressed blob for bidirectional
  // codecs (so compression + server EF behave identically to the
  // worker-local baseline, where every worker's optax step consumed
  // that same decode), the raw f32 sum otherwise.  `out` is then
  // replaced by the updated parameters: pulls adopt params, and
  // param_version increments exactly once — the stale-round push guard
  // upstream is what makes a replayed push unable to re-enter here.
  // Every f32 operation matches the optax eager op sequence
  // (docs/server-optimizer.md "Equivalence").
  void OptUpdateStage(KeyState& ks, uint64_t key, bool served_compressed) {
    const size_t ne = ks.params.size();
    if (ne == 0) {
      if (!ks.opt_warned) {
        ks.opt_warned = true;
        std::fprintf(stderr,
                     "[byteps server] server-opt key %llu has an active "
                     "optimizer but no seeded parameters; publishing "
                     "sums until CMD_OPT seeds them (param_version "
                     "stalls — doctor rule param_version_stall)\n",
                     static_cast<unsigned long long>(key));
      }
      return;
    }
    // Reusable scratch: the raw path overwrites it whole (memcpy) and
    // the compressed path lets DecompressTo zero it exactly when the
    // codec's scatter semantics need zeros — no per-round allocation,
    // no unconditional memset.
    std::vector<float>& g = ks.opt_scratch;
    if (g.size() != ne) g.resize(ne);
    if (served_compressed) {
      uint32_t n32 = 0;
      if (ks.out.size() >= 5)
        std::memcpy(&n32, ks.out.data() + 1, 4);
      if (n32 != ne ||
          !codec::DecompressTo(ks.out.data(), ks.out.size(), g.data(),
                               n32, /*zero_dst=*/true)) {
        std::fprintf(stderr,
                     "[byteps server] server-opt key %llu: published "
                     "blob failed to decode (n=%u, params=%zu); update "
                     "skipped\n",
                     static_cast<unsigned long long>(key), n32, ne);
        return;
      }
    } else {
      if (ks.out.size() != ne * 4) {
        if (!ks.opt_warned) {
          ks.opt_warned = true;
          std::fprintf(stderr,
                       "[byteps server] server-opt key %llu: published "
                       "sum is %zu bytes but params hold %zu elements; "
                       "update skipped (param_version stalls)\n",
                       static_cast<unsigned long long>(key),
                       ks.out.size(), ne);
        }
        return;
      }
      std::memcpy(g.data(), ks.out.data(), ne * 4);
    }
    if (ks.opt_gscale != 1.0) {
      // The baseline scales the pulled sum before its optax step
      // (grad = gscale * sum, one weak-f32 scalar multiply) — and only
      // when the scale is not exactly 1, so the unscaled path stays
      // op-identical on both sides.
      const float gs = static_cast<float>(ks.opt_gscale);
      for (size_t i = 0; i < ne; ++i) g[i] = gs * g[i];
    }
    float* p = ks.params.data();
    // optax scale_by_learning_rate: step_size = -1 * lr in f64, rounded
    // weak-f32 at the multiply.
    const float nlr = static_cast<float>(-1.0 * ks.opt_lr);
    switch (ks.opt_kind) {
      case 1: {  // sgd: u = -lr * g; p = p + u
        for (size_t i = 0; i < ne; ++i) p[i] = p[i] + nlr * g[i];
        break;
      }
      case 2: {  // sgd+momentum (optax trace): t = g + mu*t; u = -lr*t
        if (ks.opt_m.size() != ne) ks.opt_m.assign(ne, 0.0f);
        const float mu = static_cast<float>(ks.opt_mu);
        for (size_t i = 0; i < ne; ++i) {
          const float m = g[i] + mu * ks.opt_m[i];
          ks.opt_m[i] = m;
          p[i] = p[i] + nlr * m;
        }
        break;
      }
      case 3: {  // adam (optax scale_by_adam, eps_root=0)
        if (ks.opt_m.size() != ne) ks.opt_m.assign(ne, 0.0f);
        if (ks.opt_v.size() != ne) ks.opt_v.assign(ne, 0.0f);
        const float b1f = static_cast<float>(ks.opt_b1);
        const float b2f = static_cast<float>(ks.opt_b2);
        const float onemb1 = static_cast<float>(1.0 - ks.opt_b1);
        const float onemb2 = static_cast<float>(1.0 - ks.opt_b2);
        const float epsf = static_cast<float>(ks.opt_eps);
        // safe_int32_increment: the count saturates at INT32_MAX.
        const uint64_t step = ks.opt_step >= 2147483647ULL
                                  ? 2147483647ULL : ks.opt_step + 1;
        const float bc1 = 1.0f - IntPowF32(b1f, step);
        const float bc2 = 1.0f - IntPowF32(b2f, step);
        for (size_t i = 0; i < ne; ++i) {
          const float gi = g[i];
          const float mi = onemb1 * gi + b1f * ks.opt_m[i];
          const float vi = onemb2 * (gi * gi) + b2f * ks.opt_v[i];
          ks.opt_m[i] = mi;
          ks.opt_v[i] = vi;
          const float mh = mi / bc1;
          const float vh = vi / bc2;
          const float u = nlr * (mh / (std::sqrt(vh) + epsf));
          p[i] = p[i] + u;
        }
        break;
      }
      case 4: {  // adagrad (optax scale_by_rss): s += g*g;
                 // u = g * (s > 0 ? 1/sqrt(s+eps) : 0); p += -lr*u
        if (ks.opt_v.size() != ne)
          ks.opt_v.assign(ne, static_cast<float>(ks.opt_acc0));
        const float epsf = static_cast<float>(ks.opt_eps);
        for (size_t i = 0; i < ne; ++i) {
          const float gi = g[i];
          const float s = ks.opt_v[i] + gi * gi;
          ks.opt_v[i] = s;
          const float scale =
              s > 0.0f ? 1.0f / std::sqrt(s + epsf) : 0.0f;
          p[i] = p[i] + nlr * (scale * gi);
        }
        break;
      }
      default:
        return;
    }
    if (ks.opt_step < 2147483647ULL) ks.opt_step++;
    ks.param_version++;
    ks.out.assign(reinterpret_cast<const char*>(p),
                  reinterpret_cast<const char*>(p) + ne * 4);
    OptSlotAccount(ks);
    opt_updates_.fetch_add(1, std::memory_order_relaxed);
    StatOpt(key, ks.param_version, ks.opt_kind);
    DebugLog("opt_update", key, 0, ks.completed_round, ks.out);
  }

  // Row-wise update stage for embedding keys: runs inside PublishRound
  // after embed_out adopted the round's merged rows.  Only touched rows
  // step — per-row step counts drive Adam's bias correction (lazy
  // Adam) and the Adagrad accumulator, matching a worker-local optax
  // baseline that gathers the touched rows, steps them, and scatters
  // the result back.  param_version increments exactly once per
  // publish, the same exactly-one-update law as the dense stage.
  // Every f32 op mirrors the dense arms above element-for-element.
  void EmbedUpdateStage(KeyState& ks, uint64_t key) {
    const size_t w = ks.embed_width;
    const size_t total = static_cast<size_t>(ks.embed_rows) * w;
    if (total == 0) return;
    // Zero-init unless CMD_OPT seeded the full table (a wrong-size seed
    // is discarded — the dense stage's size guard, row-wise).
    if (ks.params.size() != total) ks.params.assign(total, 0.0f);
    if (ks.embed_row_step.size() != ks.embed_rows)
      ks.embed_row_step.assign(ks.embed_rows, 0);
    if ((ks.opt_kind == 2 || ks.opt_kind == 3) && ks.opt_m.size() != total)
      ks.opt_m.assign(total, 0.0f);
    if (ks.opt_kind == 3 && ks.opt_v.size() != total)
      ks.opt_v.assign(total, 0.0f);
    if (ks.opt_kind == 4 && ks.opt_v.size() != total)
      ks.opt_v.assign(total, static_cast<float>(ks.opt_acc0));
    const float nlr = static_cast<float>(-1.0 * ks.opt_lr);
    const float gs = static_cast<float>(ks.opt_gscale);
    const bool scaled = ks.opt_gscale != 1.0;
    const float muf = static_cast<float>(ks.opt_mu);
    const float b1f = static_cast<float>(ks.opt_b1);
    const float b2f = static_cast<float>(ks.opt_b2);
    const float onemb1 = static_cast<float>(1.0 - ks.opt_b1);
    const float onemb2 = static_cast<float>(1.0 - ks.opt_b2);
    const float epsf = static_cast<float>(ks.opt_eps);
    for (auto& kv : ks.embed_out) {
      const uint64_t r = kv.first;
      if (r >= ks.embed_rows || kv.second.size() != w) continue;
      float* g = kv.second.data();
      if (scaled)
        for (size_t i = 0; i < w; ++i) g[i] = gs * g[i];
      float* p = ks.params.data() + r * w;
      switch (ks.opt_kind) {
        case 1: {  // sgd
          for (size_t i = 0; i < w; ++i) p[i] = p[i] + nlr * g[i];
          break;
        }
        case 2: {  // momentum (optax trace — no step count needed)
          float* m = ks.opt_m.data() + r * w;
          for (size_t i = 0; i < w; ++i) {
            const float mi = g[i] + muf * m[i];
            m[i] = mi;
            p[i] = p[i] + nlr * mi;
          }
          break;
        }
        case 3: {  // adam, bias-corrected by THIS ROW's update count
          const uint32_t rs = ks.embed_row_step[r];
          const uint64_t step =
              rs >= 2147483647u ? 2147483647ULL : rs + 1ULL;
          const float bc1 = 1.0f - IntPowF32(b1f, step);
          const float bc2 = 1.0f - IntPowF32(b2f, step);
          float* m = ks.opt_m.data() + r * w;
          float* v = ks.opt_v.data() + r * w;
          for (size_t i = 0; i < w; ++i) {
            const float gi = g[i];
            const float mi = onemb1 * gi + b1f * m[i];
            const float vi = onemb2 * (gi * gi) + b2f * v[i];
            m[i] = mi;
            v[i] = vi;
            const float u = nlr * ((mi / bc1) / (std::sqrt(vi / bc2) + epsf));
            p[i] = p[i] + u;
          }
          break;
        }
        case 4: {  // adagrad (optax scale_by_rss)
          float* v = ks.opt_v.data() + r * w;
          for (size_t i = 0; i < w; ++i) {
            const float gi = g[i];
            const float s = v[i] + gi * gi;
            v[i] = s;
            const float scale =
                s > 0.0f ? 1.0f / std::sqrt(s + epsf) : 0.0f;
            p[i] = p[i] + nlr * (scale * gi);
          }
          break;
        }
        default:
          return;
      }
      if (ks.embed_row_step[r] < 2147483647u) ks.embed_row_step[r]++;
    }
    if (ks.opt_step < 2147483647ULL) ks.opt_step++;
    ks.param_version++;
    OptSlotAccount(ks);
    opt_updates_.fetch_add(1, std::memory_order_relaxed);
    StatOpt(key, ks.param_version, ks.opt_kind);
  }

  void HandleInit(Task& t) {
    // Init allocates the merged store; like the reference's init push it is
    // idempotent and sized by the declared length (reference:
    // server.cc:270-298).  Payload: u64 declared_len | u32 kwargs_len |
    // kwargs (compressor registration, reference: server.cc:232-261).
    // Responds with u64 completed_round so reconnecting workers re-seed
    // their round counters from server state.
    //
    // Ring ownership gate: once the ring epoch has advanced, an INIT
    // for a key this server no longer owns must NOT recreate state here
    // — hand over any remaining state, then redirect (kMoved carries
    // the ring table).  Checked before StateFor so a redirected key
    // never even allocates.
    if (RingMisplaced(t.key)) {
      RespondMoved(t, FindState(t.key));
      return;
    }
    KeyState& ks = StateFor(t.key);
    // Failover: adopt the chain replica BEFORE the size check below —
    // the adopted store matches the declared size, so a reconnecting
    // worker's re-INIT resumes at the replicated round instead of
    // resetting to a fresh store.
    MaybeAdoptReplica(t.key, ks);
    ks.active.store(true, std::memory_order_relaxed);
    uint64_t n = 0;
    if (t.payload.size() >= 8)
      std::memcpy(&n, t.payload.data(), 8);
    if (t.payload.size() >= 12) {
      uint32_t klen = 0;
      std::memcpy(&klen, t.payload.data() + 8, 4);
      if (t.payload.size() >= 12 + klen) {
        // "k=v,k=v" kwargs, same strings the reference ships in its
        // kCompressedPushPull init (reference: server.cc:232-261).
        // Once the key's codec epoch has advanced, the TABLE governs:
        // a reconnecting worker's re-declare (or a replayed launch
        // config) must not reset a renegotiated codec mid-round — the
        // worker learns the live codec from CMD_CODEC / kCodecStale.
        if (ks.codec_epoch == 0)
          ApplyCodecKwargs(ks, std::string(t.payload.data() + 12, klen));
        // Row-sparse embedding declaration: `embed_rows=N,embed_width=D`
        // with declared length 0 turns the key into an embedding key —
        // the dense store stays empty, round state lives row-wise.
        // Idempotent like the size path below: a re-declare with the
        // same shape touches nothing; a shape CHANGE resets the sparse
        // round state (the dense size-change reset, row-wise).
        const std::string kw(t.payload.data() + 12, klen);
        const int er = KwInt(kw, "embed_rows", 0);
        const int ew = KwInt(kw, "embed_width", 0);
        if (er > 0 && ew > 0 && n == 0) {
          const uint64_t nr = static_cast<uint64_t>(er);
          const uint32_t nw = static_cast<uint32_t>(ew);
          if (ks.embed_rows != nr || ks.embed_width != nw) {
            // Declared-footprint gauge: signed delta via unsigned
            // wraparound, the OptSlotAccount discipline.
            embed_table_bytes_.fetch_add(
                nr * nw * 4 - ks.embed_rows * ks.embed_width * 4,
                std::memory_order_relaxed);
            ks.embed_rows = nr;
            ks.embed_width = nw;
            ks.embed_merge.clear();
            ks.embed_out.clear();
            ks.embed_row_step.clear();
            ks.seen.clear();
            ks.merge_ts.clear();
          }
        }
      }
    }
    if (ks.store.size() != n) {
      ks.store.assign(n, 0);
      ks.seen.clear();
      ks.merge_ts.clear();
    }
    // Publish the declared size for the reader threads' scatter check
    // (release pairs with the reader's acquire load).
    ks.declared_len.store(n, std::memory_order_release);
    ks.dtype = t.dtype;
    uint64_t round = ks.completed_round;
    Respond(t.conn, kOk, t.req_id, t.key,
            reinterpret_cast<const char*>(&round), sizeof(round));
  }

  void HandlePush(Task& t) {
    KeyState& ks = StateFor(t.key);
    // Failover: a re-pushed open round adopts the chain replica first,
    // so the merge lands on the replicated published state (and the
    // replica's `seen` set dedups contributions the dead owner already
    // merged — the exactly-once law).
    MaybeAdoptReplica(t.key, ks);
    // A scattered frame's payload lives in ks.scatter_buf (reader-filled
    // under the scatter lease); this engine task owns releasing the
    // lease — RAII, so every validation early-return below releases it.
    struct LeaseGuard {
      std::atomic<bool>* lease;
      ~LeaseGuard() {
        if (lease) lease->store(false, std::memory_order_release);
      }
    } lease_guard{t.scattered ? &ks.scatter_leased : nullptr};
    const std::vector<char>* data =
        t.scattered ? &ks.scatter_buf : &t.payload;
    // Captured before the COPY_FIRST swap below can gut the source.
    const uint64_t wire_len = data->size();
    // Ring ownership gate (after the lease guard is armed, so a
    // scattered frame's lease always releases): a push for a key this
    // server no longer owns hands its state over, then redirects — the
    // worker replays the SAME gradient to the new owner, so no round is
    // lost and nothing merges twice (state-before-redirect).
    if (RingMisplaced(t.key)) {
      RespondMoved(t, &ks);
      return;
    }
    ks.active.store(true, std::memory_order_relaxed);
    if (t.dtype == kSeed) {
      // Store seeding for async weight-delta training: applied only if the
      // key has never been pushed, so a late-joining/rejoining worker
      // adopts the live global weights instead of resetting them.
      // Meaningless under sync rounds — reject there (fail fast beats a
      // silent round-counter desync).
      if (!async_) {
        Respond(t.conn, kError, t.req_id, t.key, nullptr, 0);
        return;
      }
      bool first = ks.push_count.load(std::memory_order_relaxed) == 0;
      ks.push_count.fetch_add(1, std::memory_order_relaxed);
      if (first) {
        ks.store = t.payload;
        ks.dtype = kF32;
      }
      ks.out = ks.store;
      StatPush(t.key, t.worker_id, wire_len, true, 0);
      Respond(t.conn, kOk, t.req_id, t.key, nullptr, 0);
      FlushPulls(ks, t.key);
      return;
    }
    if (t.dtype == kSparseRows) {
      // Row-sparse embedding push: SparseHdr | index stream | dense f32
      // rows.  A dedicated branch — the dense guards below reason about
      // store.size(), which embed keys keep at zero.  The guard order
      // mirrors the dense path exactly: stale-round ack-and-drop,
      // in-round dedup, elastic membership, pending-opt arm at the
      // round boundary.  Async mode has no round boundary for the
      // row-wise update stage to run at — reject, like CMD_OPT writes.
      // Knob/codec staleness does not apply: sparse frames carry their
      // own codec in the header and never ride fusion buckets.
      if (async_ || ks.embed_rows == 0 || ks.embed_width == 0) {
        Respond(t.conn, kError, t.req_id, t.key, nullptr, 0);
        return;
      }
      if (!RoundMatch(t.flags, ks.completed_round)) {
        StatPush(t.key, t.worker_id, wire_len, false, 0);
        Respond(t.conn, kOk, t.req_id, t.key, nullptr, 0);
        return;
      }
      if (ks.seen.count(t.worker_id)) {
        ks.push_count.fetch_add(1, std::memory_order_relaxed);
        StatPush(t.key, t.worker_id, wire_len, false, 0);
        Respond(t.conn, kOk, t.req_id, t.key, nullptr, 0);
        return;
      }
      if (epoch_atomic_.load(std::memory_order_acquire) != 0) {
        if (ks.seen.empty()) AdoptRoundMembers(ks);
        if (!ks.round_members.empty() &&
            !ks.round_members.count(t.worker_id)) {
          deferred_joins_.fetch_add(1, std::memory_order_relaxed);
          StatPush(t.key, t.worker_id, wire_len, false, 0);
          Respond(t.conn, kOk, t.req_id, t.key, nullptr, 0);
          return;
        }
      }
      if (ks.opt_epoch != 0 && ks.opt_pending && ks.seen.empty() &&
          ks.completed_round >= ks.opt_effective)
        ApplyPendingOpt(ks);
      // Validate the whole frame BEFORE any state mutates (the dense
      // path's ordering invariant): a malformed frame must leave the
      // open merge exactly as it found it.
      SparseHdr h;
      const size_t w = ks.embed_width;
      std::vector<uint32_t> idx;
      bool ok = data->size() >= sizeof(h);
      if (ok) {
        std::memcpy(&h, data->data(), sizeof(h));
        ok = h.width == w &&
             data->size() >= sizeof(h) +
                 static_cast<uint64_t>(h.idx_bytes) +
                 static_cast<uint64_t>(h.nrows) * w * 4 &&
             DecodeSparseIndices(
                 reinterpret_cast<const unsigned char*>(data->data()) +
                     sizeof(h),
                 h.idx_bytes, h.nrows, h.codec, &idx);
      }
      if (ok)
        for (uint32_t i = 0; i < h.nrows; ++i)
          if (idx[i] >= ks.embed_rows) { ok = false; break; }
      if (!ok) {
        Respond(t.conn, kError, t.req_id, t.key, nullptr, 0);
        return;
      }
      const char* rows = data->data() + sizeof(h) + h.idx_bytes;
      std::vector<float> tmp(w);
      for (uint32_t i = 0; i < h.nrows; ++i) {
        std::memcpy(tmp.data(), rows + static_cast<size_t>(i) * w * 4,
                    w * 4);
        auto it = ks.embed_merge.find(idx[i]);
        if (it == ks.embed_merge.end()) {
          // COPY_FIRST, row-wise: the row's first touch adopts the
          // pushed bytes verbatim (zero-init plus += would fold a
          // pushed -0.0 into +0.0 and break dense/sparse bit-identity).
          ks.embed_merge.emplace(idx[i], tmp);
        } else {
          float* dst = it->second.data();
          for (size_t j = 0; j < w; ++j) dst[j] += tmp[j];
        }
      }
      ks.dtype = kSparseRows;
      ks.push_count.fetch_add(1, std::memory_order_relaxed);
      ks.seen.insert(t.worker_id);
      StatPush(t.key, t.worker_id, wire_len, true, ks.completed_round + 1,
               ks.seen.size());
      Respond(t.conn, kOk, t.req_id, t.key, nullptr, 0);
      if (RoundComplete(ks))
        PublishRound(ks, t.key, t.worker_id);
      return;
    }
    // Compressed pushes are expanded to f32 before the merge — the
    // reference server's decompress-sum engine (server.cc:86-207).
    //
    // ORDERING INVARIANT: nothing that could stall a live round
    // (store wipe, seen.clear, dtype/round_compressed/push_count) is
    // mutated until the frame is fully validated — a corrupt payload
    // with a plausible header must leave the in-progress merge exactly
    // as it found it (already-acked workers never re-push, so a wiped
    // `seen` could otherwise never refill and every pull would hang).
    std::vector<char> scratch;
    uint32_t comp_n = 0;
    uint64_t want = wire_len;           // merged (f32) size this push implies
    if (t.dtype == kCompressed) {
      if (t.payload.size() < 5) {
        Respond(t.conn, kError, t.req_id, t.key, nullptr, 0);
        return;
      }
      std::memcpy(&comp_n, t.payload.data() + 1, 4);
      want = static_cast<uint64_t>(comp_n) * 4;
      if (want > max_msg_) {   // claimed-size cap, as in Decompress
        Respond(t.conn, kError, t.req_id, t.key, nullptr, 0);
        return;
      }
    }
    const bool traced = (t.flags & kFlagTraced) != 0;
    if (traced && t.recv_us) {
      // RECV: frame fully read -> engine picked it up (server-side queue
      // wait — an engine backed up behind other keys shows here).
      tracer_.Record("RECV", t.key, ks.completed_round, t.worker_id,
                     t.recv_us, NowUs() - t.recv_us, wire_len);
    }
    if (!async_ && !RoundMatch(t.flags, ks.completed_round)) {
      // Stale-round replay guard: a push's u16 flags carry the round the
      // worker staged it for; one that is not the round currently merging
      // belongs to an already-PUBLISHED round — a reconnecting worker
      // replaying a push whose ack (or whose round's completion) raced the
      // connection drop (client.py _replay_part).  Its contribution was
      // already counted, so ack-and-drop: merging it into the current
      // round would double-count this worker.  Correct clients always
      // push flags == completed_round (round counters are seeded from the
      // INIT response and advance only after the round publishes), so
      // only replays and protocol violators can land here.
      StatPush(t.key, t.worker_id, wire_len, false, 0);
      Respond(t.conn, kOk, t.req_id, t.key, nullptr, 0);
      return;
    }
    if (!async_ && ks.seen.count(t.worker_id) &&
        ks.store.size() == static_cast<size_t>(want)) {
      // Duplicate within a round — ignore merge, still ack (reference dedups
      // by seen_sender, server.cc:150-177).  Checked before the decompress:
      // a dup's payload is never expanded (or value-logged) at all.
      // The size guard keeps the dedup SUBORDINATE to the size-change
      // reset below: a worker already in `seen` that re-pushes with a NEW
      // implied size (re-declared tensor mid-round) must fall through to
      // the reset — acking-and-dropping it would leave the restarted
      // merge permanently one push short once the reset clears `seen`
      // (already-acked workers never re-push), wedging every pull.
      ks.push_count.fetch_add(1, std::memory_order_relaxed);
      StatPush(t.key, t.worker_id, wire_len, false, 0);
      Respond(t.conn, kOk, t.req_id, t.key, nullptr, 0);
      return;
    }
    if (!async_ && epoch_atomic_.load(std::memory_order_acquire) != 0) {
      // Elastic membership engaged (the epoch has advanced at least
      // once).  A round's FIRST push is its epoch boundary: snapshot the
      // live set as this round's contributor requirement.  Fixed-mode
      // runs never reach here — zero overhead, identical behavior.
      if (ks.seen.empty())
        AdoptRoundMembers(ks);
      if (!ks.round_members.empty() &&
          !ks.round_members.count(t.worker_id)) {
        // A worker that joined AFTER this round opened (its set was
        // pinned by the transition fan-out): admitted at the next round
        // boundary.  Ack-and-drop, exactly like a stale replay — its
        // pull still serves this round's published sum, so its weights
        // stay in lockstep with the incumbents, and its next push lands
        // in a round whose set includes it.
        deferred_joins_.fetch_add(1, std::memory_order_relaxed);
        StatPush(t.key, t.worker_id, wire_len, false, 0);
        Respond(t.conn, kOk, t.req_id, t.key, nullptr, 0);
        return;
      }
    }
    // Per-key codec table: a pending renegotiation takes effect at the
    // FIRST round boundary at/after its declared effective round — never
    // mid-round — and once the epoch has advanced every push's wire
    // format must match the active codec.  A mismatch (the sender missed
    // — or jumped ahead of — the switch) draws kCodecStale carrying the
    // authoritative doc BEFORE any state mutates: the worker re-encodes
    // the same gradient and replays, so the round stays format-uniform
    // and no contribution is lost.  Epoch 0 (no renegotiation ever) pays
    // one integer compare and behaves exactly as before.
    // Pending optimizer-mode switch (CMD_OPT) lands at the same round
    // boundary law as the codec table below: the round's FIRST push,
    // once completed_round reached the declared effective round — so no
    // round ever mixes update modes.  Epoch 0 pays one integer compare.
    // Global knob plane (CMD_KNOB): once the knob epoch has advanced, a
    // current-round push from a worker that has not acked the newest
    // epoch — for a key already at/past the switch's effective round —
    // draws kKnobStale carrying the authoritative table BEFORE any state
    // mutates: the worker adopts, re-applies its half of the switch
    // (re-planning fusion buckets when the layout changed), ACKs, and
    // replays.  Epoch 0 (no knob switch ever) pays one atomic load and
    // behaves exactly as before — wire byte-identical.
    if (!async_ &&
        knob_epoch_atomic_.load(std::memory_order_acquire) != 0 &&
        KnobStaleCheck(t, ks))
      return;
    if (!async_ && ks.opt_epoch != 0 && ks.opt_pending &&
        ks.seen.empty() && ks.completed_round >= ks.opt_effective)
      ApplyPendingOpt(ks);
    if (!async_ && ks.codec_epoch != 0) {
      if (ks.codec_pending && ks.seen.empty() &&
          ks.completed_round >= ks.codec_effective)
        ApplyPendingCodec(ks);
      if (t.dtype == kF32 || t.dtype == kCompressed) {
        const uint8_t got =
            (t.dtype == kCompressed && !t.payload.empty())
                ? static_cast<uint8_t>(t.payload[0]) : codec::kNone;
        if (got != ExpectedComp(ks.kwargs)) {
          RespondCodecStale(t, ks);
          return;
        }
      }
    }
    // SUM span start: everything from here to the merge landing
    // (decompress + validate + sum/copy-first) is this push's share of
    // engine work.
    const int64_t sum_t0 = traced ? NowUs() : 0;
    if (t.dtype == kCompressed) {
      if (!async_ && ks.seen.empty()) {
        // COPY_FIRST for compressed pushes: decompress straight into
        // the store — skips both the scratch allocation and the copy
        // pass (the uncompressed analog of the buffer move below).
        // Safe before full validation ONLY because seen is empty: a
        // mid-parse failure leaves garbage in `store` but no merge
        // existed, and the next valid first push overwrites it all.
        // Scatter formats need the zeroed destination; the dense ones
        // (onebit, fixed-width dithering) store every element, so
        // skipping their memset saves a full-buffer pass per round.
        if (ks.store.size() != want) ks.store.assign(want, 0);
        bool need_zero = true;
        uint8_t comp = static_cast<uint8_t>(t.payload[0]);
        if (comp == codec::kOnebit) need_zero = false;
        if (comp == codec::kQblock) need_zero = false;
        if (comp == codec::kDithering && t.payload.size() > 5
            && !(static_cast<uint8_t>(t.payload[5]) & 2))
          need_zero = false;
        if (!codec::DecompressTo(
                t.payload.data(), t.payload.size(),
                reinterpret_cast<float*>(ks.store.data()), comp_n,
                need_zero)) {
          Respond(t.conn, kError, t.req_id, t.key, nullptr, 0);
          return;
        }
        data = &ks.store;
      } else {
        // Mid-round (or async): validate into scratch BEFORE touching
        // any round state.
        if (!codec::Decompress(t.payload, &scratch, max_msg_)) {
          Respond(t.conn, kError, t.req_id, t.key, nullptr, 0);
          return;
        }
        data = &scratch;
      }
      ks.round_compressed = true;
    }
    // Frame fully validated from here on.
    if (ks.store.size() != want) {
      // Size changed mid-stream (re-declared tensor / missing INIT): restart
      // the merge consistently — clearing `seen` too, so earlier workers'
      // contributions are never silently discarded while the round counter
      // still advances on a wrong sum.
      ks.store.assign(want, 0);
      ks.seen.clear();
      ks.merge_ts.clear();   // the discarded merges' waits died with it
      // The restarted merge is a fresh round boundary: re-snapshot its
      // contributor set under elastic membership (empty = legacy count).
      ks.round_members.clear();
      if (epoch_atomic_.load(std::memory_order_acquire) != 0)
        AdoptRoundMembers(ks);
      // Keep the readers' scatter check in step with the new store size.
      ks.declared_len.store(want, std::memory_order_release);
    }
    ks.dtype = t.dtype == kCompressed ? kF32 : t.dtype;
    ks.push_count.fetch_add(1, std::memory_order_relaxed);
    const bool first = !async_ && ks.seen.empty();
    DebugLog("push_recv", t.key, t.worker_id, ks.completed_round, *data);
    if (async_) {
      // Async PS mode: store += payload immediately, no round tracking
      // (reference: server.cc:319-323, BYTEPS_ENABLE_ASYNC).
      SumInto(ks, *data);
      ks.out = ks.store;
      DebugLog("async_merge", t.key, t.worker_id, ks.completed_round,
               ks.store);
      if (traced)
        tracer_.Record("SUM", t.key, 0, t.worker_id, sum_t0,
                       NowUs() - sum_t0, wire_len);
      StatPush(t.key, t.worker_id, wire_len, true, 0);
      Respond(t.conn, kOk, t.req_id, t.key, nullptr, 0);
      FlushPulls(ks, t.key);
      return;
    }
    if (first) {
      // COPY_FIRST (reference: server.cc:299-379) — by SWAP when the
      // payload arrived uncompressed: adopting the reader's buffer
      // saves a full per-partition memory pass on the serve path, and
      // the stale same-size ex-store buffer rides back for reuse (to
      // the conn's freelist via t.payload, or as the key's next scatter
      // target) instead of freeing — steady state, the same few buffers
      // cycle socket -> store -> socket with zero allocation.
      // A compressed first push normally landed in the store above;
      // the exception is a size-change reset that PROMOTED a
      // scratch-validated push to first — copy it over.
      if (t.scattered) {
        std::swap(ks.store, ks.scatter_buf);
        data = &ks.store;
      } else if (data == &t.payload) {
        std::swap(ks.store, t.payload);
        data = &ks.store;   // t.payload now holds the stale ex-store
      } else if (data == &scratch) {
        std::memcpy(ks.store.data(), scratch.data(), scratch.size());
        data = &ks.store;
      }
    } else {
      SumInto(ks, *data);  // SUM_RECV
    }
    ks.seen.insert(t.worker_id);
    if (traced) {
      const int64_t merged_us = NowUs();
      tracer_.Record("SUM", t.key, ks.completed_round, t.worker_id,
                     sum_t0, merged_us - sum_t0, wire_len);
      // Merge landed: the clock on this worker's MERGE_WAIT starts now
      // and stops when the round publishes (below) — the span IS the
      // time this push sat waiting for the round's remaining workers.
      ks.merge_ts.emplace_back(t.worker_id, merged_us);
    }
    // round_pos = completed_round + 1: "this worker has contributed
    // through round completed_round" — equal across workers when they
    // are in step, and the lead-minus-lagger delta IS the straggler lag.
    StatPush(t.key, t.worker_id, wire_len, true, ks.completed_round + 1,
             ks.seen.size());
    Respond(t.conn, kOk, t.req_id, t.key, nullptr, 0);
    if (RoundComplete(ks))
      PublishRound(ks, t.key, t.worker_id);
  }

  // ALL_RECV: publish the completed round and start a fresh merge.
  // Bidirectional compressors re-compress the merged buffer for the
  // pull leg (reference: impl/onebit bidirectional, server engine).
  // Extracted from HandlePush's tail so the membership re-finalize path
  // (HandleMembership) publishes through the identical code — EF fold,
  // trace spans, pending-pull flush and all.
  void PublishRound(KeyState& ks, uint64_t key, uint32_t worker_id) {
    const uint64_t pub_round = ks.completed_round;
    const int64_t pub_t0 = ks.merge_ts.empty() ? 0 : NowUs();
    // Contributor snapshot for the audit record, captured before the
    // publish clears `seen` — who actually merged into this round is
    // exactly the attribution a digest mismatch needs.
    std::vector<uint32_t> audit_who;
    if (audit_armed_)
      audit_who.assign(ks.seen.begin(), ks.seen.end());
    if (ks.ef_fold_pending) {
      // A codec switch retired the server-EF recompress leg while a
      // requantization residual was still carried: fold it into this
      // publish exactly once — a renegotiation must never silently drop
      // accumulated error (the EF-across-switch law; the worker side
      // applies the same law in _apply_codec_local).
      size_t ne = ks.store.size() / 4;
      if (ne && ks.ef_err.size() == ne) {
        float* s = reinterpret_cast<float*>(ks.store.data());
        for (size_t i = 0; i < ne; ++i) s[i] += ks.ef_err[i];
      }
      ks.ef_err.clear();
      ks.ef_err.shrink_to_fit();
      ks.ef_fold_pending = false;
    }
    // Captured before the flags reset below: did this round's publish
    // leg produce a recompressed blob (what the opt stage must decode)
    // or the raw f32 sum?
    const bool served_compressed = ks.round_compressed && ks.bidirectional;
    if (ks.round_compressed && ks.bidirectional) {
      size_t ne = ks.store.size() / 4;
      float* s = reinterpret_cast<float*>(ks.store.data());
      if (ks.pull_comp == codec::kQblock) {
        // Quantized-block recompress leg, same EF law as onebit below.
        if (ks.server_ef) {
          if (ks.ef_err.size() != ne) ks.ef_err.assign(ne, 0.0f);
          for (size_t i = 0; i < ne; ++i) s[i] += ks.ef_err[i];
          codec::CompressQblock(ks.store, ks.qblock_bits,
                                ks.qblock_block, &ks.out, &ks.ef_err);
        } else {
          codec::CompressQblock(ks.store, ks.qblock_bits,
                                ks.qblock_block, &ks.out, nullptr);
        }
      } else {
        if (ks.server_ef) {
          // Vanilla EF on the requantization: fold last round's error
          // into the merged gradient before compressing (the store is a
          // fresh COPY_FIRST merge every round, so the in-place add is
          // safe).
          if (ks.ef_err.size() != ne) ks.ef_err.assign(ne, 0.0f);
          for (size_t i = 0; i < ne; ++i) s[i] += ks.ef_err[i];
        }
        codec::CompressOnebit(ks.store, ks.onebit_scaled, &ks.out);
        if (ks.server_ef) {
          // The decoded onebit value is just +-scale with the sign bit
          // taken from the corrected gradient — compute the error inline
          // instead of a full decompress round-trip + allocation.
          float scale = 1.0f;
          std::memcpy(&scale, ks.out.data() + 5, 4);
          for (size_t i = 0; i < ne; ++i)
            ks.ef_err[i] = s[i] - (s[i] < 0.0f ? -scale : scale);
        }
      }
      // Log BEFORE the increment so all_recv and its contributing
      // push_recv lines carry the same round number (the compressed
      // branch logs after the EF fold — the store it publishes).
      DebugLog("all_recv", key, worker_id, ks.completed_round, ks.store);
    } else {
      DebugLog("all_recv", key, worker_id, ks.completed_round, ks.store);
      // Publish by swap, not copy: `out` takes the merged round (what
      // pulls serve) and `store` inherits a stale same-size buffer that
      // the next round's COPY_FIRST fully overwrites — saving a
      // full-buffer memcpy per partition per round on the serve path.
      std::swap(ks.out, ks.store);
    }
    // --- server-resident optimizer update stage (CMD_OPT) ---------------
    // Merge -> update -> publish *parameters*: with an active optimizer
    // mode, the round's served bytes become the post-step params instead
    // of the sum.  Unarmed keys (opt_kind 0 — every pre-subsystem run)
    // skip on one compare; raw last-write-wins keys are not gradient
    // streams and never update.
    if (!async_ && ks.opt_kind != 0 && ks.dtype == kF32)
      OptUpdateStage(ks, key, served_compressed);
    // --- row-sparse embedding publish -----------------------------------
    // The round's merged rows become the published set (swap, like the
    // dense out/store swap above — both maps recycle their node pools
    // round to round), then the row-wise update stage steps exactly the
    // touched rows when the key is armed.  The audit digest below covers
    // ks.out, which embed keys keep empty — sparse rounds are outside
    // the audit plane (docs/sparse-embedding.md).
    if (ks.embed_rows != 0) {
      ks.embed_out.swap(ks.embed_merge);
      ks.embed_merge.clear();
      if (!async_ && ks.opt_kind != 0) {
        EmbedUpdateStage(ks, key);
      } else {
        // Unarmed publishes change the served rows too (the swap above)
        // — param_version identifies PUBLISHED TABLE STATE, so it must
        // advance either way or worker hot-row caches could serve a
        // superseded round as current (docs/sparse-embedding.md).
        ks.param_version++;
      }
    }
    ks.completed_round++;
    ks.seen.clear();
    ks.round_compressed = false;
    if (pub_t0) {
      // One MERGE_WAIT span per traced contributor: merge-complete ->
      // publish.  The LAST arriver's wait is ~0; every other worker's
      // wait is exactly how long the straggler(s) held the round open
      // — the signal the critical-path analyzer attributes.
      for (const auto& wt : ks.merge_ts)
        tracer_.Record("MERGE_WAIT", key, pub_round, wt.first,
                       wt.second, pub_t0 - wt.second, 0);
      tracer_.Record("PUBLISH", key, pub_round, worker_id, pub_t0,
                     NowUs() - pub_t0, ks.out.size());
    }
    ks.merge_ts.clear();
    if (audit_armed_) {
      // Digest the bytes pulls will SERVE (`out` — for bidirectional
      // compressors that is the recompressed blob, exactly what rides
      // the wire), and record it BEFORE the pending-pull flush below so
      // the pulls this publish releases carry this round's trailer.
      ks.audit_round = pub_round;
      ks.audit_digest = audit::Digest(ks.out.data(), ks.out.size());
      ks.audit_epoch = epoch_atomic_.load(std::memory_order_acquire);
      ks.audit_n = static_cast<uint32_t>(audit_who.size());
      std::lock_guard<std::mutex> lk(audit_mu_);
      auto& dq = audit_log_[key];
      dq.push_back(AuditRec{pub_round, ks.audit_digest, ks.audit_epoch,
                            std::move(audit_who)});
      while (dq.size() > static_cast<size_t>(audit_window_))
        dq.pop_front();
    }
    StatPublish(key, ks.completed_round);
    // Chain replication: enqueue the published state for the successor
    // BEFORE the flush below — when armed, the flush is gated on the
    // successor's ack (ReplBlocked), so this round's pulls serve only
    // once a second copy exists.  Unarmed: one boolean test, the flush
    // behaves exactly as before.
    ReplEnqueue(ks, key);
    FlushPulls(ks, key);
  }

  // Serve one audited pull: payload + 24-byte trailer carrying the
  // digest recorded at the served round's publish.  The test-only fault
  // injector (BYTEPS_TPU_AUDIT_FAULT) flips one bit in a COPY of the
  // payload here — downstream of the recorded digest, so the client's
  // re-digest must catch it; the store itself is never touched.
  void RespondAudited(Conn* c, uint32_t req_id, uint64_t key,
                      KeyState& ks) {
    AuditTrailer tr{ks.audit_digest, ks.audit_round, ks.audit_epoch,
                    ks.audit_n};
    if (fault_armed_ && key == fault_key_ && ks.audit_round == fault_round_
        && !ks.out.empty()
        && !fault_done_.exchange(true, std::memory_order_acq_rel)) {
      std::vector<char> bad(ks.out);
      const uint64_t bit = fault_bit_ % (bad.size() * 8ULL);
      bad[bit / 8] = static_cast<char>(
          static_cast<unsigned char>(bad[bit / 8]) ^ (1u << (bit & 7)));
      std::fprintf(stderr,
                   "[byteps server] AUDIT FAULT INJECTED: key=%llu "
                   "round=%llu bit=%llu\n",
                   static_cast<unsigned long long>(key),
                   static_cast<unsigned long long>(ks.audit_round),
                   static_cast<unsigned long long>(bit));
      RespondT(c, kOk, req_id, key, bad.data(), bad.size(), &tr,
               sizeof(tr));
      return;
    }
    RespondT(c, kOk, req_id, key, ks.out.data(), ks.out.size(), &tr,
             sizeof(tr));
  }

  void DebugLog(const char* stage, uint64_t key, uint32_t worker,
                uint64_t round, const std::vector<char>& buf) {
    if (!debug_ || (debug_key_ != ~0ULL && key != debug_key_)) return;
    // f32 sum + first value — the reference's per-stage sample shape
    // (sum_of_buffer; reference server.cc:124-201).
    double sum = 0.0;
    float first = 0.0f;
    size_t n = buf.size() / sizeof(float);
    const float* f = reinterpret_cast<const float*>(buf.data());
    if (n > 0) {
      first = f[0];
      for (size_t i = 0; i < n; ++i) sum += f[i];
    }
    std::fprintf(stderr,
                 "[byteps_tpu.server DEBUG] %s key=%llu worker=%u round=%llu"
                 " len=%zu f32_sum=%.6g first=%.6g\n",
                 stage, static_cast<unsigned long long>(key), worker,
                 static_cast<unsigned long long>(round), buf.size(), sum,
                 first);
  }

  void SumInto(KeyState& ks, const std::vector<char>& payload) {
    if (ks.dtype == kF32) {
      auto* dst = reinterpret_cast<float*>(ks.store.data());
      auto* src = reinterpret_cast<const float*>(payload.data());
      size_t n = payload.size() / sizeof(float);
      #pragma omp simd
      for (size_t i = 0; i < n; ++i) dst[i] += src[i];
    } else {
      std::memcpy(ks.store.data(), payload.data(), payload.size());
    }
  }

  // Serve one batched sparse row pull: parse SparseHdr + index stream
  // out of `req` and respond `u64 param_version | rows` in request
  // order.  Armed keys serve the authoritative params table (the table
  // CMD_OPT seeded / the update stage maintains); unarmed keys serve
  // the published round's merged rows, absent rows reading as zeros —
  // sum semantics, what a dense pull of an untouched slice yields.
  void RespondSparse(Conn* c, uint32_t req_id, uint64_t key, KeyState& ks,
                     const char* req, size_t req_len) {
    SparseHdr h;
    const size_t w = ks.embed_width;
    std::vector<uint32_t> idx;
    bool ok = ks.embed_rows != 0 && w != 0 && req_len >= sizeof(h);
    if (ok) {
      std::memcpy(&h, req, sizeof(h));
      ok = h.width == w && req_len >= sizeof(h) + h.idx_bytes &&
           DecodeSparseIndices(
               reinterpret_cast<const unsigned char*>(req) + sizeof(h),
               h.idx_bytes, h.nrows, h.codec, &idx);
    }
    if (ok)
      for (uint32_t i = 0; i < h.nrows; ++i)
        if (idx[i] >= ks.embed_rows) { ok = false; break; }
    if (!ok) {
      Respond(c, kError, req_id, key, nullptr, 0);
      return;
    }
    std::vector<char> resp(8 + static_cast<size_t>(h.nrows) * w * 4);
    std::memcpy(resp.data(), &ks.param_version, 8);
    char* dst = resp.data() + 8;
    // Serving law: a full-size params table IS the live table (seeded
    // via CMD_OPT or optimizer-stepped) and wins regardless of whether
    // the pending optimizer config has reached its round boundary yet —
    // a freshly seeded table must serve its seed before round 1.
    // Without params (unarmed), serve the last published per-round rows
    // (absent row = zeros, the dense sum semantics).
    const bool armed =
        ks.params.size() == static_cast<size_t>(ks.embed_rows) * w;
    for (uint32_t i = 0; i < h.nrows; ++i) {
      const uint64_t r = idx[i];
      if (armed) {
        std::memcpy(dst, ks.params.data() + r * w, w * 4);
      } else {
        auto it = ks.embed_out.find(r);
        if (it != ks.embed_out.end() && it->second.size() == w)
          std::memcpy(dst, it->second.data(), w * 4);
        else
          std::memset(dst, 0, w * 4);
      }
      dst += w * 4;
    }
    embed_rows_served_.fetch_add(h.nrows, std::memory_order_relaxed);
    Respond(c, kOk, req_id, key, resp.data(), resp.size());
  }

  void HandlePull(Task& t) {
    // Ring ownership gate: a pull for a moved key redirects like a push
    // — the published `out` buffer migrated with the state, so the new
    // owner serves the identical bytes.
    if (RingMisplaced(t.key)) {
      RespondMoved(t, FindState(t.key));
      return;
    }
    KeyState& ks = StateFor(t.key);
    MaybeAdoptReplica(t.key, ks);
    if (t.dtype == kSparseRead) {
      // Ungated inference read: serves whatever the table holds RIGHT
      // NOW — no round gate, no parking, no round-state mutation at
      // all, so a pull-only session can never stall (or be stalled by)
      // round completion.  Readers order themselves by the returned
      // param_version, which is monotone per key.  The one exception is
      // the zero-loss gate: while the newest publish awaits its
      // successor ack, the read parks (`ungated`) so an observer can
      // never consume table state that a failover would roll back —
      // param_version stays monotone ACROSS a SIGKILL because nothing
      // unreplicated is ever served.
      if (ReplBlocked(ks)) {
        AddRef(t.conn);
        ks.pending.push_back({t.conn, t.req_id, t.key, t.flags,
                              t.worker_id, false, false});
        ks.pending.back().ungated = true;
        ks.pending.back().sparse = std::move(t.payload);
        StatPendingPulls(t.key, 1);
        return;
      }
      RespondSparse(t.conn, t.req_id, t.key, ks, t.payload.data(),
                    t.payload.size());
      return;
    }
    // t.flags = the round (mod 2^15, low bits of the u16; bit 15 is the
    // trace marker) the worker just pushed; its result is ready once that
    // round has been published.  The 15-bit compare aliases only if a
    // worker's pull were exactly 32,768 rounds stale — unreachable by
    // protocol: the client's
    // sequential-use guard (client.py _stage_parts) serializes rounds per
    // key, so a pull's round is always completed_round or
    // completed_round - 1.  Asserted rather than assumed: a client that
    // violated the invariant would otherwise silently wait or read a
    // whole-epoch-stale buffer.
    const bool traced = (t.flags & kFlagTraced) != 0;
    // Audited pull (dtype marker from an audit-armed client): serve with
    // the 24-byte digest trailer.  Gated on audit_armed_ too, so a rogue
    // dtype against an unarmed server changes nothing.
    const bool audited = audit_armed_ && t.dtype == kAuditPullMark;
    if (!async_ && !RoundMatch(t.flags, ks.completed_round) &&
        !RoundMatch(t.flags, ks.completed_round - 1)) {
      Respond(t.conn, kError, t.req_id, t.key, nullptr, 0);
      return;
    }
    // The zero-loss gate joins the round check: a pull whose round is
    // ready but whose publish has not been replicated yet parks until
    // the successor acks (kReplFlushTask serves it) — unarmed runs pay
    // one boolean test.
    bool ready = (async_ || !RoundMatch(t.flags, ks.completed_round)) &&
                 !ReplBlocked(ks);
    if (ready) {
      const int64_t t0 = traced ? NowUs() : 0;
      if (t.dtype == kSparseRows)
        RespondSparse(t.conn, t.req_id, t.key, ks, t.payload.data(),
                      t.payload.size());
      else if (audited)
        RespondAudited(t.conn, t.req_id, t.key, ks);
      else
        Respond(t.conn, kOk, t.req_id, t.key, ks.out.data(),
                ks.out.size());
      if (traced)
        tracer_.Record("PULL_SEND", t.key, ks.completed_round,
                       t.worker_id, t0, NowUs() - t0, ks.out.size());
    } else {
      AddRef(t.conn);   // the stash outlives the task's own hold
      ks.pending.push_back({t.conn, t.req_id, t.key, t.flags,
                            t.worker_id, traced, audited});
      if (t.dtype == kSparseRows)
        // Round-gated sparse pull: park the request (header + index
        // stream) so FlushPulls can serve the rows once the wanted
        // round publishes.
        ks.pending.back().sparse = std::move(t.payload);
      StatPendingPulls(t.key, 1);
    }
  }

  void FlushPulls(KeyState& ks, uint64_t key) {
    // Zero-loss gate: while the newest publish awaits its successor
    // ack, NOTHING serves (the parked pulls are exactly the ones the
    // gate exists for); kReplFlushTask re-runs this the moment the ack
    // lands.  `ungated` entries (kSparseRead reads parked only by the
    // gate) ignore the round match once the gate opens.
    const bool blocked = ReplBlocked(ks);
    std::vector<PendingPull> still;
    int64_t flushed = 0;
    for (auto& p : ks.pending) {
      if (!blocked &&
          (p.ungated || async_ ||
           !RoundMatch(p.want_round, ks.completed_round))) {
        const int64_t t0 = p.traced ? NowUs() : 0;
        if (!p.sparse.empty())
          RespondSparse(p.conn, p.req_id, key, ks, p.sparse.data(),
                        p.sparse.size());
        else if (p.audited)
          RespondAudited(p.conn, p.req_id, key, ks);
        else
          Respond(p.conn, kOk, p.req_id, key, ks.out.data(),
                  ks.out.size());
        if (p.traced)
          tracer_.Record("PULL_SEND", key, ks.completed_round, p.worker,
                         t0, NowUs() - t0, ks.out.size());
        ReleaseRef(p.conn);
        ++flushed;
      } else {
        still.push_back(p);
      }
    }
    ks.pending.swap(still);
    if (flushed) StatPendingPulls(key, -flushed);
  }

  int port_;
  int num_workers_;
  // Hierarchical reduction (BYTEPS_TPU_SLICE_SIZE): chips per slice;
  // RoundComplete counts slice coverage when > 1.  1 = flat (exact
  // historical per-worker completion).
  int slice_size_ = 1;
  int engine_threads_;
  bool schedule_;
  bool async_;
  bool debug_ = false;
  uint64_t debug_key_ = ~0ULL;   // ~0 = all keys
  uint64_t max_msg_ = 1ULL << 30;  // wire frame cap (see ctor)
  int listen_fd_ = -1;
  // UDS fast path + socket tuning (see ctor).
  std::string uds_base_;
  std::string uds_path_;
  int uds_listen_fd_ = -1;
  int sock_buf_bytes_ = 0;
  // Scatter-receive telemetry: frames that took the zero-intermediate
  // reader->store path (CMD_STATS "scatter_frames").
  std::atomic<uint64_t> scatter_frames_{0};

  std::vector<EngineQueue> queues_;
  std::vector<std::thread> engines_;

  // Readers run detached (see Run); shutdown waits for this count.
  std::mutex readers_mu_;
  std::condition_variable readers_cv_;
  int active_readers_ = 0;

  std::mutex assign_mu_;
  std::unordered_map<uint64_t, int> key_engine_;
  std::vector<uint64_t> engine_load_;

  std::mutex store_mu_;
  std::map<uint64_t, KeyState> store_;

  std::mutex barrier_mu_;
  std::map<uint64_t, std::vector<PendingPull>> barrier_waiters_;
  // Generations that already released: late arrivals (elastic joiners
  // catching up to the startup rendezvous) pass straight through.
  // Generations are one-shot by contract, so this only ever holds as
  // many entries as distinct barrier calls the job makes.
  std::set<uint64_t> released_gens_;

  // Elastic membership (see the "elastic membership" section above).
  // epoch_atomic_ mirrors epoch_ for the lock-free fixed-mode
  // short-circuit on the push hot path.
  std::mutex member_mu_;
  uint64_t epoch_ = 0;
  std::map<uint32_t, MemberRec> members_;
  std::atomic<uint64_t> epoch_atomic_{0};
  double evict_timeout_s_ = 0.0;
  std::atomic<uint64_t> deferred_joins_{0};

  // Elastic PS ring (see the "elastic PS ring" section above).
  // ring_epoch_atomic_ mirrors ring_epoch_ for the lock-free data-path
  // short-circuit; everything else under ring_mu_.
  bool ring_armed_ = false;
  bool ring_join_ = false;
  std::atomic<bool> draining_{false};
  uint32_t my_server_id_ = 0;
  int ring_vnodes_ = 64;
  std::string advertise_host_;
  int advertise_port_ = 0;
  std::mutex ring_mu_;
  uint64_t ring_epoch_ = 0;
  std::vector<RingServer> ring_members_;
  // Atomically-swapped sorted point table (see RebuildRingPointsLocked):
  // readers are lock-free; the pointer is rebuilt whole per transition.
  std::shared_ptr<const std::vector<std::pair<uint64_t, uint32_t>>>
      ring_points_;
  std::map<uint32_t, std::pair<std::string, int>> peer_book_;
  std::atomic<uint64_t> ring_epoch_atomic_{0};
  std::atomic<uint64_t> migrations_in_{0};
  std::atomic<uint64_t> migrations_out_{0};
  std::atomic<uint64_t> moved_frames_{0};
  // CMD_CODEC accepted proposals / format-mismatch rejections (the
  // renegotiation race backstop firing) — CMD_STATS observability.
  std::atomic<uint64_t> codec_sets_{0};
  std::atomic<uint64_t> codec_stale_{0};
  // CMD_KNOB global knob plane: ONE epoch-versioned kwargs table per
  // server ("fusion_bytes=..,compress_threads=..,wire_conns=..") plus
  // the per-worker acked-epoch map the push-path backstop consults.
  // Guarded by knob_mu_ (reader threads write it, engine threads read it
  // on the push path); knob_epoch_atomic_ mirrors knob_epoch_ so an
  // unarmed run's pushes pay ONE relaxed load and never take the mutex —
  // wire behavior byte-identical until the first SET.
  std::mutex knob_mu_;
  uint32_t knob_epoch_ = 0;          // newest accepted epoch (0 = launch)
  uint32_t knob_applied_ = 0;        // epoch of the ACTIVE kwargs
  bool knob_pending_ = false;        // a staged switch awaits its boundary
  uint64_t knob_effective_ = 0;      // round boundary of the newest SET
  std::string knob_kwargs_;          // ACTIVE table ("" = launch config)
  std::string knob_next_;            // staged table while pending
  std::map<uint32_t, uint32_t> knob_acked_;  // worker -> last acked epoch
  std::atomic<uint32_t> knob_epoch_atomic_{0};
  std::atomic<uint64_t> knob_sets_{0};
  std::atomic<uint64_t> knob_stale_{0};
  // Server-resident optimizer plane (CMD_OPT) — CMD_STATS observability:
  // accepted declarations, idempotent param seeds, published optimizer
  // updates, and the live bytes held in server-owned optimizer slots
  // (params + m + v across keys; the bench's "per-worker optimizer-state
  // bytes ~0" claim is this gauge living HERE instead of N times on the
  // workers).
  std::atomic<uint64_t> opt_sets_{0};
  std::atomic<uint64_t> opt_seeds_{0};
  std::atomic<uint64_t> opt_updates_{0};
  std::atomic<uint64_t> opt_slot_bytes_{0};
  // Row-sparse embedding plane: total rows served by sparse pulls/reads
  // and the summed DECLARED table footprint (rows * width * 4) across
  // this server's embed keys — the CMD_STATS "embed_rows_served" /
  // "embed_table_bytes" fields feeding bps_embed_* telemetry.
  std::atomic<uint64_t> embed_rows_served_{0};
  std::atomic<uint64_t> embed_table_bytes_{0};
  std::mutex peer_mu_;
  std::map<uint32_t, int> peer_fds_;
  std::map<uint32_t, int64_t> peer_down_until_us_;  // negative cache

  // Chain replication (CMD_REPL; see the "chain replication" section).
  // repl_points_ is the ring point table minus this server's vnodes —
  // Owner(key, repl_points_) is the key's successor — published
  // lock-free like ring_points_.  Everything else under repl_mu_:
  // the newest-blob send queue + owner-side published/acked rounds
  // (engine + repl threads), and the replicas parked FOR other owners'
  // keys (reader threads in, engine threads out at adoption).
  bool repl_armed_ = false;          // BYTEPS_TPU_REPL
  uint64_t repl_lag_window_ = 0;     // BYTEPS_TPU_REPL_LAG (rounds the
                                     // publish may run ahead of the ack)
  std::shared_ptr<const std::vector<std::pair<uint64_t, uint32_t>>>
      repl_points_;
  std::mutex repl_mu_;
  std::condition_variable repl_cv_;
  std::map<uint64_t, std::vector<char>> repl_pending_;
  std::map<uint64_t, uint64_t> repl_pub_;
  std::map<uint64_t, uint64_t> repl_ack_;
  std::map<uint64_t, std::pair<uint64_t, std::vector<char>>> replicas_;
  std::atomic<uint64_t> repl_rounds_out_{0};
  std::atomic<uint64_t> repl_bytes_out_{0};
  std::atomic<uint64_t> repl_rounds_in_{0};
  std::atomic<uint64_t> repl_bytes_in_{0};
  std::atomic<uint64_t> repl_promotions_{0};

  // CMD_AUDIT publish-digest window (see AuditJson / PublishRound).
  struct AuditRec {
    uint64_t round;
    uint32_t digest;
    uint64_t epoch;
    std::vector<uint32_t> who;   // contributor ids at publish
  };
  bool audit_armed_ = false;     // BYTEPS_TPU_AUDIT
  int audit_window_ = 16;        // BYTEPS_TPU_AUDIT_WINDOW (last K rounds)
  std::mutex audit_mu_;
  std::map<uint64_t, std::deque<AuditRec>> audit_log_;
  // Test-only fault injection (BYTEPS_TPU_AUDIT_FAULT="key:round:bit").
  bool fault_armed_ = false;
  uint64_t fault_key_ = 0;
  uint64_t fault_round_ = 0;
  uint64_t fault_bit_ = 0;
  std::atomic<bool> fault_done_{false};

  // Fleet observability plane (CMD_WINDOW / CMD_FLEET): per-worker
  // rings of published window summaries, ordered by window index and
  // bounded by fleet_windows_.  fleet_mu_ is a LEAF lock: taken only
  // around ring reads/writes, never while holding (or before taking)
  // member_mu_ / stats_mu_ / repl_mu_.
  bool fleet_armed_ = false;     // BYTEPS_TPU_FLEET
  int fleet_windows_ = 32;       // BYTEPS_TPU_FLEET_WINDOWS (per worker)
  std::mutex fleet_mu_;
  std::map<uint32_t,
           std::deque<std::pair<uint64_t, std::string>>> fleet_rings_;
  std::atomic<uint64_t> fleet_publishes_{0};

  // CMD_TRACE span ring (see ServerTracer).
  ServerTracer tracer_;

  // CMD_STATS telemetry (see StatsJson).
  std::mutex stats_mu_;
  std::map<uint64_t, KeyStat> key_stats_;
  std::map<uint32_t, WorkerStat> worker_stats_;
  std::atomic<uint64_t> bytes_in_{0};
  std::atomic<uint64_t> bytes_out_{0};

  std::mutex conns_mu_;
  std::vector<Conn*> conns_;

  std::atomic<bool> shutdown_{false};
  std::atomic<uint64_t> seq_{0};
};

}  // namespace bps_server

extern "C" {

// Blocking server entry, the analog of `byteps_server()`
// (reference: server.h:186, server/__init__.py:21-27).
__attribute__((visibility("default")))
int bps_ps_server_run(int port, int num_workers, int engine_threads,
                      int enable_schedule, int enable_async) {
  bps_server::Server s(port, num_workers, engine_threads,
                       enable_schedule != 0, enable_async != 0);
  return s.Run();
}

// Ring-placement parity hook (ctypes from tests and common/ring.py
// consumers): the owner of `key` among `ids[n]` with `vnodes` virtual
// nodes per server, computed by the SAME code the server's ownership
// gate runs.  Returns the owning server id, or -1 on bad args.  Test
// surface only — the worker's hot path uses the pure-Python mirror.
__attribute__((visibility("default")))
int64_t bps_ring_owner(uint64_t key, const uint32_t* ids, int32_t n,
                       int32_t vnodes) {
  if (ids == nullptr || n <= 0 || vnodes <= 0 || vnodes > 4096) return -1;
  std::vector<std::pair<uint64_t, uint32_t>> points;
  points.reserve(static_cast<size_t>(n) * vnodes);
  for (int32_t i = 0; i < n; ++i)
    for (int32_t v = 0; v < vnodes; ++v)
      points.emplace_back(
          bps_server::ring::VnodePoint(ids[i], static_cast<uint32_t>(v)),
          ids[i]);
  std::sort(points.begin(), points.end());
  return static_cast<int64_t>(bps_server::ring::Owner(key, points));
}

// Audit-digest parity hook (ctypes from tests and the worker's digest
// fallback check): the chunked-CRC publish digest computed by the SAME
// code PublishRound runs, so the Python mirror (client.py audit_digest)
// can be asserted bit-identical.
__attribute__((visibility("default")))
uint32_t bps_audit_digest(const char* data, uint64_t n) {
  return bps_server::audit::Digest(data, static_cast<size_t>(n));
}

// Worker-side codec acceleration (ctypes from server/wire.py).  Same
// decoder the server engine runs — one implementation, one set of
// hostile-input checks.  Returns 0 on success, -1 on malformed payload
// or element-count mismatch.
__attribute__((visibility("default")))
int bps_wire_decode(const char* payload, uint64_t len, float* out,
                    uint64_t n) {
  if (n > 0xFFFFFFFFULL) return -1;
  return bps_server::codec::DecompressTo(
             payload, static_cast<size_t>(len), out,
             static_cast<uint32_t>(n)) ? 0 : -1;
}

// Onebit worker-side fused passes (ctypes from server/wire.py).  The
// numpy chain (momentum -> EF add -> sign pack -> reconstruction ->
// error store) is 7+ full-buffer passes with fresh allocations; these
// two single-pass routines replace all but the scale reduction (which
// stays in numpy — its pairwise float32 sum is the parity reference).
// All per-element float ops match the numpy expressions exactly, so
// C-path and numpy-path workers stay byte- and state-identical.

// Pass A: in-place Nesterov momentum + error-feedback correction.
//   if mom:  m = mu*m + x;  x += mu*m   (m updated in place)
//   if err:  x += err
__attribute__((visibility("default")))
void bps_wire_onebit_correct(float* x, uint64_t n, float* mom, float mu,
                             const float* err) {
  if (mom) {
    for (uint64_t i = 0; i < n; ++i) {
      float m = mu * mom[i] + x[i];
      mom[i] = m;
      x[i] = x[i] + mu * m;
    }
  }
  if (err)
    for (uint64_t i = 0; i < n; ++i) x[i] += err[i];
}

// Pass B: pack sign bits (LSB-first, 1 = negative) and, when err_out
// is non-null, store the EF error x - (sign ? -scale : +scale).
// `bits` must be zeroed ((n+7)/8 bytes).
__attribute__((visibility("default")))
void bps_wire_onebit_pack(const float* x, uint64_t n, float scale,
                          unsigned char* bits, float* err_out) {
  bps_server::codec::PackSigns(x, n, bits);
  if (err_out)
    for (uint64_t i = 0; i < n; ++i) {
      float q = x[i] < 0.0f ? -scale : scale;   // compiles to a blend
      err_out[i] = x[i] - q;
    }
}

// Quantized-block encode (see codec::EncodeQblock) — the worker-side
// qblock fast path, the exact routine the server's recompress leg runs
// (CompressQblock), so C-path and numpy-path workers stay byte- and
// EF-state-identical.  `recon`, when non-null, receives the dequantized
// reconstruction (the worker EF leg).  Returns bytes written, -1 on bad
// args / insufficient cap.
__attribute__((visibility("default")))
int64_t bps_wire_encode_qblock(const float* x, uint64_t n, int bits,
                               uint32_t block, float* recon,
                               unsigned char* out, uint64_t cap) {
  if (n > 0xFFFFFFFFULL) return -1;
  return bps_server::codec::EncodeQblock(
      x, static_cast<uint32_t>(n), bits, block, recon, out, cap);
}

// Dithering encode (see codec::EncodeDithering).  Returns bytes
// written, -1 on bad args / insufficient cap.
__attribute__((visibility("default")))
int64_t bps_wire_encode_dithering(const float* x, uint64_t n, uint32_t s,
                                  int natural, int elias, float norm,
                                  uint32_t* rng, float* recon,
                                  unsigned char* out, uint64_t cap) {
  if (n > 0xFFFFFFFFULL) return -1;
  return bps_server::codec::EncodeDithering(
      x, static_cast<uint32_t>(n), s, natural, elias, norm, rng, recon,
      out, cap);
}

}  // extern "C"

#ifdef BPS_SERVER_MAIN
// Standalone executable entry (used for sanitizer builds, where the TSAN
// runtime must be loaded at process start and cannot be dlopen'd into an
// interpreter).  argv: port num_workers engine_threads schedule async
int main(int argc, char** argv) {
  if (argc != 6) return 64;
  return bps_ps_server_run(atoi(argv[1]), atoi(argv[2]), atoi(argv[3]),
                           atoi(argv[4]), atoi(argv[5]));
}
#endif
