"""ctypes bindings for the native host core, with a pure-Python fallback.

The reference binds its C++ core to Python per-framework via pybind11/ctypes
(reference: byteps/common/__init__.py:52-77 dlopens c_lib).  pybind11 is not
available in this image, so we use a flat C ABI + ctypes.  If the toolchain is
missing or the build fails we degrade to `_PyCore`, a behaviorally identical
Python implementation — everything stays usable, just without native speed.
"""

from __future__ import annotations

import ctypes
import threading
import time
from typing import List, Optional, Tuple

from ..common.logging import get_logger


class _CCore:
    """ctypes facade over libbyteps_core.so."""

    def __init__(self, lib: ctypes.CDLL):
        self._lib = lib
        # Python-side mirror of the native tracer's on/off flag: hot paths
        # (the PS dispatcher) read this attribute instead of crossing the
        # ctypes boundary and taking the tracer mutex per partition.
        self.trace_on = False
        L = lib
        L.bps_declare_tensor.argtypes = [ctypes.c_char_p]
        L.bps_declare_tensor.restype = ctypes.c_int32
        L.bps_get_declared_key.argtypes = [ctypes.c_char_p]
        L.bps_get_declared_key.restype = ctypes.c_int32
        L.bps_num_declared.restype = ctypes.c_int32
        L.bps_declared_name.argtypes = [ctypes.c_int32, ctypes.c_char_p,
                                        ctypes.c_int32]
        L.bps_declared_name.restype = ctypes.c_int32
        L.bps_reset_registry.restype = None
        L.bps_encode_key.argtypes = [ctypes.c_int32, ctypes.c_int32]
        L.bps_encode_key.restype = ctypes.c_uint64
        L.bps_decode_declared_key.argtypes = [ctypes.c_uint64]
        L.bps_decode_declared_key.restype = ctypes.c_int32
        L.bps_decode_part_idx.argtypes = [ctypes.c_uint64]
        L.bps_decode_part_idx.restype = ctypes.c_int32
        L.bps_align.argtypes = [ctypes.c_int64, ctypes.c_int64]
        L.bps_align.restype = ctypes.c_int64
        L.bps_partition_count.argtypes = [ctypes.c_int64, ctypes.c_int64]
        L.bps_partition_count.restype = ctypes.c_int32
        L.bps_partition_bounds.argtypes = [
            ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64)]
        L.bps_partition_bounds.restype = ctypes.c_int32
        L.bps_key_to_server.argtypes = [ctypes.c_uint64, ctypes.c_int32,
                                        ctypes.c_char_p]
        L.bps_key_to_server.restype = ctypes.c_int32
        L.bps_queue_create.argtypes = [ctypes.c_int32, ctypes.c_int64]
        L.bps_queue_create.restype = ctypes.c_void_p
        L.bps_queue_destroy.argtypes = [ctypes.c_void_p]
        L.bps_queue_add.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                    ctypes.c_int32, ctypes.c_int64]
        L.bps_queue_get.argtypes = [ctypes.c_void_p,
                                    ctypes.POINTER(ctypes.c_uint64),
                                    ctypes.POINTER(ctypes.c_int32)]
        L.bps_queue_get.restype = ctypes.c_int64
        L.bps_queue_get_key.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        L.bps_queue_get_key.restype = ctypes.c_int64
        L.bps_queue_report_finish.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        L.bps_queue_pending.argtypes = [ctypes.c_void_p]
        L.bps_queue_pending.restype = ctypes.c_int64
        L.bps_telemetry_set_window_us.argtypes = [ctypes.c_int64]
        L.bps_telemetry_record.argtypes = [ctypes.c_int64]
        L.bps_telemetry_speed_mbps.restype = ctypes.c_double
        L.bps_trace_enable.argtypes = [ctypes.c_int32]
        L.bps_trace_now_us.restype = ctypes.c_int64
        L.bps_trace_record.argtypes = [ctypes.c_char_p, ctypes.c_char_p,
                                       ctypes.c_int64, ctypes.c_int64]
        L.bps_trace_record_part.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int32]
        L.bps_trace_count.restype = ctypes.c_int64
        L.bps_trace_dump.argtypes = [ctypes.c_char_p, ctypes.c_int32]
        L.bps_trace_dump.restype = ctypes.c_int32
        L.bps_handle_allocate.restype = ctypes.c_int32
        L.bps_handle_mark_done.argtypes = [ctypes.c_int32]
        L.bps_handle_poll.argtypes = [ctypes.c_int32]
        L.bps_handle_poll.restype = ctypes.c_int32
        L.bps_handle_release.argtypes = [ctypes.c_int32]

    # -- registry --
    def declare_tensor(self, name: str) -> int:
        return self._lib.bps_declare_tensor(name.encode())

    def get_declared_key(self, name: str) -> int:
        return self._lib.bps_get_declared_key(name.encode())

    def num_declared(self) -> int:
        return self._lib.bps_num_declared()

    def declared_name(self, idx: int) -> Optional[str]:
        buf = ctypes.create_string_buffer(1024)
        n = self._lib.bps_declared_name(idx, buf, 1024)
        return None if n < 0 else buf.value.decode()

    def reset_registry(self) -> None:
        self._lib.bps_reset_registry()

    # -- keys / partitioning --
    def encode_key(self, declared_key: int, part_idx: int) -> int:
        return self._lib.bps_encode_key(declared_key, part_idx)

    def decode_key(self, key: int) -> Tuple[int, int]:
        return (self._lib.bps_decode_declared_key(key),
                self._lib.bps_decode_part_idx(key))

    def partition_bounds(self, nbytes: int,
                         partition_bytes: int) -> List[Tuple[int, int]]:
        n = self._lib.bps_partition_count(nbytes, partition_bytes)
        offs = (ctypes.c_int64 * n)()
        lens = (ctypes.c_int64 * n)()
        self._lib.bps_partition_bounds(nbytes, partition_bytes, offs, lens)
        return [(offs[i], lens[i]) for i in range(n)]

    def key_to_server(self, key: int, num_servers: int,
                      hash_fn: str = "djb2") -> int:
        return self._lib.bps_key_to_server(key, num_servers, hash_fn.encode())

    # -- scheduled queue --
    def queue_create(self, credit_bytes: int = 0) -> "NativeQueue":
        return NativeQueue(self._lib, credit_bytes)

    # -- telemetry --
    def telemetry_record(self, nbytes: int) -> None:
        self._lib.bps_telemetry_record(nbytes)

    def telemetry_speed_mbps(self) -> float:
        return self._lib.bps_telemetry_speed_mbps()

    def telemetry_set_window_us(self, us: int) -> None:
        self._lib.bps_telemetry_set_window_us(us)

    def telemetry_reset(self) -> None:
        self._lib.bps_telemetry_reset()

    # -- tracing --
    def trace_enable(self, on: bool) -> None:
        self.trace_on = bool(on)
        self._lib.bps_trace_enable(1 if on else 0)

    def trace_now_us(self) -> int:
        return self._lib.bps_trace_now_us()

    def trace_record(self, name: str, stage: str, ts_us: int,
                     dur_us: int) -> None:
        self._lib.bps_trace_record(name.encode(), stage.encode(), ts_us, dur_us)

    def trace_record_part(self, name: str, stage: str, ts_us: int,
                          dur_us: int, key: int, nbytes: int,
                          priority: int) -> None:
        """Per-partition span (QUEUE/PUSH/PULL) with key/bytes/priority args
        (reference: per-partition spans in global.cc:463-579)."""
        self._lib.bps_trace_record_part(name.encode(), stage.encode(), ts_us,
                                        dur_us, key, nbytes, priority)

    def trace_count(self) -> int:
        return self._lib.bps_trace_count()

    def trace_dump(self, path: str, rank: int) -> int:
        return self._lib.bps_trace_dump(path.encode(), rank)

    # -- handles --
    def handle_allocate(self) -> int:
        return self._lib.bps_handle_allocate()

    def handle_mark_done(self, h: int) -> None:
        self._lib.bps_handle_mark_done(h)

    def handle_poll(self, h: int) -> int:
        return self._lib.bps_handle_poll(h)

    def handle_release(self, h: int) -> None:
        self._lib.bps_handle_release(h)


class NativeQueue:
    """Priority ScheduledQueue handle (native)."""

    def __init__(self, lib: ctypes.CDLL, credit_bytes: int):
        self._lib = lib
        self._q = lib.bps_queue_create(1 if credit_bytes > 0 else 0,
                                       credit_bytes)

    def add(self, key: int, priority: int, nbytes: int) -> None:
        self._lib.bps_queue_add(self._q, key, priority, nbytes)

    def get(self) -> Optional[Tuple[int, int, int]]:
        """Returns (key, priority, nbytes) or None."""
        k = ctypes.c_uint64()
        p = ctypes.c_int32()
        n = self._lib.bps_queue_get(self._q, ctypes.byref(k), ctypes.byref(p))
        return None if n < 0 else (k.value, p.value, n)

    def get_key(self, key: int) -> Optional[int]:
        n = self._lib.bps_queue_get_key(self._q, key)
        return None if n < 0 else n

    def report_finish(self, nbytes: int) -> None:
        self._lib.bps_queue_report_finish(self._q, nbytes)

    def pending(self) -> int:
        return self._lib.bps_queue_pending(self._q)

    def __del__(self):
        try:
            self._lib.bps_queue_destroy(self._q)
        except Exception:
            pass


# ---------------------------------------------------------------------------
# Pure-Python fallback with identical semantics (used when g++ is unavailable).
# ---------------------------------------------------------------------------
class _PyQueue:
    def __init__(self, credit_bytes: int = 0):
        self._tasks: list = []
        self._credit_enabled = credit_bytes > 0
        self._credit = credit_bytes
        self._lock = threading.Lock()

    def add(self, key, priority, nbytes):
        with self._lock:
            self._tasks.append((key, priority, nbytes))
            self._tasks.sort(key=lambda t: (-t[1], t[0]))

    def get(self):
        with self._lock:
            for i, (k, p, n) in enumerate(self._tasks):
                if self._credit_enabled and n > self._credit:
                    continue
                self._tasks.pop(i)
                if self._credit_enabled:
                    self._credit -= n
                return (k, p, n)
            return None

    def get_key(self, key):
        with self._lock:
            for i, (k, p, n) in enumerate(self._tasks):
                if k == key:
                    # Same eligibility check as get(): an oversized task
                    # stays queued instead of driving the credit negative.
                    if self._credit_enabled and n > self._credit:
                        return None
                    self._tasks.pop(i)
                    if self._credit_enabled:
                        self._credit -= n
                    return n
            return None

    def report_finish(self, nbytes):
        with self._lock:
            if self._credit_enabled:
                self._credit += nbytes

    def pending(self):
        with self._lock:
            return len(self._tasks)


class _PyCore:
    def __init__(self):
        self.trace_on = False  # same hot-path gate as _CCore
        self._name2key: dict = {}
        self._names: list = []
        self._lock = threading.Lock()
        self._tel_events: list = []
        self._tel_window_us = 10_000_000
        self._trace_on = False
        self._trace_events: list = []
        self._next_handle = 0
        self._handles: dict = {}

    def declare_tensor(self, name):
        with self._lock:
            if name in self._name2key:
                return self._name2key[name]
            key = len(self._names)
            self._name2key[name] = key
            self._names.append(name)
            return key

    def get_declared_key(self, name):
        with self._lock:
            return self._name2key.get(name, -1)

    def num_declared(self):
        with self._lock:
            return len(self._names)

    def declared_name(self, idx):
        with self._lock:
            return self._names[idx] if 0 <= idx < len(self._names) else None

    def reset_registry(self):
        with self._lock:
            self._name2key.clear()
            self._names.clear()

    def encode_key(self, declared_key, part_idx):
        return (declared_key << 16) | (part_idx & 0xFFFF)

    def decode_key(self, key):
        return key >> 16, key & 0xFFFF

    def partition_bounds(self, nbytes, partition_bytes):
        if nbytes <= 0:
            return [(0, max(nbytes, 0))]
        out, off = [], 0
        while off < nbytes:
            ln = min(partition_bytes, nbytes - off)
            out.append((off, ln))
            off += ln
        return out

    def key_to_server(self, key, num_servers, hash_fn="djb2"):
        if num_servers <= 0:
            return 0
        s = str(key)

        def djb2():
            h = 5381
            for c in s:
                h = (((h << 5) + h) + ord(c)) & 0xFFFFFFFFFFFFFFFF
            return h

        def sdbm():
            h = 0
            for c in s:
                h = (ord(c) + (h << 6) + (h << 16) - h) & 0xFFFFFFFFFFFFFFFF
            return h

        if hash_fn == "naive":
            h = key
        elif hash_fn == "sdbm":
            h = sdbm()
        elif hash_fn == "mixed":
            h = djb2() ^ sdbm()  # full 64-bit XOR, matching core.cc
        else:
            h = djb2()
        return h % num_servers

    def queue_create(self, credit_bytes=0):
        return _PyQueue(credit_bytes)

    def telemetry_set_window_us(self, us):
        self._tel_window_us = us

    def telemetry_record(self, nbytes):
        t = time.monotonic_ns() // 1000
        self._tel_events.append((t, nbytes))
        cutoff = t - self._tel_window_us
        self._tel_events = [e for e in self._tel_events if e[0] >= cutoff]

    def telemetry_speed_mbps(self):
        t = time.monotonic_ns() // 1000
        cutoff = t - self._tel_window_us
        total = sum(b for ts, b in self._tel_events if ts >= cutoff)
        return (total / 1e6) / (self._tel_window_us / 1e6)

    def telemetry_reset(self):
        self._tel_events.clear()

    def trace_enable(self, on):
        self.trace_on = self._trace_on = bool(on)

    def trace_now_us(self):
        return time.monotonic_ns() // 1000

    def trace_record(self, name, stage, ts_us, dur_us):
        if self._trace_on:
            self._trace_events.append((name, stage, ts_us, dur_us, None))

    def trace_record_part(self, name, stage, ts_us, dur_us, key, nbytes,
                          priority):
        if self._trace_on:
            self._trace_events.append(
                (name, stage, ts_us, dur_us,
                 {"key": key, "bytes": nbytes, "priority": priority}))

    def trace_count(self):
        return len(self._trace_events)

    def trace_dump(self, path, rank):
        import json
        events = [{"name": n, "cat": "comm", "ph": "X", "ts": ts, "dur": d,
                   "pid": rank, "tid": stage,
                   **({"args": args} if args else {})}
                  for (n, stage, ts, d, args) in self._trace_events]
        with open(path, "w") as f:
            json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
        self._trace_events.clear()
        return 0

    def handle_allocate(self):
        with self._lock:
            h = self._next_handle
            self._next_handle += 1
            self._handles[h] = 0
            return h

    def handle_mark_done(self, h):
        with self._lock:
            self._handles[h] = 1

    def handle_poll(self, h):
        with self._lock:
            return self._handles.get(h, -1)

    def handle_release(self, h):
        with self._lock:
            self._handles.pop(h, None)


_core = None
_core_lock = threading.Lock()


def get_core():
    """Returns the process-wide core (native if buildable, Python otherwise)."""
    global _core
    with _core_lock:
        if _core is None:
            try:
                from . import build
                path = build.build()
                _core = _CCore(ctypes.CDLL(path))
                get_logger().debug("loaded native core from %s", path)
            except Exception as e:  # toolchain missing / build failure
                get_logger().warning(
                    "native core unavailable (%s); using Python fallback", e)
                _core = _PyCore()
        return _core


def is_native() -> bool:
    return isinstance(get_core(), _CCore)
