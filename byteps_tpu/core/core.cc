// byteps_tpu native host core.
//
// TPU-native re-design of the reference worker core runtime
// (reference: byteps/common/{global.cc,operations.cc,scheduled_queue.cc,
// ready_table.cc}).  On TPU, the device data plane is XLA collectives, so the
// native layer keeps only what genuinely belongs on the host: the named-tensor
// registry with deterministic key assignment, tensor partitioning, key→server
// placement hashing, the priority ScheduledQueue with credit-based flow
// control, push-pull speed telemetry, and the Chrome-trace timeline recorder.
// Exposed as a flat C ABI consumed via ctypes (no pybind11 in this image).
//
// Deliberately ABSENT: the reference's ReadyTable (ready_table.{h,cc}).  Its
// job is rendezvous across the one-process-per-GPU layout — non-root local
// processes signal readiness over UDS and the root counts signals before
// driving NCCL/PUSH (reference: communicator.cc:164-207, global.cc:207-235).
// Here ONE process drives all local chips (in-jit mesh collectives replace
// the intra-host tier) so there are no local peers to count, and the PS
// plane's cross-worker rendezvous lives on the server (round tracking /
// barrier-by-generation in server.cc).  An earlier revision carried an
// unused port of it; it was removed rather than kept as dead surface.
//
// Thread-safety: every public entry point locks the owning object's mutex;
// objects are opaque handles created/destroyed by the caller.

#include <algorithm>
#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

#define BPS_API extern "C" __attribute__((visibility("default")))

namespace {

int64_t now_us() {
  return std::chrono::duration_cast<std::chrono::microseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

// ---------------------------------------------------------------------------
// Tensor registry: name -> declared key, assigned in declaration order so all
// workers agree without communication (reference: global.cc:427-451).  The
// registry survives suspend/resume; re-declaring an existing name returns the
// original key, which is what keeps keys stable across elastic restarts
// (reference: operations.cc:96-119).
// ---------------------------------------------------------------------------
struct Registry {
  std::mutex mu;
  std::unordered_map<std::string, int32_t> name2key;
  std::vector<std::string> names_in_order;
};

Registry g_registry;

}  // namespace

BPS_API int32_t bps_declare_tensor(const char* name) {
  std::lock_guard<std::mutex> lk(g_registry.mu);
  auto it = g_registry.name2key.find(name);
  if (it != g_registry.name2key.end()) return it->second;
  int32_t key = static_cast<int32_t>(g_registry.names_in_order.size());
  g_registry.name2key.emplace(name, key);
  g_registry.names_in_order.emplace_back(name);
  return key;
}

BPS_API int32_t bps_get_declared_key(const char* name) {
  std::lock_guard<std::mutex> lk(g_registry.mu);
  auto it = g_registry.name2key.find(name);
  return it == g_registry.name2key.end() ? -1 : it->second;
}

BPS_API int32_t bps_num_declared() {
  std::lock_guard<std::mutex> lk(g_registry.mu);
  return static_cast<int32_t>(g_registry.names_in_order.size());
}

// Copies the i-th declared name into buf (for resume re-declaration walks).
BPS_API int32_t bps_declared_name(int32_t idx, char* buf, int32_t buf_len) {
  std::lock_guard<std::mutex> lk(g_registry.mu);
  if (idx < 0 || idx >= (int32_t)g_registry.names_in_order.size()) return -1;
  const std::string& s = g_registry.names_in_order[idx];
  int32_t n = std::min<int32_t>(buf_len - 1, (int32_t)s.size());
  std::memcpy(buf, s.data(), n);
  buf[n] = '\0';
  return n;
}

BPS_API void bps_reset_registry() {
  std::lock_guard<std::mutex> lk(g_registry.mu);
  g_registry.name2key.clear();
  g_registry.names_in_order.clear();
}

// ---------------------------------------------------------------------------
// Key encoding + partitioning.
// The reference encodes partition i of declared tensor k as (k << 16) | i
// (reference: operations.cc:301-311) and splits tensors into page-aligned
// partitions of at most BYTEPS_PARTITION_BYTES (reference:
// operations.cc:140-180, global.cc:134-144).
// ---------------------------------------------------------------------------
BPS_API uint64_t bps_encode_key(int32_t declared_key, int32_t part_idx) {
  return (static_cast<uint64_t>(declared_key) << 16) |
         static_cast<uint64_t>(part_idx & 0xffff);
}

BPS_API int32_t bps_decode_declared_key(uint64_t key) {
  return static_cast<int32_t>(key >> 16);
}

BPS_API int32_t bps_decode_part_idx(uint64_t key) {
  return static_cast<int32_t>(key & 0xffff);
}

BPS_API int64_t bps_align(int64_t size, int64_t alignment) {
  return ((size + alignment - 1) / alignment) * alignment;
}

// Number of partitions for a tensor of `nbytes` with partition size
// `partition_bytes` (already page-aligned by the caller).
BPS_API int32_t bps_partition_count(int64_t nbytes, int64_t partition_bytes) {
  if (nbytes <= 0) return 1;
  return static_cast<int32_t>((nbytes + partition_bytes - 1) / partition_bytes);
}

// Fills offsets[i], lens[i] for each partition. Returns the count.
BPS_API int32_t bps_partition_bounds(int64_t nbytes, int64_t partition_bytes,
                                     int64_t* offsets, int64_t* lens) {
  int32_t n = bps_partition_count(nbytes, partition_bytes);
  int64_t off = 0;
  for (int32_t i = 0; i < n; ++i) {
    int64_t len = std::min(partition_bytes, nbytes - off);
    offsets[i] = off;
    lens[i] = len;
    off += len;
  }
  return n;
}

// ---------------------------------------------------------------------------
// Key -> server placement hashing (reference: global.cc:581-692 — naive,
// built_in, djb2, sdbm, mixed).  Used by the PS-parity tier to spread
// partitions over server shards, and by tests to pin down determinism.
// ---------------------------------------------------------------------------
namespace {
uint64_t hash_djb2(uint64_t k) {
  // djb2 over the decimal digits of the key, like the reference hashes the
  // stringified key.
  char buf[24];
  int n = std::snprintf(buf, sizeof(buf), "%llu", (unsigned long long)k);
  uint64_t h = 5381;
  for (int i = 0; i < n; ++i) h = ((h << 5) + h) + buf[i];
  return h;
}
uint64_t hash_sdbm(uint64_t k) {
  char buf[24];
  int n = std::snprintf(buf, sizeof(buf), "%llu", (unsigned long long)k);
  uint64_t h = 0;
  for (int i = 0; i < n; ++i) h = buf[i] + (h << 6) + (h << 16) - h;
  return h;
}
}  // namespace

BPS_API int32_t bps_key_to_server(uint64_t key, int32_t num_servers,
                                  const char* hash_fn) {
  if (num_servers <= 0) return 0;
  uint64_t h;
  if (std::strcmp(hash_fn, "naive") == 0) {
    h = key;
  } else if (std::strcmp(hash_fn, "sdbm") == 0) {
    h = hash_sdbm(key);
  } else if (std::strcmp(hash_fn, "mixed") == 0) {
    h = hash_djb2(key) ^ hash_sdbm(key);
  } else {  // djb2 (default) and built_in both map here
    h = hash_djb2(key);
  }
  return static_cast<int32_t>(h % static_cast<uint64_t>(num_servers));
}

// ---------------------------------------------------------------------------
// Priority ScheduledQueue (reference: scheduled_queue.{h,cc}).
// Tasks are ordered by (priority desc, key asc); getTask() additionally
// enforces a credit budget of bytes in flight when enabled (reference:
// scheduled_queue.cc:26-46,82-102,136-139,197-203).  Unlike the reference we
// keep a heap-free sorted insert into a deque: queues are short (hundreds of
// buckets) and the host side is not the bottleneck on TPU.
// ---------------------------------------------------------------------------
namespace {
struct QTask {
  uint64_t key;
  int32_t priority;
  int64_t nbytes;
};

struct ScheduledQueue {
  std::mutex mu;
  std::deque<QTask> tasks;
  bool credit_enabled;
  int64_t credit;  // bytes allowed in flight
  std::atomic<int64_t> pending{0};
};
}  // namespace

BPS_API void* bps_queue_create(int32_t credit_enabled, int64_t credit_bytes) {
  auto* q = new ScheduledQueue();
  q->credit_enabled = credit_enabled != 0;
  q->credit = credit_bytes;
  return q;
}

BPS_API void bps_queue_destroy(void* qp) {
  delete static_cast<ScheduledQueue*>(qp);
}

BPS_API void bps_queue_add(void* qp, uint64_t key, int32_t priority,
                           int64_t nbytes) {
  auto* q = static_cast<ScheduledQueue*>(qp);
  std::lock_guard<std::mutex> lk(q->mu);
  QTask t{key, priority, nbytes};
  // Sorted insert: higher priority first; ties broken by smaller key
  // (reference: scheduled_queue.cc:82-102).
  auto it = std::upper_bound(
      q->tasks.begin(), q->tasks.end(), t, [](const QTask& a, const QTask& b) {
        if (a.priority != b.priority) return a.priority > b.priority;
        return a.key < b.key;
      });
  q->tasks.insert(it, t);
  q->pending.fetch_add(1);
}

// Pops the highest-priority task whose size fits in the remaining credit.
// Returns nbytes and writes the key, or -1 if nothing is eligible.
BPS_API int64_t bps_queue_get(void* qp, uint64_t* out_key,
                              int32_t* out_priority) {
  auto* q = static_cast<ScheduledQueue*>(qp);
  std::lock_guard<std::mutex> lk(q->mu);
  for (auto it = q->tasks.begin(); it != q->tasks.end(); ++it) {
    if (q->credit_enabled && it->nbytes > q->credit) continue;
    QTask t = *it;
    q->tasks.erase(it);
    if (q->credit_enabled) q->credit -= t.nbytes;
    q->pending.fetch_sub(1);
    *out_key = t.key;
    if (out_priority) *out_priority = t.priority;
    return t.nbytes;
  }
  return -1;
}

// Pops the task with a specific key (signal-directed dequeue, reference:
// scheduled_queue.cc:165-190).  Applies the same credit-eligibility check
// as bps_queue_get: a task larger than the remaining credit stays queued
// and -1 is returned — subtracting unconditionally would drive the credit
// negative and stall bps_queue_get until enough finishes were reported.
BPS_API int64_t bps_queue_get_key(void* qp, uint64_t key) {
  auto* q = static_cast<ScheduledQueue*>(qp);
  std::lock_guard<std::mutex> lk(q->mu);
  for (auto it = q->tasks.begin(); it != q->tasks.end(); ++it) {
    if (it->key == key) {
      if (q->credit_enabled && it->nbytes > q->credit) return -1;
      int64_t n = it->nbytes;
      if (q->credit_enabled) q->credit -= n;
      q->tasks.erase(it);
      q->pending.fetch_sub(1);
      return n;
    }
  }
  return -1;
}

BPS_API void bps_queue_report_finish(void* qp, int64_t nbytes) {
  auto* q = static_cast<ScheduledQueue*>(qp);
  std::lock_guard<std::mutex> lk(q->mu);
  if (q->credit_enabled) q->credit += nbytes;
}

BPS_API int64_t bps_queue_pending(void* qp) {
  return static_cast<ScheduledQueue*>(qp)->pending.load();
}

// ---------------------------------------------------------------------------
// Push-pull speed telemetry (reference: global.cc:712-767): ring buffer of
// (timestamp, bytes) push events; speed is a moving average over the last
// `window_us` (reference uses 10 s).
// ---------------------------------------------------------------------------
namespace {
struct Telemetry {
  std::mutex mu;
  std::deque<std::pair<int64_t, int64_t>> events;  // (us, bytes)
  int64_t window_us = 10 * 1000 * 1000;
};

Telemetry g_telemetry;
}  // namespace

BPS_API void bps_telemetry_set_window_us(int64_t window_us) {
  std::lock_guard<std::mutex> lk(g_telemetry.mu);
  g_telemetry.window_us = window_us;
}

BPS_API void bps_telemetry_record(int64_t bytes) {
  std::lock_guard<std::mutex> lk(g_telemetry.mu);
  int64_t t = now_us();
  g_telemetry.events.emplace_back(t, bytes);
  while (!g_telemetry.events.empty() &&
         g_telemetry.events.front().first < t - g_telemetry.window_us) {
    g_telemetry.events.pop_front();
  }
}

// Moving-average push throughput in MB/s over the telemetry window.
BPS_API double bps_telemetry_speed_mbps() {
  std::lock_guard<std::mutex> lk(g_telemetry.mu);
  int64_t t = now_us();
  int64_t total = 0;
  for (auto& e : g_telemetry.events) {
    if (e.first >= t - g_telemetry.window_us) total += e.second;
  }
  double secs = g_telemetry.window_us / 1e6;
  return (total / 1e6) / secs;
}

BPS_API void bps_telemetry_reset() {
  std::lock_guard<std::mutex> lk(g_telemetry.mu);
  g_telemetry.events.clear();
}

// ---------------------------------------------------------------------------
// Chrome-trace timeline recorder (reference: global.cc:463-579, format in
// docs/timeline.md).  Complete events ("ph":"X") with (name, stage, ts, dur,
// tid=stage-id) accumulated in memory and dumped to <dir>/<rank>/comm.json.
// ---------------------------------------------------------------------------
namespace {
struct TraceEvent {
  std::string name;
  std::string stage;
  int64_t ts_us;
  int64_t dur_us;
  // Per-partition detail (reference closes one span per partition per
  // pipeline stage, global.cc:463-579).  key < 0 means "not a partition
  // event" and the args object is omitted from the dump.
  int64_t key = -1;
  int64_t bytes = 0;
  int32_t priority = 0;
};

struct Tracer {
  std::mutex mu;
  std::vector<TraceEvent> events;
  bool on = false;
};

Tracer g_tracer;
}  // namespace

BPS_API void bps_trace_enable(int32_t on) {
  std::lock_guard<std::mutex> lk(g_tracer.mu);
  g_tracer.on = on != 0;
}

BPS_API int64_t bps_trace_now_us() { return now_us(); }

BPS_API void bps_trace_record(const char* name, const char* stage,
                              int64_t ts_us, int64_t dur_us) {
  std::lock_guard<std::mutex> lk(g_tracer.mu);
  if (!g_tracer.on) return;
  g_tracer.events.push_back(TraceEvent{name, stage, ts_us, dur_us});
}

// Per-partition span: one row per partition per stage (QUEUE/PUSH/PULL on
// the PS plane), carrying the partition key, wire bytes, and priority as
// Chrome-trace args.
BPS_API void bps_trace_record_part(const char* name, const char* stage,
                                   int64_t ts_us, int64_t dur_us,
                                   int64_t key, int64_t bytes,
                                   int32_t priority) {
  std::lock_guard<std::mutex> lk(g_tracer.mu);
  if (!g_tracer.on) return;
  g_tracer.events.push_back(
      TraceEvent{name, stage, ts_us, dur_us, key, bytes, priority});
}

BPS_API int64_t bps_trace_count() {
  std::lock_guard<std::mutex> lk(g_tracer.mu);
  return (int64_t)g_tracer.events.size();
}

namespace {
// Minimal JSON string escaping (quotes, backslashes, control chars).
std::string json_escape(const std::string& s) {
  std::string out;
  out.reserve(s.size());
  for (unsigned char c : s) {
    switch (c) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\n': out += "\\n"; break;
      case '\r': out += "\\r"; break;
      case '\t': out += "\\t"; break;
      default:
        if (c < 0x20) {
          char buf[8];
          std::snprintf(buf, sizeof(buf), "\\u%04x", c);
          out += buf;
        } else {
          out += (char)c;
        }
    }
  }
  return out;
}
}  // namespace

// Dumps accumulated events as a Chrome trace (JSON array of complete events,
// one pid per rank) and clears the buffer. Returns 0 on success.
BPS_API int32_t bps_trace_dump(const char* path, int32_t rank) {
  std::lock_guard<std::mutex> lk(g_tracer.mu);
  FILE* f = std::fopen(path, "w");
  if (!f) return -1;
  std::fputs("{\"traceEvents\":[\n", f);
  bool first = true;
  for (auto& e : g_tracer.events) {
    if (!first) std::fputs(",\n", f);
    first = false;
    std::fprintf(f,
                 "{\"name\":\"%s\",\"cat\":\"comm\",\"ph\":\"X\",\"ts\":%lld,"
                 "\"dur\":%lld,\"pid\":%d,\"tid\":\"%s\"",
                 json_escape(e.name).c_str(), (long long)e.ts_us,
                 (long long)e.dur_us, rank, json_escape(e.stage).c_str());
    if (e.key >= 0) {
      std::fprintf(f,
                   ",\"args\":{\"key\":%lld,\"bytes\":%lld,\"priority\":%d}",
                   (long long)e.key, (long long)e.bytes, e.priority);
    }
    std::fputs("}", f);
  }
  std::fputs("\n],\"displayTimeUnit\":\"ms\"}\n", f);
  std::fclose(f);
  g_tracer.events.clear();
  return 0;
}

// ---------------------------------------------------------------------------
// Handle manager (reference: torch/handle_manager.{h,cc}): int handle ->
// completion status for the eager async API.
// ---------------------------------------------------------------------------
namespace {
struct HandleManager {
  std::mutex mu;
  int32_t next = 0;
  std::unordered_map<int32_t, int32_t> done;  // handle -> 1 when complete
};

HandleManager g_handles;
}  // namespace

BPS_API int32_t bps_handle_allocate() {
  std::lock_guard<std::mutex> lk(g_handles.mu);
  int32_t h = g_handles.next++;
  g_handles.done[h] = 0;
  return h;
}

BPS_API void bps_handle_mark_done(int32_t h) {
  std::lock_guard<std::mutex> lk(g_handles.mu);
  g_handles.done[h] = 1;
}

BPS_API int32_t bps_handle_poll(int32_t h) {
  std::lock_guard<std::mutex> lk(g_handles.mu);
  auto it = g_handles.done.find(h);
  return it == g_handles.done.end() ? -1 : it->second;
}

BPS_API void bps_handle_release(int32_t h) {
  std::lock_guard<std::mutex> lk(g_handles.mu);
  g_handles.done.erase(h);
}
