"""bpslaunch-equivalent process launcher.

The reference's `bpslaunch` dispatches on DMLC_ROLE: a worker machine
spawns one training process per GPU with BYTEPS_LOCAL_RANK/SIZE and NUMA
pinning; servers/schedulers exec `python -c 'import byteps.server'`
(reference: launcher/launch.py:147-218, NUMA logic at 45-123).

TPU redesign: one JAX process drives every local chip, so the worker role
launches a SINGLE training process per host (local_rank fan-out and NUMA
cpusets disappear — XLA owns chip placement).  Server and scheduler roles
start the native KV tier: servers run the full engine; the scheduler runs
the same binary as a barrier/rendezvous endpoint on the root port, playing
the reference scheduler's Postoffice role for PS mode.  Worker multi-host
rendezvous rides `jax.distributed` via DMLC_PS_ROOT_URI/PORT, so reference
launch configs carry over unchanged.

Usage:  bpslaunch python train.py ...   (role from DMLC_ROLE)
"""

from __future__ import annotations

import os
import subprocess
import sys
from typing import Dict, List, Optional


def build_worker_env(env: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    """Environment for the (single) worker training process on this host."""
    e = dict(os.environ if env is None else env)
    e.setdefault("BYTEPS_LOCAL_RANK", "0")
    e.setdefault("BYTEPS_LOCAL_SIZE", "1")
    # Multi-host: map the reference's scheduler to the JAX coordinator.
    if int(e.get("DMLC_NUM_WORKER", "1")) > 1:
        e.setdefault("BYTEPS_TPU_JAX_DIST", "1")
    return e


def worker_command(argv: List[str],
                   env: Optional[Dict[str, str]] = None) -> List[str]:
    """The command a worker host runs — gdb-wrapped when
    BYTEPS_ENABLE_GDB=1, like the reference (launcher/launch.py:147-150)."""
    e = os.environ if env is None else env
    if e.get("BYTEPS_ENABLE_GDB", "0") == "1":
        return ["gdb", "-ex", "run", "-ex", "bt", "-batch", "--args"] + argv
    return list(argv)


def server_command(role: str) -> List[str]:
    """Server/scheduler both run the native KV tier
    (scheduler = barrier-only instance on the root port)."""
    if role == "scheduler":
        return [sys.executable, "-c",
                "import byteps_tpu.server as s; s.serve(port=None)"]
    return [sys.executable, "-m", "byteps_tpu.server"]


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    role = os.environ.get("DMLC_ROLE", "worker").lower()
    procs = []
    if role in ("server", "scheduler", "joint"):
        env = dict(os.environ)
        if role == "scheduler":
            # The scheduler binds the root port itself.
            env["DMLC_SERVER_ID"] = "-1"  # port = root_port + 1 + (-1)
        cmd = server_command(role)
        if role == "joint":
            procs.append(subprocess.Popen(cmd, env=env))
        else:
            return subprocess.call(cmd, env=env)
    if role in ("worker", "joint"):
        if not argv:
            print("bpslaunch: no training command given", file=sys.stderr)
            return 2
        rc = subprocess.call(worker_command(argv),
                             env=build_worker_env())
        for p in procs:
            p.terminate()
            p.wait()
        return rc
    return 0


if __name__ == "__main__":
    sys.exit(main())
