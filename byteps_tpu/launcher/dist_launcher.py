"""SSH fan-out launcher for multi-host runs.

Mirrors the reference's launcher/dist_launcher.py:78-118: read worker and
server hostfiles, ssh to every host with the right DMLC_* environment, and
stream logs to sshlog/.  The scheduler runs on the first server host (or
--scheduler host).
"""

from __future__ import annotations

import argparse
import os
import shlex
import subprocess
import sys
import threading
from typing import Dict, List, Optional


def read_hostfile(path: str) -> List[str]:
    with open(path) as f:
        return [ln.strip() for ln in f if ln.strip()
                and not ln.startswith("#")]


def role_env(role: str, rank: int, args) -> Dict[str, str]:
    env = {
        "DMLC_ROLE": role,
        "DMLC_PS_ROOT_URI": args.scheduler_host,
        "DMLC_PS_ROOT_PORT": str(args.scheduler_port),
        "DMLC_NUM_WORKER": str(args.num_workers),
        "DMLC_NUM_SERVER": str(args.num_servers),
    }
    if role == "worker":
        env["DMLC_WORKER_ID"] = str(rank)
    if role == "server":
        env["DMLC_SERVER_ID"] = str(rank)
    return env


def ssh_command(host: str, env: Dict[str, str], cmd: str) -> List[str]:
    exports = " ".join(f"{k}={shlex.quote(v)}" for k, v in env.items())
    return ["ssh", "-o", "StrictHostKeyChecking=no", host,
            f"export {exports}; {cmd}"]


def _stream(proc: subprocess.Popen, logfile: str) -> None:
    with open(logfile, "wb") as f:
        for line in proc.stdout:  # type: ignore[union-attr]
            f.write(line)
            f.flush()


def launch(args, dry_run: bool = False) -> List[List[str]]:
    """Builds (and unless dry_run, starts) every ssh command.
    Returns the command list for inspection/testing."""
    workers = read_hostfile(args.worker_hostfile)[:args.num_workers]
    servers = read_hostfile(args.server_hostfile)[:args.num_servers] \
        if args.num_servers else []
    if not args.scheduler_host:
        args.scheduler_host = (servers or workers)[0]

    cmds = []
    plans = []
    plans.append(("scheduler", 0, args.scheduler_host,
                  "python -m byteps_tpu.launcher.launch"))
    for i, h in enumerate(servers):
        plans.append(("server", i, h, "python -m byteps_tpu.launcher.launch"))
    for i, h in enumerate(workers):
        plans.append(("worker", i, h,
                      f"python -m byteps_tpu.launcher.launch {args.command}"))

    os.makedirs(args.log_dir, exist_ok=True)
    threads = []
    for role, rank, host, cmd in plans:
        full = ssh_command(host, role_env(role, rank, args), cmd)
        cmds.append(full)
        if not dry_run:
            p = subprocess.Popen(full, stdout=subprocess.PIPE,
                                 stderr=subprocess.STDOUT)
            t = threading.Thread(
                target=_stream, args=(p, os.path.join(
                    args.log_dir, f"{role}-{rank}-{host}.log")), daemon=True)
            t.start()
            threads.append((p, t))
    for p, t in threads:
        p.wait()
        t.join()
    return cmds


def parse_args(argv: Optional[List[str]] = None):
    ap = argparse.ArgumentParser(
        description="byteps_tpu distributed launcher (ssh fan-out)")
    ap.add_argument("--num-workers", type=int, required=True)
    ap.add_argument("--num-servers", type=int, default=0)
    ap.add_argument("--worker-hostfile", required=True)
    ap.add_argument("--server-hostfile", default="")
    ap.add_argument("--scheduler-host", default="")
    ap.add_argument("--scheduler-port", type=int, default=9000)
    ap.add_argument("--log-dir", default="sshlog")
    ap.add_argument("command", nargs=argparse.REMAINDER,
                    help="training command for workers")
    args = ap.parse_args(argv)
    # Preserve each token through the remote shell (spaces, $, ; ...).
    args.command = " ".join(shlex.quote(t) for t in args.command)
    return args


def main(argv: Optional[List[str]] = None) -> int:
    launch(parse_args(argv))
    return 0


if __name__ == "__main__":
    sys.exit(main())
