"""Worker-side codecs for compressed PS payloads.

numpy implementations of the PS-tier wire formats, bit-identical to the
C++ server codec (core/server.cc `namespace codec`), so a compressed
push_pull through the server tier reproduces the server's
decompress-sum-recompress exactly (reference: server/server.cc:86-207,
fed by kwargs from the init push, operations.cc:396-408).

This byte codec is the PS plane's contract and is independent of the
collective plane's on-device formats: the JAX compressors pack sign bits
in the uint32 sublane layout of ops/compressor/bitpack.py (a Pallas
kernel), while this wire keeps LSB-first uint8 bytes — payloads from the
two planes are NOT interchangeable.

Wire layout (little-endian):
    u8 comp_id | u32 n_elems | body
    onebit(1):    f32 scale | u8 bits[ceil(n/8)]       (LSB-first, 1 = neg)
    topk(2):      u32 k | i32 idx[k] | f32 val[k]
    randomk(3):   u32 k | i32 idx[k] | f32 val[k]
    dithering(4): u8 flags(bit0=natural, bit1=elias) | u8 s | f32 norm | ...
      dense (bit1=0): level bitstream [ceil(n*b/8)] | u8 signs[ceil(n/8)]
                  where b = ceil(log2(s+1)); levels are packed LSB-first at
                  b bits each, byte-contiguous.  (The on-device JAX plane
                  also bit-packs levels, but into sublane-layout uint32
                  words at 32//b levels per word — bitpack.pack_levels —
                  so the two planes' level streams are NOT interchangeable,
                  like the sign streams.)  s=15 ships 4+1 bits/elem,
                  within the reference's Elias-delta budget (reference:
                  compressor/impl/dithering.cc:51-120) without
                  variable-length decode.
      elias (bit1=1, kwargs coding=elias): u32 nbits | stream — per
                  NONZERO level in index order, EliasDelta(index gap,
                  prev=-1) · sign bit · EliasDelta(level) — the
                  reference's sparse entropy coding.  Bits are LSB-first
                  within bytes; within one code, MSB-of-code-first.
                  Denser than the dense form whenever most levels
                  quantize to 0 (typical gradients).
    qblock(5):    u8 bits(4|8) | u16 block | f32 scale[nblocks] | ints
                  — EQuARX-flavored blockwise integer quantization
                  (arXiv 2506.17615): per `block` elements one f32
                  scale = absmax/qmax (qmax = 2^(bits-1)-1), each
                  element round-half-even(x/scale) clipped to
                  [-qmax, qmax]; bits=4 packs two two's-complement
                  nibbles per byte, low nibble first.  Dense layout,
                  flat decode, deterministic (no PRNG) — the aggressive
                  end of the adaptive-compression dial, EF-capable on
                  both legs under the same law as onebit.
"""

from __future__ import annotations

import struct
import threading
from typing import Dict, Optional, Tuple

import numpy as np

COMP_ONEBIT, COMP_TOPK, COMP_RANDOMK, COMP_DITHERING, COMP_QBLOCK = \
    1, 2, 3, 4, 5

_NAMES = {"onebit": COMP_ONEBIT, "topk": COMP_TOPK,
          "randomk": COMP_RANDOMK, "dithering": COMP_DITHERING,
          "qblock": COMP_QBLOCK}

_CWIRE = False   # False = untried, None = unavailable, else the CDLL


def _c_wire():
    """ctypes handle to the C codec in libbyteps_core.so (the same
    decoder/encoder the server engine runs), or None when the native
    build is unavailable — every caller keeps a numpy fallback, so a
    toolchain-less install stays fully functional, just slower (the
    numpy dithering/elias paths are 10-1000x off the C ones)."""
    global _CWIRE
    if _CWIRE is False:
        try:
            import ctypes

            from ..core import native
            core = native.get_core()
            lib = getattr(core, "_lib", None)
            if lib is None:
                _CWIRE = None
            else:
                u64, u32 = ctypes.c_uint64, ctypes.c_uint32
                lib.bps_wire_decode.argtypes = [
                    ctypes.c_char_p, u64, ctypes.c_void_p, u64]
                lib.bps_wire_decode.restype = ctypes.c_int
                lib.bps_wire_encode_dithering.argtypes = [
                    ctypes.c_void_p, u64, u32, ctypes.c_int, ctypes.c_int,
                    ctypes.c_float, ctypes.c_void_p, ctypes.c_void_p,
                    ctypes.c_void_p, u64]
                lib.bps_wire_encode_dithering.restype = ctypes.c_int64
                lib.bps_wire_onebit_correct.argtypes = [
                    ctypes.c_void_p, u64, ctypes.c_void_p, ctypes.c_float,
                    ctypes.c_void_p]
                lib.bps_wire_onebit_correct.restype = None
                lib.bps_wire_onebit_pack.argtypes = [
                    ctypes.c_void_p, u64, ctypes.c_float, ctypes.c_void_p,
                    ctypes.c_void_p]
                lib.bps_wire_onebit_pack.restype = None
                lib.bps_wire_encode_qblock.argtypes = [
                    ctypes.c_void_p, u64, ctypes.c_int, u32,
                    ctypes.c_void_p, ctypes.c_void_p, u64]
                lib.bps_wire_encode_qblock.restype = ctypes.c_int64
                _CWIRE = lib
        except Exception:   # pragma: no cover - defensive
            _CWIRE = None
    return _CWIRE


def _pack_bits(bits: np.ndarray) -> np.ndarray:
    """bits [n] in {0,1} -> uint8 [ceil(n/8)], LSB-first (matches the C++
    server codec)."""
    return np.packbits(bits.astype(np.uint8), bitorder="little")


def _unpack_bits(packed: np.ndarray, n: int) -> np.ndarray:
    return np.unpackbits(packed, bitorder="little")[:n]


def _level_bits(s: int) -> int:
    """Bits per level on the wire: ceil(log2(s+1)) for values 0..s."""
    return max(1, int(s).bit_length())


def _pack_levels(level: np.ndarray, s: int) -> np.ndarray:
    """uint8 levels [n] (each <= s) -> LSB-first bitstream at b bits each."""
    b = _level_bits(s)
    bits = ((level[:, None].astype(np.uint8)
             >> np.arange(b, dtype=np.uint8)) & 1)
    return np.packbits(bits.ravel(), bitorder="little")


def _unpack_levels(packed: np.ndarray, n: int, s: int) -> np.ndarray:
    b = _level_bits(s)
    raw = np.unpackbits(packed, bitorder="little",
                        count=n * b).reshape(n, b).astype(np.int32)
    return (raw << np.arange(b, dtype=np.int32)).sum(axis=1)


def _bit_length(v: np.ndarray) -> np.ndarray:
    """Vectorized bit_length for int64 1 <= v < 2^62 (the correction
    shifts clip at 62; wire values — u32 index gaps, u8 levels — are far
    inside the domain)."""
    L = np.floor(np.log2(v.astype(np.float64))).astype(np.int64) + 1
    # float edges: force 2^(L-1) <= v < 2^L exactly
    L = np.where(v >> L.clip(0, 62) > 0, L + 1, L)
    L = np.where((v < (np.int64(1) << (L - 1).clip(0, 62))) & (L > 1),
                 L - 1, L)
    return L


def _elias_delta_codes(v: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Elias-delta (code, length) pairs for int64 v >= 1.

    Code layout (emitted MSB-of-code-first): LL-1 zeros, then L in LL bits
    (MSB first), then v's low L-1 bits (MSB first) — where L = bitlen(v),
    LL = bitlen(L).  The leading zeros carry no value, so the numeric code
    is L's bits followed by v's low bits; `length` includes the zeros.
    """
    L = _bit_length(v)
    LL = _bit_length(L)
    length = 2 * LL + L - 2
    low_mask = (np.int64(1) << (L - 1)) - 1
    code = (L.astype(np.uint64) << (L - 1).astype(np.uint64)) \
        | (v & low_mask).astype(np.uint64)
    return code, length


def _emit_bitstream(codes: np.ndarray, lengths: np.ndarray) -> Tuple[
        np.ndarray, int]:
    """Concatenate (code, length) pairs into an LSB-first-per-byte
    bitstream; returns (uint8 bytes, total_bits).  Bit i of the stream is
    (byte[i>>3] >> (i&7)) & 1; within one code, bits appear in
    MSB-of-code-first order."""
    total = int(lengths.sum())
    if total == 0:
        return np.zeros(0, np.uint8), 0
    starts = np.concatenate([[0], np.cumsum(lengths)[:-1]])
    owner = np.repeat(np.arange(len(codes)), lengths)
    k = np.arange(total) - starts[owner]          # position within code
    shift = (lengths[owner] - 1 - k).astype(np.uint64)
    bits = ((codes[owner] >> shift) & np.uint64(1)).astype(np.uint8)
    return np.packbits(bits, bitorder="little"), total


class _BitCursor:
    """Sequential LSB-first-per-byte bit reader (decode reference path —
    the C++ server codec is the production decoder)."""

    def __init__(self, data: np.ndarray, nbits: int):
        self.bits = np.unpackbits(data, bitorder="little", count=nbits)
        self.pos = 0

    def left(self) -> int:
        return len(self.bits) - self.pos

    def take(self) -> int:
        if self.pos >= len(self.bits):
            raise ValueError("truncated elias stream")
        b = int(self.bits[self.pos])
        self.pos += 1
        return b

    def take_int(self, nbits: int) -> int:
        v = 0
        for _ in range(nbits):
            v = (v << 1) | self.take()
        return v

    def elias_delta(self) -> int:
        ll = 1
        while self.left() and self.take() == 0:
            ll += 1
        if ll == 1:
            return 1        # L = 1 -> v = 1
        L = (1 << (ll - 1)) | self.take_int(ll - 1)
        return (1 << (L - 1)) | self.take_int(L - 1)


def _xorshift32(x: np.ndarray) -> np.ndarray:
    x = x ^ (x << np.uint32(13))
    x = x ^ (x >> np.uint32(17))
    x = x ^ (x << np.uint32(5))
    return x


def _seed_state(seed: int, n: int) -> np.ndarray:
    """Mirror of ops/compressor/base.seed_state (numpy)."""
    lanes = np.arange(1, n + 1, dtype=np.uint32)
    with np.errstate(over="ignore"):
        s = lanes * np.uint32(2654435761) + np.uint32(seed | 1)
    s = np.where(s == 0, np.uint32(0x9E3779B9), s)
    return _xorshift32(s)


class WireCompressor:
    """Per-tensor compressed-wire codec with per-partition PRNG state.

    Built from the same string kwargs as the registry
    (ops/compressor/registry.py), which are also shipped verbatim to the
    server at INIT.
    """

    def __init__(self, kwargs: Dict[str, str]):
        from ..ops.compressor.registry import (  # shared parse
            _get, _get_bool, parse_ef, parse_momentum)
        ctype = (kwargs.get("compressor") or kwargs.get("compressor_type")
                 or kwargs.get("byteps_compressor_type"))
        if ctype not in _NAMES:
            raise ValueError(
                f"unsupported PS-wire compressor {ctype!r}; "
                f"known: {sorted(_NAMES)}")
        self.name = ctype
        self.comp_id = _NAMES[ctype]
        self.kwargs = dict(kwargs)
        self.scaled = _get_bool(kwargs, "onebit_scaling", True)
        self.k = int(_get(kwargs, "k", 0))
        self.seed = int(_get(kwargs, "seed", 2020))
        self.s = int(_get(kwargs, "k", 127)) if ctype == "dithering" else 0
        self.partition = str(_get(kwargs, "partition", "linear"))
        self.normalize = str(_get(kwargs, "normalize", "max"))
        # Dithering wire coding: "dense" = fixed ceil(log2(s+1)) bits per
        # level; "elias" = the reference's sparse entropy coding — per
        # NONZERO level, EliasDelta(index gap) · sign bit ·
        # EliasDelta(level) (reference: compressor/impl/dithering.cc:
        # 51-120).  Elias wins when most levels quantize to 0 (real
        # gradients); dense wins on incompressible level streams and
        # keeps decode a flat loop.
        self.coding = str(_get(kwargs, "coding", "dense"))
        if self.coding not in ("dense", "elias"):
            raise ValueError(f"dithering coding={self.coding!r}; "
                             f"options: dense, elias")
        if ctype in ("topk", "randomk") and self.k <= 0:
            raise ValueError(f"{ctype} requires k > 0")
        # Quantized-block params (EQuARX-flavored dense int format).
        self.qb_bits = int(_get(kwargs, "bits", 8)) if ctype == "qblock" \
            else 0
        self.qb_block = min(0xFFFF, max(1, int(_get(kwargs, "block", 256)))
                            ) if ctype == "qblock" else 0
        if ctype == "qblock" and self.qb_bits not in (4, 8):
            raise ValueError(f"qblock bits={self.qb_bits}; options: 4, 8")
        self.bidirectional = ctype in ("onebit", "qblock")
        # Worker-side vanilla error feedback (reference:
        # error_feedback.cc:22-34: grad += e; c = Compress(grad);
        # e = grad - Decompress(c)), per partition key.  The server never
        # applies EF to PUSHES — it only sees corrected payloads (it does
        # run EF on its own recompress leg, core/server.cc ALL_RECV).
        self.ef = parse_ef(kwargs)
        self._err: Dict[int, np.ndarray] = {}
        # Guards _err/_mom against concurrent encoders (different
        # partition keys push from multiple threads) and set_lr_scale's
        # iteration.
        self._state_lock = threading.Lock()
        # Worker-side Nesterov momentum, applied BEFORE EF + compression
        # (reference layering momentum -> ef -> compressor,
        # compressor_registry.cc:39-56; momentum.cc:20-31: m = mu*m + g;
        # g += mu*m).  Worker-only — the kwargs still ship to the server,
        # which ignores momentum like the reference's server registry.
        # Shared parse with the JAX-plane registry so both planes accept
        # the exact same kwargs strings.
        self.momentum_mu = parse_momentum(kwargs)
        self._mom: Dict[int, np.ndarray] = {}
        self._rng: Dict[int, np.ndarray] = {}  # per-partition-key PRNG lanes
        self._last_recon: Optional[np.ndarray] = None  # see encode()

    def set_lr_scale(self, scale: float) -> None:
        """Rescale the carried EF error once when the learning rate
        changes — the reference's `lr.s` mechanism as an explicit API.
        `scale` = prev_lr / new_lr (reference:
        impl/vanilla_error_feedback.cc applies `pre_lr/cur_lr` then sets
        `pre_lr = cur_lr`; multiplying the stored error once is the same
        one-shot semantics, matching the JAX plane's
        ops.compressor.set_lr_scale)."""
        s = np.float32(scale)
        with self._state_lock:
            for k in self._err:
                self._err[k] = self._err[k] * s

    def ef_residual_norm(self) -> float:
        """l2 norm of the carried error-feedback residual across this
        tensor's partitions (0.0 without EF).  The gradient-health
        monitor samples it: a residual growing without bound means the
        compressor is systematically under-shooting (e.g. a scale stuck
        at an overflow) and the "correction" will eventually dwarf the
        gradient itself."""
        if not self.ef:
            return 0.0
        with self._state_lock:
            total = 0.0
            for e in self._err.values():
                total += float(np.dot(e, e))
        return float(np.sqrt(total))

    def take_ef_state(self) -> Dict[int, np.ndarray]:
        """Detach and return the carried per-partition EF residuals — the
        codec-switch handoff: when source and target codecs share vanilla
        EF semantics (an additive residual in gradient space, true for
        every EF-capable wire codec here) the new compressor adopts them
        via :meth:`adopt_ef_state`; otherwise the session folds each
        residual into the key's next push, so a switch can never silently
        drop accumulated error."""
        with self._state_lock:
            err, self._err = self._err, {}
        return err

    def adopt_ef_state(self, err: Dict[int, np.ndarray]) -> None:
        """Adopt residuals from a predecessor codec (see take_ef_state).
        Adds into any residual this compressor already carries — the
        conservation law, not last-write-wins."""
        if not self.ef or not err:
            return
        with self._state_lock:
            for pk, e in err.items():
                mine = self._err.get(pk)
                if mine is not None and mine.size == e.size:
                    self._err[pk] = mine + e
                else:
                    self._err[pk] = np.asarray(e, np.float32)

    def wire_cap_bytes(self, n: int) -> int:
        """Worst-case wire payload size for an n-element partition.

        The codec pipeline charges scheduling credit at enqueue time,
        BEFORE the encode has produced actual wire bytes — this bound
        keeps the charge at compressed scale (an onebit partition charges
        ~n/8, not 4n, preserving the credit law's in-flight concurrency).
        The bound must not meaningfully under-estimate (the charge is
        returned verbatim by report_finish, so bookkeeping stays
        symmetric regardless, but the credit law meters wire bytes).
        The client clamps the charge to the raw partition size: the
        credit floor guarantees one raw partition always fits, and
        elias's worst case exceeds raw by its ~80-byte framing."""
        if self.comp_id == COMP_ONEBIT:
            return 9 + (n + 7) // 8
        if self.comp_id == COMP_QBLOCK:
            nb = (n + self.qb_block - 1) // self.qb_block
            return 8 + 4 * nb + (n if self.qb_bits == 8 else (n + 1) // 2)
        if self.comp_id in (COMP_TOPK, COMP_RANDOMK):
            return 9 + 8 * min(self.k, n)
        # dithering — the same caps the C encoder is given (elias's
        # worst case is ~raw size; dense is b bits + sign per element).
        if self.coding == "elias":
            return 15 + 4 * n + 64
        return 15 + (n * _level_bits(self.s) + 7) // 8 + (n + 7) // 8

    def kwargs_string(self) -> str:
        """Canonical "k=v,k=v" form sent in the INIT payload."""
        kw = {"compressor": self.name}
        if self.ef:
            kw["ef"] = "vanilla"
        if self.momentum_mu:
            kw["momentum"] = "nesterov"
            kw["momentum_mu"] = repr(self.momentum_mu)
        if self.name == "onebit":
            kw["onebit_scaling"] = "1" if self.scaled else "0"
        if self.name == "qblock":
            kw.update(bits=str(self.qb_bits), block=str(self.qb_block))
        if self.name in ("topk", "randomk"):
            kw["k"] = str(self.k)
        if self.name == "randomk":
            kw["seed"] = str(self.seed)
        if self.name == "dithering":
            kw.update(k=str(self.s), seed=str(self.seed),
                      partition=self.partition, normalize=self.normalize)
            if self.coding != "dense":
                kw["coding"] = self.coding
        return ",".join(f"{k}={v}" for k, v in sorted(kw.items()))

    # -- encode -------------------------------------------------------------
    def encode(self, pkey: int, x: np.ndarray) -> bytes:
        x = np.ascontiguousarray(x, np.float32)
        if not (self.momentum_mu or self.ef):
            return self._encode_raw(pkey, x)
        # One lock across the whole stateful read-correct-write: a
        # set_lr_scale landing between the EF read and the error store
        # would otherwise be silently overwritten by an error computed
        # from the unscaled value.  The codec pipeline routinely encodes
        # DIFFERENT partitions of one tensor concurrently on this object:
        # the stateful paths serialize here (state correctness over
        # encode parallelism), while the stateless _encode_raw path runs
        # unlocked and must touch only per-pkey dict entries (GIL-atomic)
        # — no cross-key shared scratch outside this lock.  Same-key
        # rounds stay ordered: the session submits round r+1's encode
        # only after round r's partition fully completed.
        with self._state_lock:
            if self.comp_id == COMP_ONEBIT and x.size:
                lib = _c_wire()
                if lib is not None:
                    # Fused C path: momentum+EF correction in one pass,
                    # sign-pack + error store in another — same float
                    # ops per element as the numpy chain below, so both
                    # paths stay byte- and EF-state-identical (asserted
                    # by the codec parity test).
                    return self._encode_onebit_fused(lib, pkey, x)
            if self.momentum_mu:
                # m = mu*m + g; g += mu*m (Nesterov) — before EF, matching
                # the reference layering and the JAX NesterovMomentum.
                m = self._mom.get(pkey)
                m = (self.momentum_mu * m + x) if m is not None \
                    and m.size == x.size else x.copy()
                self._mom[pkey] = m
                x = x + self.momentum_mu * m
            if not self.ef:
                return self._encode_raw(pkey, x)
            e = self._err.get(pkey)
            if e is not None and e.size == x.size:
                x = x + e
            blob = self._encode_raw(pkey, x)
            # The dithering encoder hands back its reconstruction directly
            # (the elias decode loop is sequential — don't pay it per
            # push); other formats decode the blob, which doubles as a
            # the-error-matches-the-wire self check.
            recon = self._last_recon
            self._last_recon = None
            if recon is None:
                recon = decode(blob, x.size)
            self._err[pkey] = x - recon
            return blob

    def _encode_onebit_fused(self, lib, pkey: int, x: np.ndarray) -> bytes:
        """C-fused onebit encode with momentum/EF state (caller holds
        _state_lock).  The scale reduction stays numpy: its pairwise
        float32 sum is the byte-parity reference for both paths."""
        n = x.size
        xw = np.array(x, np.float32, copy=True)  # never mutate caller's
        mom = None
        if self.momentum_mu:
            mom = self._mom.get(pkey)
            if mom is None or mom.size != n:
                # First push (or size change): m = mu*0 + x == x, the
                # same value the numpy path's m = x.copy() produces.
                mom = np.zeros(n, np.float32)
            self._mom[pkey] = mom
        err = self._err.get(pkey) if self.ef else None
        if err is not None and err.size != n:
            err = None
        lib.bps_wire_onebit_correct(
            xw.ctypes.data, n,
            mom.ctypes.data if mom is not None else None,
            float(self.momentum_mu or 0.0),
            err.ctypes.data if err is not None else None)
        scale = (np.abs(xw).sum() / max(n, 1)) if self.scaled else 1.0
        bits = np.zeros((n + 7) // 8, np.uint8)
        if self.ef:
            new_err = np.empty(n, np.float32)
            lib.bps_wire_onebit_pack(xw.ctypes.data, n, np.float32(scale),
                                     bits.ctypes.data, new_err.ctypes.data)
            self._err[pkey] = new_err
        else:
            lib.bps_wire_onebit_pack(xw.ctypes.data, n, np.float32(scale),
                                     bits.ctypes.data, None)
        return (struct.pack("<BI", self.comp_id, n)
                + struct.pack("<f", np.float32(scale)) + bits.tobytes())

    def _encode_raw(self, pkey: int, x: np.ndarray) -> bytes:
        n = x.size
        self._last_recon = None
        hdr = struct.pack("<BI", self.comp_id, n)
        if self.comp_id == COMP_ONEBIT:
            scale = (np.abs(x).sum() / max(n, 1)) if self.scaled else 1.0
            signs = x < 0
            bits = _pack_bits(signs)
            if self.ef:
                # Reconstruction directly from the signs — the decoded
                # onebit value is just +-scale, so the EF path never
                # needs to re-decode the blob it just wrote.
                self._last_recon = np.where(
                    signs, np.float32(-scale),
                    np.float32(scale)).astype(np.float32)
            return hdr + struct.pack("<f", np.float32(scale)) + bits.tobytes()
        if self.comp_id == COMP_TOPK:
            k = min(self.k, n)
            idx = np.argpartition(np.abs(x), -k)[-k:].astype(np.int32)
            return (hdr + struct.pack("<I", k) + idx.tobytes()
                    + x[idx].tobytes())
        if self.comp_id == COMP_QBLOCK:
            return self._encode_qblock(hdr, x, n)
        if self.comp_id == COMP_RANDOMK:
            k = min(self.k, n)
            rng = self._rng.get(pkey)
            if rng is None:
                rng = _seed_state(self.seed, self.k)
            rng = _xorshift32(rng)
            self._rng[pkey] = rng
            u = (rng >> np.uint32(8)).astype(np.float32) / np.float32(1 << 24)
            idx = np.minimum((u[:k] * n).astype(np.int32), n - 1)
            return (hdr + struct.pack("<I", k) + idx.tobytes()
                    + x[idx].tobytes())
        # dithering
        s = self.s
        if self.normalize == "max":
            norm = float(np.max(np.abs(x))) if n else 0.0
        else:
            norm = float(np.sqrt(np.sum(x * x)))
        norm = max(norm, float(np.finfo(np.float32).tiny))
        lib = _c_wire()
        if lib is not None and n:
            # C fast path: same float32 quantization arithmetic and PRNG
            # as the numpy code below, asserted byte-identical by
            # tests/test_ps_compression.py.  norm stays Python-computed
            # (numpy's pairwise float32 sum is the l2 parity reference).
            rng = self._rng.get(pkey)
            if rng is None or rng.size < n:
                rng = _seed_state(self.seed, n)
            # The C encoder advances the lanes IN PLACE — hand it a private
            # copy and store that back only on success, so a failed encode
            # (wrote <= 0, cap exhausted) leaves the per-key state
            # untouched and the numpy fallback below continues from
            # unadvanced lanes (byte/PRNG parity with a pure-numpy worker;
            # ADVICE round 5).
            rng = np.array(rng[:n], dtype=np.uint32)
            recon = np.empty(n, np.float32) if self.ef else None
            elias = self.coding == "elias"
            cap = 15 + (4 * n + 64 if elias
                        else (n * _level_bits(s) + 7) // 8 + (n + 7) // 8)
            out = np.empty(cap, np.uint8)
            wrote = lib.bps_wire_encode_dithering(
                x.ctypes.data, n, s,
                1 if self.partition == "natural" else 0,
                1 if elias else 0, float(np.float32(norm)),
                rng.ctypes.data,
                recon.ctypes.data if recon is not None else None,
                out.ctypes.data, cap)
            if wrote > 0:
                self._rng[pkey] = rng
                if recon is not None:
                    self._last_recon = recon
                return out[:wrote].tobytes()
        mag = np.abs(x) / np.float32(norm)
        levels = self._levels()
        j = np.clip(np.searchsorted(levels, mag, side="right") - 1, 0, s - 1)
        lo, hi = levels[j], levels[j + 1]
        with np.errstate(divide="ignore", invalid="ignore"):
            p_up = np.where(hi > lo, (mag - lo) / np.maximum(hi - lo, 1e-30),
                            0.0)
        rng = self._rng.get(pkey)
        if rng is None:
            rng = _seed_state(self.seed, n)
        rng = _xorshift32(rng[:n])
        self._rng[pkey] = rng
        u = (rng >> np.uint32(8)).astype(np.float32) / np.float32(1 << 24)
        level = (j + (u < p_up)).astype(np.uint8)
        signs = x < 0
        if self.ef:
            # EF reconstruction computed here so encode() never needs the
            # (sequential) elias decode loop; skipped entirely without EF
            # (no extra O(n) pass or retained buffer).
            if self.partition == "natural":
                mag = np.where(level == 0, 0.0,
                               2.0 ** (level.astype(np.float32) - s))
            else:
                mag = level.astype(np.float32) / np.float32(s)
            self._last_recon = ((1.0 - 2.0 * signs) * mag
                                * np.float32(norm)).astype(np.float32)
        flags = 1 if self.partition == "natural" else 0
        if self.coding == "elias":
            flags |= 2
            nz = np.flatnonzero(level)
            if nz.size:
                gaps = np.diff(nz, prepend=-1).astype(np.int64)
                gcode, glen = _elias_delta_codes(gaps)
                lcode, llen = _elias_delta_codes(level[nz].astype(np.int64))
                scode = signs[nz].astype(np.uint64)
                slen = np.ones(nz.size, np.int64)
                codes = np.stack([gcode, scode, lcode], 1).ravel()
                lens = np.stack([glen, slen, llen], 1).ravel()
                stream, nbits = _emit_bitstream(codes, lens)
            else:
                stream, nbits = np.zeros(0, np.uint8), 0
            return (hdr + struct.pack("<BBfI", flags, s, np.float32(norm),
                                      nbits) + stream.tobytes())
        return (hdr + struct.pack("<BBf", flags, s, np.float32(norm))
                + _pack_levels(level, s).tobytes()
                + _pack_bits(signs).tobytes())

    def _encode_qblock(self, hdr: bytes, x: np.ndarray, n: int) -> bytes:
        """Blockwise int4/int8 quantization (COMP_QBLOCK).  The C path is
        byte-identical to the numpy fallback below: both compute the
        per-block scale as f32 absmax/qmax, quantize by TRUE f32 division
        then round-half-to-even (np.rint / rintf), and reconstruct as
        q * scale — asserted by tests/test_tuner.py."""
        bits, block = self.qb_bits, self.qb_block
        qmax = (1 << (bits - 1)) - 1
        nb = (n + block - 1) // block
        lib = _c_wire()
        if lib is not None and n:
            cap = 8 + 4 * nb + (n if bits == 8 else (n + 1) // 2)
            out = np.empty(cap, np.uint8)
            recon = np.empty(n, np.float32) if self.ef else None
            wrote = lib.bps_wire_encode_qblock(
                x.ctypes.data, n, bits, block,
                recon.ctypes.data if recon is not None else None,
                out.ctypes.data, cap)
            if wrote > 0:
                if recon is not None:
                    self._last_recon = recon
                return out[:wrote].tobytes()
        xp = np.zeros(nb * block, np.float32)
        xp[:n] = x
        xb = xp.reshape(nb, block)
        amax = np.abs(xb).max(axis=1) if n else np.zeros(nb, np.float32)
        scale = (amax / np.float32(qmax)).astype(np.float32)
        safe = np.where(scale > 0, scale, np.float32(1)).astype(np.float32)
        q = np.clip(np.rint(xb / safe[:, None]), -qmax, qmax)
        q = np.where(scale[:, None] > 0, q, 0).astype(np.int8)
        if self.ef:
            self._last_recon = (q.astype(np.float32)
                                * scale[:, None]).ravel()[:n].astype(
                                    np.float32)
        qflat = q.ravel()[:n]
        if bits == 8:
            body = qflat.tobytes()
        else:
            u = (qflat.astype(np.int16) & 0xF).astype(np.uint8)
            if n % 2:
                u = np.append(u, np.uint8(0))
            body = (u[0::2] | (u[1::2] << 4)).astype(np.uint8).tobytes()
        return (hdr + struct.pack("<BH", bits, block)
                + scale.tobytes() + body)

    def _levels(self) -> np.ndarray:
        s = self.s
        if self.partition == "linear":
            return np.arange(s + 1, dtype=np.float32) / np.float32(s)
        pts = 2.0 ** np.arange(-(s - 1), 1, dtype=np.float32)
        return np.concatenate([np.zeros(1, np.float32), pts])


def decode(data, n: int, out: Optional[np.ndarray] = None) -> np.ndarray:
    """Decode any compressed wire payload to an n-element f32 vector
    (the worker pull-leg decompress for bidirectional compressors).

    ``data`` may be bytes OR any buffer-protocol object (bytearray /
    memoryview) — the receive path hands pooled buffer views straight in,
    with no bytes() snapshot.  ``out``, when given, is a contiguous
    n-element float32 array the decode lands in directly (the handle's
    output sink on the pull path); it is also returned.

    Rides the C decoder from libbyteps_core.so when available (the
    exact routine the server engine runs — the numpy paths below are
    the behavioral reference and the toolchain-less fallback; the
    elias path in particular is ~1000x slower in Python)."""
    comp, wn = struct.unpack_from("<BI", data, 0)
    if wn != n:
        raise ValueError(f"wire n={wn} != expected {n}")
    if out is not None and (out.size != n or out.dtype != np.float32
                            or not out.flags.c_contiguous):
        raise ValueError("decode out= must be a contiguous f32[n] array")
    lib = _c_wire()
    if lib is not None:
        dst = out if out is not None else np.empty(n, np.float32)
        if lib.bps_wire_decode(_c_buf(data), len(data),
                               dst.ctypes.data, n) == 0:
            return dst
        raise ValueError("malformed compressed wire payload (C decoder)")
    res = _decode_py(data, n)
    if out is not None:
        out[:] = res
        return out
    return res


def _c_buf(data):
    """`data` as a ctypes-compatible char buffer WITHOUT copying: bytes
    pass through (c_char_p converts natively); writable buffers
    (bytearray, pooled memoryviews) wrap via from_buffer; anything
    read-only falls back to one snapshot."""
    if isinstance(data, bytes):
        return data
    import ctypes
    try:
        return (ctypes.c_char * len(data)).from_buffer(data)
    except (TypeError, BufferError):
        return bytes(data)


def _decode_py(data: bytes, n: int) -> np.ndarray:
    """numpy reference decoder (kept as the toolchain-less fallback and
    the cross-implementation parity target for tests)."""
    comp, wn = struct.unpack_from("<BI", data, 0)
    if wn != n:
        raise ValueError(f"wire n={wn} != expected {n}")
    body = memoryview(data)[5:]
    if comp == COMP_ONEBIT:
        (scale,) = struct.unpack_from("<f", body, 0)
        bits = _unpack_bits(
            np.frombuffer(body[4:4 + (n + 7) // 8], np.uint8), n)
        return np.where(bits.astype(bool), -scale, scale).astype(np.float32)
    if comp in (COMP_TOPK, COMP_RANDOMK):
        (k,) = struct.unpack_from("<I", body, 0)
        idx = np.frombuffer(body[4:4 + 4 * k], np.int32)
        val = np.frombuffer(body[4 + 4 * k:4 + 8 * k], np.float32)
        out = np.zeros(n, np.float32)
        np.add.at(out, idx, val)
        return out
    if comp == COMP_QBLOCK:
        bits, block = struct.unpack_from("<BH", body, 0)
        if bits not in (4, 8) or block == 0:
            raise ValueError(f"qblock bits={bits} block={block}")
        nb = (n + block - 1) // block
        scales = np.frombuffer(body[3:3 + 4 * nb], np.float32)
        qb = body[3 + 4 * nb:]
        if bits == 8:
            q = np.frombuffer(qb[:n], np.int8).astype(np.float32)
        else:
            u = np.frombuffer(qb[:(n + 1) // 2], np.uint8)
            nib = np.empty(2 * u.size, np.uint8)
            nib[0::2] = u & 0xF
            nib[1::2] = u >> 4
            q = (((nib[:n].astype(np.int16)) ^ 8) - 8).astype(np.float32)
        qp = np.zeros(nb * block, np.float32)
        qp[:n] = q
        return (qp.reshape(nb, block)
                * scales[:, None]).ravel()[:n].astype(np.float32)
    if comp == COMP_DITHERING:
        flags, s, norm = struct.unpack_from("<BBf", body, 0)
        if flags & 2:
            # Sparse elias coding: EliasDelta(gap) · sign · EliasDelta(lvl)
            # per nonzero.  Sequential reference decoder — the C++ server
            # codec is the production path; encode-side EF uses the direct
            # reconstruction and never calls this.
            (nbits,) = struct.unpack_from("<I", body, 6)
            cur = _BitCursor(np.frombuffer(
                body[10:10 + (nbits + 7) // 8], np.uint8), nbits)
            level = np.zeros(n, np.int64)
            signs = np.zeros(n, np.uint8)
            pos = -1
            while cur.left() > 0:
                pos += cur.elias_delta()
                if pos >= n:
                    raise ValueError("elias stream overruns tensor")
                sgn = cur.take()
                lvl = cur.elias_delta()
                if lvl > s:
                    raise ValueError(f"elias level {lvl} > s={s}")
                level[pos] = lvl
                signs[pos] = sgn
        else:
            lvlbytes = (n * _level_bits(s) + 7) // 8
            level = _unpack_levels(
                np.frombuffer(body[6:6 + lvlbytes], np.uint8), n, s)
            signs = _unpack_bits(
                np.frombuffer(body[6 + lvlbytes:6 + lvlbytes + (n + 7) // 8],
                              np.uint8), n)
        if flags & 1:
            mag = np.where(level == 0, 0.0,
                           2.0 ** (level.astype(np.float32) - s))
        else:
            mag = level.astype(np.float32) / np.float32(s)
        sign = 1.0 - 2.0 * signs.astype(np.float32)
        return (sign * mag * norm).astype(np.float32)
    raise ValueError(f"unknown comp_id {comp}")


# ---------------------------------------------------------------------------
# Row-sparse embedding wire format (WireDtype kSparseRows / kSparseRead).
#
# Block header, little-endian, 16 bytes (C++ SparseHdr):
#     u32 nrows | u32 width | u8 codec | u8 pad | u16 pad | u32 idx_bytes
# codec 0 = raw u32 LE indices; codec 1 = elias-delta over the gaps of
# the SORTED UNIQUE index list (first code = idx[0]+1, then
# idx[i]-idx[i-1]; every code >= 1), bit-matched to the dithering
# codec's elias stream (LSB-first within bytes, MSB-of-code-first).
#
# Push payload   = header | index stream | nrows*width f32 rows (in
#                  index order).
# Pull request   = header | index stream (width pinned so the server can
#                  cross-check the declared table).
# Pull response  = u64 param_version | nrows*width f32 rows in REQUEST
#                  order.
# ---------------------------------------------------------------------------

SPARSE_HDR = struct.Struct("<IIBBHI")
SPARSE_CODEC_RAW = 0
SPARSE_CODEC_ELIAS = 1


def encode_sparse_indices(idx: np.ndarray) -> Tuple[int, bytes]:
    """Encode a SORTED UNIQUE u32 index vector -> (codec, bytes).

    Picks elias-delta when it is strictly smaller than raw u32 — a
    deterministic rule, so identical index sets always produce identical
    wire bytes (the byte-identity tests depend on it)."""
    idx = np.ascontiguousarray(idx, dtype=np.uint32)
    if idx.size == 0:
        return SPARSE_CODEC_RAW, b""
    gaps = np.empty(idx.size, np.int64)
    gaps[0] = int(idx[0]) + 1
    gaps[1:] = np.diff(idx.astype(np.int64))
    if np.any(gaps[1:] <= 0):
        raise ValueError("sparse indices must be sorted and unique")
    codes, lengths = _elias_delta_codes(gaps)
    stream, _ = _emit_bitstream(codes, lengths)
    if stream.nbytes < idx.nbytes:
        return SPARSE_CODEC_ELIAS, stream.tobytes()
    return SPARSE_CODEC_RAW, idx.tobytes()


def decode_sparse_indices(codec: int, data: bytes, nrows: int) -> np.ndarray:
    """Inverse of encode_sparse_indices (reference decoder; the C++
    server's DecodeSparseIndices is the production path)."""
    if codec == SPARSE_CODEC_RAW:
        if len(data) < 4 * nrows:
            raise ValueError("truncated raw index stream")
        return np.frombuffer(data[:4 * nrows], np.uint32).copy()
    if codec != SPARSE_CODEC_ELIAS:
        raise ValueError(f"unknown sparse index codec {codec}")
    cur = _BitCursor(np.frombuffer(data, np.uint8), len(data) * 8)
    out = np.empty(nrows, np.uint32)
    pos = -1
    for i in range(nrows):
        pos += cur.elias_delta()
        out[i] = pos
    return out


def encode_sparse_block(idx: np.ndarray, rows: Optional[np.ndarray],
                        width: int) -> bytes:
    """Header + index stream (+ f32 rows when `rows` is given — the push
    form; None gives the pull-request form)."""
    idx = np.ascontiguousarray(idx, dtype=np.uint32)
    codec, istream = encode_sparse_indices(idx)
    hdr = SPARSE_HDR.pack(idx.size, width, codec, 0, 0, len(istream))
    if rows is None:
        return hdr + istream
    rows = np.ascontiguousarray(rows, dtype=np.float32)
    if rows.size != idx.size * width:
        raise ValueError(
            f"rows {rows.size} != nrows {idx.size} * width {width}")
    return hdr + istream + rows.tobytes()


def decode_sparse_block(payload) -> Tuple[np.ndarray, Optional[np.ndarray]]:
    """Inverse of encode_sparse_block: -> (indices, rows-or-None)."""
    buf = bytes(payload)
    nrows, width, codec, _, _, ibytes = SPARSE_HDR.unpack_from(buf, 0)
    idx = decode_sparse_indices(codec, buf[16:16 + ibytes], nrows)
    body = buf[16 + ibytes:]
    if not body:
        return idx, None
    want = nrows * width * 4
    if len(body) < want:
        raise ValueError("truncated sparse row payload")
    rows = np.frombuffer(body[:want], np.float32).reshape(nrows, width)
    return idx, rows.copy()


def decode_sparse_response(payload, nrows: int,
                           width: int) -> Tuple[int, np.ndarray]:
    """Pull/read response -> (param_version, rows [nrows, width] f32)."""
    buf = memoryview(payload)
    if len(buf) < 8 + nrows * width * 4:
        raise ValueError(
            f"sparse response {len(buf)}B < {8 + nrows * width * 4}B "
            f"({nrows} rows x {width})")
    (version,) = struct.unpack_from("<Q", buf, 0)
    rows = np.frombuffer(buf[8:8 + nrows * width * 4],
                         np.float32).reshape(nrows, width).copy()
    return version, rows
