"""PS server tier (parity mode).

`python -m byteps_tpu.server` starts the native KV server, mirroring the
reference's `import byteps.server` entry that dlopens the C++ lib and calls
`byteps_server()` (reference: byteps/server/__init__.py:21-27,
server.cc:450-523).  Configuration comes from the same env vars the
reference uses (DMLC_PS_ROOT_PORT, DMLC_NUM_WORKER,
BYTEPS_SERVER_ENGINE_THREAD, BYTEPS_SERVER_ENABLE_SCHEDULE,
BYTEPS_ENABLE_ASYNC — reference: server.cc:416-448).
"""

from __future__ import annotations

import ctypes
import os


def serve(port: int | None = None, num_workers: int | None = None,
          engine_threads: int | None = None, schedule: bool | None = None,
          async_mode: bool | None = None) -> int:
    """Run the native PS server (blocking). Returns its exit code —
    EXCEPT under a sanitizer (BYTEPS_TPU_TSAN=1 / BYTEPS_TPU_ASAN=1),
    where this call never returns: the server runs as a standalone
    sanitized binary (sanitizer runtimes cannot be dlopen'd into an
    interpreter) and os.execv REPLACES the calling process with it, so
    the binary's exit code becomes the process's.  Don't call the
    sanitized path from a process that has work after serve().
    """
    from ..core import build
    from ..common.config import get_config
    cfg = get_config(refresh=True)
    # Single-host port convention matches PSSession.from_config: server i
    # listens on scheduler_port + 1 + i (the scheduler port itself is
    # reserved for the jax coordinator).  DMLC_SERVER_ID selects i.
    server_id = int(os.environ.get("DMLC_SERVER_ID", "0"))
    default_port = cfg.scheduler_port + 1 + server_id
    args = (
        int(port if port is not None else default_port),
        int(num_workers if num_workers is not None else cfg.num_worker),
        int(engine_threads if engine_threads is not None
            else cfg.server_engine_threads),
        int(schedule if schedule is not None else cfg.server_enable_schedule),
        int(async_mode if async_mode is not None else cfg.enable_async),
    )
    if build.sanitized():
        # exec, don't spawn: a subprocess.call child would survive as an
        # orphan when the supervising python gets SIGTERM (holding the
        # parent's stderr pipe open — observed as a communicate() hang in
        # the debug-tracing test), and signals wouldn't reach the server.
        # The sanitized binary replaces this process; its exit code is the
        # process exit code.
        exe = build.build_server_exe()
        os.execv(exe, [exe] + [str(a) for a in args])
    lib = ctypes.CDLL(build.build())
    lib.bps_ps_server_run.argtypes = [ctypes.c_int] * 5
    lib.bps_ps_server_run.restype = ctypes.c_int
    return lib.bps_ps_server_run(*args)
