"""Worker-side codec pipeline engine.

The reference runs COMPRESS and DECOMPRESS as dedicated pipeline loop
threads, so codec work overlaps wire transfer instead of serializing on
the caller or receiver threads (reference: core_loops.cc COMPRESS /
DECOMPRESS stages of the 13-loop state machine).  This is the TPU-host
analog: a small priority thread pool shared by both directions.

  - ENCODE jobs are drained in (priority desc, key asc) order — the same
    control law as the dispatcher's ScheduledQueue
    (scheduled_queue.cc:26-46) — so the encoder works *ahead of* the
    dispatcher: while partition k's bytes are on the wire, partition k+1
    is being compressed.
  - DECODE jobs carry the partition's scheduling priority too, so a
    high-priority tensor's pull leg is decoded before a backlog of
    low-priority ones.

Jobs are plain callables and must do their own error containment (the
session's jobs resolve the partition's handle with the exception); the
pool's catch-all only guards against a job that leaks — a dead codec
thread would silently wedge every waiter behind it.
"""

from __future__ import annotations

import heapq
import threading
import time
from typing import Callable, List

from ..common.logging import get_logger


class CompressionPool:
    """Priority thread pool for wire encode/decode jobs.

    `threads == 0` is the inline fallback: callers must not construct a
    pool at all (the session keeps the pre-pipeline inline paths); this
    class always owns at least one thread.
    """

    # Canonical stats schema — the single source for the all-zero shape
    # returned by PSSession.codec_stats / bps.get_codec_stats when no
    # pool exists, so the three surfaces can never drift apart.
    ZERO_STATS = {"threads": 0, "pending": 0, "encoded_parts": 0,
                  "decoded_parts": 0, "encode_busy_us": 0,
                  "decode_busy_us": 0}

    def __init__(self, threads: int, name: str = "bps-ps-codec"):
        if threads < 1:
            raise ValueError("CompressionPool needs >= 1 thread; "
                             "use threads=0 at the session level for the "
                             "inline fallback")
        self._cv = threading.Condition()
        self._heap: list = []    # (-priority, key, seq, job)
        self._seq = 0            # FIFO tiebreak for equal (priority, key)
        self._closed = False
        # Telemetry counters (read via stats(); exposed through
        # bps.get_codec_stats for tooling like tools/wire_bench.py).
        self._counts = {"ENCODE": 0, "DECODE": 0}
        self._busy_us = {"ENCODE": 0, "DECODE": 0}
        # Registry histograms for per-job codec latency (the busy-time
        # counters above only expose totals; operators alerting on a codec
        # regression need the distribution).  Resolved once; observe() is
        # lock-free.
        from ..common import telemetry as _tm
        reg = _tm.get_registry()
        self._m_lat = {
            "ENCODE": reg.histogram(
                "bps_codec_encode_seconds",
                help="per-partition wire-compressor encode latency"),
            "DECODE": reg.histogram(
                "bps_codec_decode_seconds",
                help="per-partition wire-compressor decode latency"),
        }
        self.num_threads = threads
        self._name = name
        self._spawned = threads   # lifetime thread counter (names only)
        self._retire = 0         # threads asked to exit at their next pick
        self._threads: List[threading.Thread] = [
            threading.Thread(target=self._loop, daemon=True,
                             name=f"{name}-{i}")
            for i in range(threads)]
        for t in self._threads:
            t.start()

    def resize(self, threads: int) -> int:
        """Grow/shrink the pool to `threads` workers WITHOUT dropping
        staged work — the COMPRESS_THREADS knob's actuation point.

        Growing starts fresh threads immediately.  Shrinking marks the
        surplus for retirement: each retiring thread exits at its next
        queue pick, never mid-job, and queued jobs stay in the heap for
        the survivors — so a switch can never lose an encode (whose
        partition's ready event the dispatcher waits on) or a decode
        (whose handle nothing else would resolve).  Clamped to >= 1: the
        pool always owns a thread (0 <-> N is a launch-only transition,
        documented in docs/performance.md "Knob plane").  Returns the
        applied size."""
        threads = max(1, int(threads))
        with self._cv:
            if self._closed:
                return self.num_threads
            # Outstanding retirements still count against the live total:
            # resize(1) -> resize(4) on a pool that hasn't drained its
            # retiring threads yet must only top up the difference.
            live = len([t for t in self._threads if t.is_alive()]) \
                - self._retire
            if threads > live:
                for _ in range(threads - live):
                    t = threading.Thread(
                        target=self._loop, daemon=True,
                        name=f"{self._name}-{self._spawned}")
                    self._spawned += 1
                    self._threads.append(t)
                    t.start()
            elif threads < live:
                self._retire += live - threads
                self._cv.notify_all()
            self.num_threads = threads
        return threads

    def submit(self, priority: int, key: int, job: Callable[[], None]) -> None:
        """Queue `job`; higher priority first, then ascending key, then
        submission order."""
        with self._cv:
            if self._closed:
                raise RuntimeError("CompressionPool closed")
            self._seq += 1
            heapq.heappush(self._heap, (-priority, key, self._seq, job))
            self._cv.notify()

    def record(self, stage: str, dur_us: int) -> None:
        """Count one finished codec job.  Only pool-owning sessions count
        anything: with compress_threads=0 there is no pool and codec_stats
        stays all-zero — zeros mean "nothing measured", not "no codec
        work" (inline mode does its codec work uncounted on the
        caller/receiver threads).  The receiver-thread fallback decode
        during shutdown is the one non-pool-thread path that records."""
        m = self._m_lat.get(stage)
        if m is not None:
            m.observe(max(0, int(dur_us)) / 1e6)
        with self._cv:
            self._counts[stage] = self._counts.get(stage, 0) + 1
            self._busy_us[stage] = self._busy_us.get(stage, 0) + max(
                0, int(dur_us))

    def stats(self) -> dict:
        with self._cv:
            s = dict(self.ZERO_STATS)
            s.update(
                threads=self.num_threads,
                pending=len(self._heap),
                encoded_parts=self._counts.get("ENCODE", 0),
                decoded_parts=self._counts.get("DECODE", 0),
                encode_busy_us=self._busy_us.get("ENCODE", 0),
                decode_busy_us=self._busy_us.get("DECODE", 0),
            )
            return s

    def _loop(self) -> None:
        while True:
            with self._cv:
                while (not self._heap and not self._closed
                       and not self._retire):
                    self._cv.wait()
                if self._retire:
                    # A resize() shrink claimed this thread: exit between
                    # jobs.  Queued work stays in the heap for the
                    # survivors — nothing staged is ever dropped.
                    self._retire -= 1
                    try:
                        self._threads.remove(threading.current_thread())
                    except ValueError:
                        pass
                    return
                if not self._heap:          # closed and drained
                    return
                _, _, _, job = heapq.heappop(self._heap)
            try:
                job()
            except Exception:   # pragma: no cover - jobs contain their own
                get_logger().exception("codec pipeline job failed")

    def close(self) -> None:
        """Drain queued jobs, then stop the threads.

        Draining (not dropping) matters: queued DECODE jobs hold pull
        payloads whose handles nothing else will ever resolve, and queued
        ENCODE jobs must still set their partition's ready event or the
        dispatcher would wait on it forever during shutdown.
        """
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        for t in list(self._threads):
            t.join(timeout=10)


class HealthMonitor:
    """Gradient value-health sampler (``BYTEPS_TPU_HEALTH_SAMPLE_ROUNDS``
    > 0; docs/monitoring.md "Auditing & postmortem").

    The time-domain planes (metrics/traces) say nothing about the
    VALUES riding the wire: an fp16 overflow turning a codec's output
    into a NaN storm, or an error-feedback residual growing without
    bound, is invisible until the loss curve goes sideways hours later.
    This monitor samples every Nth round per key on the push path (the
    staged gradient, before the wire) and the pull path (the landed
    sum), exporting ``bps_grad_*`` gauges through the PR 4 registry and
    firing a structured ERROR — key, round, worker, membership/ring
    epoch — the moment a non-finite value appears.

    The sampling pass is O(n) numpy over the staged buffer; push-side
    samples run on the codec pool when the session has one, so the
    caller thread never pays it.  ``sample_rounds`` gates the cadence —
    with the knob at 0 the session never constructs a monitor and the
    hot path carries zero new work.
    """

    def __init__(self, sample_rounds: int, context=None):
        import numpy as _np  # noqa: F401  (fail construction early)
        self.sample_rounds = max(1, int(sample_rounds))
        self._context = context          # () -> {"worker", "ring_epoch"}
        self._lock = threading.Lock()
        self._snap: dict = {}            # label -> last sample record
        self.nonfinite_total = 0
        from ..common import telemetry as _tm
        self._reg = _tm.get_registry()
        self._m_nonfinite = self._reg.counter(
            "bps_grad_nonfinite_total",
            help="sampled tensors containing NaN/Inf values")

    def _ctx(self) -> dict:
        try:
            return dict(self._context()) if self._context else {}
        except Exception:
            return {}

    def sample_push(self, label: str, arr, rnd: int,
                    pool: "CompressionPool" = None, comp=None) -> bool:
        """Maybe-sample one staged (push-side) tensor; returns True when
        round ``rnd`` (the key's actual sync round — so push and pull
        samples land on the same rounds and survive a failover rebase)
        was due.  The numpy pass runs on ``pool`` when given, over a
        SNAPSHOT of the buffer: the caller's zero-copy no-mutate
        contract ends when the handle resolves, which does not wait for
        a deferred observer job — sampling the live buffer late would
        attribute round N+1's values (and NaNs) to round N."""
        if rnd % self.sample_rounds:
            return False
        if pool is not None:
            import numpy as np
            snap = np.array(arr, copy=True)
            try:
                pool.submit(0, 0, lambda: self._compute(
                    label, snap, "push", rnd, comp))
                return True
            except RuntimeError:
                pass                     # pool closing: sample inline
        self._compute(label, arr, "push", rnd, comp)
        return True

    def pull_due(self, rnd: int) -> bool:
        """True when round ``rnd`` is a sampled round — the session uses
        this at pull-ISSUE time to skip the zero-copy sink for sampled
        rounds, so the check below runs on a codec-pool thread over the
        pooled buffer instead of stalling the receiver thread."""
        return rnd % self.sample_rounds == 0

    def check_pull(self, part_label: str, rnd: int, arr,
                   worker: int = 0) -> None:
        """Maybe-check one landed (pull-side) partition for non-finite
        values — the sum a NaN storm on ANY worker poisons.  Gated by
        the round id so every worker samples the same rounds."""
        if not self.pull_due(rnd):
            return
        import numpy as np
        a = np.asarray(arr)
        nonfinite = int(a.size - np.isfinite(a).sum())
        if nonfinite:
            label = part_label.rsplit(".part", 1)[0]
            self._flag_nonfinite(label, "pull", rnd, nonfinite, a.size)

    # -- internals ----------------------------------------------------------
    def _compute(self, label: str, arr, direction: str, rnd: int,
                 comp=None) -> None:
        import numpy as np
        try:
            a = np.asarray(arr, dtype=np.float32).ravel()
            finite_mask = np.isfinite(a)
            n_bad = int(a.size - finite_mask.sum())
            vals = a if n_bad == 0 else a[finite_mask]
            norm = float(np.sqrt(float(np.dot(vals, vals)))) \
                if vals.size else 0.0
            absmax = float(np.max(np.abs(vals))) if vals.size else 0.0
            ef = None
            if comp is not None and hasattr(comp, "ef_residual_norm"):
                ef = float(comp.ef_residual_norm())
            rec = {"direction": direction, "round": int(rnd),
                   "norm": norm, "absmax": absmax, "nonfinite": n_bad,
                   "size": int(a.size), "ts": time.time()}
            lbl = {"key": label}
            self._reg.gauge(
                "bps_grad_norm", labels=lbl,
                help="l2 norm of the last sampled gradient "
                     "(finite values)").set(norm)
            self._reg.gauge(
                "bps_grad_absmax", labels=lbl,
                help="largest |value| in the last sampled gradient "
                     "(finite values)").set(absmax)
            self._reg.gauge(
                "bps_grad_nonfinite", labels=lbl,
                help="NaN/Inf count in the last sampled gradient"
                ).set(n_bad)
            if ef is not None:
                rec["ef_residual_norm"] = ef
                self._reg.gauge(
                    "bps_grad_ef_residual_norm", labels=lbl,
                    help="l2 norm of the worker-side error-feedback "
                         "residual carried for this key").set(ef)
            with self._lock:
                self._snap[label] = rec
            if n_bad:
                self._flag_nonfinite(label, direction, rnd, n_bad,
                                     int(a.size))
        except Exception:
            get_logger().exception("gradient-health sample failed")

    def _flag_nonfinite(self, label: str, direction: str, rnd: int,
                        n_bad: int, size: int) -> None:
        ctx = self._ctx()
        with self._lock:
            self.nonfinite_total += 1
            rec = self._snap.setdefault(label, {})
            rec["nonfinite"] = n_bad
            rec["nonfinite_round"] = int(rnd)
        self._m_nonfinite.inc()
        get_logger().error(
            "GRADIENT HEALTH: non-finite values in %s tensor %r round %d "
            "(%d of %d elements NaN/Inf; worker %s, membership epoch %s, "
            "ring epoch %s) — overflowing codec, fp16 blowup, or a "
            "poisoned sum from a peer; see docs/troubleshooting.md "
            "\"My loss diverged\"",
            direction, label, rnd, n_bad, size,
            ctx.get("worker", "?"), ctx.get("epoch", "?"),
            ctx.get("ring_epoch", "?"))
        from ..common import flightrec as _fr
        _fr.record("nonfinite", key=label, direction=direction,
                   round=int(rnd), count=n_bad, size=size, **ctx)

    def snapshot(self) -> dict:
        """Last sample per key + the running non-finite total — the
        ``bps.get_health()`` payload."""
        with self._lock:
            return {"sample_rounds": self.sample_rounds,
                    "nonfinite_total": self.nonfinite_total,
                    "keys": {k: dict(v) for k, v in self._snap.items()}}
