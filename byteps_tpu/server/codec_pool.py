"""Worker-side codec pipeline engine.

The reference runs COMPRESS and DECOMPRESS as dedicated pipeline loop
threads, so codec work overlaps wire transfer instead of serializing on
the caller or receiver threads (reference: core_loops.cc COMPRESS /
DECOMPRESS stages of the 13-loop state machine).  This is the TPU-host
analog: a small priority thread pool shared by both directions.

  - ENCODE jobs are drained in (priority desc, key asc) order — the same
    control law as the dispatcher's ScheduledQueue
    (scheduled_queue.cc:26-46) — so the encoder works *ahead of* the
    dispatcher: while partition k's bytes are on the wire, partition k+1
    is being compressed.
  - DECODE jobs carry the partition's scheduling priority too, so a
    high-priority tensor's pull leg is decoded before a backlog of
    low-priority ones.

Jobs are plain callables and must do their own error containment (the
session's jobs resolve the partition's handle with the exception); the
pool's catch-all only guards against a job that leaks — a dead codec
thread would silently wedge every waiter behind it.
"""

from __future__ import annotations

import heapq
import threading
from typing import Callable, List

from ..common.logging import get_logger


class CompressionPool:
    """Priority thread pool for wire encode/decode jobs.

    `threads == 0` is the inline fallback: callers must not construct a
    pool at all (the session keeps the pre-pipeline inline paths); this
    class always owns at least one thread.
    """

    # Canonical stats schema — the single source for the all-zero shape
    # returned by PSSession.codec_stats / bps.get_codec_stats when no
    # pool exists, so the three surfaces can never drift apart.
    ZERO_STATS = {"threads": 0, "pending": 0, "encoded_parts": 0,
                  "decoded_parts": 0, "encode_busy_us": 0,
                  "decode_busy_us": 0}

    def __init__(self, threads: int, name: str = "bps-ps-codec"):
        if threads < 1:
            raise ValueError("CompressionPool needs >= 1 thread; "
                             "use threads=0 at the session level for the "
                             "inline fallback")
        self._cv = threading.Condition()
        self._heap: list = []    # (-priority, key, seq, job)
        self._seq = 0            # FIFO tiebreak for equal (priority, key)
        self._closed = False
        # Telemetry counters (read via stats(); exposed through
        # bps.get_codec_stats for tooling like tools/wire_bench.py).
        self._counts = {"ENCODE": 0, "DECODE": 0}
        self._busy_us = {"ENCODE": 0, "DECODE": 0}
        # Registry histograms for per-job codec latency (the busy-time
        # counters above only expose totals; operators alerting on a codec
        # regression need the distribution).  Resolved once; observe() is
        # lock-free.
        from ..common import telemetry as _tm
        reg = _tm.get_registry()
        self._m_lat = {
            "ENCODE": reg.histogram(
                "bps_codec_encode_seconds",
                help="per-partition wire-compressor encode latency"),
            "DECODE": reg.histogram(
                "bps_codec_decode_seconds",
                help="per-partition wire-compressor decode latency"),
        }
        self.num_threads = threads
        self._threads: List[threading.Thread] = [
            threading.Thread(target=self._loop, daemon=True,
                             name=f"{name}-{i}")
            for i in range(threads)]
        for t in self._threads:
            t.start()

    def submit(self, priority: int, key: int, job: Callable[[], None]) -> None:
        """Queue `job`; higher priority first, then ascending key, then
        submission order."""
        with self._cv:
            if self._closed:
                raise RuntimeError("CompressionPool closed")
            self._seq += 1
            heapq.heappush(self._heap, (-priority, key, self._seq, job))
            self._cv.notify()

    def record(self, stage: str, dur_us: int) -> None:
        """Count one finished codec job.  Only pool-owning sessions count
        anything: with compress_threads=0 there is no pool and codec_stats
        stays all-zero — zeros mean "nothing measured", not "no codec
        work" (inline mode does its codec work uncounted on the
        caller/receiver threads).  The receiver-thread fallback decode
        during shutdown is the one non-pool-thread path that records."""
        m = self._m_lat.get(stage)
        if m is not None:
            m.observe(max(0, int(dur_us)) / 1e6)
        with self._cv:
            self._counts[stage] = self._counts.get(stage, 0) + 1
            self._busy_us[stage] = self._busy_us.get(stage, 0) + max(
                0, int(dur_us))

    def stats(self) -> dict:
        with self._cv:
            s = dict(self.ZERO_STATS)
            s.update(
                threads=self.num_threads,
                pending=len(self._heap),
                encoded_parts=self._counts.get("ENCODE", 0),
                decoded_parts=self._counts.get("DECODE", 0),
                encode_busy_us=self._busy_us.get("ENCODE", 0),
                decode_busy_us=self._busy_us.get("DECODE", 0),
            )
            return s

    def _loop(self) -> None:
        while True:
            with self._cv:
                while not self._heap and not self._closed:
                    self._cv.wait()
                if not self._heap:          # closed and drained
                    return
                _, _, _, job = heapq.heappop(self._heap)
            try:
                job()
            except Exception:   # pragma: no cover - jobs contain their own
                get_logger().exception("codec pipeline job failed")

    def close(self) -> None:
        """Drain queued jobs, then stop the threads.

        Draining (not dropping) matters: queued DECODE jobs hold pull
        payloads whose handles nothing else will ever resolve, and queued
        ENCODE jobs must still set their partition's ready event or the
        dispatcher would wait on it forever during shutdown.
        """
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        for t in self._threads:
            t.join(timeout=10)
