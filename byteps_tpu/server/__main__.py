import sys

from . import serve

if __name__ == "__main__":
    sys.exit(serve())
