"""PS client session: the worker side of PS-parity mode.

The reference worker talks to servers through ps-lite ZPush/ZPull with
per-partition keys spread over servers by hash
(reference: core_loops.cc:536-616, global.cc:643-692).  This is the
TPU-host redesign of that data path:

  - every tensor is split into <= BYTEPS_PARTITION_BYTES partitions with
    per-partition keys `declared_key << 16 | part_idx`
    (reference: operations.cc:140-180, 301-311),
  - each partition key is placed on a server by the configured hash with
    accumulated-load logging (reference: global.cc:643-692),
  - partition pushes are issued by a dispatcher thread in
    (priority desc, key asc) order through the native priority
    ScheduledQueue, gated by a credit of
    BYTEPS_SCHEDULING_CREDIT x BYTEPS_PARTITION_BYTES bytes in flight;
    completions return credit (reference: scheduled_queue.cc:26-46,136-139),
  - each connection multiplexes outstanding requests by req_id, the
    redesign of ps-lite's completion callbacks (core_loops.cc:536-616),
    so per-partition pushes/pulls to one server pipeline instead of
    serializing on a blocking round-trip,
  - codec work rides a CompressionPool (BYTEPS_TPU_COMPRESS_THREADS,
    the redesign of the reference's COMPRESS/DECOMPRESS pipeline loop
    threads, core_loops.cc): partitions are encoded ahead of the
    dispatcher in the same (priority desc, key asc) order, so the wire
    send of partition k overlaps the encode of k+1, and compressed pull
    payloads are decoded off the receiver thread, so one slow decode
    never stalls other partitions' responses on the same socket,
  - the transport is fault-tolerant when BYTEPS_TPU_RECONNECT_ATTEMPTS > 0
    (default 0 = fail-fast): a dropped connection parks its in-flight
    partitions, re-dials under bounded exponential backoff with jitter,
    re-runs the HELLO mode check and the idempotent CMD_INIT re-declare
    (re-seeding rounds from server `completed_round` state so a replayed
    push can never double-count and a pull can never return a stale
    round), then replays parked pushes through the dispatcher and
    re-issues parked pull legs, in (priority desc, key asc) order.  A
    round-stall watchdog (BYTEPS_TPU_STALL_TIMEOUT_S) dumps a diagnostic
    snapshot and fails stuck handles loudly — the worker-side analog of
    server.cc's ORDERING INVARIANT guard.  bps.get_transport_stats()
    exposes the counters,
  - the receive path is pooled and zero-copy: raw pull payloads land
    directly in the handle's output buffer (the per-request sink),
    everything else rides a size-classed pooled-buffer ring
    (_RecvBufPool) instead of a fresh allocation per frame, and
    compressed pulls decode straight from the pooled view into the
    output buffer,
  - partitions spread over BYTEPS_TPU_WIRE_CONNS data lanes per server
    by BYTE CREDIT at dispatch time (least-outstanding-bytes wins, ties
    to least-used) — the multi-lane analog of ps-lite's per-connection
    threads, minus the head-of-line blocking a fixed stripe invites,
  - a colocated server is reached over AF_UNIX when
    BYTEPS_TPU_SERVER_UDS is set ("<path>.<port>", bit-identical
    protocol, transparent TCP fallback), and BYTEPS_TPU_SOCK_BUF_KB
    sizes both directions' socket buffers.
"""

from __future__ import annotations

import os
import random
import socket
import struct
import threading
import time
from collections import OrderedDict, deque
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..common import flightrec as _flightrec
from ..common import signals as _signals
from ..common.config import Config
from ..common.logging import get_logger
from ..common.ring import DEFAULT_VNODES, RingTable
from ..core.native import get_core
from .codec_pool import CompressionPool

_REQ = struct.Struct("<BBHIIQQ")   # cmd dtype flags req_id worker_id key len
_RESP = struct.Struct("<BIQQ")     # status req_id key len

CMD_HELLO, CMD_INIT, CMD_PUSH, CMD_PULL, CMD_BARRIER, CMD_SHUTDOWN, \
    CMD_PING, CMD_LR_SCALE, CMD_STATS, CMD_TRACE, CMD_LEAVE, \
    CMD_MEMBERS, CMD_RING, CMD_RING_SET, CMD_DRAIN, CMD_MIGRATE, \
    CMD_AUDIT, CMD_CODEC, CMD_OPT, CMD_KNOB = range(20)

# Fleet observability plane (server.cc kWindow / kFleet).  Deliberately
# NOT part of the range(20) enum above: wire value 20 is kRepl, the
# peer-only chain-replication command no client ever sends — skipping it
# keeps the client constants exactly aligned with the server's Cmd
# values.  CMD_WINDOW publishes one worker's window summary (key =
# window index); CMD_FLEET reads the merged per-worker rings and doubles
# as the bootstrap probe (the CMD_AUDIT downgrade law).
CMD_WINDOW, CMD_FLEET = 21, 22

# Response status bytes (server.cc Status).  MOVED carries the server's
# current ring table as JSON: the addressed server is not (or no longer)
# the consistent-hash owner of the frame's key — re-plan and re-route.
# Emitted only once the ring epoch has advanced, so a fixed-topology job
# never sees it.  CODEC_STALE carries the key's authoritative codec doc:
# this push's wire format does not match the codec-table entry for the
# round currently merging (the sender missed — or jumped ahead of — a
# CMD_CODEC renegotiation); the session re-encodes the SAME gradient
# with the right codec and replays.  Emitted only once the key's codec
# epoch has advanced, so a job that never renegotiates never sees it.
# KNOB_STALE carries the server's GLOBAL knob doc (the CMD_KNOB table):
# this push came from a worker that has not acked the newest knob epoch
# while the key's round is already at/past the switch boundary — the
# session adopts the table, re-applies its half of the switch (fusion
# re-plan / pool resize / lane resize), ACKs, and replays.  Emitted only
# once the knob epoch has advanced, so a job that never renegotiates a
# knob never sees it.
STATUS_OK, STATUS_ERROR, STATUS_MOVED, STATUS_CODEC_STALE, \
    STATUS_KNOB_STALE = 0, 1, 2, 3, 4

# dtype byte on the wire (server.cc WireDtype)
DT_F32, DT_RAW, DT_COMPRESSED, DT_SEED = 0, 1, 2, 3
# Row-sparse embedding plane (server.cc kSparseRows / kSparseRead):
# DT_SPARSE rides the round plane — a push merges (indices, rows) into
# the key's embed_merge and counts toward round completion; a pull with
# it parks until the round publishes.  DT_SPARSE_READ is the ungated
# inference read: served immediately from the last published table,
# never touching round state — what pull-only sessions use.
DT_SPARSE, DT_SPARSE_READ = 4, 5

# HELLO flags bit 0 (server.cc kHello observer gate): a pull-only
# session introduces itself WITHOUT being admitted to the worker
# membership, so a reader can never stall round completion.
HELLO_FLAG_OBSERVER = 1

# Request dtype marker on PULL frames (server.cc kAuditPullMark): "append
# the 24-byte audit trailer to the response payload".  Sent ONLY once the
# session has probed an audit-armed server over CMD_AUDIT (see
# _audit_bootstrap) — an unarmed run's wire never carries it, and an
# unarmed/old server ignores the pull dtype entirely, so a mixed
# deployment degrades to "no trailer", never to corruption.
DT_AUDIT_PULL = 0xAD

# Audited-pull trailer (server.cc AuditTrailer, little-endian):
# u32 digest | u64 published round | u64 membership epoch at publish |
# u32 contributor count (0 = no digest recorded, skip verification).
_AUDIT_TRAILER = struct.Struct("<IQQI")

# Digest chunk size — must match server.cc audit::kChunk.
_AUDIT_CHUNK = 65536

_AUDIT_C = False    # False = untried, None = unavailable, else the fn


def _audit_c_digest():
    """ctypes handle to the C digest in libbyteps_core.so (the exact
    routine the server's PublishRound runs), or None — the zlib
    fallback below is bit-identical, just ~2x slower."""
    global _AUDIT_C
    if _AUDIT_C is False:
        try:
            import ctypes

            from ..core import native
            lib = getattr(native.get_core(), "_lib", None)
            if lib is None:
                _AUDIT_C = None
            else:
                lib.bps_audit_digest.argtypes = [ctypes.c_char_p,
                                                 ctypes.c_uint64]
                lib.bps_audit_digest.restype = ctypes.c_uint32
                _AUDIT_C = lib.bps_audit_digest
        except Exception:   # pragma: no cover - defensive
            _AUDIT_C = None
    return _AUDIT_C


def audit_digest(buf) -> int:
    """Order-independent digest of a published buffer: CRC-32 (the zlib
    polynomial) per 64 KiB chunk, summed mod 2^32 across chunks.
    Bit-identical on both sides — the server's ``audit::Digest``
    (core/server.cc) is the C implementation, reachable here through
    the ``bps_audit_digest`` ctypes export (with a pure
    ``zlib.crc32``-chunked fallback for toolchain-less installs; parity
    asserted by tests/test_audit.py) — so a worker re-digesting the
    bytes it pulled is directly comparable against what the server
    recorded at publish: the single-bit-corruption / divergent-sum
    detector."""
    fn = _audit_c_digest()
    if fn is not None:
        from .wire import _c_buf
        return int(fn(_c_buf(buf), len(buf)))
    import zlib
    mv = memoryview(buf)
    s = 0
    for off in range(0, len(mv), _AUDIT_CHUNK):
        s = (s + zlib.crc32(mv[off:off + _AUDIT_CHUNK])) & 0xFFFFFFFF
    return s

# Header `flags` bit 15 (server.cc kFlagTraced): this frame is inside the
# worker's trace window.  PUSH/PULL frames now carry their round in the
# LOW 15 BITS always — bit 15 belongs exclusively to the marker, traced
# or not, so an untraced long run can never have a round counter bleed
# into it (which would make the server record spans for 32768 consecutive
# rounds).  A run with tracing off is byte-identical to the pre-trace
# wire through round 32767 per key (beyond that the old 16-bit round
# differed anyway each 65536 rounds; the guard-aliasing distance is
# 32768 — see server.cc RoundMatch).  A traced PING asks the server for
# its clock (the offset-estimation leg).
FLAG_TRACED = 0x8000
ROUND_MASK = 0x7FFF

_CMD_NAMES = {0: "HELLO", 1: "INIT", 2: "PUSH", 3: "PULL", 4: "BARRIER",
              5: "SHUTDOWN", 6: "PING", 7: "LR_SCALE", 8: "STATS",
              9: "TRACE", 10: "LEAVE", 11: "MEMBERS", 12: "RING",
              13: "RING_SET", 14: "DRAIN", 15: "MIGRATE", 16: "AUDIT",
              17: "CODEC", 18: "OPT", 19: "KNOB", 21: "WINDOW",
              22: "FLEET"}


def _round_flags(rnd: int, traced: bool) -> int:
    """The u16 round flags for one PUSH/PULL frame: the round mod 2^15,
    plus — inside a trace window — the marker bit the server records
    spans for.  Bit 15 is never round data (see FLAG_TRACED)."""
    return (rnd & ROUND_MASK) | (FLAG_TRACED if traced else 0)


def estimate_clock_offset(samples) -> Tuple[float, float]:
    """NTP-style offset of a server's clock relative to this worker's.

    ``samples`` is a list of ``(t0_us, server_ts_us, t1_us)`` tuples from
    timestamped pings: the worker read its clock at t0, the server stamped
    server_ts somewhere inside the round trip, the worker read t1 on the
    response.  Assuming a symmetric path, server_ts corresponds to the
    midpoint (t0+t1)/2, so ``offset = server_ts - (t0+t1)/2`` with error
    bounded by rtt/2 — the minimum-RTT sample is therefore the tightest
    estimate and wins (classic NTP peer filtering).  Returns
    ``(offset_us, rtt_us)`` of that best sample; ``server_ts - offset``
    maps a server timestamp onto the worker's timeline.
    """
    if not samples:
        raise ValueError("estimate_clock_offset: no samples")
    t0, ts, t1 = min(samples, key=lambda s: s[2] - s[0])
    return ts - (t0 + t1) / 2.0, float(t1 - t0)

def _merge_member_rec(workers: dict, worker: int, rec: dict) -> None:
    """Fold one server's view of one worker into a merged workers map:
    alive only if EVERY server agrees (one server evicting it means its
    rounds there re-finalize without it — the operative fact), lease age
    takes the max (staleness anywhere is the honest signal).  The ONE
    merge law, shared by merge_membership (CMD_MEMBERS) and
    server_stats (CMD_STATS) so the two surfaces can never disagree."""
    alive = bool(rec.get("alive"))
    age = float(rec.get("age_ms", 0.0))
    prev = workers.get(worker)
    if prev is None:
        workers[worker] = {"alive": alive, "age_ms": age}
    else:
        prev["alive"] = prev["alive"] and alive
        prev["age_ms"] = max(prev["age_ms"], age)


def merge_membership(views: list) -> dict:
    """Merge per-server CMD_MEMBERS snapshots into one worker-set view.

    Epoch takes the max across servers (each server versions its own
    table; transitions reach every server through the same worker
    actions, so the max is the freshest view).  A worker counts as alive
    only if EVERY server that knows it says so — one server evicting it
    means its rounds there will re-finalize without it, which is the
    operative fact for the training loop.  Lease ages take the max
    (staleness anywhere is the honest signal) and barrier arrivals
    union (in practice barriers live on server 0 only).

    Returns ``{"epoch", "workers": {id: {"alive", "age_ms"}}, "alive":
    [ids], "barrier": {gen: [ids]}}``.
    """
    merged: dict = {"epoch": 0, "workers": {}, "barrier": {}}
    for st in views:
        merged["epoch"] = max(merged["epoch"], int(st.get("epoch", 0)))
        for w, rec in (st.get("members") or {}).items():
            _merge_member_rec(merged["workers"], int(w), rec)
        for g, ids in (st.get("barrier") or {}).items():
            g = int(g)
            merged["barrier"][g] = sorted(
                set(merged["barrier"].get(g, ())) | {int(i) for i in ids})
    merged["alive"] = sorted(w for w, r in merged["workers"].items()
                             if r["alive"])
    return merged


# How often the barrier wait logs a "still waiting" warning; module-level so
# tests can shrink it (bps.barrier legitimately blocks on peers for a long
# time — silence is the failure mode being fixed, not the waiting itself).
BARRIER_WARN_INTERVAL_S = 10.0


class _KeyMoved(Exception):
    """A request drew status MOVED: the addressed server is not the ring
    owner of the key.  ``doc`` is the server's current ring table (the
    MOVED payload) — the session adopts it, re-plans, and replays the
    partition against the new owner (state already migrated there:
    the server's contract is state-before-redirect)."""

    def __init__(self, key: int, doc: dict):
        super().__init__(f"key {key} moved (ring epoch "
                         f"{doc.get('epoch', '?')})")
        self.key = key
        self.doc = doc


class _CodecStale(Exception):
    """A push drew status CODEC_STALE: its wire format does not match
    the key's codec-table entry for the round being merged.  ``doc`` is
    the server's authoritative codec doc (the CODEC_STALE payload) —
    the session adopts it, re-encodes the partition from its staged
    gradient with the right codec (EF residual carried, never dropped),
    and replays the push — so no round ever mixes wire formats and no
    contribution is lost."""

    def __init__(self, key: int, doc: dict):
        super().__init__(f"key {key} codec stale (epoch "
                         f"{doc.get('epoch', '?')})")
        self.key = key
        self.doc = doc


class _KnobStale(Exception):
    """A push drew status KNOB_STALE: this session has not acked the
    server's newest GLOBAL knob epoch and the key's round is already
    at/past the switch boundary.  ``doc`` is the authoritative knob doc
    (the KNOB_STALE payload) — the session adopts the table, applies its
    half of the switch, ACKs the epoch, and either replays the partition
    in place (pool/lane knobs, payload unchanged) or fails its handle
    with :class:`KnobReplan` (the fusion layout changed, so the staged
    bucket keys no longer exist fleet-wide and the caller must re-plan)."""

    def __init__(self, key: int, doc: dict):
        super().__init__(f"key {key} knob stale (epoch "
                         f"{doc.get('epoch', '?')})")
        self.key = key
        self.doc = doc


class KnobReplan(RuntimeError):
    """A staged push was withdrawn because a FUSION_BYTES knob switch
    re-partitioned the tree under it: the bucket keys it was planned
    against are no longer what the fleet pushes from the effective round
    on.  Raised out of the affected handles' ``wait()``; the fusion
    dispatch layer (common/api.py) catches it, re-plans the tree under
    the live fusion_bytes, and re-dispatches exactly the failed units —
    idempotent against the server's seen-dedup and stale-round guards,
    so nothing double-merges.  ``doc`` is the knob doc that triggered
    the withdrawal (None when the switch was applied locally)."""

    def __init__(self, msg: str, doc: Optional[dict] = None):
        super().__init__(msg)
        self.doc = doc


class _ConnLost(ConnectionError):
    """The connection dropped with a request outstanding.

    ``will_reconnect`` distinguishes a drop the transport is actively
    recovering from (BYTEPS_TPU_RECONNECT_ATTEMPTS > 0: the owner may PARK
    the request and replay it after the re-dial) from a terminal loss,
    which must fail the request exactly like the pre-reconnect transport.
    """

    def __init__(self, msg: str, will_reconnect: bool = False):
        super().__init__(msg)
        self.will_reconnect = will_reconnect


class _PooledBuf:
    """One checked-out receive buffer: an exact-length view of a pooled
    bytearray plus the ticket to return it.

    The receiver fills ``mv`` straight off the socket and hands the whole
    object down the pull-completion path; exactly ONE consumer calls
    ``release()`` after the payload's bytes have been consumed (copied
    into the handle's output buffer or decoded out of it).  release() is
    idempotent so error paths can call it defensively.
    """

    __slots__ = ("mv", "_pool", "_cls", "_buf")

    def __init__(self, pool: "_RecvBufPool", cls: int, buf: bytearray,
                 n: int):
        self._pool, self._cls, self._buf = pool, cls, buf
        self.mv = memoryview(buf)[:n]

    def __len__(self) -> int:
        return len(self.mv)

    def release(self) -> None:
        buf, self._buf = self._buf, None
        if buf is not None:
            self.mv.release()
            self._pool._put(self._cls, buf)


class _RecvBufPool:
    """Size-classed pooled receive buffers for the payload hot path.

    The pre-pool receiver allocated (and the allocator zero-filled) a
    fresh bytearray per frame — a 4MB partition pull paid a 4MB
    allocation + page-touch every round.  Here buffers recycle through
    power-of-two size classes (4 KiB .. 16 MiB; larger payloads fall back
    to a one-shot allocation): steady-state training traffic re-uses the
    same few buffers round after round, so the per-frame cost drops to a
    freelist pop.  Shared by every connection of a session — the classes
    are locked, but acquire/release is two list ops per frame.

    No-aliasing invariant: a buffer is EITHER on a freelist OR owned by
    exactly one _PooledBuf (the receiver thread hands each checkout to a
    single consumer, and release() nulls the ticket), so two concurrent
    pulls can never scribble on the same backing storage — asserted by
    tests/test_transport_speed.py.
    """

    MIN_CLASS = 12                       # 4 KiB — below this, pooling is
    #                                      churn for no measurable win
    MAX_CLASS = 24                       # 16 MiB
    PER_CLASS = 8                        # buffers retained per class

    def __init__(self):
        self._lock = threading.Lock()
        self._free: Dict[int, list] = {}
        self.hits = 0
        self.misses = 0

    def _class_for(self, n: int) -> Optional[int]:
        if n <= 0 or n > (1 << self.MAX_CLASS):
            return None
        return max(self.MIN_CLASS, (n - 1).bit_length())

    def acquire(self, n: int) -> _PooledBuf:
        cls = self._class_for(n)
        buf = None
        if cls is not None:
            with self._lock:
                lst = self._free.get(cls)
                if lst:
                    buf = lst.pop()
                    self.hits += 1
                else:
                    self.misses += 1
        if buf is None:
            buf = bytearray(1 << cls) if cls is not None else bytearray(n)
        return _PooledBuf(self, cls, buf, n)

    def _put(self, cls: Optional[int], buf: bytearray) -> None:
        if cls is None:
            return
        with self._lock:
            lst = self._free.setdefault(cls, [])
            if len(lst) < self.PER_CLASS:
                lst.append(buf)

    def stats(self) -> Tuple[int, int, int]:
        """(hits, misses, buffers currently held on freelists)."""
        with self._lock:
            held = sum(len(v) for v in self._free.values())
            return self.hits, self.misses, held


class _Future:
    """Completion slot for one outstanding request."""

    __slots__ = ("event", "data", "error", "callback", "sink", "sink_live",
                 "pool_ok", "cmd", "key", "req_id", "t0")

    def __init__(self, callback: Optional[Callable] = None,
                 sink: Optional[memoryview] = None,
                 sink_live: Optional[Callable[[], bool]] = None,
                 pool_ok: bool = False):
        self.event = None if callback else threading.Event()
        self.data: bytes = b""
        self.error: Optional[Exception] = None
        self.callback = callback
        # Optional preallocated destination: a response whose payload length
        # matches len(sink) is received straight into it (no intermediate
        # buffer — the ZPull-into-shm stance, reference core_loops.cc:582-616).
        self.sink = sink
        # Guard consulted just before the receiver commits to the sink: a
        # False return (e.g. the owning handle timed out and the caller may
        # be reusing the buffer) diverts the payload to a scratch buffer.
        self.sink_live = sink_live
        # True when the response payload may land in a pooled buffer (the
        # pull data leg, whose completion path has a single well-defined
        # consumer that releases it); control responses keep the private
        # allocation so wait() callers can hold the bytes indefinitely.
        self.pool_ok = pool_ok
        # Request context for diagnosable timeouts (filled in by send()).
        self.cmd = -1
        self.key = 0
        self.req_id = 0
        self.t0 = time.monotonic()

    def resolve(self, data: bytes, error: Optional[Exception]) -> None:
        self.data, self.error = data, error
        if self.callback is not None:
            self.callback(data, error)
        else:
            self.event.set()

    def wait(self, timeout: Optional[float] = None) -> bytes:
        if not self.event.wait(timeout):
            raise TimeoutError(
                f"PS request timed out: cmd={_CMD_NAMES.get(self.cmd, self.cmd)}"
                f" key={self.key} req_id={self.req_id}"
                f" elapsed={time.monotonic() - self.t0:.1f}s"
                f" (timeout={timeout}s)")
        if self.error is not None:
            raise self.error
        return self.data


class _ServerConn:
    """One multiplexed connection to a PS server.

    Any thread may `send`; a dedicated receiver thread matches responses to
    futures by req_id and runs completion callbacks (the ZPush/ZPull
    callback model, reference: core_loops.cc:564-616).

    With ``reconnect_attempts > 0`` the connection survives transport
    faults: on a drop the receiver resolves every pending future with a
    `_ConnLost(will_reconnect=True)` (the session parks its partitions for
    replay), re-dials ``host:port`` under bounded exponential backoff with
    jitter, then runs ``on_reconnect`` (the session's handshake + replay)
    on a fresh thread while the receiver resumes on the new socket.  With
    the default 0, a drop fails all pending requests permanently — the
    pre-reconnect fail-fast contract, unchanged.
    """

    def __init__(self, host: str, port: int, timeout: float = 60.0,
                 reconnect_attempts: int = 0,
                 reconnect_backoff_ms: float = 100.0,
                 on_reconnect: Optional[Callable] = None,
                 on_give_up: Optional[Callable] = None,
                 uds_path: str = "",
                 sock_buf_kb: int = 0,
                 recv_pool: Optional[_RecvBufPool] = None):
        self.host, self.port = host, port
        self.timeout = timeout
        self.reconnect_attempts = max(0, int(reconnect_attempts))
        self.reconnect_backoff_ms = max(1.0, float(reconnect_backoff_ms))
        self.on_reconnect = on_reconnect
        self.on_give_up = on_give_up
        self.reconnects = 0          # successful re-dials, for stats
        # UDS fast path (BYTEPS_TPU_SERVER_UDS): dial AF_UNIX at
        # "<uds_path>.<port>" first — same framing, bit-identical
        # protocol, measurably lower per-frame cost for a colocated
        # server — with transparent TCP fallback (including on re-dials,
        # so a replacement server without the socket file still recovers).
        self.uds_path = uds_path
        self.sock_buf_kb = max(0, int(sock_buf_kb))
        self.transport = "tcp"       # what _dial actually connected over
        self._recv_pool = recv_pool
        # Byte-credit lane accounting (the per-lane scheduling signal):
        # outstanding_bytes is the wire payload in flight on this conn
        # (charged at push dispatch / pull issue, returned on completion);
        # lane_bytes_total / lane_sends are lifetime counters for stats.
        self._lane_lock = threading.Lock()
        self.outstanding_bytes = 0
        self.lane_bytes_total = 0
        self.lane_sends = 0
        # WIRE_CONNS knob: a retiring lane takes no NEW dispatches
        # (excluded from _pick_lane) while its outstanding bytes drain;
        # the resize worker closes it once quiet (_resize_lanes).
        self.retiring = False
        self.sock = self._dial()
        self.lock = threading.Lock()          # send serialization
        self.replay_lock = threading.Lock()   # serializes on_reconnect runs
        self._pending: Dict[int, _Future] = {}
        self._pending_lock = threading.Lock()
        self._req_counter = 0
        self._closed = False
        self._down = False           # dropped, re-dial in progress
        self.down_since = 0.0        # monotonic ts of the current outage
        #                              (0 = up) — the server-failover
        #                              scanner's lease signal
        self._recv_thread = threading.Thread(
            target=self._recv_loop, daemon=True, name="bps-ps-recv")
        self._recv_thread.start()

    def _dial(self) -> socket.socket:
        if self.uds_path:
            # AF_UNIX first: "<base>.<port>" is the server's convention
            # (core/server.cc UDS listener), so one env var covers a
            # multi-server host.  Any failure (no socket file, refused,
            # AF_UNSUPPORTED) falls back to TCP — the UDS path is an
            # optimization, never a new failure mode.
            sock = None
            try:
                sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                sock.settimeout(self.timeout)
                sock.connect(f"{self.uds_path}.{self.port}")
                sock.settimeout(None)
                self.transport = "uds"
                self._tune(sock)
                return sock
            except OSError as e:
                if sock is not None:
                    try:
                        sock.close()
                    except OSError:
                        pass
                get_logger().debug(
                    "UDS dial to %s.%d failed (%s); falling back to TCP",
                    self.uds_path, self.port, e)
        sock = socket.create_connection((self.host, self.port),
                                        timeout=self.timeout)
        sock.settimeout(None)  # receiver blocks until data or close
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.transport = "tcp"
        self._tune(sock)
        return sock

    def _tune(self, sock: socket.socket) -> None:
        """Apply BYTEPS_TPU_SOCK_BUF_KB (0 = kernel default) to both
        directions; best-effort — the kernel clamps/doubles as it sees
        fit, and an EPERM on an exotic transport must not kill a dial."""
        if self.sock_buf_kb <= 0:
            return
        nbytes = self.sock_buf_kb * 1024
        for opt in (socket.SO_SNDBUF, socket.SO_RCVBUF):
            try:
                sock.setsockopt(socket.SOL_SOCKET, opt, nbytes)
            except OSError:
                pass

    # -- byte-credit lane accounting ------------------------------------
    def lane_charge(self, nbytes: int) -> None:
        with self._lane_lock:
            self.outstanding_bytes += nbytes
            self.lane_bytes_total += nbytes
            self.lane_sends += 1

    def lane_return(self, nbytes: int) -> None:
        with self._lane_lock:
            self.outstanding_bytes = max(0, self.outstanding_bytes - nbytes)

    def state(self) -> str:
        """'up' | 'reconnecting' | 'closed' — for watchdog dumps/stats."""
        with self._pending_lock:
            if self._closed:
                return "closed"
            return "reconnecting" if self._down else "up"

    def _lost_exc(self, msg: str) -> _ConnLost:
        """A connection-lost error tagged with whether this conn will try
        to recover (so the session knows to park instead of fail)."""
        return _ConnLost(msg, will_reconnect=self.reconnect_attempts > 0
                         and not self._closed)

    def send(self, cmd: int, key: int = 0, payload: bytes = b"",
             worker_id: int = 0, dtype: int = 0, flags: int = 0,
             callback: Optional[Callable] = None,
             sink: Optional[memoryview] = None,
             sink_live: Optional[Callable[[], bool]] = None,
             pool_ok: bool = False) -> _Future:
        fut = _Future(callback, sink, sink_live, pool_ok)
        with self._pending_lock:
            if self._closed:
                raise ConnectionError("PS connection closed")
            if self._down:
                # Mid-reconnect: nothing can go on the wire right now.  The
                # tagged error lets the dispatcher park the partition for
                # replay instead of failing the handle.
                raise self._lost_exc(
                    f"PS connection to {self.host}:{self.port} is "
                    f"reconnecting")
            self._req_counter = (self._req_counter + 1) & 0xFFFFFFFF
            req_id = self._req_counter
            fut.cmd, fut.key, fut.req_id = cmd, key, req_id
            self._pending[req_id] = fut
        hdr = _REQ.pack(cmd, dtype, flags & 0xFFFF, req_id, worker_id, key,
                        len(payload))
        sock = self.sock   # the socket this send commits to (see except arm)
        try:
            with self.lock:
                if len(payload) >= 65536:
                    # Zero-copy gather send for data partitions: the
                    # memoryview goes straight to the socket (the
                    # reference's ZPush zero-copy SArray stance,
                    # core_loops.cc:564-569) and header+payload ride ONE
                    # sendmsg — under TCP_NODELAY a separate header
                    # sendall is its own packet + syscall + server-reader
                    # wakeup per partition (mirror of the server-side
                    # Respond coalescing).
                    self._send_gather(sock, hdr, payload)
                else:
                    sock.sendall(hdr + bytes(payload))
        except OSError as e:
            # Wake the receiver so IT drives the reconnect (single owner):
            # shut down the exact socket this send wrote to — if a re-dial
            # already swapped in a healthy one, this is a no-op on a dead fd.
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            with self._pending_lock:
                popped = self._pending.pop(req_id, None)
            if popped is None:
                # The drop handler already took (and resolved/parked) this
                # future — it owns the error path; raising here too would
                # double-handle it (e.g. return scheduler credit twice).
                return fut
            raise self._lost_exc(f"PS send failed: {e}") from e
        return fut

    def _send_gather(self, sock: socket.socket, hdr: bytes, payload) -> None:
        """header+payload in one gather syscall, with the partial-write
        loop sendmsg needs (unlike sendall it returns after one write)."""
        mv_h, mv_p = memoryview(hdr), memoryview(payload)
        total = len(mv_h) + len(mv_p)
        sent = sock.sendmsg([mv_h, mv_p])
        while sent < total:
            if sent < len(mv_h):
                sent += sock.sendmsg([mv_h[sent:], mv_p])
            else:
                sock.sendall(mv_p[sent - len(mv_h):])
                sent = total

    def request(self, cmd: int, key: int = 0, payload: bytes = b"",
                worker_id: int = 0, dtype: int = 0, flags: int = 0,
                timeout: Optional[float] = 60.0,
                barrier_diag: Optional[Callable[[], str]] = None) -> bytes:
        """Blocking request/response (INIT, BARRIER, control commands).

        BARRIER legitimately blocks on peers, so its default deadline is
        infinite (`timeout=None`; `BYTEPS_TPU_BARRIER_TIMEOUT_S` routes a
        finite one through PSSession.barrier) — but it logs a periodic
        "still waiting" warning so a dead peer is never silent.  Everything
        else fails loudly after `timeout` instead of hanging a training job
        on a wedged server.  ``barrier_diag``, when given, is called on
        each warning/timeout to append the live membership picture (which
        ranks the barrier is actually waiting on).
        """
        fut = self.send(cmd, key, payload, worker_id, dtype, flags)
        if cmd == CMD_BARRIER:
            return self._wait_barrier(fut, key, timeout, barrier_diag)
        return fut.wait(timeout)

    def _wait_barrier(self, fut: _Future, gen: int,
                      timeout: Optional[float],
                      diag: Optional[Callable[[], str]] = None) -> bytes:
        """Barrier wait with periodic progress warnings and an optional
        overall deadline (0/None = wait forever, the historical default).

        The warning/timeout text reports the live epoch membership and the
        ranks the barrier is actually waiting on (via ``diag``, wired by
        PSSession.barrier to a CMD_MEMBERS fetch) — a dead-or-evicted peer
        is named, instead of the old blanket "DMLC_NUM_WORKER over-counts
        the world" guess."""
        if not timeout or timeout <= 0:
            timeout = None
        deadline = None if timeout is None else time.monotonic() + timeout

        def diag_text() -> str:
            if diag is None:
                return "a peer is down, slow, or not yet started"
            try:
                return diag()
            except Exception as e:   # old server / mid-outage: degrade
                return (f"a peer is down, slow, or not yet started "
                        f"(membership unavailable: {e})")

        t0 = time.monotonic()
        while True:
            chunk = BARRIER_WARN_INTERVAL_S
            if deadline is not None:
                chunk = min(chunk, max(0.0, deadline - time.monotonic()))
            if fut.event.wait(chunk):
                break
            elapsed = time.monotonic() - t0
            if deadline is not None and time.monotonic() >= deadline:
                _flightrec.record("barrier_timeout", gen=gen,
                                  elapsed_s=round(elapsed, 1))
                raise TimeoutError(
                    f"PS barrier timed out: gen={gen} elapsed={elapsed:.1f}s"
                    f" (BYTEPS_TPU_BARRIER_TIMEOUT_S={timeout});"
                    f" {diag_text()}")
            get_logger().warning(
                "still waiting on barrier gen=%d after %.1fs (server %s:%d;"
                " %s)", gen, elapsed, self.host, self.port, diag_text())
            _flightrec.record("barrier_wait", gen=gen,
                              elapsed_s=round(elapsed, 1))
        if fut.error is not None:
            raise fut.error
        return fut.data

    def _recv_loop(self) -> None:
        while True:
            try:
                self._recv_pump()
                return      # unreachable: _recv_pump only exits by raising
            except (ConnectionError, OSError) as e:
                if not self._begin_reconnect(e):
                    self._fail_pending(e)
                    return

    def _recv_pump(self) -> None:
        # One persistent header buffer per pump: 21-byte RESP headers
        # arrive once per response, so a fresh bytearray each time was
        # pure allocator churn on the hot path.
        hdr = bytearray(_RESP.size)
        hdr_mv = memoryview(hdr)
        while True:
            self._recv_into(hdr_mv)
            status, req_id, rkey, length = _RESP.unpack(hdr)
            # Pop BEFORE the payload read: this thread owns the future
            # (and its sink buffer) exclusively, so a concurrent
            # _fail_pending can neither resolve it mid-write nor race a
            # retry into the same sink.  The except arm below resolves
            # it if the connection dies mid-payload — no orphaning.
            with self._pending_lock:
                fut = self._pending.pop(req_id, None)
            pooled = None
            try:
                if (fut is not None and fut.sink is not None
                        and status == 0 and length == len(fut.sink)
                        and (fut.sink_live is None or fut.sink_live())):
                    # Matched sink: payload lands in the caller's buffer.
                    self._recv_into(fut.sink)
                    data = fut.sink
                elif (fut is not None and fut.pool_ok and status == 0
                        and length and self._recv_pool is not None):
                    # Pull data leg with no sink match (compressed pull,
                    # or a failed handle's diverted payload): land it in
                    # a pooled buffer — the completion path consumes the
                    # bytes and releases it (see _complete_pull).
                    pooled = self._recv_pool.acquire(length)
                    self._recv_into(pooled.mv)
                    data = pooled
                else:
                    data = self._recv_exact(length) if length else b""
            except (ConnectionError, OSError) as e:
                if pooled is not None:
                    pooled.release()
                if fut is not None:
                    try:
                        fut.resolve(
                            b"", self._lost_exc(f"PS connection lost "
                                                f"mid-payload: {e}"))
                    except Exception:
                        get_logger().exception(
                            "PS completion callback failed")
                raise
            if fut is None:
                continue  # response for a cancelled request
            err = None
            if status == STATUS_MOVED:
                # The key's ring owner changed: the payload is the
                # server's current ring table.  Parsed here (it is tiny)
                # so every completion path gets a structured error.
                import json as _json
                try:
                    doc = _json.loads(bytes(data).decode())
                except Exception:
                    doc = {}
                err = _KeyMoved(rkey, doc)
            elif status == STATUS_CODEC_STALE:
                # Codec renegotiation race: the payload is the key's
                # authoritative codec doc — tiny, parsed here like MOVED.
                import json as _json
                try:
                    doc = _json.loads(bytes(data).decode())
                except Exception:
                    doc = {}
                err = _CodecStale(rkey, doc)
            elif status == STATUS_KNOB_STALE:
                # Global knob renegotiation race: the payload is the
                # server's authoritative knob doc — tiny, parsed like
                # MOVED/CODEC_STALE above.
                import json as _json
                try:
                    doc = _json.loads(bytes(data).decode())
                except Exception:
                    doc = {}
                err = _KnobStale(rkey, doc)
            elif status != 0:
                err = RuntimeError(f"PS server error for key {rkey}")
            try:
                fut.resolve(data, err)
            except Exception:
                get_logger().exception("PS completion callback failed")

    def _begin_reconnect(self, exc: Exception) -> bool:
        """Runs on the receiver thread after a transport fault.  Returns
        True once a new socket is live (the receive loop resumes on it);
        False when reconnect is disabled/exhausted or the conn was closed
        deliberately — the caller then fails pending requests for good."""
        if self.reconnect_attempts <= 0:
            return False
        with self._pending_lock:
            if self._closed:
                return False
            self._down = True
            if not self.down_since:
                self.down_since = time.monotonic()
            dropped, self._pending = self._pending, {}
        # Park-don't-fail: pending futures resolve with a reconnect-tagged
        # loss so the session can stash their partitions for replay.
        lost = _ConnLost(f"PS connection to {self.host}:{self.port} "
                         f"dropped: {exc}", will_reconnect=True)
        for fut in dropped.values():
            try:
                fut.resolve(b"", lost)
            except Exception:
                get_logger().exception("PS completion callback failed")
        try:
            self.sock.close()
        except OSError:
            pass
        get_logger().warning(
            "PS connection to %s:%d dropped (%s); reconnecting "
            "(attempts=%d, backoff=%.0fms, %d requests parked/failed)",
            self.host, self.port, exc, self.reconnect_attempts,
            self.reconnect_backoff_ms, len(dropped))
        _flightrec.record("conn_drop", host=self.host, port=self.port,
                          pending=len(dropped), error=str(exc))
        for attempt in range(1, self.reconnect_attempts + 1):
            # Bounded exponential backoff with jitter (0.5x-1.5x), capped
            # at 10s per attempt, so a worker fleet never re-dials a
            # restarting server in lockstep.
            backoff = min(10.0, self.reconnect_backoff_ms / 1000.0
                          * (2.0 ** (attempt - 1)))
            time.sleep(backoff * (0.5 + random.random()))
            with self._pending_lock:
                if self._closed:
                    return False
            try:
                sock = self._dial()
            except OSError as e:
                get_logger().warning(
                    "PS reconnect to %s:%d attempt %d/%d failed: %s",
                    self.host, self.port, attempt,
                    self.reconnect_attempts, e)
                continue
            self.sock = sock
            with self._pending_lock:
                if self._closed:        # closed while dialing
                    try:
                        sock.close()
                    except OSError:
                        pass
                    return False
                self._down = False
                self.down_since = 0.0
            self.reconnects += 1
            get_logger().warning(
                "PS connection to %s:%d re-established (attempt %d/%d)",
                self.host, self.port, attempt, self.reconnect_attempts)
            if self.on_reconnect is not None:
                # The handshake/replay sends requests over THIS conn and
                # waits on their futures — which needs the receive loop
                # running — so it rides its own thread.
                threading.Thread(
                    target=self._run_on_reconnect, daemon=True,
                    name="bps-ps-replay").start()
            return True
        with self._pending_lock:
            self._closed = True
        get_logger().error(
            "PS reconnect to %s:%d gave up after %d attempts",
            self.host, self.port, self.reconnect_attempts)
        if self.on_give_up is not None:
            try:
                self.on_give_up(self, exc)
            except Exception:
                get_logger().exception("PS reconnect give-up hook failed")
        return False

    def _run_on_reconnect(self) -> None:
        with self.replay_lock:    # serialize overlapping reconnect cycles
            try:
                self.on_reconnect(self)
            except Exception:
                get_logger().exception(
                    "PS post-reconnect handshake/replay failed")

    def _fail_pending(self, exc: Exception) -> None:
        with self._pending_lock:
            self._closed = True
            pending, self._pending = self._pending, {}
        for fut in pending.values():
            try:
                fut.resolve(b"", ConnectionError(f"PS connection lost: {exc}"))
            except Exception:
                pass

    def _recv_exact(self, n: int):
        # recv_into a single preallocated buffer: no per-chunk allocation
        # and no join copy (a 4MB partition pull is one buffer, filled in
        # place).  Callers treat the result as a read-only byte buffer.
        buf = bytearray(n)
        self._recv_into(memoryview(buf))
        return buf

    def _recv_into(self, view: memoryview) -> None:
        n = len(view)
        got = 0
        while got < n:
            r = self.sock.recv_into(view[got:], n - got)
            if r == 0:
                raise ConnectionError("PS server closed connection")
            got += r

    def close(self):
        with self._pending_lock:
            self._closed = True   # stops any in-progress re-dial loop
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass
        self._fail_pending(ConnectionError("closed"))


class PSHandle:
    """Async push_pull completion handle (the torch-plugin handle analog,
    reference: handle_manager.h:33-46)."""

    def __init__(self, shape, dtype, num_parts: int, out: np.ndarray):
        self.shape = shape
        self.dtype = dtype
        self.out = out                      # flat f32 result buffer
        self._remaining = num_parts
        self._lock = threading.Lock()
        self._event = threading.Event()
        self._error: Optional[Exception] = None
        self._outstanding: set = set()      # pkeys not yet completed
        self._timed_out = False             # wait() gave up: discard late

    def _register_part(self, pkey: int) -> None:
        with self._lock:
            self._outstanding.add(pkey)

    def _part_done(self, error: Optional[Exception] = None,
                   pkey: Optional[int] = None) -> None:
        with self._lock:
            if pkey is not None:
                self._outstanding.discard(pkey)
            if error is not None and self._error is None:
                self._error = error
            self._remaining -= 1
            done = self._remaining <= 0
        if done or error is not None:
            self._event.set()

    def _store_result(self, off_f32: int, got: np.ndarray) -> bool:
        """Land one partition's pulled values in `out` — unless the handle
        already failed (wait() timed out, or another partition errored /
        was failed by the watchdog), in which case the result is dead and
        a late write could corrupt a buffer the owner stopped tracking.
        The check-and-write runs under the handle lock so a concurrent
        timeout can't interleave with it.  (The zero-copy sink path checks
        `failed()` before committing to the in-place receive instead; a
        failure arriving DURING that receive can still land bytes in
        `out`, which is safe because `out` is session-allocated and wait()
        never returns it after a failure.)"""
        with self._lock:
            if self.failed():
                return False
            self.out[off_f32:off_f32 + got.size] = got
            return True

    def failed(self) -> bool:
        """True once the handle can no longer succeed (wait() timeout, a
        partition error, or a watchdog/give-up failure): late resolutions
        must be discarded."""
        return self._timed_out or self._error is not None

    def done(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout: Optional[float] = 300.0) -> np.ndarray:
        with self._lock:
            if self._timed_out:
                # A handle that timed out once stays failed: a later wait()
                # must not hand out a buffer that late partitions may have
                # partially filled.
                raise TimeoutError(
                    "PS push_pull handle already timed out")
        if not self._event.wait(timeout):
            with self._lock:
                self._timed_out = True
                stuck = sorted(self._outstanding)
            shown = ", ".join(str(k) for k in stuck[:16])
            if len(stuck) > 16:
                shown += f", ... ({len(stuck)} total)"
            raise TimeoutError(
                f"PS push_pull timed out after {timeout}s; outstanding "
                f"partition keys: [{shown}]")
        if self._error is not None:
            raise self._error
        return self.out.reshape(self.shape).astype(self.dtype, copy=False)


class _PartTask:
    """One in-flight partition (the reference's TensorTableEntry partition,
    common.h:221-264)."""

    __slots__ = ("pkey", "payload", "off", "ln", "round", "srv", "conn",
                 "handle", "dtype", "done_evt", "wire_ln", "bidirectional",
                 "label", "priority", "enq_ts", "push_ts", "pull_ts",
                 "ready", "enc_err", "credit_ln", "phase", "parked",
                 "enq_mono", "send_mono", "ack_mono", "lane_debt",
                 "audit", "seg", "stale_retries", "knob_gen")

    def __init__(self, pkey, payload, off, ln, rnd, srv, handle,
                 dtype=DT_F32, bidirectional=False, label=""):
        self.pkey = pkey
        self.payload = payload        # wire bytes (raw f32 or compressed);
        #                               None while a pipelined encode runs
        self.off = off                # raw byte offset in the tensor
        self.ln = ln                  # raw byte length of the partition
        self.wire_ln = len(payload) if payload is not None else ln
        self.round = rnd
        # Server placement is fixed by the plan; the LANE (self.conn) is
        # picked per dispatch by byte credit (_pick_lane) and charged
        # lane_debt bytes until the round trip settles.
        self.srv = srv
        self.conn = None
        self.lane_debt = 0
        self.handle = handle
        self.dtype = dtype
        self.bidirectional = bidirectional  # pull leg may arrive compressed
        self.done_evt = threading.Event()  # this partition left _inflight
        # Per-partition trace spans (reference closes one span per partition
        # per stage, global.cc:463-579): QUEUE = enq->dispatch,
        # PUSH = dispatch->ack, PULL = issue->data.
        self.label = label
        self.priority = 0
        self.enq_ts = 0
        self.push_ts = 0
        self.pull_ts = 0
        # Codec pipeline state: `ready` is set once the pool has produced
        # (or failed to produce) this partition's wire payload; None means
        # the payload was ready at staging time (raw parts, inline mode).
        self.ready = None
        self.enc_err = None
        # Scheduling-credit charge: actual wire bytes when known, else
        # the codec's worst-case bound (set by _stage_parts for pipelined
        # encodes, whose true size doesn't exist at enqueue time).
        self.credit_ln = self.wire_ln
        # Fault-tolerance state: `phase` records how far this partition got
        # ("push" = the push must (still/again) be issued, "pull" = the push
        # was acked and only the pull leg is outstanding); `parked` marks a
        # partition stashed for replay while its connection reconnects.
        self.phase = "push"
        self.parked = False
        # Telemetry timestamps (time.monotonic; always set, unlike the
        # trace-gated *_ts fields): enqueue -> dispatch feeds the queue-wait
        # histogram, dispatch -> ack the push-RTT histogram, and ack ->
        # pull-data (`ack_mono`) the signal plane's per-key serve-wait
        # component (the cheap always-on straggler-wait stand-in for the
        # trace plane's MERGE_WAIT spans).
        self.enq_mono = 0.0
        self.send_mono = 0.0
        self.ack_mono = 0.0
        # Auditor: this pull leg was sent with the trailer marker, so its
        # response carries 24 trailing digest bytes to strip+verify.
        # Recorded per ISSUE at pull-issue time (not read globally at
        # completion) so a mid-flight audit downgrade can never make the
        # completion path mis-split a trailerless payload.
        self.audit = False
        # Knob plane: the session's fusion-layout generation this part was
        # staged under (_stage stamps it).  A FUSION_BYTES switch bumps
        # the generation; stale-generation parts at/past the switch round
        # are withdrawn with KnobReplan instead of pushed/replayed — their
        # bucket keys no longer exist fleet-wide.
        self.knob_gen = 0
        # The staged f32 view this partition was encoded from (None for
        # raw parts, whose payload IS the f32 bytes).  Held so a
        # CODEC_STALE rejection can re-encode the same gradient with the
        # renegotiated codec — a reference into memory the zero-copy
        # contract already keeps alive until the handle completes.
        self.seg = None
        # CODEC_STALE replays of THIS partition: the retry loop is
        # bounded (a persistent format mismatch — e.g. per-worker
        # MIN_COMPRESS_BYTES disagreement — must fail loudly, never
        # spin the push hot forever while the round wedges silently).
        self.stale_retries = 0


class PSSession:
    """One worker's sessions to all PS servers.

    push_pull partitions the tensor, spreads partitions across servers, and
    drives them through the priority-scheduled, credit-gated dispatcher —
    the eager analog of the reference's PUSH/PULL loops
    (reference: core_loops.cc:536-616, operations.cc:429-485).
    """

    # Canonical transport-stats schema — the all-zero shape returned by
    # bps.get_transport_stats() outside PS mode, mirroring
    # CompressionPool.ZERO_STATS so the surfaces can never drift apart.
    TRANSPORT_ZERO_STATS = {
        "reconnects": 0,          # successful re-dials across all conns
        "reconnects_failed": 0,   # conns whose backoff budget ran out
        "replayed_pushes": 0,     # partitions re-pushed after a reconnect
        "replayed_pulls": 0,      # pull legs re-issued after a reconnect
        "parked_parts": 0,        # partitions currently parked for replay
        "parked_total": 0,        # partitions ever parked
        "watchdog_trips": 0,      # stall-watchdog dumps fired
        "ring_redirects": 0,      # partitions re-routed by status MOVED
        "codec_switches": 0,      # per-key codec renegotiations applied
        "codec_stale_retries": 0,  # pushes re-encoded after CODEC_STALE
        "knob_switches": 0,       # global knob-table applications
        "knob_stale_retries": 0,  # pushes replayed/withdrawn, KNOB_STALE
        "opt_reseeds": 0,         # server-opt configs+params re-seeded
        #                           onto a fresh owner during a rebase
        "server_failovers": 0,    # dead servers this worker failed over
        "pool_hits": 0,           # recv buffers served from the pool
        "pool_misses": 0,         # recv buffers freshly allocated
        "pool_buffers_held": 0,   # buffers currently on pool freelists
        "lane_bytes_total": 0,    # lifetime payload bytes across lanes
        "lane_outstanding_bytes": 0,  # payload bytes in flight right now
        "lanes": [],              # per-lane rows: {server, lane,
        #                           transport, bytes_total,
        #                           outstanding_bytes, sends}
    }

    def __init__(self, hosts: List[str], ports: List[int], worker_id: int,
                 num_servers: int, hash_fn: str = "djb2",
                 partition_bytes: int = 4 * 1024 * 1024,
                 scheduling_credit: int = 0,
                 min_compress_bytes: int = 65536,
                 wire_conns: int = 4,
                 compress_threads: int = 2,
                 reconnect_attempts: int = 0,
                 reconnect_backoff_ms: float = 100.0,
                 stall_timeout_s: float = 0.0,
                 barrier_timeout_s: float = 0.0,
                 clock_sync_s: float = 30.0,
                 uds_path: str = "",
                 sock_buf_kb: int = 0,
                 evict_timeout_s: float = 0.0,
                 ring: bool = False,
                 ring_vnodes: int = DEFAULT_VNODES,
                 server_evict_timeout_s: float = 0.0,
                 audit: bool = False,
                 audit_window: int = 16,
                 fleet: bool = False,
                 fleet_windows: int = 32,
                 health_sample_rounds: int = 0,
                 slice_size: int = 1,
                 pull_only: bool = False):
        self.worker_id = worker_id
        self.num_servers = max(1, num_servers)
        # Pull-only "inference" session (docs/sparse-embedding.md): the
        # HELLO carries the observer flag, so the servers never admit
        # this worker_id to the round membership — a reader that never
        # pushes cannot stall round completion, and its embedding reads
        # ride the ungated DT_SPARSE_READ plane.  Pushes from a
        # pull-only session are a caller bug and raise locally.
        self.pull_only = bool(pull_only)
        # Hierarchical reduction (parallel/hierarchy.py;
        # BYTEPS_TPU_SLICE_SIZE): chips per slice for leader election.
        # 1 (default) = flat mode — every worker is its own slice and
        # always its own leader; nothing else in the session changes.
        self.slice_size = max(1, int(slice_size))
        self.hash_fn = hash_fn
        self.partition_bytes = max(1, partition_bytes)
        # Partitions below this size skip compression — the
        # BYTEPS_MIN_COMPRESS_BYTES floor (reference: global.cc:43,
        # operations.cc:362-364).
        self.min_compress_bytes = min_compress_bytes
        # Codec pipeline width (BYTEPS_TPU_COMPRESS_THREADS).  0 = inline
        # fallback: encode on the caller thread, decode on the receiver
        # thread, exactly the pre-pipeline data path.
        self.compress_threads = max(0, compress_threads)
        # Fault tolerance (BYTEPS_TPU_RECONNECT_* / _STALL_ / _BARRIER_):
        # 0 attempts = fail-fast on a drop, the pre-reconnect behavior.
        self.reconnect_attempts = max(0, int(reconnect_attempts))
        self.reconnect_backoff_ms = float(reconnect_backoff_ms)
        self.stall_timeout_s = max(0.0, float(stall_timeout_s))
        self.barrier_timeout_s = max(0.0, float(barrier_timeout_s))
        # Cross-host clock-sync cadence (BYTEPS_TPU_CLOCK_SYNC_S): how
        # often the background thread re-estimates server clock offsets
        # while tracing is on, bounding drift across a long trace window.
        self.clock_sync_s = max(1.0, float(clock_sync_s))
        # UDS fast path + socket buffer tuning (BYTEPS_TPU_SERVER_UDS /
        # BYTEPS_TPU_SOCK_BUF_KB).  The UDS dial only applies to servers
        # this worker is actually colocated with (loopback hosts) — a
        # remote server's conns keep dialing TCP.
        self.uds_path = str(uds_path or "")
        self.sock_buf_kb = max(0, int(sock_buf_kb))
        # Elastic membership (BYTEPS_TPU_EVICT_TIMEOUT_S): when eviction
        # is armed, this worker must keep its server-side lease warm even
        # while idle (blocked on a pull, between steps) — a lease is
        # refreshed by any traffic, and the heartbeat PING below is the
        # idle-time traffic.  0 (default) = no heartbeat thread, no extra
        # wire bytes: a fixed-membership job's traffic is untouched.
        self.evict_timeout_s = max(0.0, float(evict_timeout_s))
        # Elastic PS tier (docs/elasticity.md "The server half").
        # `ring` arms consistent-hash placement (the shared law in
        # common/ring.py) — required for drain/scale-up/failover;
        # `server_evict_timeout_s` > 0 additionally arms the worker-side
        # server-lease scanner: a server whose every lane has been down
        # that long is declared dead, the survivors adopt the next ring
        # epoch, and this worker re-declares + re-pushes the open round
        # from gradient state.  Both default off: placement is then the
        # legacy fixed hash and the wire is byte-identical to pre-ring.
        self.server_evict_timeout_s = max(0.0,
                                          float(server_evict_timeout_s))
        self.ring_armed = bool(ring) or self.server_evict_timeout_s > 0
        self.ring_vnodes = max(1, int(ring_vnodes))
        # Value-domain consistency auditor (BYTEPS_TPU_AUDIT=1,
        # docs/monitoring.md "Auditing & postmortem"): every pull carries
        # the server's publish digest and this session re-digests the
        # received bytes, keeping a last-K (round, digest) window per key
        # for the CMD_AUDIT cross-check.  Off (default): the wire is
        # byte-identical to pre-audit and nothing is digested.
        self.audit = bool(audit)
        self.audit_window = max(1, int(audit_window))
        # Fleet observability plane (BYTEPS_TPU_FLEET=1): each signal-
        # window roll publishes this worker's compact summary to its
        # rank-0 server (CMD_WINDOW) and any endpoint answers the merged
        # per-worker view (CMD_FLEET).  Armed only after the bootstrap
        # probe confirms the server tier retains windows — otherwise it
        # downgrades loudly and the wire stays byte-identical.
        self.fleet = bool(fleet)
        self.fleet_windows = max(1, int(fleet_windows))
        # Chain replication armed on the server tier (BYTEPS_TPU_REPL=1,
        # docs/elasticity.md "zero-loss law"): a SIGKILLed owner's fresh
        # replacement adopts the ring successor's replica at the last
        # publish boundary — with an EMPTY open round.  Reconcile must
        # then re-push a round whose pushes died with the old owner even
        # from a partition already parked in its pull phase (the server's
        # per-worker `seen` dedup absorbs the duplicate whenever the push
        # DID survive, so the replay is always safe).
        self._repl_armed = os.environ.get(
            "BYTEPS_TPU_REPL", "").strip().lower() not in (
                "", "0", "false", "no", "off")
        # Gradient-health monitor (BYTEPS_TPU_HEALTH_SAMPLE_ROUNDS > 0):
        # per-key norm/max/NaN/Inf/EF-residual sampling on the push path.
        self.health_sample_rounds = max(0, int(health_sample_rounds))
        # Any failure before __init__ returns (a connect, the dispatcher,
        # the HELLO mode check) must tear down every socket and receiver
        # thread already created — the caller gets an exception, not a
        # session, so nothing else can ever close them.
        self.conns: List[_ServerConn] = []
        self._data_conns: List[List[_ServerConn]] = []
        self._session_ready = False
        try:
            self._init_connections(hosts, ports, max(1, wire_conns))
            self._init_state(scheduling_credit)
            self._hello_mode_check(worker_id)
            if self.ring_armed:
                self._ring_bootstrap()
            if self.audit:
                self._audit_bootstrap()
            if self.fleet:
                self._fleet_bootstrap()
        except Exception:
            self._abort_init()
            raise
        self._session_ready = True

    _LOOPBACK_HOSTS = ("127.0.0.1", "localhost", "::1")

    def _init_connections(self, hosts, ports, wire_conns: int) -> None:
        """Primary conn per server + optional extra data lanes.

        Partitions spread across a server's lane pool by byte credit
        (least-outstanding-bytes wins, picked at DISPATCH time — see
        _pick_lane), splitting the send-lock and receive-thread work over
        more sockets (the reference gets the same effect from ps-lite's
        per-connection threads).  Control traffic (barrier/hello/
        shutdown) stays on the primary."""
        self._recv_pool = _RecvBufPool()
        self._wire_conns = wire_conns
        self._hosts, self._ports = list(hosts), list(ports)

        for h, p in zip(hosts, ports):
            c = self._make_conn(h, p)
            self.conns.append(c)
            self._data_conns.append([c])
        for pool, (h, p) in zip(self._data_conns, zip(hosts, ports)):
            for _ in range(wire_conns - 1):
                pool.append(self._make_conn(h, p))
        for i, c in enumerate(self.conns):
            if c.transport != "tcp":
                get_logger().info(
                    "PS server %d (%s:%d) connected over %s fast path",
                    i, c.host, c.port, c.transport)

    def _make_conn(self, h: str, p: int) -> "_ServerConn":
        # With server failover armed, a drop must PARK partitions (and
        # keep re-dialing under backoff) rather than fail-fast: the
        # scanner decides whether the server is dead — at which point the
        # ring transitions and the parked parts replay on the new owner —
        # or merely rebooting, in which case the re-dial heals it.  The
        # effectively-unbounded budget is cut short by conn.close() when
        # the dead server is retired from the ring.
        attempts = self.reconnect_attempts
        if self.server_evict_timeout_s > 0:
            attempts = max(attempts, 1 << 30)
        return _ServerConn(
            h, p,
            reconnect_attempts=attempts,
            reconnect_backoff_ms=self.reconnect_backoff_ms,
            on_reconnect=self._on_conn_reconnected,
            on_give_up=self._on_conn_gave_up,
            uds_path=(self.uds_path
                      if h in self._LOOPBACK_HOSTS else ""),
            sock_buf_kb=self.sock_buf_kb,
            recv_pool=self._recv_pool)

    def _abort_init(self) -> None:
        _flightrec.remove_extra_provider("session", owner=self)
        if getattr(self, "_watchdog_stop", None) is not None:
            self._watchdog_stop.set()
        if getattr(self, "_srvdown_stop", None) is not None:
            self._srvdown_stop.set()
        if getattr(self, "_lease_stop", None) is not None:
            self._lease_stop.set()
        if getattr(self, "_clock_sync_stop", None) is not None:
            self._clock_sync_stop.set()
        if getattr(self, "_dispatcher", None) is not None:
            with self._cv:
                self._closed = True
                self._cv.notify_all()
            self._dispatcher.join(timeout=5)
            self._warn_if_wedged(self._dispatcher)
        if getattr(self, "_codec_pool", None) is not None:
            self._codec_pool.close()
        for pool in self._data_conns:
            for c in pool:
                c.close()

    def _init_state(self, scheduling_credit: int) -> None:
        self._inited: Dict[int, tuple] = {}     # pkey -> (length, kwargs)
        self._round: Dict[int, int] = {}        # pkey -> next round index
        self._compressors: Dict[int, object] = {}  # declared_key -> codec
        # Per-key codec renegotiation table (CMD_CODEC; the adaptive-
        # compression tuner's actuation surface).  All keyed by DECLARED
        # key: `_codec_epoch` = newest epoch this session has seen
        # accepted (0 = launch config, the unarmed state — none of this
        # machinery touches the wire until a proposal is made),
        # `_codec_applied` = the epoch of the compressor currently
        # installed, `_codec_next` = a pending switch {"epoch",
        # "effective_round", "kwargs_str"} applied at stage time once the
        # key's round counter reaches effective_round — the same round
        # the server applies its half, so no round mixes wire formats
        # (the CODEC_STALE replay is the race backstop).  `_ef_fold`
        # holds per-PARTITION EF residuals detached by a switch to a
        # codec that cannot carry them (raw / no EF): each is folded
        # into that partition's next push exactly once — a switch never
        # silently drops accumulated error.
        self._codec_lock = threading.Lock()
        self._codec_epoch: Dict[int, int] = {}
        self._codec_applied: Dict[int, int] = {}
        self._codec_next: Dict[int, dict] = {}
        self._ef_fold: Dict[int, np.ndarray] = {}
        self._codec_retry_queue: List[tuple] = []
        self._codec_retry_thread: Optional[threading.Thread] = None
        # Global knob plane (CMD_KNOB): the session half of the
        # epoch-versioned GLOBAL knob table — the CMD_CODEC law lifted
        # from one key's wire format to the job's performance knobs.
        # `_knob_live` holds the actuated values (fusion_bytes /
        # compress_threads / wire_conns; a missing knob means launch
        # config rules), `_knob_next` a staged switch applied at stage
        # time once any key's round reaches effective_round — the same
        # boundary the server applies its half, so no round mixes fusion
        # layouts, pool sizes, or lane sets (KNOB_STALE is the race
        # backstop).  `_knob_gen` is the fusion-LAYOUT generation: a
        # FUSION_BYTES value change bumps it, and parts staged under an
        # older generation at/past `_knob_fusion_eff` are withdrawn with
        # KnobReplan instead of pushed (their bucket keys no longer exist
        # fleet-wide).  All empty/zero until a proposal — an unarmed
        # session never emits a CMD_KNOB frame and the wire stays
        # byte-identical.
        self._knob_lock = threading.Lock()
        self._knob_epoch = 0          # newest epoch seen accepted
        self._knob_applied = 0        # epoch of the values in _knob_live
        self._knob_next: Optional[dict] = None
        self._knob_live: Dict[str, int] = {}
        self._knob_gen = 0            # fusion-layout generation
        self._knob_fusion_eff = 0     # boundary of the last fusion bump
        self._knob_acked = 0          # newest epoch ACKed to the servers
        # ACK deferral: after a fusion-layout switch the ACK is held until
        # every stale-generation push has left the wire — once the server
        # sees the ACK it stops rejecting this worker, so a still-in-
        # flight old-layout push could otherwise merge into an orphaned
        # bucket key (see _knob_retry_loop).
        self._knob_ack_due: Optional[int] = None
        self._knob_history: List[dict] = []
        self._knob_retry_queue: List[tuple] = []
        self._knob_retry_thread: Optional[threading.Thread] = None
        # Declared keys whose identity depends on the fusion plan (bucket
        # and solo-leaf units registered by the fusion dispatch layer via
        # note_fusion_keys) — the only keys a FUSION_BYTES switch may
        # withdraw with KnobReplan.  Caller-owned keys (plain
        # push_pull_async) are layout-independent and always replay in
        # place.
        self._fusion_keys: set = set()
        # Server-resident optimizer plane (CMD_OPT): per declared key the
        # armed config {"epoch", "kwargs_str", "params_fn", "nbytes"} —
        # params_fn is the rebase re-seed source after a failover hands
        # the key's range to a fresh owner.  Empty until arm_server_opt()
        # — an unarmed session never emits a CMD_OPT frame and the wire
        # stays byte-identical (shares _codec_lock: both tables are tiny
        # control-plane state touched off the hot path).
        self._opt_armed: Dict[int, dict] = {}
        self._server_load = [0] * len(self.conns)
        self._plans: Dict[Tuple[int, int], list] = {}
        # _plan's read-modify-write of _plans/_server_load must be atomic:
        # two threads planning concurrently would double-count server
        # load and cache divergent plans.
        self._plan_lock = threading.Lock()
        self._trace_labels: Dict[int, str] = {}

        # Dispatcher: native priority ScheduledQueue + credit flow control
        # (reference: scheduled_queue.cc:26-46,136-139).  credit = 0 means
        # unlimited in-flight bytes, matching the reference default.
        credit_bytes = scheduling_credit * self.partition_bytes
        if credit_bytes > 0:
            credit_bytes = max(credit_bytes, self.partition_bytes)
        self._queue = get_core().queue_create(credit_bytes)
        # Codec pipeline engine (the reference's COMPRESS/DECOMPRESS loop
        # threads, core_loops.cc): encodes run ahead of the dispatcher in
        # the same (priority desc, key asc) order, decodes run off the
        # receiver thread.  NOTE: with the pipeline on, a compressed
        # partition's credit is charged at the codec's worst-case wire
        # size (WireCompressor.wire_cap_bytes, clamped to raw size) —
        # the true encoded size is not known at enqueue time.
        self._codec_pool = (CompressionPool(self.compress_threads)
                            if self.compress_threads > 0 else None)
        self._inflight: Dict[int, _PartTask] = {}
        self._inflight_lock = threading.Lock()
        self._cv = threading.Condition()
        self._closed = False
        self._paused = False
        # Dispatch-order recording is off by default: the list is unbounded
        # and only priority-order tests/tracing read it.
        self.record_push_order = False
        self.push_order: List[int] = []
        # Fault-tolerance bookkeeping: wire-key -> server index (for
        # re-declare invalidation after a reconnect — a key's lane is
        # picked per dispatch, but its SERVER is fixed by the hash) and
        # the transport counter surface (bps.get_transport_stats, the
        # codec/fusion-stats analog).
        self._pkey_srv: Dict[int, int] = {}
        self._transport_lock = threading.Lock()
        # Int counters only: the template's "lanes" list is mutable and
        # must never be shared (transport_stats() builds lanes fresh from
        # the live conns anyway).
        self._tstats = {k: v for k, v in self.TRANSPORT_ZERO_STATS.items()
                        if isinstance(v, int)}
        # Round-stall watchdog (BYTEPS_TPU_STALL_TIMEOUT_S > 0): the
        # worker-side analog of server.cc's ORDERING INVARIANT guard — no
        # partition completing for the window with work outstanding dumps
        # a diagnostic snapshot, then fails the stuck handles loudly.
        self._last_progress = time.monotonic()
        self._watchdog_stop = threading.Event()
        self._watchdog: Optional[threading.Thread] = None
        # Distributed-trace state: per-server clock-offset HISTORY
        # (NTP-style midpoint over timestamped CMD_PINGs; each entry is
        # (server_clock_at_sync_us, offset_us)), fusion-bucket member
        # names for span annotation, and the periodic re-sync thread
        # (started lazily by sync_clocks, active only while tracing).
        # fetch_server_trace corrects each span with the history entry
        # nearest the span's own timestamp, so the periodic samples are
        # what bounds clock drift across a long trace window.
        self._clock_offsets: Dict[int, list] = {}
        self._clock_lock = threading.Lock()
        self._clock_sync_stop = threading.Event()
        self._clock_sync_thread: Optional[threading.Thread] = None
        self._trace_members: Dict[int, list] = {}    # declared_key -> names
        # Metrics-registry feeds (common/telemetry.py).  The objects are
        # resolved once here; the per-partition hot path then pays only a
        # lock-free observe()/set() per event.  The queue-depth gauge
        # samples the scheduler lazily at snapshot time (detached again in
        # close() so a dead session can't pin itself via the registry).
        from ..common import telemetry as _tm
        reg = _tm.get_registry()
        self._m_push_rtt = reg.histogram(
            "bps_push_rtt_seconds",
            help="per-partition push dispatch -> server ack round trip")
        self._m_queue_wait = reg.histogram(
            "bps_dispatch_queue_wait_seconds",
            help="per-partition time from enqueue to dispatcher pick")
        self._queue_depth_fn = lambda: self._queue.pending()
        self._m_queue_depth = reg.gauge(
            "bps_dispatch_queue_depth",
            help="partitions waiting in the priority scheduler",
            fn=self._queue_depth_fn)
        # Row-sparse embedding plane (docs/sparse-embedding.md): per
        # declared key the (rows, width) shape, the accumulating-round
        # counter, and the param_version-keyed hot-row LRU cache.  A
        # cached row serves WITHOUT a wire frame iff the key's last-seen
        # param_version is still fresh (refreshed by any embed response
        # within BYTEPS_TPU_SPARSE_CACHE_TTL_MS) — a version advance
        # invalidates the whole key's cache, never serves stale rows.
        self._embed_lock = threading.Lock()
        self._embed_meta: Dict[int, Tuple[int, int]] = {}
        self._embed_cache: Dict[int, OrderedDict] = {}
        self._embed_ver: Dict[int, int] = {}
        self._embed_ver_ts: Dict[int, float] = {}
        self._embed_cache_rows = max(
            0, int(os.environ.get("BYTEPS_TPU_SPARSE_CACHE_ROWS",
                                  "65536")))
        self._embed_cache_ttl = max(
            0.0, float(os.environ.get("BYTEPS_TPU_SPARSE_CACHE_TTL_MS",
                                      "50"))) / 1000.0
        self._m_embed_hits = reg.counter(
            "bps_embed_cache_hits",
            help="embedding rows served from the hot-row cache (no wire)")
        self._m_embed_misses = reg.counter(
            "bps_embed_cache_misses",
            help="embedding rows that had to be pulled over the wire")
        self._m_embed_pull_bytes = reg.counter(
            "bps_embed_pull_bytes_total",
            help="wire bytes moved by embedding row pulls (both legs)")
        # Auditor state: this worker's last-K (round, digest, epoch, n)
        # window per partition key — what audit_check() compares against
        # the server's CMD_AUDIT window — plus the armed-wire flag (set
        # only once the bootstrap probe confirmed the server records
        # digests) and the verdict counters.  bps_audit_* export through
        # the registry so a mismatch is scrapeable, not just logged.
        self._audit_lock = threading.Lock()
        self._audit_window_log: Dict[int, object] = {}   # pkey -> deque
        self._audit_wire = False
        self._audit_stats = {"checked": 0, "mismatches": 0,
                             "round_skew": 0, "unverified": 0}
        # Fleet-plane state: armed-wire flag (set only once the
        # bootstrap probe confirmed every server retains windows),
        # publish accounting, and the cached clock-offset estimate that
        # rides each published summary (refreshed off the plane thread,
        # never on a round's critical path).
        self._fleet_wire = False
        self._fleet_publishes = 0
        self._fleet_publish_errors = 0
        self._fleet_clock: Optional[Tuple[float, float]] = None
        self._audit_last: Optional[dict] = None   # last verdict detail
        self._m_audit_checked = reg.counter(
            "bps_audit_checked_total",
            help="audited pulls whose digest was re-verified")
        self._m_audit_mismatch = reg.counter(
            "bps_audit_mismatch_total",
            help="audited pulls whose re-digest differed from the "
                 "server's publish digest (corruption/divergence)")
        self._m_audit_skew = reg.counter(
            "bps_audit_round_skew_total",
            help="audited pulls served a different round than staged "
                 "(lost/skewed round, e.g. the failover lost-round "
                 "window)")
        # Gradient-health monitor (BYTEPS_TPU_HEALTH_SAMPLE_ROUNDS > 0):
        # push-path value sampling, computed on the codec pool when one
        # exists so the caller thread never pays the norm pass.
        # Last membership epoch this session OBSERVED (CMD_MEMBERS
        # fetches and audit trailers both update it) — attribution
        # context for health/audit verdicts without a wire fetch.
        self._last_epoch = 0
        # Last merged CMD_MEMBERS view — what slice_leader() elects
        # from, so leadership rides the same epoch rounds are pinned
        # to.  None until the first fetch (launch set semantics).
        self._members_cache: Optional[dict] = None
        # Postmortem bundles dumped anywhere in this process carry this
        # session's local sections (transport/audit/ring/health) via the
        # provider registry — computed once per dump, unregistered at
        # close() so a dead session can't pin itself.
        _flightrec.set_extra_provider(self._bundle_extra, name="session")
        if self.health_sample_rounds > 0:
            from .codec_pool import HealthMonitor
            self._health: Optional[object] = HealthMonitor(
                self.health_sample_rounds,
                context=lambda: {
                    "worker": self.worker_id,
                    "epoch": self._last_epoch,
                    "ring_epoch": (self._ring.epoch
                                   if self._ring is not None else 0)})
        else:
            self._health = None
        self._join_timeout_s = 10.0   # close()'s thread-join budget
        # Lease heartbeat (elastic eviction armed): periodic untraced
        # CMD_PINGs keep this worker's lease warm while it is idle, so
        # only a worker that is actually GONE ever expires.  `_left` stops
        # the heartbeat after a graceful leave — a departed worker must
        # not keep renewing the lease it just gave up.
        self._left = False
        self._lease_stop = threading.Event()
        self._lease_thread: Optional[threading.Thread] = None
        # Elastic PS ring (ring_armed): the worker's copy of the
        # epoch-versioned server ring (common/ring.py — same law the
        # server enforces), the server-id -> conn-slot map (slots are
        # stable for the session; a joiner appends one, a dead/drained
        # server's slot is retired but never reused), and the remap
        # queue: partitions whose key moved (status MOVED or a failover
        # transition) wait here for the remap worker to re-declare and
        # replay them against the new owner.
        self._ring_lock = threading.Lock()
        self._ring: Optional[RingTable] = None
        self._srv_slot: Dict[int, int] = {}
        self._slot_srv: Dict[int, int] = {}
        self._dead_slots: set = set()
        if self.ring_armed:
            self._ring = RingTable(
                [(i, self._hosts[i], self._ports[i])
                 for i in range(len(self.conns))],
                self.ring_vnodes, epoch=0)
            self._srv_slot = {i: i for i in range(len(self.conns))}
            self._slot_srv = {i: i for i in range(len(self.conns))}
        self._remap_lock = threading.Lock()
        self._remap_queue: List[int] = []
        self._remap_thread: Optional[threading.Thread] = None
        self._srvdown_stop = threading.Event()
        self._srvdown_thread: Optional[threading.Thread] = None
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, daemon=True, name="bps-ps-dispatch")
        self._dispatcher.start()
        if self.stall_timeout_s > 0:
            self._watchdog = threading.Thread(
                target=self._watchdog_loop, daemon=True,
                name="bps-ps-watchdog")
            self._watchdog.start()
        if self.evict_timeout_s > 0:
            self._lease_thread = threading.Thread(
                target=self._lease_loop, daemon=True, name="bps-ps-lease")
            self._lease_thread.start()
        if self.server_evict_timeout_s > 0:
            self._srvdown_thread = threading.Thread(
                target=self._server_lease_loop, daemon=True,
                name="bps-ps-srvlease")
            self._srvdown_thread.start()

    def _hello_mode_check(self, worker_id: int) -> None:
        # HELLO returns the server's mode flags (u8 async | u8 schedule).
        # All servers must agree — a mixed fleet silently corrupts training
        # (partitions on a sync server would round-SUM async deltas).
        modes = []
        hello_flags = HELLO_FLAG_OBSERVER if self.pull_only else 0
        for c in self.conns:
            mode = c.request(CMD_HELLO, worker_id=worker_id,
                             flags=hello_flags)
            modes.append((bool(mode[0]), bool(mode[1]))
                         if len(mode) >= 2 else (False, False))
        if len(set(modes)) > 1:
            raise RuntimeError(
                f"PS servers report mixed modes (async, schedule): {modes}; "
                "all servers must share BYTEPS_ENABLE_ASYNC / "
                "BYTEPS_SERVER_ENABLE_SCHEDULE settings")
        self.server_async, self.server_schedule = modes[0]

    @classmethod
    def from_config(cls, cfg: Config) -> "PSSession":
        n = max(1, cfg.num_server)
        # Single-host convention: servers at scheduler_port+1+i.  Multi-host
        # deployments list hosts via BYTEPS_TPU_PS_HOSTS=host:port,host:port.
        import os
        spec = os.environ.get("BYTEPS_TPU_PS_HOSTS", "")
        if spec:
            pairs = [s.rsplit(":", 1) for s in spec.split(",") if s]
            hosts = [p[0] for p in pairs]
            ports = [int(p[1]) for p in pairs]
        else:
            hosts = [cfg.scheduler_uri] * n
            ports = [cfg.scheduler_port + 1 + i for i in range(n)]
        return cls(hosts, ports, cfg.worker_id, n, cfg.key_hash_fn,
                   partition_bytes=cfg.partition_bytes,
                   scheduling_credit=cfg.scheduling_credit,
                   min_compress_bytes=cfg.min_compress_bytes,
                   wire_conns=cfg.wire_conns,
                   compress_threads=cfg.compress_threads,
                   reconnect_attempts=cfg.reconnect_attempts,
                   reconnect_backoff_ms=cfg.reconnect_backoff_ms,
                   stall_timeout_s=cfg.stall_timeout_s,
                   barrier_timeout_s=cfg.barrier_timeout_s,
                   clock_sync_s=cfg.clock_sync_s,
                   uds_path=cfg.server_uds,
                   sock_buf_kb=cfg.sock_buf_kb,
                   evict_timeout_s=cfg.evict_timeout_s,
                   ring=cfg.ring,
                   ring_vnodes=cfg.ring_vnodes,
                   server_evict_timeout_s=cfg.server_evict_timeout_s,
                   audit=cfg.audit,
                   audit_window=cfg.audit_window,
                   fleet=cfg.fleet,
                   fleet_windows=cfg.fleet_windows,
                   health_sample_rounds=cfg.health_sample_rounds,
                   slice_size=cfg.slice_size)

    def set_lr_scale(self, scale: float) -> None:
        """One-shot EF-error rescale after a learning-rate change;
        `scale` = prev_lr / new_lr (reference `lr.s` mechanism; see
        WireCompressor.set_lr_scale).

        Covers BOTH EF legs: the local worker-side errors, and — from
        worker 0 only, so N workers don't compound the rescale N times —
        the servers' recompress-leg errors via CMD_LR_SCALE.  Call between
        steps on every worker (each owns its local errors).
        """
        for comp in self._compressors.values():
            comp.set_lr_scale(scale)
        if self.worker_id == 0:
            payload = struct.pack("<f", float(scale))
            for c in self.conns:
                c.request(CMD_LR_SCALE, 0, payload,
                          worker_id=self.worker_id)

    def register_compressor(self, declared_key: int, kwargs: dict) -> None:
        """Register an inter-node compressor for a tensor's PS traffic.

        Must be called before the tensor's first push_pull: the kwargs are
        shipped to the server in each partition's INIT (the
        kCompressedPushPull analog, reference: operations.cc:396-408,
        server.cc:232-261), and the server builds its decompress-sum(-
        recompress) path from them.
        """
        from .wire import WireCompressor
        self._compressors[declared_key] = WireCompressor(
            {str(k): str(v) for k, v in kwargs.items()})

    # -- per-key codec renegotiation (CMD_CODEC) ----------------------------
    @staticmethod
    def _kwargs_to_str(kwargs: Optional[dict]) -> str:
        """Canonical kwargs string for a codec proposal ("" = raw) —
        normalized through WireCompressor so every worker proposing the
        same config emits the same bytes (the server compares strings)."""
        if not kwargs:
            return ""
        from .wire import WireCompressor
        return WireCompressor(
            {str(k): str(v) for k, v in kwargs.items()}).kwargs_string()

    @staticmethod
    def _kwargs_from_str(kwstr: str) -> Optional[dict]:
        if not kwstr:
            return None
        return dict(kv.split("=", 1) for kv in kwstr.split(",") if "=" in kv)

    def _codec_pkeys(self, declared_key: int) -> list:
        """This key's already-declared partition keys that actually ride
        the codec (>= the MIN_COMPRESS_BYTES floor — smaller partitions
        always go raw, so renegotiating them would only manufacture
        CODEC_STALE noise)."""
        return sorted(
            pk for pk, (ln, _) in self._inited.items()
            if pk >> 16 == declared_key and ln >= self.min_compress_bytes)

    def propose_codec(self, declared_key: int, kwargs: Optional[dict],
                      margin_rounds: int = 2,
                      effective_round: Optional[int] = None) -> dict:
        """Propose switching ``declared_key``'s wire codec (None = raw),
        atomically at a future round boundary.

        Sends an epoch-versioned CMD_CODEC SET for each of the key's
        codec-eligible partitions to its owner server ("applied only if
        newer", the CMD_RING_SET idempotency law — racing proposers
        converge on one winner, and the losers adopt the winner's doc
        from the response).  The switch takes effect at the first round
        boundary at/after ``effective_round`` (default: the key's current
        round + ``margin_rounds``); workers that miss the memo are caught
        by the server's format check and replay via CODEC_STALE, so no
        round ever mixes wire formats.  Returns {"accepted", "epoch",
        "effective_round", "doc"}."""
        import json as _json
        kwstr = self._kwargs_to_str(kwargs)
        pkeys = self._codec_pkeys(declared_key)
        if not pkeys:
            # Never pushed (or every partition below the compress floor):
            # there is no wire state to renegotiate — install locally so
            # the first INIT ships the new config.
            with self._codec_lock:
                self._apply_codec_locked(declared_key, kwstr, epoch=0)
            return {"accepted": True, "epoch": 0, "effective_round": 0,
                    "doc": None}
        with self._codec_lock:
            epoch = self._codec_epoch.get(declared_key, 0) + 1
        eff = (int(effective_round) if effective_round is not None
               else max(self._round.get(pk, 0) for pk in pkeys)
               + max(1, int(margin_rounds)))
        kb = kwstr.encode()
        payload = struct.pack("<IQI", epoch, eff, len(kb)) + kb
        best: Optional[dict] = None
        for pk in pkeys:
            srv = self._pkey_srv.get(pk, 0)
            for attempt in range(3):
                conn = self.conns[srv]
                try:
                    resp = conn.request(CMD_CODEC, pk, payload,
                                        worker_id=self.worker_id,
                                        flags=1, timeout=30.0)
                except _KeyMoved as e:
                    # Ring transition mid-proposal: adopt, re-aim at the
                    # new owner, retry (bounded — a healthy ring settles
                    # in one hop).
                    self._safe_adopt_ring(e.doc)
                    srv = self._pkey_srv.get(pk, srv)
                    continue
                except RuntimeError as e:
                    raise RuntimeError(
                        "CMD_CODEC failed — server too old for codec "
                        "renegotiation (rebuild libbyteps_core.so)"
                    ) from e
                doc = _json.loads(bytes(resp).decode())
                if best is None or int(doc.get("epoch", 0)) > int(
                        best.get("epoch", 0)):
                    best = doc
                break
        accepted = bool(best) and int(best.get("epoch", -1)) == epoch and (
            (int(best.get("pending", 0)) == 1
             and best.get("kwargs_next", "") == kwstr)
            or (int(best.get("pending", 0)) == 0
                and best.get("kwargs", "") == kwstr))
        if best is not None:
            self._adopt_codec_doc(declared_key, best)
        get_logger().info(
            "codec proposal for key %d (%s): %s -> %r at round >= %d "
            "(epoch %d)", declared_key, self._label(declared_key),
            "accepted" if accepted else "superseded", kwstr or "raw",
            eff, epoch)
        return {"accepted": accepted, "epoch": epoch,
                "effective_round": eff, "doc": best}

    def poll_codec(self) -> None:
        """Refresh this session's view of every renegotiated key's codec
        doc (CMD_CODEC GET on the key's first eligible partition) — how a
        non-proposing worker learns of pending switches BEFORE its round
        counter crosses the boundary; the CODEC_STALE replay remains the
        correctness backstop either way.  Keys this session has never
        seen renegotiated are not polled (nothing to refresh, no wire
        noise) — they discover switches through CODEC_STALE."""
        import json as _json
        with self._codec_lock:
            dks = list(self._codec_epoch)
        for dk in dks:
            pkeys = self._codec_pkeys(dk)
            if not pkeys:
                continue
            pk = pkeys[0]
            try:
                resp = self.conns[self._pkey_srv.get(pk, 0)].request(
                    CMD_CODEC, pk, b"", worker_id=self.worker_id,
                    timeout=10.0)
                self._adopt_codec_doc(dk, _json.loads(bytes(resp).decode()))
            except Exception as e:
                get_logger().debug("codec poll for key %d failed: %s",
                                   dk, e)

    def _adopt_codec_doc(self, declared_key: int, doc: dict) -> None:
        """Fold one authoritative codec doc into the local table: apply
        anything the server already applied (epoch-gated), stage anything
        still pending for the stage-time boundary check."""
        with self._codec_lock:
            epoch = int(doc.get("epoch", 0))
            applied = int(doc.get("applied_epoch", 0))
            if applied > self._codec_applied.get(declared_key, 0):
                self._apply_codec_locked(declared_key,
                                         str(doc.get("kwargs", "")),
                                         applied)
            if (int(doc.get("pending", 0))
                    and epoch > self._codec_applied.get(declared_key, 0)):
                self._codec_next[declared_key] = {
                    "epoch": epoch,
                    "effective_round": int(doc.get("effective_round", 0)),
                    "kwargs_str": str(doc.get("kwargs_next", "")),
                }
            if epoch > self._codec_epoch.get(declared_key, 0):
                self._codec_epoch[declared_key] = epoch

    def _apply_codec_locked(self, declared_key: int, kwstr: str,
                            epoch: int) -> None:
        """Install ``kwstr`` ("" = raw) as the key's active codec (caller
        holds _codec_lock).  The EF-across-switch law: residuals carried
        by the outgoing compressor transfer to the new one when both run
        vanilla EF, and otherwise stage per-partition folds that the next
        push adds in — accumulated error is never dropped."""
        from .wire import WireCompressor
        old = self._compressors.get(declared_key)
        kw = self._kwargs_from_str(kwstr)
        new = WireCompressor(kw) if kw else None
        if old is not None and getattr(old, "ef", False):
            err = old.take_ef_state()
            if new is not None and new.ef:
                new.adopt_ef_state(err)
            else:
                for pk, e in err.items():
                    prev = self._ef_fold.get(pk)
                    self._ef_fold[pk] = (e if prev is None
                                         or prev.size != e.size
                                         else prev + e)
        if old is not None and new is not None \
                and getattr(old, "momentum_mu", 0.0) \
                and new.momentum_mu == old.momentum_mu:
            # Same momentum law on both sides: carry the velocity too.
            with old._state_lock:
                mom, old._mom = old._mom, {}
            with new._state_lock:
                new._mom.update(mom)
        if new is not None:
            self._compressors[declared_key] = new
        else:
            self._compressors.pop(declared_key, None)
        self._codec_applied[declared_key] = epoch
        self._codec_epoch[declared_key] = max(
            self._codec_epoch.get(declared_key, 0), epoch)
        pend = self._codec_next.get(declared_key)
        if pend is not None and pend["epoch"] <= epoch:
            self._codec_next.pop(declared_key, None)
        if epoch > 0:
            with self._transport_lock:
                self._tstats["codec_switches"] += 1
            label = self._label(declared_key)
            comp_id = new.comp_id if new is not None else 0
            try:
                from ..common import telemetry as _tm
                _tm.get_registry().gauge(
                    "bps_codec_active", labels={"key": label},
                    help="active wire codec per key (0=raw 1=onebit "
                         "2=topk 3=randomk 4=dithering 5=qblock)"
                ).set(comp_id)
            except Exception:
                pass
            _flightrec.record("codec_switch", key=label, epoch=epoch,
                              kwargs=kwstr, comp_id=comp_id,
                              worker=self.worker_id)
            get_logger().info(
                "codec switch applied: key %s -> %s (epoch %d)",
                label, kwstr or "raw", epoch)

    def _current_compressor(self, declared_key: int, plan) -> object:
        """The compressor to stage this push with, applying any pending
        renegotiation whose effective round the key has reached — the
        worker half of the atomic switch (the server applies its half at
        the same round's first push).  Safe here: the sequential-use
        guard means the previous round's encodes fully completed before
        this round stages, so no encoder still holds the old state."""
        pend = self._codec_next.get(declared_key)
        if pend is not None:
            rnd = max((self._round.get(pk, 0) for pk, _, _, _ in plan),
                      default=0)
            if rnd >= pend["effective_round"]:
                with self._codec_lock:
                    pend = self._codec_next.get(declared_key)
                    if pend is not None and rnd >= pend["effective_round"]:
                        self._apply_codec_locked(
                            declared_key, pend["kwargs_str"],
                            pend["epoch"])
        return self._compressors.get(declared_key)

    def codec_table(self) -> dict:
        """Per-key codec state for tooling (bps.get_tuner / bps_top):
        {label: {"epoch", "applied_epoch", "name", "pending",
        "effective_round"}} for every key whose codec epoch advanced."""
        out = {}
        with self._codec_lock:
            for dk, ep in self._codec_epoch.items():
                comp = self._compressors.get(dk)
                pend = self._codec_next.get(dk)
                out[self._label(dk)] = {
                    "declared_key": dk,
                    "epoch": ep,
                    "applied_epoch": self._codec_applied.get(dk, 0),
                    "name": getattr(comp, "name", None) or "raw",
                    "pending": (dict(pend) if pend else None),
                }
        return out

    # -- CODEC_STALE replay (the renegotiation race backstop) ---------------
    def _on_codec_stale(self, pkey: int, phase: str,
                        err: "_CodecStale") -> None:
        """A push was rejected for carrying the wrong wire format: park
        the partition and hand it — with the authoritative codec doc —
        to the retry worker, which adopts the doc, re-encodes the SAME
        staged gradient with the right codec, and replays.  Runs on a
        receiver-callback thread, so it must never block."""
        claimed = self._park_for_remap(pkey, phase)
        with self._transport_lock:
            self._tstats["codec_stale_retries"] += 1
        with self._codec_lock:
            self._codec_retry_queue.append((pkey if claimed else None,
                                            err.doc))
            if self._codec_retry_thread is None:
                self._codec_retry_thread = threading.Thread(
                    target=self._codec_retry_loop, daemon=True,
                    name="bps-ps-codec-retry")
                self._codec_retry_thread.start()

    def _codec_retry_loop(self) -> None:
        while True:
            with self._codec_lock:
                if not self._codec_retry_queue:
                    self._codec_retry_thread = None
                    return
                pkey, doc = self._codec_retry_queue.pop(0)
            try:
                if doc:
                    self._adopt_codec_doc((pkey if pkey is not None
                                           else int(doc.get("key", 0)))
                                          >> 16, doc)
            except Exception:
                get_logger().exception("codec doc adoption failed")
            if pkey is None:
                continue
            with self._inflight_lock:
                part = self._inflight.get(pkey)
            if part is None or not self._unpark(part):
                continue
            part.stale_retries += 1
            if part.stale_retries > 4:
                # Bounded like every other replay path (_KeyMoved is
                # bounded by ring settlement): a mismatch that survives
                # several authoritative-doc adoptions is a config
                # disagreement (e.g. this worker's MIN_COMPRESS_BYTES
                # floor excludes a partition the proposer renegotiated)
                # — fail the handle loudly instead of replaying the
                # same rejected push forever while the round wedges.
                self._finish_part(pkey, RuntimeError(
                    f"push for key {pkey} was rejected CODEC_STALE "
                    f"{part.stale_retries} times in a row despite "
                    f"adopting the server's codec doc each time — the "
                    f"re-encoded format still mismatches the table "
                    f"(check that BYTEPS_MIN_COMPRESS_BYTES and codec "
                    f"config agree across workers)"))
                continue
            try:
                self._reencode_part(part)
            except Exception as e:
                self._finish_part(pkey, e)
                continue
            with self._transport_lock:
                self._tstats["replayed_pushes"] += 1
            with self._cv:
                self._queue.add(part.pkey, part.priority, part.credit_ln)
                self._cv.notify_all()

    def _reencode_part(self, part: "_PartTask") -> None:
        """Re-produce one rejected partition's wire payload under the
        key's CURRENT codec.  The input is what the rejected payload
        would have delivered (its decode) — so for an EF codec whose
        residual already moved to the new compressor at switch time, the
        conservation law holds exactly: decode(old) + carried residual
        == gradient + pre-switch residual."""
        from .wire import decode as wire_decode
        n = part.ln // 4
        if part.dtype == DT_COMPRESSED and part.payload is not None:
            x = wire_decode(bytes(part.payload), n)
        elif part.seg is not None:
            x = np.ascontiguousarray(part.seg, np.float32)
        else:
            x = np.frombuffer(bytes(part.payload), np.float32).copy()
        dk = part.pkey >> 16
        comp = self._compressors.get(dk)
        fold = self._ef_fold.pop(part.pkey, None)
        use_comp = (comp is not None
                    and part.dtype in (DT_F32, DT_COMPRESSED)
                    and part.ln >= self.min_compress_bytes)
        if fold is not None and fold.size == n:
            if use_comp and comp.ef:
                comp.adopt_ef_state({part.pkey: fold})
            else:
                x = x + fold
        if use_comp:
            blob = comp.encode(part.pkey, x)
            part.payload = blob
            part.wire_ln = len(blob)
            part.dtype = DT_COMPRESSED
            part.bidirectional = comp.bidirectional
        else:
            buf = np.ascontiguousarray(x, np.float32)
            part.payload = buf.tobytes()
            part.wire_ln = part.ln
            part.dtype = DT_F32
            part.bidirectional = False
        part.phase = "push"
        part.ready = None   # payload is materialized; dispatcher sends it

    # -- global knob plane (CMD_KNOB) ---------------------------------------
    # The CMD_CODEC epoch law generalized to the job's GLOBAL performance
    # knobs: one epoch-versioned kwargs table per fleet, three actuated
    # knobs (fusion_bytes / compress_threads / wire_conns), applied on
    # every participant at the first round boundary at/after the declared
    # effective round — so no round ever mixes fusion layouts, pool
    # sizes, or lane sets — with the KNOB_STALE push rejection as the
    # backstop for workers that miss the memo.

    ACTUATED_KNOBS = ("fusion_bytes", "compress_threads", "wire_conns")

    @staticmethod
    def _knob_kwargs_to_str(kwargs: Optional[dict]) -> str:
        """Canonical "k=v,k=v" string for a knob proposal: sorted keys,
        integer values — every worker proposing the same config emits
        the same bytes (the server compares epochs, not strings, but the
        doc round-trips through this form)."""
        if not kwargs:
            return ""
        return ",".join(f"{k}={int(kwargs[k])}" for k in sorted(kwargs))

    @staticmethod
    def _knob_kwargs_from_str(kwstr: str) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for kv in (kwstr or "").split(","):
            if "=" in kv:
                k, v = kv.split("=", 1)
                try:
                    out[k.strip()] = int(v)
                except ValueError:
                    pass
        return out

    def current_round(self) -> int:
        """This session's round high-water mark — the boundary proxy the
        knob plane compares against effective_round (all keys advance in
        lockstep under sync rounds)."""
        return max(self._round.values(), default=0)

    def note_fusion_keys(self, declared_keys) -> None:
        """Register declared keys whose IDENTITY derives from the fusion
        plan (bucket/solo units).  Only these may be withdrawn with
        KnobReplan when FUSION_BYTES changes; everything else replays in
        place (its key is layout-independent)."""
        self._fusion_keys.update(int(dk) for dk in declared_keys)

    def propose_knobs(self, kwargs: dict, margin_rounds: int = 2,
                      effective_round: Optional[int] = None) -> dict:
        """Propose new values for the GLOBAL actuated knobs, atomically
        at a future round boundary.

        Sends one epoch-versioned CMD_KNOB SET to EVERY server (the
        table is global — a ring drain must find the same epoch on every
        owner): "applied only if newer", the CMD_RING_SET idempotency
        law, so racing proposers converge and the losers adopt the
        winner's doc from the response.  The switch takes effect at the
        first round boundary at/after ``effective_round`` (default: the
        session's current round + ``margin_rounds``) on the servers and
        on every worker; workers that miss the memo are caught by the
        per-worker acked check and recover via KNOB_STALE.  Returns
        {"accepted", "epoch", "effective_round", "doc"}."""
        import json as _json
        unknown = set(kwargs) - set(self.ACTUATED_KNOBS)
        if unknown:
            raise ValueError(
                f"not actuated knob(s) {sorted(unknown)}: the knob plane "
                f"actuates {list(self.ACTUATED_KNOBS)} only (everything "
                f"else is launch-only; see docs/performance.md)")
        kwstr = self._knob_kwargs_to_str(kwargs)
        with self._knob_lock:
            epoch = self._knob_epoch + 1
        eff = (int(effective_round) if effective_round is not None
               else self.current_round() + max(1, int(margin_rounds)))
        kb = kwstr.encode()
        payload = struct.pack("<IQI", epoch, eff, len(kb)) + kb
        best: Optional[dict] = None
        for conn in self.conns:
            try:
                resp = conn.request(CMD_KNOB, 0, payload,
                                    worker_id=self.worker_id,
                                    flags=1, timeout=30.0)
            except RuntimeError as e:
                raise RuntimeError(
                    "CMD_KNOB failed — server too old for the knob "
                    "plane (rebuild libbyteps_core.so)") from e
            doc = _json.loads(bytes(resp).decode())
            if best is None or int(doc.get("epoch", 0)) > int(
                    best.get("epoch", 0)):
                best = doc
        accepted = bool(best) and int(best.get("epoch", -1)) == epoch and (
            (int(best.get("pending", 0)) == 1
             and best.get("kwargs_next", "") == kwstr)
            or (int(best.get("pending", 0)) == 0
                and best.get("kwargs", "") == kwstr))
        if accepted:
            # The SET doubled as this worker's ACK server-side; mirror
            # that locally so the boundary apply won't re-ack.
            with self._knob_lock:
                if epoch > self._knob_acked:
                    self._knob_acked = epoch
        if best is not None:
            self._adopt_knob_doc(best)
        get_logger().info(
            "knob proposal %r: %s at round >= %d (epoch %d)",
            kwstr, "accepted" if accepted else "superseded", eff, epoch)
        return {"accepted": accepted, "epoch": epoch,
                "effective_round": eff, "doc": best}

    def poll_knobs(self) -> Optional[dict]:
        """Refresh this session's view of the global knob table (CMD_KNOB
        GET against server 0) — how a non-proposing worker learns of a
        pending switch BEFORE its round crosses the boundary; KNOB_STALE
        remains the correctness backstop either way.  Returns the doc
        (None on transport trouble — the backstop covers it)."""
        import json as _json
        if not self.conns:
            return None
        try:
            resp = self.conns[0].request(CMD_KNOB, 0, b"",
                                         worker_id=self.worker_id,
                                         timeout=10.0)
            doc = _json.loads(bytes(resp).decode())
        except Exception:
            return None
        self._adopt_knob_doc(doc)
        return doc

    def knob_table(self) -> dict:
        """This session's live view of the knob plane (the bps_top /
        tuner introspection surface)."""
        with self._knob_lock:
            return {
                "epoch": self._knob_epoch,
                "applied_epoch": self._knob_applied,
                "acked_epoch": self._knob_acked,
                "live": dict(self._knob_live),
                "pending": (dict(self._knob_next)
                            if self._knob_next else None),
                "fusion_gen": self._knob_gen,
                "history": [dict(h) for h in self._knob_history[-8:]],
            }

    def live_fusion_bytes(self) -> Optional[int]:
        """The actuated FUSION_BYTES value, or None while launch config
        rules.  Applies a staged switch whose boundary this call's round
        has reached — the fusion planner reads this per dispatch, which
        is exactly the re-plan actuation point (bucket identity is
        composition-derived, so a new value re-declares new keys via
        idempotent CMD_INIT)."""
        self._maybe_apply_knobs()
        with self._knob_lock:
            v = self._knob_live.get("fusion_bytes")
            return None if v is None else int(v)

    def _maybe_apply_knobs(self, rnd: Optional[int] = None) -> None:
        """Worker half of the boundary apply: install the staged knob
        table once this session's round reaches its effective round —
        the same boundary the server applies its half, so no round mixes
        configurations.  Called at stage time (every _stage) and from
        live_fusion_bytes; a session with no staged switch pays one
        attribute read."""
        if self._knob_next is None:
            return
        ack = None
        with self._knob_lock:
            pend = self._knob_next
            if pend is None:
                return
            if rnd is None:
                rnd = self.current_round()
            if rnd < pend["effective_round"]:
                return
            self._apply_knobs_locked(pend["kwargs_str"], pend["epoch"],
                                     pend["effective_round"])
            self._knob_next = None
            if pend["epoch"] > self._knob_acked:
                ack = pend["epoch"]
        if ack is not None:
            self._ack_knobs(ack)

    def _apply_knobs_locked(self, kwstr: str, epoch: int,
                            eff: int) -> bool:
        """Install one knob kwargs string as the ACTIVE table (caller
        holds _knob_lock).  Returns True when the fusion LAYOUT changed
        (the generation bumped) — the caller then defers the ACK until
        stale-generation pushes have left the wire."""
        kv = self._knob_kwargs_from_str(kwstr)
        applied: Dict[str, int] = {}
        fusion_changed = False
        if "fusion_bytes" in kv:
            val = max(0, int(kv["fusion_bytes"]))
            if self._knob_live.get("fusion_bytes") != val:
                self._knob_gen += 1
                self._knob_fusion_eff = max(1, int(eff))
                fusion_changed = True
            self._knob_live["fusion_bytes"] = val
            applied["fusion_bytes"] = val
        if "compress_threads" in kv:
            val = max(1, int(kv["compress_threads"]))
            if self._codec_pool is not None:
                # Resize without dropping staged work (grow = start
                # threads now; shrink = surplus threads exit between
                # jobs).  threads=0 sessions have no pool: 0 <-> N stays
                # launch-only, documented in docs/performance.md.
                self._codec_pool.resize(val)
                self.compress_threads = val
                self._knob_live["compress_threads"] = val
                applied["compress_threads"] = val
        if "wire_conns" in kv:
            val = max(1, int(kv["wire_conns"]))
            self._resize_lanes(val)
            self._knob_live["wire_conns"] = val
            applied["wire_conns"] = val
        self._knob_applied = max(self._knob_applied, int(epoch))
        self._knob_history.append({"epoch": int(epoch),
                                   "effective_round": int(eff),
                                   "kwargs": kwstr,
                                   "ts": time.time()})
        del self._knob_history[:-32]
        with self._transport_lock:
            self._tstats["knob_switches"] += 1
        try:
            from ..common import telemetry as _tm
            reg = _tm.get_registry()
            reg.gauge("bps_knob_epoch",
                      help="newest applied global knob epoch"
                      ).set(int(epoch))
            for name, val in applied.items():
                reg.gauge("bps_knob_value", labels={"knob": name},
                          help="live value of an actuated global knob"
                          ).set(val)
            reg.counter("bps_knob_switches_total",
                        help="global knob-table applications"
                        ).inc()
        except Exception:
            pass
        _flightrec.record("knob_switch", epoch=int(epoch),
                          kwargs=kwstr, effective_round=int(eff),
                          fusion_gen=self._knob_gen,
                          worker=self.worker_id)
        get_logger().info(
            "knob switch applied (epoch %d, round >= %d): %r%s",
            epoch, eff, kwstr,
            " [fusion re-plan]" if fusion_changed else "")
        return fusion_changed

    def _resize_lanes(self, n: int) -> None:
        """WIRE_CONNS actuation: dial every server's data-lane pool to
        `n` sockets.  Growing dials new lanes immediately (the
        _apply_ring joiner path's move); shrinking marks surplus lanes
        RETIRING — excluded from _pick_lane, so no new dispatch lands on
        them — and a drain worker closes each once its outstanding bytes
        and pending requests hit zero.  The primary conn (control
        traffic) never retires."""
        n = max(1, int(n))
        self._wire_conns = n
        to_drain: List[tuple] = []
        for srv, pool in enumerate(self._data_conns):
            if srv in self._dead_slots:
                continue
            primary = (self.conns[srv] if srv < len(self.conns)
                       else pool[0] if pool else None)
            live = [c for c in pool if not c.retiring]
            if len(live) < n:
                # Reactivate retiring lanes first (a shrink->grow bounce
                # must not leak half-drained sockets), then dial fresh.
                for c in pool:
                    if len(live) >= n:
                        break
                    if c.retiring:
                        c.retiring = False
                        live.append(c)
                anchor = live[0] if live else primary
                while len(live) < n and anchor is not None:
                    c = self._make_conn(anchor.host, anchor.port)
                    pool.append(c)
                    live.append(c)
            elif len(live) > n:
                for c in reversed(pool):
                    if len(live) <= n:
                        break
                    if c.retiring or c is primary:
                        continue
                    c.retiring = True
                    live.remove(c)
                    to_drain.append((pool, c))
        if to_drain:
            threading.Thread(target=self._drain_retired_lanes,
                             args=(to_drain,), daemon=True,
                             name="bps-ps-lane-drain").start()

    def _drain_retired_lanes(self, to_drain: List[tuple]) -> None:
        """Close retiring lanes once quiet: outstanding byte credit
        returned AND no response outstanding — a lane is never cut with
        a round trip in flight, so a WIRE_CONNS shrink can never lose a
        push ack or a pull payload."""
        deadline = time.monotonic() + 60.0
        for pool, c in to_drain:
            while time.monotonic() < deadline:
                with c._pending_lock:
                    busy = bool(c._pending)
                if c.outstanding_bytes <= 0 and not busy:
                    break
                time.sleep(0.02)
            else:
                get_logger().warning(
                    "retiring lane %s:%d still busy after drain window; "
                    "closing anyway", c.host, c.port)
            try:
                pool.remove(c)
            except ValueError:
                pass
            try:
                c.close()
            except Exception:
                pass

    def _ack_knobs(self, epoch: int) -> None:
        """Report adoption of knob epoch `epoch` to every server (the
        per-worker acked map is what the push-path backstop checks).
        Best effort: a lost ACK just means one more KNOB_STALE round
        trip — the backstop is idempotent."""
        payload = struct.pack("<I", int(epoch))
        for conn in self.conns:
            try:
                conn.request(CMD_KNOB, 0, payload,
                             worker_id=self.worker_id, flags=2,
                             timeout=10.0)
            except Exception as e:
                get_logger().warning(
                    "knob ACK (epoch %d) to %s:%d failed: %s — the "
                    "KNOB_STALE backstop will retry", epoch,
                    conn.host, conn.port, e)
        with self._knob_lock:
            if int(epoch) > self._knob_acked:
                self._knob_acked = int(epoch)

    def _adopt_knob_doc(self, doc: dict, defer_ack: bool = False) -> None:
        """Adopt the authoritative knob doc (SET/GET response or a
        KNOB_STALE payload): record the newest epoch, apply the ACTIVE
        table when the server already crossed the boundary, stage the
        pending one otherwise.  With defer_ack (the stale path), a
        fusion-layout change holds the ACK until the stale-generation
        flight drains (see _knob_retry_loop)."""
        ack = None
        with self._knob_lock:
            ep = int(doc.get("epoch", 0))
            if ep > self._knob_epoch:
                self._knob_epoch = ep
            applied = int(doc.get("applied_epoch", 0))
            if applied > self._knob_applied:
                fusion_changed = self._apply_knobs_locked(
                    doc.get("kwargs", ""), applied,
                    int(doc.get("effective_round", 0)))
                if self._knob_next is not None and \
                        self._knob_next["epoch"] <= applied:
                    self._knob_next = None
                if applied > self._knob_acked:
                    if defer_ack and fusion_changed:
                        self._knob_ack_due = applied
                        self._knob_ack_deadline = \
                            time.monotonic() + 30.0
                    else:
                        ack = applied
            if int(doc.get("pending", 0)) and ep > self._knob_applied:
                self._knob_next = {
                    "epoch": ep,
                    "effective_round": int(doc.get("effective_round", 0)),
                    "kwargs_str": doc.get("kwargs_next", ""),
                }
        if ack is not None:
            self._ack_knobs(ack)

    # -- KNOB_STALE replay (the knob renegotiation race backstop) -----------
    def _on_knob_stale(self, pkey: int, phase: str,
                       err: "_KnobStale") -> None:
        """A push was rejected because this worker missed a knob switch:
        park the partition and hand it — with the authoritative doc — to
        the retry worker.  Runs on a receiver-callback thread, so it
        must never block."""
        claimed = self._park_for_remap(pkey, phase)
        with self._transport_lock:
            self._tstats["knob_stale_retries"] += 1
        with self._knob_lock:
            self._knob_retry_queue.append((pkey if claimed else None,
                                           err.doc))
            if self._knob_retry_thread is None:
                self._knob_retry_thread = threading.Thread(
                    target=self._knob_retry_loop, daemon=True,
                    name="bps-ps-knob-retry")
                self._knob_retry_thread.start()

    def _knob_retry_loop(self) -> None:
        """Adopt-and-recover worker for KNOB_STALE rejections.

        Order matters: (1) adopt the doc and APPLY the switch (the
        server already crossed the boundary — that is why it rejected
        us); (2) while a fusion-layout change holds the ACK, withdraw
        every stale-generation part that is parked or queued (the
        dispatcher gate catches queued ones too) and WAIT for the ones
        already on the wire to resolve — the server keeps rejecting them
        until the ACK lands, which is exactly the guarantee that no
        old-layout push can merge into an orphaned bucket key AFTER the
        ACK re-admits this worker; (3) send the ACK; (4) replay the
        rejected parts whose keys are layout-independent in place."""
        pending_parts: List[int] = []
        while True:
            with self._knob_lock:
                item = (self._knob_retry_queue.pop(0)
                        if self._knob_retry_queue else None)
                if (item is None and self._knob_ack_due is None
                        and not pending_parts):
                    self._knob_retry_thread = None
                    return
            if item is not None:
                pkey, doc = item
                try:
                    if doc:
                        self._adopt_knob_doc(doc, defer_ack=True)
                except Exception:
                    get_logger().exception("knob doc adoption failed")
                if pkey is not None:
                    pending_parts.append(pkey)
            # ACK gate: a deferred ACK goes out only once no stale-
            # generation push can still reach the server.
            with self._knob_lock:
                due = self._knob_ack_due
                deadline = getattr(self, "_knob_ack_deadline", 0.0)
            if due is not None:
                parked_stale: List[_PartTask] = []
                busy = False
                with self._inflight_lock:
                    for p in self._inflight.values():
                        if (p.knob_gen != self._knob_gen
                                and p.phase == "push"
                                and p.round >= self._knob_fusion_eff):
                            if p.parked:
                                parked_stale.append(p)
                            elif p.conn is not None:
                                busy = True   # on the wire: rejection due
                for p in parked_stale:
                    if self._unpark(p):
                        pending_parts = [k for k in pending_parts
                                         if k != p.pkey]
                        self._finish_part(p.pkey, KnobReplan(
                            f"push for key {p.pkey} withdrawn: a "
                            f"FUSION_BYTES switch re-partitioned the "
                            f"tree (generation {p.knob_gen} -> "
                            f"{self._knob_gen}) — re-plan and "
                            f"re-dispatch"))
                if not busy or time.monotonic() > deadline:
                    if busy:
                        get_logger().warning(
                            "knob ACK (epoch %d) released with stale-"
                            "generation pushes still in flight after "
                            "the drain window", due)
                    with self._knob_lock:
                        if self._knob_ack_due == due:
                            self._knob_ack_due = None
                    self._ack_knobs(due)
                else:
                    time.sleep(0.005)
                    continue
            # Replay/withdraw the rejected parts now that the ACK (if
            # any) is out — an in-place replay sent before the ACK would
            # only be rejected again.
            if pending_parts:
                todo, pending_parts = pending_parts, []
                for pkey in todo:
                    self._knob_retry_part(pkey)

    def _knob_retry_part(self, pkey: int) -> None:
        """Replay one KNOB_STALE-rejected partition in place, or fail it
        with KnobReplan when its key's identity died with the old
        fusion plan."""
        with self._inflight_lock:
            part = self._inflight.get(pkey)
        if part is None or not self._unpark(part):
            return
        if (part.knob_gen != self._knob_gen
                and part.round >= self._knob_fusion_eff
                and (pkey >> 16) in self._fusion_keys):
            self._finish_part(pkey, KnobReplan(
                f"push for key {pkey} withdrawn: a FUSION_BYTES switch "
                f"re-partitioned the tree (generation {part.knob_gen} "
                f"-> {self._knob_gen}) — re-plan and re-dispatch"))
            return
        part.stale_retries += 1
        if part.stale_retries > 4:
            # Bounded like the CODEC_STALE replay: a push still rejected
            # after several adopt-and-ack cycles means the acked epoch
            # keeps moving under us (knob thrash) or a server/worker
            # disagreement — fail the handle loudly instead of replaying
            # forever while the round wedges.
            self._finish_part(pkey, RuntimeError(
                f"push for key {pkey} was rejected KNOB_STALE "
                f"{part.stale_retries} times in a row despite adopting "
                f"the server's knob doc each time — check for knob "
                f"thrash (bps doctor: knob_thrash)"))
            return
        part.phase = "push"
        # Stamp the current generation: the part survives THIS switch
        # (its key is layout-independent), so the dispatcher gate must
        # not withdraw it.
        part.knob_gen = self._knob_gen
        with self._transport_lock:
            self._tstats["replayed_pushes"] += 1
        with self._cv:
            self._queue.add(part.pkey, part.priority, part.credit_ln)
            self._cv.notify_all()

    # -- server-resident optimizer plane (CMD_OPT) --------------------------
    @staticmethod
    def _opt_kwargs_to_str(kwargs: Optional[dict]) -> str:
        """Canonical kwargs string for an optimizer declaration ("" =
        off): ``opt`` leads, the remaining hyperparams follow sorted,
        float values ride ``repr()`` — the shortest decimal that
        round-trips, which the server's strtod parses back to the
        IDENTICAL f64 the worker-local optax baseline holds.  The
        f32-exact equivalence law starts at this string."""
        if not kwargs:
            return ""
        kw = {str(k): v for k, v in kwargs.items()}
        name = str(kw.pop("opt", "sgd"))
        parts = [f"opt={name}"]
        for k in sorted(kw):
            v = kw[k]
            parts.append(
                f"{k}={repr(float(v)) if isinstance(v, float) else v}")
        return ",".join(parts)

    def _opt_pkeys(self, declared_key: int) -> list:
        """ALL of this key's partition keys — unlike the codec table,
        the optimizer plane covers every partition (a sub-floor raw
        partition's slice of the params updates server-side exactly
        like a compressed one's).  Once armed, derived from the plan
        rather than `_inited`: a ring transition invalidates the moved
        partitions' `_inited` rows until their next push, and the doc
        surface must keep covering them (the drain test reads slots_crc
        on BOTH sides of the handoff)."""
        with self._codec_lock:
            rec = self._opt_armed.get(declared_key)
        if rec and rec.get("nbytes"):
            return sorted(pk for pk, _, _, _ in
                          self._plan(declared_key, rec["nbytes"]))
        return sorted(pk for pk in self._inited
                      if pk >> 16 == declared_key)

    def propose_opt(self, declared_key: int, kwargs,
                    effective_round: int = 0) -> dict:
        """Declare (or switch) ``declared_key``'s server-resident
        optimizer, atomically at a round boundary.

        Sends an epoch-versioned CMD_OPT SET for each declared partition
        to its owner ("applied only if newer" — the CMD_CODEC law, so
        every worker declaring the same trainer config is idempotent and
        racing proposers converge on one winner).  The mode takes effect
        at the first round boundary at/after ``effective_round``; from
        that round on the key publishes post-update *parameters* instead
        of sums.  ``kwargs`` is a dict like ``{"opt": "adam", "lr":
        1e-3, ...}`` (or a pre-canonicalized string); None/"" switches
        the update stage off.  Returns {"accepted", "epoch", "doc"}."""
        import json as _json
        kwstr = (kwargs if isinstance(kwargs, str)
                 else self._opt_kwargs_to_str(kwargs))
        pkeys = self._opt_pkeys(declared_key)
        if not pkeys:
            raise RuntimeError(
                f"propose_opt: key {declared_key} has no declared "
                f"partitions yet — arm_server_opt() declares them first")
        with self._codec_lock:
            rec = self._opt_armed.get(declared_key) or {}
            epoch = int(rec.get("epoch", 0)) + 1
        kb = kwstr.encode()
        payload = struct.pack("<IQI", epoch, int(effective_round),
                              len(kb)) + kb
        best: Optional[dict] = None
        for pk in pkeys:
            srv = self._pkey_srv.get(pk, 0)
            doc = None
            for _attempt in range(3):
                conn = self.conns[srv]
                try:
                    resp = conn.request(CMD_OPT, pk, payload,
                                        worker_id=self.worker_id,
                                        flags=1, timeout=30.0)
                except _KeyMoved as e:
                    self._safe_adopt_ring(e.doc)
                    srv = self._pkey_srv.get(pk, srv)
                    continue
                except RuntimeError as e:
                    raise RuntimeError(
                        "CMD_OPT failed — server too old for the "
                        "server-resident optimizer plane (rebuild "
                        "libbyteps_core.so)") from e
                doc = _json.loads(bytes(resp).decode())
                if best is None or int(doc.get("epoch", 0)) > int(
                        best.get("epoch", 0)):
                    best = doc
                break
            if doc is None:
                # A half-armed key is silent corruption (some partitions
                # would keep publishing sums the trainer adopts as
                # params, and their opt_mode 0 keeps the doctor quiet) —
                # every partition must take the declaration, or nobody
                # trains on it.
                raise RuntimeError(
                    f"ring kept moving while declaring the server "
                    f"optimizer for partition {pk} of key "
                    f"{declared_key}; declaration aborted (retry once "
                    f"the ring settles)")
        accepted = bool(best) and int(best.get("epoch", -1)) == epoch and (
            (int(best.get("pending", 0)) == 1
             and best.get("kwargs_next", "") == kwstr)
            or (int(best.get("pending", 0)) == 0
                and best.get("kwargs", "") == kwstr))
        with self._codec_lock:
            rec = self._opt_armed.setdefault(declared_key, {})
            rec["epoch"] = max(int(rec.get("epoch", 0)),
                               int(best.get("epoch", epoch))
                               if best else epoch)
            if best is not None:
                rec["kwargs_str"] = (best.get("kwargs_next")
                                     or best.get("kwargs") or kwstr)
            else:
                rec["kwargs_str"] = kwstr
        get_logger().info(
            "server-opt proposal for key %d (%s): %s -> %r at round >= "
            "%d (epoch %d)", declared_key, self._label(declared_key),
            "accepted" if accepted else "superseded", kwstr or "off",
            int(effective_round), epoch)
        return {"accepted": accepted, "epoch": epoch, "doc": best}

    def seed_params(self, declared_key: int, flat) -> None:
        """Bootstrap the key's initial parameters to each partition's
        owner (CMD_OPT flags bit1): raw f32, applied only while the
        server holds none — idempotent across workers shipping the same
        broadcast weights, a no-op against migrated-in state."""
        flat = np.ascontiguousarray(np.asarray(flat), np.float32).ravel()
        plan = self._plan(declared_key, flat.nbytes)
        mv = memoryview(flat).cast("B")
        for pkey, off, ln, srv in plan:
            payload = bytes(mv[off:off + ln])
            srv_i = self._pkey_srv.get(pkey, srv)
            for _attempt in range(3):
                try:
                    self.conns[srv_i].request(
                        CMD_OPT, pkey, payload,
                        worker_id=self.worker_id, flags=2, timeout=60.0)
                    break
                except _KeyMoved as e:
                    self._safe_adopt_ring(e.doc)
                    srv_i = self._pkey_srv.get(pkey, srv_i)
            else:
                # An unseeded partition never updates (param_version
                # stalls while its siblings train) — fail the bootstrap
                # loudly instead.
                raise RuntimeError(
                    f"ring kept moving while seeding params for "
                    f"partition {pkey} of key {declared_key}; seed "
                    f"aborted (retry once the ring settles)")

    def arm_server_opt(self, declared_key: int, params, opt_kwargs,
                       params_fn=None, effective_round: int = 0) -> dict:
        """One-call bootstrap for the parameter-pull session mode:
        declare the key's partitions (idempotent CMD_INIT, carrying the
        key's current codec kwargs so the push-leg compression contract
        is untouched), send the epoch-versioned optimizer declaration to
        each partition's owner, and seed the initial parameters.

        ``params_fn`` (optional but recommended) returns the caller's
        CURRENT flat f32 params — the re-seed source when a
        post-failover fresh owner answers round 0 for this key
        (ServerOptTrainer wires its adopted view in here)."""
        flat = np.ascontiguousarray(np.asarray(params), np.float32).ravel()
        comp = self._compressors.get(declared_key)
        kw_bytes = comp.kwargs_string().encode() if comp else b""
        plan = self._plan(declared_key, flat.nbytes)
        self._init_parts(plan, kw_bytes)
        res = self.propose_opt(declared_key, opt_kwargs,
                               effective_round=effective_round)
        self.seed_params(declared_key, flat)
        with self._codec_lock:
            rec = self._opt_armed.setdefault(declared_key, {})
            rec["params_fn"] = params_fn
            rec["nbytes"] = flat.nbytes
        return res

    def fetch_opt_docs(self, declared_key: int,
                       timeout: float = 10.0) -> dict:
        """{pkey: authoritative opt doc} via CMD_OPT GET on each of the
        key's partitions — param_version / opt_step / slots_crc, the
        exactly-one-update audit surface tests and tooling read."""
        import json as _json
        out = {}
        for pk in self._opt_pkeys(declared_key):
            srv = self._pkey_srv.get(pk, 0)
            for _attempt in range(3):
                try:
                    resp = self.conns[srv].request(
                        CMD_OPT, pk, b"", worker_id=self.worker_id,
                        timeout=timeout)
                except _KeyMoved as e:
                    self._safe_adopt_ring(e.doc)
                    srv = self._pkey_srv.get(pk, srv)
                    continue
                out[pk] = _json.loads(bytes(resp).decode())
                break
        return out

    def opt_table(self) -> dict:
        """Local view of the armed server-opt keys (the codec_table()
        analog for tooling): {label: {"declared_key", "epoch",
        "kwargs"}}."""
        out = {}
        with self._codec_lock:
            for dk, rec in self._opt_armed.items():
                out[self._label(dk)] = {
                    "declared_key": dk,
                    "epoch": int(rec.get("epoch", 0)),
                    "kwargs": rec.get("kwargs_str", ""),
                }
        return out

    def _opt_rebase_reseed(self, conn: "_ServerConn",
                           part: "_PartTask") -> None:
        """A server answered a round BEHIND ours for an opt-armed key (a
        restart, or a SIGKILL failover handed the range to a fresh owner
        with no migrated state): re-declare the optimizer config and
        re-seed this partition's params slice from the trainer's adopted
        view, so the rebased rounds continue the trajectory.  Stateless
        modes (sgd) recover bit-identically — the params after round r
        are exactly what every worker pulled; stateful slots
        (momentum/adam m, v) cannot be rebuilt from the workers and
        restart zeroed (docs/server-optimizer.md "Failover"; drain and
        scale-up migrate them byte-equal instead)."""
        dk = part.pkey >> 16
        with self._codec_lock:
            rec = dict(self._opt_armed.get(dk) or {})
        if not rec or rec.get("params_fn") is None:
            return
        try:
            # Probe first: a replication-armed ring hands the fresh owner
            # the replicated params/m/v (docs/elasticity.md "zero-loss
            # law"), so a rebase onto an owner that already HOLDS params
            # must not re-seed (the server would ignore the flags&2 seed
            # anyway) and must not count an opt_reseed — the counter is
            # the proof surface for slot continuity.
            import json as _json
            doc = _json.loads(bytes(conn.request(
                CMD_OPT, part.pkey, b"", worker_id=self.worker_id,
                timeout=10.0)).decode())
            if int(doc.get("param_version", 0)) > 0 \
                    or int(doc.get("params_n", 0)) > 0:
                get_logger().info(
                    "server-opt key %d: owner %s:%d already holds "
                    "params (param_version=%s) — skipping re-seed",
                    part.pkey, conn.host, conn.port,
                    doc.get("param_version"))
                return
        except Exception:
            pass    # probe is best-effort; fall through to the re-seed
        try:
            kwstr = rec.get("kwargs_str", "")
            kb = kwstr.encode()
            payload = struct.pack("<IQI", int(rec.get("epoch", 1)), 0,
                                  len(kb)) + kb
            conn.request(CMD_OPT, part.pkey, payload,
                         worker_id=self.worker_id, flags=1, timeout=30.0)
            flat = np.ascontiguousarray(
                np.asarray(rec["params_fn"]()), np.float32).ravel()
            mv = memoryview(flat).cast("B")
            conn.request(CMD_OPT, part.pkey,
                         bytes(mv[part.off:part.off + part.ln]),
                         worker_id=self.worker_id, flags=2, timeout=60.0)
            with self._transport_lock:
                self._tstats["opt_reseeds"] += 1
            get_logger().warning(
                "server-opt key %d: re-seeded optimizer config + params "
                "onto %s:%d after rebase", part.pkey, conn.host,
                conn.port)
        except Exception:
            get_logger().exception(
                "server-opt re-seed for key %d failed (rounds will "
                "publish sums and param_version will stall)", part.pkey)

    # -- partition planning -------------------------------------------------
    def _plan(self, declared_key: int, nbytes: int) -> list:
        """[(pkey, offset, length, server_idx)] for a tensor of `nbytes`
        bytes.

        Partition bounds and key encoding come from the native core; server
        placement uses the configured hash over the partition key, with
        accumulated per-server load logged like the reference's placement
        summary (reference: global.cc:643-692, 675-682).  The LANE within
        a server's pool is deliberately NOT planned here: it is picked at
        dispatch time by byte credit (_pick_lane), so a large fused bucket
        in flight can never head-of-line-block small high-priority
        partitions onto the same socket.
        """
        with self._plan_lock:
            cached = self._plans.get((declared_key, nbytes))
            if cached is not None:
                return cached
            core = get_core()
            bounds = core.partition_bounds(nbytes, self.partition_bytes)
            plan = []
            for idx, (off, ln) in enumerate(bounds):
                pkey = core.encode_key(declared_key, idx)
                if self._ring is not None:
                    # Ring placement (the elastic law, common/ring.py):
                    # owner id -> this session's conn slot.  The server
                    # enforces the same law once the epoch advances, so a
                    # stale plan self-corrects via status MOVED.
                    with self._ring_lock:
                        srv = self._srv_slot[self._ring.owner(pkey)]
                else:
                    srv = core.key_to_server(pkey, len(self.conns),
                                             self.hash_fn)
                self._server_load[srv] += ln
                plan.append((pkey, off, ln, srv))
                self._pkey_srv[pkey] = srv
            self._plans[(declared_key, nbytes)] = plan
            total = sum(self._server_load) or 1
        get_logger().debug(
            "PS placement: tensor key=%d parts=%d; server load %s",
            declared_key, len(plan),
            ["%.1f%%" % (100.0 * l / total) for l in self._server_load])
        return plan

    def _pick_lane(self, srv: int, nbytes: int) -> _ServerConn:
        """Byte-credit lane pick: the lane of server `srv` with the least
        outstanding payload bytes wins (ties broken by fewest lifetime
        sends, so idle lanes still rotate), charged with this partition's
        push + expected pull bytes until the round trip settles
        (_lane_settle).  Replaces the plan-time round-robin stripe, which
        let a 4MB fused bucket head-of-line-block a late high-priority
        partition assigned to the same socket."""
        conn = self._pick_lane_from(self._data_conns[srv])
        conn.lane_charge(nbytes)
        return conn

    @staticmethod
    def _pick_lane_from(pool) -> _ServerConn:
        """Least-loaded pick among the "up" lanes of one server's pool
        (static so the scheduler policy is unit-testable on stub conns).
        Retiring lanes (a WIRE_CONNS shrink draining outstanding bytes
        before close) never take new work unless they are ALL that's
        left mid-transition."""
        if len(pool) == 1:
            return pool[0]
        live = [c for c in pool
                if not getattr(c, "retiring", False)] or pool
        up = [c for c in live if c.state() == "up"] or live
        return min(up, key=lambda c: (c.outstanding_bytes, c.lane_sends))

    def _lane_settle(self, part: "_PartTask") -> None:
        """Return a partition's outstanding-byte charge to its lane —
        idempotent, called wherever the partition leaves the wire (pull
        completed, parked for replay, or failed)."""
        debt, part.lane_debt = part.lane_debt, 0
        if debt and part.conn is not None:
            part.conn.lane_return(debt)

    # -- dispatcher ---------------------------------------------------------
    def _dispatch_loop(self) -> None:
        while True:
            with self._cv:
                while not self._closed and (
                        self._paused or self._queue.pending() == 0):
                    self._cv.wait()
                if self._closed:
                    return
                task = self._queue.get()
                if task is None:
                    # Credit exhausted: wait for report_finish to return it.
                    self._cv.wait(timeout=1.0)
                    continue
            pkey, _prio, nbytes = task
            with self._inflight_lock:
                part = self._inflight.get(pkey)
            if part is None:  # cancelled (session closing)
                self._queue.report_finish(nbytes)
                continue
            if part.parked:
                # Parked mid-queue (ring remap / server failover claimed
                # it before this entry popped): return the credit and let
                # the replay path re-enqueue it against the new owner.
                self._queue.report_finish(nbytes)
                with self._cv:
                    self._cv.notify_all()
                continue
            if (part.knob_gen != self._knob_gen
                    and part.phase == "push"
                    and part.round >= self._knob_fusion_eff
                    and (pkey >> 16) in self._fusion_keys):
                # A FUSION_BYTES switch landed between staging and
                # dispatch: this part's bucket key no longer exists in
                # the fleet's layout at/past the switch round.  Sending
                # it would merge old-layout bytes into an orphaned key
                # (or leave a solo key one contributor short forever) —
                # withdraw it and let the fusion layer re-plan.
                self._queue.report_finish(nbytes)
                with self._cv:
                    self._cv.notify_all()
                self._finish_part(pkey, KnobReplan(
                    f"push for key {pkey} withdrawn before dispatch: a "
                    f"FUSION_BYTES switch re-partitioned the tree "
                    f"(generation {part.knob_gen} -> {self._knob_gen}) "
                    f"— re-plan and re-dispatch"))
                continue
            if self.record_push_order:
                self.push_order.append(pkey)
            if part.ready is not None and not part.ready.is_set():
                # Codec pipeline: the pool encodes in this same
                # (priority desc, key asc) order ahead of this loop, so
                # the wait is the pipeline-fill case (first partition) or
                # an encoder still catching up — either way the pool keeps
                # working k+1 while k's bytes go out below.
                while not part.ready.wait(timeout=1.0):
                    with self._cv:
                        if self._closed:
                            self._queue.report_finish(nbytes)
                            return
            if part.enc_err is not None:
                self._queue.report_finish(nbytes)
                with self._cv:
                    self._cv.notify_all()
                self._finish_part(pkey, part.enc_err)
                continue
            core = get_core()
            if core.trace_on and part.enq_ts:
                part.push_ts = core.trace_now_us()
                core.trace_record_part(part.label, "QUEUE", part.enq_ts,
                                       part.push_ts - part.enq_ts, pkey,
                                       part.wire_ln, part.priority)
            part.send_mono = time.monotonic()
            if part.enq_mono:
                self._m_queue_wait.observe(part.send_mono - part.enq_mono)
            # Byte-credit lane pick, charged with the push payload plus
            # the expected pull reply (both legs ride this conn).
            self._lane_settle(part)     # replays drop any stale charge
            part.conn = self._pick_lane(part.srv, part.wire_ln + part.ln)
            part.lane_debt = part.wire_ln + part.ln
            try:
                part.conn.send(
                    CMD_PUSH, pkey, part.payload, worker_id=self.worker_id,
                    dtype=part.dtype,
                    flags=_round_flags(part.round, core.trace_on),
                    callback=lambda data, err, pkey=pkey, nbytes=nbytes:
                        self._on_push_ack(pkey, nbytes, err))
            except ConnectionError as e:
                self._queue.report_finish(nbytes)
                if not self._park_part(pkey, "push", e):
                    self._finish_part(pkey, e)

    def _on_push_ack(self, pkey: int, nbytes: int,
                     error: Optional[Exception]) -> None:
        # Push landed on the server: return its credit (the reference
        # reportFinish, scheduled_queue.cc:197-203) and issue the pull.
        self._queue.report_finish(nbytes)
        with self._cv:
            self._cv.notify_all()
        if error is not None:
            # Ring redirect: the server handed the key's state to its new
            # owner and told us so — park the partition and replay it
            # there (same gradient, so no round is lost and the server's
            # seen-dedup keeps it single-counted).
            if isinstance(error, _KeyMoved):
                self._on_key_moved(pkey, "push", error)
                return
            # Codec renegotiation race: the push carried the wrong wire
            # format for the round being merged — re-encode the same
            # gradient under the authoritative codec and replay.
            if isinstance(error, _CodecStale):
                self._on_codec_stale(pkey, "push", error)
                return
            # Global knob renegotiation race: this worker missed a knob
            # switch — adopt the table, apply, ACK, then replay in place
            # (pool/lane knobs) or withdraw for re-plan (fusion layout).
            if isinstance(error, _KnobStale):
                self._on_knob_stale(pkey, "push", error)
                return
            # A reconnect-tagged loss parks the partition for replay (the
            # ack never arrived, so the push phase must be re-run — the
            # server's seen-dedup and the stale-round push guard make the
            # replay idempotent); anything else fails the handle as before.
            if not self._park_part(pkey, "push", error):
                self._finish_part(pkey, error)
            return
        self._mark_progress()
        with self._inflight_lock:
            part = self._inflight.get(pkey)
            if part is not None:
                part.phase = "pull"   # push acked: only the pull remains
        if part is None:
            return
        part.ack_mono = time.monotonic()
        if part.send_mono:
            self._m_push_rtt.observe(part.ack_mono - part.send_mono)
        core = get_core()
        if core.trace_on and part.push_ts:
            part.pull_ts = core.trace_now_us()
            core.trace_record_part(part.label, "PUSH", part.push_ts,
                                   part.pull_ts - part.push_ts, pkey,
                                   part.wire_ln, part.priority)
        try:
            self._issue_pull(part)
        except ConnectionError as e:
            if not self._park_part(pkey, "pull", e):
                self._finish_part(pkey, e)

    def _issue_pull(self, part: "_PartTask") -> None:
        """Send one partition's pull leg (first issue and replay share
        this).  Raises ConnectionError if the conn can't take it."""
        # Non-compressed pulls land straight in the output buffer (the
        # receiver matches on length); bidirectional compressed pulls
        # come back re-encoded at a different length and take the
        # allocating path + wire_decode.  sink_live guards the in-place
        # write against a handle whose wait() already timed out.
        #
        # With the auditor armed, the response is payload + 24 trailer
        # bytes, so the zero-copy sink cannot length-match: audited pulls
        # ride a pooled buffer instead and _complete_pull splits/verifies
        # before landing the body (one extra body copy per pull — the
        # armed-only cost BENCH_AUDIT=1 measures; the unarmed path is
        # untouched).  Health-SAMPLED rounds skip the sink for the same
        # reason: the pooled payload routes through the codec pool, so
        # the O(n) non-finite scan never runs on the receiver thread.
        part.audit = self._audit_wire
        health_due = (self._health is not None
                      and self._health.pull_due(part.round))
        sink = None
        if not part.bidirectional and not part.audit and not health_due:
            sink = memoryview(part.handle.out).cast("B")[
                part.off:part.off + part.ln]
        part.conn.send(
            CMD_PULL, part.pkey, worker_id=self.worker_id,
            dtype=DT_AUDIT_PULL if part.audit else 0,
            flags=_round_flags(part.round, get_core().trace_on),
            sink=sink,
            sink_live=lambda h=part.handle: not h.failed(),
            pool_ok=True,
            callback=lambda data, err, pkey=part.pkey:
                self._on_pull(pkey, data, err))

    def _on_pull(self, pkey: int, data: bytes,
                 error: Optional[Exception]) -> None:
        if error is not None:
            # Ring redirect on the pull leg: the published round migrated
            # with the key — re-pull from the new owner.
            if isinstance(error, _KeyMoved):
                self._on_key_moved(pkey, "pull", error)
                return
            # Pull leg lost to a recoverable drop: the push WAS acked, so
            # replay re-issues only the pull (round flags unchanged — the
            # server serves completed_round or pends until it publishes).
            if not self._park_part(pkey, "pull", error):
                self._finish_part(pkey, error)
            return
        self._mark_progress()
        with self._inflight_lock:
            part = self._inflight.pop(pkey, None)
            if part is not None:
                # Bump inside the lock: a waiter in push_pull_async must see
                # the new round the moment the key leaves _inflight.
                self._round[pkey] = part.round + 1
        if part is None:
            if isinstance(data, _PooledBuf):
                data.release()
            return
        self._lane_settle(part)     # round trip done: return lane credit
        if _signals.plane() is not None:
            # Per-key timer feed for the windowed signal plane: one call
            # per completed partition round trip, module-None-checked so
            # an unarmed run (SIGNAL_WINDOW_S=0) pays a single global
            # read.  serve = push-ack -> pull-data: the server's merge
            # wait on peers' pushes (+ the pull wire) — the always-on
            # straggler component.
            now_m = time.monotonic()
            _signals.note_part(
                part.label or f"key_{pkey >> 16}",
                part.ln, part.ln, wire_bytes=part.wire_ln,
                queue_s=(part.send_mono - part.enq_mono
                         if part.enq_mono and part.send_mono else 0.0),
                rtt_s=(part.ack_mono - part.send_mono
                       if part.send_mono and part.ack_mono else 0.0),
                serve_s=(now_m - part.ack_mono if part.ack_mono
                         else 0.0))
        core = get_core()
        if core.trace_on and part.pull_ts:
            core.trace_record_part(part.label, "PULL", part.pull_ts,
                                   core.trace_now_us() - part.pull_ts, pkey,
                                   len(data), part.priority)
        if (self._codec_pool is not None
                and not isinstance(data, memoryview)
                and (part.audit
                     or (self._health is not None
                         and self._health.pull_due(part.round))
                     or (part.bidirectional
                         and len(data) != part.ln))):
            # Compressed pull payload: decode OFF the receiver thread, so
            # one slow decode cannot stall every other partition's
            # response parsing on this socket (the reference's DECOMPRESS
            # loop thread, core_loops.cc:618-646).  The part already left
            # _inflight above, so a staged re-push of the same key
            # proceeds while this round's payload decodes.  Audited pulls
            # route here too: the digest pass (and the body copy the
            # trailer forces) runs on a codec thread, not the receiver.
            try:
                self._codec_pool.submit(
                    part.priority, pkey,
                    lambda part=part, data=data:
                        self._complete_pull(part, data))
                return
            except RuntimeError:
                pass    # pool already closing: finish inline below
        self._complete_pull(part, data)

    def _complete_pull(self, part: "_PartTask", data) -> None:
        """Land one pull payload in the handle's output buffer.

        Runs on the receiver thread for raw/sink payloads (a straight
        frombuffer/no-op), and on a codec pool thread for compressed
        payloads (wire_decode is real work) — inline mode
        (compress_threads=0) keeps everything on the receiver thread.
        """
        core = get_core()
        verify = None
        try:
            n = part.ln // 4
            if isinstance(data, memoryview):
                # Sink path: the receiver already landed the payload in
                # part.handle.out (length-matched) — nothing to copy.
                pass
            else:
                raw = data.mv if isinstance(data, _PooledBuf) else data
                if part.audit:
                    # Audited pull: the last 24 bytes are the server's
                    # publish-digest trailer.  Stripping is immediate;
                    # the digest pass itself is DEFERRED until after the
                    # handle resolves (bottom of this function) — the
                    # auditor observes, it never fails the handle, so
                    # its CRC belongs off the round's critical path.
                    raw, verify = self._audit_split(part, raw)
                if part.bidirectional and len(raw) != part.ln:
                    # Bidirectional compressor: the merged buffer came back
                    # re-compressed; decode it (reference: worker DECOMPRESS
                    # stage, core_loops.cc:618-646) — straight from the
                    # (pooled) receive view INTO the handle's output slice:
                    # no bytes() snapshot, no scratch f32 array, no copy
                    # pass.  Writing into `out` directly mirrors the raw
                    # sink path's contract (out is session-allocated and
                    # wait() never returns it after a failure), so the
                    # failed() check only skips dead work.
                    from .wire import decode as wire_decode
                    t0 = (core.trace_now_us()
                          if core.trace_on
                          or self._codec_pool is not None
                          or _signals.plane() is not None
                          else 0)
                    if part.handle.failed():
                        get_logger().debug(
                            "discarding late pull for key %d: handle "
                            "already timed out", part.pkey)
                    else:
                        off = part.off // 4
                        wire_decode(raw, n,
                                    out=part.handle.out[off:off + n])
                    if t0:
                        dur = core.trace_now_us() - t0
                        if core.trace_on:
                            core.trace_record_part(
                                part.label, "DECODE", t0, dur, part.pkey,
                                len(raw), part.priority)
                        if self._codec_pool is not None:
                            self._codec_pool.record("DECODE", dur)
                        _signals.note_codec(
                            part.label or f"key_{part.pkey >> 16}",
                            "decode", dur)
                else:
                    got = np.frombuffer(raw, np.float32)
                    if got.size != n:
                        raise ValueError(
                            f"PS pull size mismatch for key {part.pkey}: "
                            f"got {got.size} f32, want {n}")
                    if not part.handle._store_result(part.off // 4, got):
                        get_logger().debug(
                            "discarding late pull for key %d: handle "
                            "already timed out", part.pkey)
            if self._health is not None and not part.handle.failed():
                # Pull-side value health: the landed sum, sampled at the
                # monitor's cadence — a NaN storm that originated on
                # ANOTHER worker is caught here within the same round.
                off = part.off // 4
                self._health.check_pull(
                    part.label, part.round,
                    part.handle.out[off:off + n], worker=self.worker_id)
            part.handle._part_done(pkey=part.pkey)
            if part.handle.done() and not part.handle.failed():
                # Flight-recorder round marker: one event per tensor per
                # completed sync round — the timeline postmortem.py merges
                # across workers to name where trajectories diverged.
                _flightrec.record(
                    "round", key=part.label.rsplit(".part", 1)[0],
                    round=part.round)
            if verify is not None:
                # Digest + verdict AFTER the handle resolved: the caller
                # is already staging the next round while this CRC runs
                # (on the codec pool thread the audited path rode in
                # on).  The pooled buffer is still checked out — release
                # below happens strictly after.
                verify()
        except Exception as e:
            part.handle._part_done(e, pkey=part.pkey)
        finally:
            if isinstance(data, _PooledBuf):
                data.release()
            part.done_evt.set()

    def _finish_part(self, pkey: int, error: Exception) -> None:
        with self._inflight_lock:
            part = self._inflight.pop(pkey, None)
        if part is not None:
            self._lane_settle(part)
            part.handle._part_done(error, pkey=pkey)
            part.done_evt.set()

    # -- fault tolerance: parking, replay, watchdog -------------------------
    def _mark_progress(self) -> None:
        self._last_progress = time.monotonic()

    def _park_part(self, pkey: int, phase: str,
                   error: Exception) -> bool:
        """Stash an in-flight partition for post-reconnect replay instead
        of failing its handle.  Only recoverable drops park (`_ConnLost`
        with an active reconnect policy); returns False when the caller
        should fail the partition as before.  Idempotent: the send-raise
        and drop-resolution paths can both observe one loss.  Server
        failover (server_evict_timeout_s > 0) arms parking too: a drop
        must hold partitions until the lease scanner rules the server
        dead (ring transition + remap to the new owner) or merely
        rebooting (re-dial + replay)."""
        recovery_armed = (self.reconnect_attempts > 0
                          or self.server_evict_timeout_s > 0)
        if not (recovery_armed
                and isinstance(error, _ConnLost) and error.will_reconnect):
            return False
        if getattr(self, "server_async", False) and phase == "push":
            # Async mode has no rounds: the server can't tell a replayed
            # push (whose ack was lost AFTER the sum applied) from a new
            # delta — neither the seen-dedup nor the stale-round guard is
            # active.  An at-least-once push would silently double-apply
            # the gradient, so async push losses fail loudly instead of
            # parking (pull legs are idempotent and still replay).
            return False
        with self._inflight_lock:
            part = self._inflight.get(pkey)
            if part is None:
                return True     # already finished/cancelled elsewhere
            if part.parked:
                return True     # the other path got here first
            part.parked = True
            part.phase = phase
        self._lane_settle(part)    # parked work holds no lane credit
        with self._transport_lock:
            self._tstats["parked_parts"] += 1
            self._tstats["parked_total"] += 1
        get_logger().debug("parked partition key=%d phase=%s (%s)",
                           pkey, phase, error)
        if part.conn.state() == "up" and part.conn.on_reconnect is not None:
            # The conn finished re-dialing before this parking landed (a
            # fast re-dial can beat the thread that observed the loss), so
            # the post-reconnect replay scan ran too early to see this
            # part and no future drop is guaranteed — kick another pass.
            # Idempotent: replay_lock serializes passes and _unpark lets
            # exactly one claim each part.
            threading.Thread(target=part.conn._run_on_reconnect,
                             daemon=True, name="bps-ps-replay").start()
        return True

    def _unpark(self, part: "_PartTask") -> bool:
        """Atomically claim a parked part for replay (False if another
        replay pass already took it or it finished meanwhile)."""
        with self._inflight_lock:
            if self._inflight.get(part.pkey) is not part or not part.parked:
                return False
            part.parked = False
        with self._transport_lock:
            self._tstats["parked_parts"] -= 1
        return True

    def _on_conn_gave_up(self, conn: "_ServerConn", exc: Exception) -> None:
        """Reconnect budget exhausted: everything parked on this conn fails
        loudly now (the fail-fast contract, just delayed by the backoff)."""
        with self._transport_lock:
            self._tstats["reconnects_failed"] += 1
        _flightrec.record("conn_gave_up", host=conn.host, port=conn.port,
                          error=str(exc), worker=self.worker_id)
        with self._inflight_lock:
            mine = [p for p in self._inflight.values()
                    if p.conn is conn and p.parked]
        err = ConnectionError(
            f"PS reconnect to {conn.host}:{conn.port} gave up after "
            f"{conn.reconnect_attempts} attempts: {exc}")
        for p in mine:
            self._finish_part(p.pkey, err)

    def _on_conn_reconnected(self, conn: "_ServerConn") -> None:
        """Post-reconnect handshake + replay (runs on the conn's replay
        thread, serialized by conn.replay_lock).

        Order matters: (1) HELLO re-checks the server's mode flags — a
        replacement server booted with different async/schedule settings
        would silently corrupt training; (2) the conn's keys drop out of
        `_inited` so the next stage re-declares and re-seeds rounds from
        server state; (3) every parked partition is re-declared via
        CMD_INIT, reconciled against the server's completed_round (skip
        the push if its round already published — never double-count;
        rebase the round if the server restarted and lost it), then
        replayed in (priority desc, key asc) order — pushes through the
        scheduler/dispatcher, pull legs directly.
        """
        if not getattr(self, "_session_ready", False):
            return      # drop during __init__: nothing staged to replay yet
        if self._left:
            # A departed worker must NOT re-run the handshake: HELLO is
            # the join door, and re-sending it after leave() would
            # re-admit this worker into the membership — every future
            # round would then wait on pushes that are never coming.
            # (A deliberate rejoin is a NEW session, which HELLOs fresh.)
            self._fail_parked_on(conn, ConnectionError(
                "worker left the membership; not replaying"))
            return
        # The peer may be a RESTARTED process with a fresh steady_clock
        # epoch: its pre-restart offset history would place post-restart
        # trace spans wildly off the worker timeline.  Drop it; the next
        # sync/fetch re-estimates against the live process.
        conn_srv = next((i for i, pool in enumerate(self._data_conns)
                         if conn in pool), None)
        if conn_srv is not None:
            with self._clock_lock:
                self._clock_offsets.pop(conn_srv, None)
        try:
            mode = conn.request(
                CMD_HELLO, worker_id=self.worker_id,
                flags=HELLO_FLAG_OBSERVER if self.pull_only else 0)
            modes = ((bool(mode[0]), bool(mode[1]))
                     if len(mode) >= 2 else (False, False))
            if modes != (self.server_async, self.server_schedule):
                raise RuntimeError(
                    f"PS server at {conn.host}:{conn.port} came back with "
                    f"different mode flags (async, schedule): {modes} vs "
                    f"{(self.server_async, self.server_schedule)} — a "
                    f"replacement server must share BYTEPS_ENABLE_ASYNC / "
                    f"BYTEPS_SERVER_ENABLE_SCHEDULE settings")
        except ConnectionError as e:
            # Dropped again before the handshake finished: the next
            # reconnect cycle re-runs this whole procedure.
            get_logger().warning("PS reconnect handshake interrupted: %s", e)
            return
        except Exception as e:
            get_logger().error("PS reconnect handshake failed: %s", e)
            self._fail_parked_on(conn, e)
            return
        if self._audit_wire:
            # The peer may be a REPLACEMENT server booted without
            # BYTEPS_TPU_AUDIT: its pulls would carry no trailer, and a
            # marker-sending client would strip 24 bytes of real payload.
            # Downgrade the whole session loudly BEFORE any pull replays
            # (the auditor is an observer — losing it must never corrupt
            # the data path it watches).
            try:
                doc = self._audit_probe(conn)
                if not doc.get("armed"):
                    get_logger().error(
                        "PS server at %s:%d came back WITHOUT "
                        "BYTEPS_TPU_AUDIT; disabling pull auditing for "
                        "this session (redeploy the server audit-armed "
                        "to restore it)", conn.host, conn.port)
                    self._audit_wire = False
            except ConnectionError as e:
                get_logger().warning(
                    "PS reconnect audit re-probe interrupted: %s", e)
                return
            except Exception as e:
                get_logger().error(
                    "PS server at %s:%d no longer answers CMD_AUDIT "
                    "(%s); disabling pull auditing for this session",
                    conn.host, conn.port, e)
                self._audit_wire = False
        _flightrec.record("reconnected", host=conn.host, port=conn.port,
                          worker=self.worker_id)
        # Invalidate the re-declare cache for every key planned on this
        # conn's SERVER: a server restart lost its store sizes and round
        # counters, and the next _init_parts must re-seed from live state.
        # (Keys whose state survived just get a cheap idempotent re-INIT.)
        stale = [pkey for pkey, s in list(self._pkey_srv.items())
                 if s == conn_srv]
        for pkey in stale:
            self._inited.pop(pkey, None)
        with self._inflight_lock:
            mine = [p for p in self._inflight.values()
                    if p.conn is conn and p.parked]
        mine.sort(key=lambda p: (-p.priority, p.pkey))
        if mine:
            get_logger().warning(
                "replaying %d parked partition(s) on %s:%d",
                len(mine), conn.host, conn.port)
            _flightrec.record("replay", host=conn.host, port=conn.port,
                              parts=len(mine), worker=self.worker_id)
        for part in mine:
            try:
                self._replay_part(conn, part)
            except _KeyMoved as e:
                # The reconnected server no longer owns this key (a ring
                # transition landed during the outage): hand the part to
                # the remap path instead of failing it.
                self._on_key_moved(part.pkey, part.phase, e)
            except ConnectionError as e:
                # Dropped mid-replay: re-park; the next reconnect cycle
                # picks the remainder up.  (The part was already claimed
                # by _unpark, so re-park it explicitly.)  If the conn
                # meanwhile gave up for good, parking is refused — fail
                # the part so its handle never dangles.
                err = (e if isinstance(e, _ConnLost)
                       else conn._lost_exc(str(e)))
                if not self._park_part(part.pkey, part.phase, err):
                    self._finish_part(part.pkey, err)
                get_logger().warning(
                    "replay interrupted on %s:%d: %s", conn.host,
                    conn.port, e)
                return
            except Exception as e:
                self._finish_part(part.pkey, e)

    def _fail_parked_on(self, conn: "_ServerConn", exc: Exception) -> None:
        with self._inflight_lock:
            mine = [p for p in self._inflight.values()
                    if p.conn is conn and p.parked]
        for p in mine:
            self._finish_part(p.pkey, exc)

    def _replay_part(self, conn: "_ServerConn", part: "_PartTask") -> None:
        """Reconcile one parked partition against server state and replay
        the outstanding leg(s).  Never double-counts a push: the server's
        completed_round (from the idempotent re-INIT) tells whether the
        partition's round already published, the per-worker `seen` dedup
        absorbs a replay into a still-open round, and the server drops
        pushes whose round flag is stale."""
        if not self._unpark(part):
            return      # another replay pass or a failure beat us to it
        replay_push = self._reconcile_part(conn, part)
        if replay_push:
            # Back through the scheduler: replays dispatch in the same
            # (priority desc, key asc) order as first sends, and re-charge
            # the same credit (returned when the original send failed).
            with self._transport_lock:
                self._tstats["replayed_pushes"] += 1
            with self._cv:
                self._queue.add(part.pkey, part.priority, part.credit_ln)
                self._cv.notify_all()
        else:
            with self._transport_lock:
                self._tstats["replayed_pulls"] += 1
            # Pull-only replay: re-pick a live lane on the partition's
            # (possibly re-ringed) server and re-charge it for the reply
            # leg (the original charge was returned at park time).
            part.conn = self._pick_lane(part.srv, part.ln)
            part.lane_debt = part.ln
            self._issue_pull(part)

    def _reconcile_part(self, conn: "_ServerConn",
                        part: "_PartTask") -> bool:
        """Idempotent CMD_INIT against ``conn``'s server + round
        reconciliation for one partition; returns True when the push leg
        must (re)run.  Shared by the reconnect replay and the ring-remap
        path (where ``conn`` is the key's NEW owner — a fresh owner after
        failover answers completed_round 0 and the partition rebases,
        re-pushing the open round from gradient state)."""
        comp = self._compressors.get(part.pkey >> 16)
        kw_bytes = comp.kwargs_string().encode() if comp else b""
        init_payload = struct.pack("<QI", part.ln, len(kw_bytes)) + kw_bytes
        resp = conn.send(CMD_INIT, part.pkey, init_payload,
                         worker_id=self.worker_id).wait(60.0)
        (completed,) = struct.unpack("<Q", resp)
        self._inited[part.pkey] = (part.ln, kw_bytes)
        replay_push = part.phase == "push"
        if not self.server_async:
            if completed == part.round + 1:
                # The round published while we were away: our push WAS
                # counted (sync rounds publish only with all workers in),
                # so re-pushing would pollute the next round — pull only.
                replay_push = False
                part.phase = "pull"
            elif completed == part.round and part.phase == "pull" \
                    and self._repl_armed:
                # Replication failover: the fresh owner adopted the
                # successor's replica at the LAST publish boundary, so
                # round `part.round` is open again with an empty `seen`
                # set — every worker's push for it died with the old
                # owner even though each was individually acked.  Re-push
                # from gradient state; if the owner in fact survived (a
                # plain reconnect) its `seen` dedup drops the duplicate.
                get_logger().warning(
                    "PS server %s:%d at replica boundary for key %d "
                    "(completed=%d == staged round): re-pushing the open "
                    "round (repl failover; seen-dedup absorbs duplicates)",
                    conn.host, conn.port, part.pkey, completed)
                replay_push = True
                part.phase = "push"
            elif completed < part.round:
                # The server lost state (restart): rebase this partition
                # onto the server's round and re-push — the store is gone,
                # so the push must be re-applied regardless of phase.
                get_logger().warning(
                    "PS server %s:%d lost round state for key %d "
                    "(completed=%d < round=%d): rebasing and re-pushing",
                    conn.host, conn.port, part.pkey, completed, part.round)
                with self._inflight_lock:
                    part.round = completed
                    self._round[part.pkey] = completed
                replay_push = True
                part.phase = "push"
                # Opt-armed key on a state-less owner: re-declare the
                # optimizer + re-seed params BEFORE the push replays, so
                # the rebased round publishes parameters, not sums.
                self._opt_rebase_reseed(conn, part)
            elif completed > part.round + 1:
                raise RuntimeError(
                    f"PS server round state for key {part.pkey} is ahead "
                    f"of this worker by {completed - part.round} rounds "
                    f"(completed={completed}, staged round={part.round}) — "
                    f"another worker is reusing this worker_id?")
        if not replay_push:
            part.phase = "pull"
        return replay_push

    def _watchdog_loop(self) -> None:
        interval = max(0.2, min(self.stall_timeout_s / 4.0, 5.0))
        while not self._watchdog_stop.wait(interval):
            with self._inflight_lock:
                outstanding = list(self._inflight.values())
            if not outstanding:
                self._mark_progress()   # idle ≠ stalled
                continue
            elapsed = time.monotonic() - self._last_progress
            if elapsed < self.stall_timeout_s:
                continue
            self._dump_stall(outstanding, elapsed)
            with self._transport_lock:
                self._tstats["watchdog_trips"] += 1
            _flightrec.record(
                "stall", elapsed_s=round(elapsed, 2),
                stuck_keys=sorted(p.pkey for p in outstanding)[:16],
                worker=self.worker_id)
            # The black-box moment the flight recorder exists for: dump
            # the ring + local state into a postmortem bundle BEFORE
            # failing the handles (the evidence must survive whatever
            # the caller does with the error).
            _flightrec.dump_bundle("stall")
            err = RuntimeError(
                f"PS round stalled: no partition completed for "
                f"{elapsed:.1f}s (BYTEPS_TPU_STALL_TIMEOUT_S="
                f"{self.stall_timeout_s}); stuck keys: "
                f"{sorted(p.pkey for p in outstanding)[:16]}")
            for p in outstanding:
                self._finish_part(p.pkey, err)
            self._mark_progress()

    def _dump_stall(self, outstanding, elapsed: float) -> None:
        """Diagnostic snapshot before failing loudly — the worker-side
        analog of the ORDERING INVARIANT guard in server.cc."""
        lines = [
            f"PS STALL: no partition completed for {elapsed:.1f}s "
            f"(timeout={self.stall_timeout_s}s); "
            f"{len(outstanding)} partition(s) outstanding, "
            f"queue pending={self._queue.pending()}",
        ]
        for p in sorted(outstanding, key=lambda p: p.pkey):
            conn = (f"{p.conn.host}:{p.conn.port}[{p.conn.state()}]"
                    if p.conn is not None else "<undispatched>")
            lines.append(
                f"  key={p.pkey} round={p.round} phase={p.phase}"
                f" parked={p.parked} priority={p.priority}"
                f" bytes={p.wire_ln} conn={conn}")
        for i, pool in enumerate(self._data_conns):
            states = ",".join(c.state() for c in pool)
            dead = " [retired from ring]" if i in self._dead_slots else ""
            lines.append(f"  server[{i}] conns: {states}{dead}")
        # A dead SERVER reads as "slow keys" without this: name every
        # server whose entire lane pool is down, with the keys planned on
        # it — those keys are not slow, their store is unreachable (and,
        # with failover armed, about to be claimed by the survivors).
        for slot, host, port, owned in self._down_servers():
            shown = ", ".join(str(k) for k in owned[:16])
            if len(owned) > 16:
                shown += f", ... ({len(owned)} total)"
            lines.append(
                f"  server[{slot}] {host}:{port} is DOWN (every lane) — "
                f"owns {len(owned)} planned key(s): [{shown}]"
                + ("; failover armed: the surviving ring will claim them"
                   if self.server_evict_timeout_s > 0 else
                   "; these keys are unreachable, not slow"))
        with self._transport_lock:
            lines.append(f"  transport stats: {dict(self._tstats)}")
        # A stuck partition's round may be waiting on a peer that is GONE
        # (evicted/left), not merely slow — name it, so the operator (and
        # the log reader) stops hunting for a straggler that no longer
        # exists.  Best-effort: a dead server tier degrades to a note.
        try:
            m = self.membership(timeout=2.0)
            gone = sorted(w for w, r in m["workers"].items()
                          if not r["alive"])
            lines.append(
                f"  membership: epoch={m['epoch']} alive={m['alive']}"
                f" gone={gone}"
                + (" — stuck rounds re-finalize at the next epoch"
                   " transition; a gone peer is not coming back"
                   if gone else ""))
        except Exception as e:
            lines.append(f"  membership: unavailable ({e})")
        get_logger().error("%s", "\n".join(lines))

    # -- elastic membership: heartbeat, leave, membership view --------------
    def _lease_loop(self) -> None:
        """Keep this worker's server-side lease warm while idle: an
        untraced CMD_PING per server every third of the evict timeout.
        Fire-and-forget — a mid-reconnect conn just skips a beat (the
        re-dial's HELLO touches the lease anyway).

        Every few beats it also SELF-CHECKS the membership: a worker
        falsely evicted while its sockets stayed up (GC pause or stall
        just past the timeout) would otherwise become a silent zombie —
        every push acked-and-dropped as a non-member, its pulls still
        served, training "successfully" while contributing nothing.  On
        detecting its own eviction it logs loudly and re-HELLOs, which
        re-admits it at the next epoch boundary."""
        interval = max(0.05, self.evict_timeout_s / 3.0)
        beat = 0
        while not self._lease_stop.wait(interval):
            if self._left:
                return
            for c in self.conns:
                try:
                    c.send(CMD_PING, worker_id=self.worker_id,
                           callback=lambda data, err: None)
                except (ConnectionError, OSError):
                    pass
            beat += 1
            if beat % 3 == 0:       # ~once per evict timeout
                try:
                    self._readmit_if_evicted()
                except Exception as e:
                    get_logger().debug("membership self-check failed: %s",
                                       e)

    def _readmit_if_evicted(self) -> None:
        """Detect this worker's own (false) eviction and re-admit it via
        HELLO — see _lease_loop.  Safe to call any time; no-op while the
        membership agrees this worker is alive, or after leave()."""
        if self._left:
            return
        m = self.membership(timeout=5.0)
        rec = m["workers"].get(self.worker_id)
        if rec is None or rec["alive"]:
            return
        get_logger().error(
            "worker %d was evicted while still alive (lease lapsed — a "
            "stall longer than BYTEPS_TPU_EVICT_TIMEOUT_S=%.1fs?); "
            "re-admitting via HELLO.  Rounds merged while evicted did "
            "not include this worker's pushes.", self.worker_id,
            self.evict_timeout_s)
        _flightrec.record("evicted", worker=self.worker_id,
                          epoch=int(m.get("epoch", 0)), self_heal=True)
        # An eviction is a they-declared-us-dead moment: the bundle
        # preserves which rounds went on without this worker.
        _flightrec.dump_bundle("evicted")
        for c in self.conns:
            try:
                c.request(CMD_HELLO, worker_id=self.worker_id,
                          flags=HELLO_FLAG_OBSERVER if self.pull_only
                          else 0, timeout=10.0)
            except (ConnectionError, OSError, RuntimeError) as e:
                get_logger().warning("re-admission HELLO to %s:%d "
                                     "failed: %s", c.host, c.port, e)

    def leave(self, drain_timeout_s: float = 60.0) -> None:
        """Graceful departure: drain in-flight rounds, then tell every
        server to drop this worker from the membership at the next epoch
        boundary (CMD_LEAVE).  The session stays usable for pulls/close;
        pushes after leave() would be deferred-dropped by the servers, so
        the training loop should stop stepping first.

        Raises TimeoutError if in-flight partitions do not drain in
        ``drain_timeout_s`` — leaving with rounds half-pushed would strand
        peers waiting on contributions that already happened."""
        deadline = time.monotonic() + max(0.0, drain_timeout_s)
        while True:
            with self._inflight_lock:
                n = len(self._inflight)
            if n == 0:
                break
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"bps.leave(): {n} partition(s) still in flight after "
                    f"{drain_timeout_s}s; wait on outstanding handles "
                    f"before leaving")
            time.sleep(0.02)
        self._left = True
        self._lease_stop.set()
        for c in self.conns:
            try:
                c.request(CMD_LEAVE, worker_id=self.worker_id, timeout=10.0)
            except RuntimeError as e:
                raise RuntimeError(
                    f"PS server at {c.host}:{c.port} does not support "
                    f"CMD_LEAVE (server too old — rebuild/redeploy the "
                    f"server tier to match this client): {e}") from e
            except (ConnectionError, OSError) as e:
                # A server that is itself gone cannot hold our lease open
                # anyway (it lost all state); best-effort is correct here.
                get_logger().warning(
                    "leave notification to %s:%d failed: %s",
                    c.host, c.port, e)
        get_logger().info("worker %d left the membership", self.worker_id)

    def membership(self, timeout: float = 10.0) -> dict:
        """Live membership view merged across servers (CMD_MEMBERS):
        ``{"epoch", "workers": {id: {"alive", "age_ms"}}, "alive": [ids],
        "barrier": {gen: [arrived ids]}}`` — see merge_membership for the
        merge law.  A pre-CMD_MEMBERS server surfaces as a clean "server
        too old" RuntimeError, never a hang."""
        import json as _json
        views = []
        for c in self.conns:
            try:
                raw = c.request(CMD_MEMBERS, worker_id=self.worker_id,
                                timeout=timeout)
            except RuntimeError as e:
                raise RuntimeError(
                    f"PS server at {c.host}:{c.port} does not support "
                    f"CMD_MEMBERS (server too old — rebuild/redeploy the "
                    f"server tier to match this client): {e}") from e
            views.append(_json.loads(bytes(raw).decode()))
        merged = merge_membership(views)
        if int(merged.get("epoch", 0)) > self._last_epoch:
            self._last_epoch = int(merged["epoch"])
        self._members_cache = merged
        return merged

    def cached_alive(self) -> Optional[list]:
        """Worker ids alive per the last CMD_MEMBERS fetch, or None when
        nothing has been fetched (or the epoch never advanced) — the
        launch set is then authoritative, matching size()'s law."""
        m = self._members_cache
        if m is None or int(m.get("epoch", 0)) == 0:
            return None
        return list(m.get("alive", ()))

    def slice_leader(self, slice_size: Optional[int] = None,
                     world: Optional[int] = None) -> Optional[int]:
        """The leader of THIS worker's slice: the lowest ALIVE member
        under the last observed membership epoch (docs/architecture.md
        "Hierarchical reduction" — the leader law).

        Before any membership fetch — or while the epoch has never
        advanced — the launch set is the electorate, so the leader is
        simply the slice's lowest id.  After an eviction the next
        membership refresh moves leadership to the lowest survivor;
        None means the whole slice has departed."""
        from ..parallel.hierarchy import elect_leader, slice_members, \
            slice_of
        s = self.slice_size if slice_size is None else max(1,
                                                           int(slice_size))
        members = slice_members(slice_of(self.worker_id, s), s,
                                world=world)
        return elect_leader(members, self.cached_alive())

    def _barrier_diag_text(self, generation: int) -> str:
        """One line naming who the barrier is waiting on: live epoch
        membership + arrived ranks from server 0 (where barriers live)."""
        m = self.membership(timeout=5.0)
        arrived = m.get("barrier", {}).get(generation, [])
        waiting_on = sorted(set(m["alive"]) - set(arrived))
        gone = sorted(w for w, r in m["workers"].items() if not r["alive"])
        txt = (f"membership epoch={m['epoch']} alive={m['alive']}, "
               f"arrived={sorted(arrived)}, waiting on rank(s) "
               f"{waiting_on}")
        if gone:
            txt += f"; gone (left/evicted): {gone}"
        down = self._down_servers()
        if down:
            txt += ("; PS server(s) unreachable: "
                    + ", ".join(f"{slot} ({host}:{port})"
                                for slot, host, port, _ in down))
        return txt

    # -- elastic PS ring: placement, redirects, drain, failover -------------
    def _ring_bootstrap(self) -> None:
        """Adopt the server tier's ring at session start (CMD_RING from
        server 0).  A late-starting or restarted worker joining a fleet
        whose ring already transitioned must learn the live epoch —
        including any joiner's address — before planning a single key.
        A pre-ring server answers the unknown command with an error
        status, surfaced as a clean "server too old" (never a hang); a
        server with the ring unarmed (or a different vnode count) is a
        configuration mismatch and fails loudly too — a silent placement
        disagreement would redirect-livelock every push."""
        import json as _json
        try:
            raw = self.conns[0].request(CMD_RING, worker_id=self.worker_id,
                                        timeout=30.0)
        except RuntimeError as e:
            raise RuntimeError(
                f"PS server at {self.conns[0].host}:{self.conns[0].port} "
                f"does not support CMD_RING (server too old — "
                f"rebuild/redeploy the server tier to match this client, "
                f"or unset BYTEPS_TPU_RING): {e}") from e
        doc = _json.loads(bytes(raw).decode())
        if not doc.get("armed"):
            raise RuntimeError(
                "BYTEPS_TPU_RING is armed on this worker but not on the "
                "server tier — set BYTEPS_TPU_RING=1 (plus DMLC_SERVER_ID/"
                "DMLC_NUM_SERVER) on every server, or unset it here")
        if int(doc.get("vnodes", self.ring_vnodes)) != self.ring_vnodes:
            raise RuntimeError(
                f"BYTEPS_TPU_RING_VNODES mismatch: worker={self.ring_vnodes}"
                f" server={doc.get('vnodes')} — placement laws must agree")
        if int(doc.get("epoch", 0)) > 0:
            self._adopt_ring_doc(doc)

    def get_ring(self, timeout: float = 10.0) -> dict:
        """The server tier's current ring table (CMD_RING JSON) from the
        first reachable server: epoch, vnodes, member (id, host, port)
        rows, keys_owned, draining.  "Server too old" on a pre-ring
        server, never a hang."""
        import json as _json
        last: Optional[Exception] = None
        for slot, c in enumerate(self.conns):
            if slot in self._dead_slots:
                continue
            try:
                raw = c.request(CMD_RING, worker_id=self.worker_id,
                                timeout=timeout)
                return _json.loads(bytes(raw).decode())
            except RuntimeError as e:
                raise RuntimeError(
                    f"PS server at {c.host}:{c.port} does not support "
                    f"CMD_RING (server too old — rebuild/redeploy the "
                    f"server tier to match this client): {e}") from e
            except (ConnectionError, OSError, TimeoutError) as e:
                last = e
        raise ConnectionError(f"no PS server reachable for CMD_RING: {last}")

    def drain_server(self, server_id: int, timeout_s: float = 120.0,
                     shutdown: bool = False) -> dict:
        """Gracefully scale the PS tier down: drain ``server_id`` out of
        the ring (CMD_DRAIN).  The survivors adopt the next ring epoch
        first (so migrations land under the new law), then the target
        streams every owned key's state — declared meta, merge store,
        published round, completed_round, the open round's contributor
        set — to its new owner and answers every later frame with a
        redirect.  Blocks until the target reports zero owned keys (its
        drain is complete); ``shutdown=True`` then also retires the
        process.  Returns the target's final CMD_RING document."""
        if not self.ring_armed:
            raise RuntimeError(
                "drain_server requires the elastic ring "
                "(BYTEPS_TPU_RING=1 on workers and servers)")
        import json as _json
        # Compose from the server tier's FRESH table, not this session's
        # cached one: servers silently ignore (and idempotently ack) a
        # STALE-epoch proposal, which would otherwise surface only as a
        # misleading poll timeout below.
        self._safe_adopt_ring(self.get_ring())
        with self._ring_lock:
            ring = self._ring
            if ring is None or server_id not in ring.ids():
                raise ValueError(
                    f"server {server_id} is not in the current ring "
                    f"{ring.ids() if ring else []}")
            proposal = ring.without(server_id)   # raises on last member
            target_slot = self._srv_slot[server_id]
            survivors = [(sid, slot) for sid, slot in self._srv_slot.items()
                         if sid != server_id
                         and slot not in self._dead_slots]
        wire = proposal.to_wire()
        # Survivors first: every migration the drain streams must land on
        # a server that already accepts the new epoch — otherwise a push
        # racing the handoff could bounce between two stale owners.
        for sid, slot in survivors:
            self.conns[slot].request(CMD_RING_SET, payload=wire,
                                     worker_id=self.worker_id, timeout=30.0)
        raw = self.conns[target_slot].request(
            CMD_DRAIN, payload=wire, worker_id=self.worker_id, timeout=30.0)
        doc = _json.loads(bytes(raw).decode())
        if not doc.get("draining"):
            # The target rejected the epoch (a transition raced this
            # drain): fail loudly NOW with the real cause instead of
            # burning the poll deadline on a server that never drained.
            raise RuntimeError(
                f"PS server {server_id} did not enter draining (a ring "
                f"transition raced this drain: server epoch "
                f"{doc.get('epoch')} vs proposed {proposal.epoch}); "
                f"re-run drain_server")
        # NOTE: the new table is adopted only AFTER the target reports
        # zero owned keys (below).  Until then this worker keeps
        # planning by the OLD ring, so its pushes land on the draining
        # target and follow the migrate-then-redirect path — adopting
        # early would let a concurrent push fresh-INIT a key on the new
        # owner while that key's migration is still streaming (the
        # install-race HandleMigrate refuses loudly).
        deadline = time.monotonic() + max(1.0, timeout_s)
        while True:
            raw = self.conns[target_slot].request(
                CMD_RING, worker_id=self.worker_id, timeout=10.0)
            doc = _json.loads(bytes(raw).decode())
            if int(doc.get("keys_owned", 0)) == 0:
                break
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"drain of PS server {server_id} still reports "
                    f"{doc.get('keys_owned')} owned key(s) after "
                    f"{timeout_s}s")
            time.sleep(0.05)
        self._safe_adopt_ring(doc)   # every key's state has landed
        get_logger().info("PS server %d drained (ring epoch %s)",
                          server_id, doc.get("epoch"))
        if shutdown:
            try:
                self.conns[target_slot].request(
                    CMD_SHUTDOWN, worker_id=self.worker_id, timeout=10.0)
            except (ConnectionError, OSError) as e:
                get_logger().debug("drained-server shutdown race: %s", e)
            # The process is going away: retire the slot and close its
            # lanes NOW, or (with failover armed) their effectively-
            # unbounded re-dial loops would spin against a dead address
            # for the life of the session.  Without shutdown the server
            # stays up answering redirects/stats, so its conns stay.
            self._dead_slots.add(target_slot)
            for c in self._data_conns[target_slot]:
                try:
                    c.close()
                except Exception:
                    pass
        return doc

    def _adopt_ring_doc(self, doc: dict) -> bool:
        """Adopt a server-sent ring table (CMD_RING / RING_SET response /
        MOVED payload) if its epoch is newer than ours."""
        try:
            table = RingTable.from_json(doc)
        except Exception as e:
            get_logger().warning("unparseable ring table ignored: %s", e)
            return False
        if not table.servers:
            return False
        return self._apply_ring(table)

    def _apply_ring(self, table: RingTable) -> bool:
        """Install a newer ring table: merge addresses (this session's
        dial address wins for servers it already knows — it may be a
        test proxy), dial any joiner, rebuild the id->slot map, then
        invalidate the placement caches so the next plan (and every
        remap) follows the new law.  Returns True when the epoch
        advanced, False when the table is stale OR a joiner could not be
        dialed — adoption is all-or-nothing (a half-applied table whose
        owner has no conn slot would crash every plan), and a False here
        is always retryable: the next MOVED redirect or scanner pass
        re-presents the table."""
        with self._ring_lock:
            if self._ring is None or table.epoch <= self._ring.epoch:
                return False
            merged = []
            joiners = []
            for sid, h, p in table.servers:
                slot = self._srv_slot.get(sid)
                if slot is not None and slot not in self._dead_slots:
                    c = self.conns[slot]
                    merged.append((sid, c.host, c.port))
                else:
                    merged.append((sid, h, p))
                    if slot is None:
                        joiners.append((sid, h, p))
        # Dial every joiner's lane pool OUTSIDE the ring lock (connects
        # can block for seconds against a still-booting pod, and _plan
        # needs the lock on every staging thread), and BEFORE committing
        # anything — adoption is all-or-nothing: a half-applied table
        # whose owner has no conn slot would crash every plan.
        dialed = []
        try:
            for sid, h, p in joiners:
                pool = [self._make_conn(h, p)]
                for _ in range(self._wire_conns - 1):
                    pool.append(self._make_conn(h, p))
                dialed.append((sid, h, p, pool))
        except OSError as e:
            for _sid, _h, _p, pool in dialed:
                for c in pool:
                    try:
                        c.close()
                    except Exception:
                        pass
            get_logger().warning(
                "not adopting ring epoch %d yet: cannot dial joining "
                "PS server (%s) — will retry on the next redirect",
                table.epoch, e)
            return False
        if self._audit_wire:
            # A joiner that is not audit-armed would answer trailerless
            # pulls a marker-sending client mis-splits: downgrade the
            # session loudly BEFORE the adoption commits (pulls issued
            # from here on are unmarked; in-flight marked pulls ride
            # only the already-verified members).
            for sid, h, p, pool in dialed:
                try:
                    armed = bool(self._audit_probe(pool[0]).get("armed"))
                except Exception:
                    armed = False
                if not armed:
                    get_logger().error(
                        "joining PS server %d (%s:%d) is not audit-armed "
                        "(BYTEPS_TPU_AUDIT); disabling pull auditing for "
                        "this session", sid, h, p)
                    self._audit_wire = False
                    break
        with self._ring_lock:
            if self._ring is None or table.epoch <= self._ring.epoch:
                # Another adoption won while we were dialing.
                for _sid, _h, _p, pool in dialed:
                    for c in pool:
                        try:
                            c.close()
                        except Exception:
                            pass
                return False
            for sid, h, p, pool in dialed:
                live = self._srv_slot.get(sid)
                if live is not None and live not in self._dead_slots:
                    # A concurrent lower-epoch adoption already slotted
                    # this joiner while we were dialing — keep its pool.
                    for c in pool:
                        try:
                            c.close()
                        except Exception:
                            pass
                    continue
                slot = len(self.conns)
                self.conns.append(pool[0])
                self._data_conns.append(pool)
                self._server_load.append(0)
                self._hosts.append(h)
                self._ports.append(p)
                self._srv_slot[sid] = slot
                self._slot_srv[slot] = sid
                get_logger().info(
                    "PS server %d (%s:%d) joined the ring; dialed as "
                    "slot %d", sid, h, p, slot)
            self._ring = RingTable(merged, table.vnodes, table.epoch)
            live_ids = set(self._ring.ids())
            self._srv_slot = {sid: slot for sid, slot
                              in self._srv_slot.items() if sid in live_ids}
            epoch = table.epoch
        # Placement-cache invalidation OUTSIDE ring_mu_ (the _plan path
        # takes _plan_lock THEN _ring_lock; same order here).
        with self._plan_lock:
            self._plans.clear()
            with self._ring_lock:
                ring, slots = self._ring, dict(self._srv_slot)
            for pkey, old_slot in list(self._pkey_srv.items()):
                new_slot = slots.get(ring.owner(pkey))
                if new_slot is not None and new_slot != old_slot:
                    # Moved key: the next stage must re-INIT on the new
                    # owner (re-seeding its round from migrated — or,
                    # after failover, fresh — server state).
                    self._pkey_srv[pkey] = new_slot
                    self._inited.pop(pkey, None)
        get_logger().warning(
            "adopted PS ring epoch %d: servers %s", epoch,
            sorted(slots))
        _flightrec.record("ring_epoch", epoch=epoch,
                          servers=sorted(slots), worker=self.worker_id)
        return True

    def _park_for_remap(self, pkey: int,
                        phase: Optional[str] = None) -> bool:
        """Claim one in-flight partition for the ring-remap path: mark it
        parked (so the dispatcher skips any queued entry), settle its
        lane credit, and count it — the ONE bookkeeping block shared by
        every redirect/failover site, mirroring what _park_part does for
        reconnect parking.  Returns False when the part is gone or
        already claimed."""
        with self._inflight_lock:
            part = self._inflight.get(pkey)
            if part is None or part.parked:
                return False
            part.parked = True
            if phase is not None:
                part.phase = phase
        self._lane_settle(part)
        with self._transport_lock:
            self._tstats["parked_parts"] += 1
            self._tstats["parked_total"] += 1
        return True

    def _safe_adopt_ring(self, doc: dict) -> bool:
        """_adopt_ring_doc that can never take down its calling thread:
        both callers (the receiver-callback redirect path and the remap
        worker) must survive a transiently undialable joiner — adoption
        is retryable by construction (the next redirect re-presents the
        table)."""
        try:
            return self._adopt_ring_doc(doc)
        except Exception:
            get_logger().exception("ring adoption failed (will retry on "
                                   "the next redirect)")
            return False

    def _on_key_moved(self, pkey: int, phase: str,
                      err: _KeyMoved) -> None:
        """A push/pull drew status MOVED: park the partition and hand it
        — with the attached ring table — to the remap worker, which
        adopts the table and replays the partition against the new owner
        (whose state the old owner already streamed over:
        state-before-redirect is the server's contract).  Runs on a
        receiver-callback thread, so it must never block: adoption (which
        may dial a joiner) belongs to the remap worker."""
        claimed = self._park_for_remap(pkey, phase)
        if claimed:
            with self._transport_lock:
                self._tstats["ring_redirects"] += 1
            self._queue_remap(pkey, err.doc)
        else:
            self._queue_remap(None, err.doc)   # still adopt the table

    def _queue_remap(self, pkey: Optional[int],
                     doc: Optional[dict] = None) -> None:
        # The worker nulls _remap_thread UNDER _remap_lock just before
        # exiting (see _remap_loop), so this check can never observe a
        # thread that has already decided to stop — the
        # append-then-strand TOCTOU a bare is_alive() test would allow.
        with self._remap_lock:
            self._remap_queue.append((pkey, doc))
            if self._remap_thread is None:
                self._remap_thread = threading.Thread(
                    target=self._remap_loop, daemon=True,
                    name="bps-ps-remap-ring")
                self._remap_thread.start()

    def _remap_loop(self) -> None:
        """Drain the remap queue: route each parked partition to its
        current ring owner and replay it (re-INIT + round reconcile +
        push/pull replay — the same idempotent machinery reconnects
        use).  Runs on a transient daemon thread so no receiver thread
        ever blocks on a cross-server round trip."""
        while True:
            with self._remap_lock:
                if not self._remap_queue:
                    self._remap_thread = None   # hand-off point: a later
                    return                      # _queue_remap starts fresh
                pkey, doc = self._remap_queue.pop(0)
            if doc is not None:
                self._safe_adopt_ring(doc)
            if pkey is None:
                continue        # adoption-only entry
            with self._inflight_lock:
                part = self._inflight.get(pkey)
            if part is None:
                continue        # finished/failed while queued
            with self._ring_lock:
                ring = self._ring
                slot = (None if ring is None
                        else self._srv_slot.get(ring.owner(pkey)))
            if slot is None or slot in self._dead_slots:
                self._finish_part(pkey, ConnectionError(
                    f"no live ring owner for moved key {pkey}"))
                continue
            part.srv = slot
            self._pkey_srv[pkey] = slot
            conn = self.conns[slot]
            try:
                self._replay_part(conn, part)
            except _KeyMoved as e:
                # Moved again mid-remap (back-to-back transitions, or a
                # joiner not yet dialable): adopt the newer table and
                # requeue.  The tiny sleep stops a hot redirect loop
                # while an undialable joiner keeps adoption at bay —
                # each retry is otherwise only RTT-throttled.
                requeue = self._park_for_remap(pkey)
                if not self._safe_adopt_ring(e.doc):
                    time.sleep(0.1)
                if requeue:
                    self._queue_remap(pkey)
            except ConnectionError as e:
                err = (e if isinstance(e, _ConnLost)
                       else conn._lost_exc(str(e)))
                if not self._park_part(pkey, part.phase, err):
                    self._finish_part(pkey, err)
            except Exception as e:
                self._finish_part(pkey, e)

    def _down_servers(self) -> list:
        """[(slot, host, port, planned_pkeys)] for servers whose EVERY
        lane is down — the "dead server, not slow keys" diagnostic."""
        rows = []
        # list() snapshots: _plan/_remap mutate _pkey_srv concurrently,
        # and a python-level iteration racing an insert raises
        # "dictionary changed size" — which would kill the watchdog
        # thread exactly when it is needed.
        placed = list(self._pkey_srv.items())
        for slot, pool in enumerate(list(self._data_conns)):
            if slot in self._dead_slots or not pool:
                continue
            if all(c.state() != "up" for c in pool):
                owned = sorted(k for k, s in placed if s == slot)
                rows.append((slot, pool[0].host, pool[0].port, owned))
        return rows

    def _server_lease_loop(self) -> None:
        """Worker-side server-lease scanner (armed by
        BYTEPS_TPU_SERVER_EVICT_TIMEOUT_S > 0 — the server-tier mirror
        of PR 7's worker eviction): a ring member whose every lane has
        been down longer than the timeout is declared dead.  The
        survivors adopt the next ring epoch (CMD_RING_SET; idempotent
        under racing workers — all observed the same death, so all
        propose the same transition), this worker re-routes everything
        parked on the corpse, and the open round's gradients re-push to
        the claimed ranges — no round is lost."""
        interval = max(0.05, min(self.server_evict_timeout_s / 4.0, 1.0))
        while not self._srvdown_stop.wait(interval):
            if not self.ring_armed or self._ring is None:
                continue
            now = time.monotonic()
            with self._ring_lock:
                members = list(self._srv_slot.items())
            live = [(sid, slot) for sid, slot in members
                    if slot not in self._dead_slots]
            for sid, slot in live:
                pool = self._data_conns[slot]
                dead = all(
                    c.state() != "up" and c.down_since
                    and now - c.down_since > self.server_evict_timeout_s
                    for c in pool)
                if not dead:
                    continue
                if len(live) <= 1:
                    get_logger().error(
                        "PS server %d is down past the evict timeout but "
                        "is the LAST ring member — nothing to fail over "
                        "to", sid)
                    continue
                try:
                    self._declare_server_dead(sid, slot)
                except Exception:
                    get_logger().exception("server failover failed")

    def _declare_server_dead(self, sid: int, slot: int) -> None:
        age = max((time.monotonic() - c.down_since)
                  for c in self._data_conns[slot] if c.down_since)
        get_logger().error(
            "PS server %d (%s:%d) declared DEAD: every lane down for "
            "%.1fs (> BYTEPS_TPU_SERVER_EVICT_TIMEOUT_S=%.1fs); the "
            "surviving ring claims its key ranges and the open round "
            "re-pushes from gradient state",
            sid, self.conns[slot].host, self.conns[slot].port, age,
            self.server_evict_timeout_s)
        import json as _json
        with self._ring_lock:
            ring = self._ring
            if ring is None or sid not in ring.ids():
                return          # another thread/worker beat us to it
            proposal = ring.without(sid)
            survivors = [(osid, oslot) for osid, oslot
                         in self._srv_slot.items()
                         if osid != sid and oslot not in self._dead_slots]
        wire = proposal.to_wire()
        adopted = None
        for osid, oslot in survivors:
            try:
                raw = self.conns[oslot].request(
                    CMD_RING_SET, payload=wire, worker_id=self.worker_id,
                    timeout=15.0)
                doc = _json.loads(bytes(raw).decode())
                if adopted is None or (int(doc.get("epoch", 0))
                                       > int(adopted.get("epoch", 0))):
                    adopted = doc
            except Exception as e:
                get_logger().warning(
                    "failover RING_SET to server %d failed: %s", osid, e)
        if adopted is None:
            # NO survivor accepted the proposal: this worker may be the
            # partitioned one, not the server.  Transitioning locally
            # anyway would split the fleet across two rings (this worker
            # pushing a key's fresh lineage to a survivor while everyone
            # else still pushes it to the "dead" server).  Hold the
            # line and retry next scan — parked parts stay parked.
            get_logger().error(
                "failover of PS server %d aborted: no survivor accepted "
                "the ring proposal (is THIS worker partitioned?); "
                "retrying", sid)
            return
        self._adopt_ring_doc(adopted)
        with self._transport_lock:
            self._tstats["server_failovers"] += 1
        _flightrec.record(
            "server_dead", server=sid, host=self.conns[slot].host,
            port=self.conns[slot].port, down_s=round(age, 2),
            epoch=int(adopted.get("epoch", 0)), worker=self.worker_id)
        # Failover is a they-died moment: drop a postmortem bundle so the
        # lost-round window (if any) has its evidence on disk even if the
        # job later looks healthy.
        _flightrec.dump_bundle("server-failover")
        # Park-and-remap everything routed at the corpse, THEN close its
        # conns (ending the background re-dial loops).  Parked parts in
        # the scheduler queue are skipped by the dispatcher until the
        # remap re-enqueues them against the new owner.
        with self._inflight_lock:
            stuck = [p.pkey for p in self._inflight.values()
                     if p.srv == slot]
        for pkey in stuck:
            self._park_for_remap(pkey)   # no-op if already parked — the
            #                              remap claims each exactly once
            self._queue_remap(pkey)
        self._dead_slots.add(slot)
        for c in self._data_conns[slot]:
            try:
                c.close()
            except Exception:
                pass

    def transport_stats(self) -> dict:
        """Fault-tolerance + raw-speed transport counters: reconnects,
        replayed/parked parts, watchdog trips, receive-pool hit/miss, and
        per-lane bytes/outstanding (the byte-credit scheduler's working
        signal) — the get_codec_stats() analog for the transport.  The
        numeric keys export through the telemetry registry's transport
        collector; `lanes` is the per-lane detail list (skipped by the
        exporter, which only takes numbers)."""
        with self._transport_lock:
            s = dict(self._tstats)
        s["reconnects"] = sum(c.reconnects for pool in self._data_conns
                              for c in pool)
        hits, misses, held = self._recv_pool.stats()
        s["pool_hits"], s["pool_misses"] = hits, misses
        s["pool_buffers_held"] = held
        lanes = []
        total_bytes = outstanding = 0
        for srv, pool in enumerate(self._data_conns):
            for li, c in enumerate(pool):
                lanes.append({
                    "server": srv, "lane": li, "transport": c.transport,
                    "bytes_total": c.lane_bytes_total,
                    "outstanding_bytes": c.outstanding_bytes,
                    "sends": c.lane_sends,
                })
                total_bytes += c.lane_bytes_total
                outstanding += c.outstanding_bytes
        s["lane_bytes_total"] = total_bytes
        s["lane_outstanding_bytes"] = outstanding
        s["lanes"] = lanes
        return s

    def server_stats(self, timeout: float = 10.0) -> dict:
        """Server-side CMD_STATS snapshot, merged across all servers.

        Returns {"bytes_in", "bytes_out", "async", "num_workers",
        "keys": {wire_key: {pushes, merges, completed_round,
        round_pushes, pending_pulls, bytes}}, "workers": {worker_id:
        {pushes, round}}}.  `round_pushes` is how many workers have
        merged into the key's OPEN round — pending-push depth is
        num_workers - round_pushes, the "who is the round waiting on"
        signal; `pending_pulls` counts pulls parked for a round that
        has not published yet.
        Keys are disjoint across servers (hash placement) so their maps
        union; per-worker rounds take the MIN across servers — a worker
        lagging on any server gates every sync round it participates in.

        A pre-CMD_STATS server routes the unknown command to an engine
        whose default arm answers with an error status, which surfaces
        here as a clean "server too old" RuntimeError — never a hang.
        """
        merged = {"bytes_in": 0, "bytes_out": 0, "async": False,
                  "num_workers": 0, "scatter_frames": 0, "keys": {},
                  "workers": {}, "epoch": 0, "deferred_joins": 0,
                  "members": {}, "ring_epoch": 0, "servers": {},
                  "codec_sets": 0, "codec_stale_frames": 0,
                  "opt_sets": 0, "opt_updates": 0, "opt_slot_bytes": 0,
                  "embed_rows_served": 0, "embed_table_bytes": 0,
                  "slice_size": 1, "repl_armed": False,
                  "repl_bytes_total": 0, "repl_lag_rounds": 0,
                  "repl_replicas_held": 0, "repl_promotions": 0,
                  "fleet_armed": False, "fleet_workers": 0,
                  "fleet_windows_held": 0, "fleet_publishes": 0}
        import json as _json
        for slot, c in enumerate(self.conns):
            sid = self._slot_srv.get(slot, slot)
            if slot in self._dead_slots:
                merged["servers"][sid] = {"alive": False, "keys_owned": 0,
                                          "draining": False}
                continue
            try:
                raw = c.request(CMD_STATS, worker_id=self.worker_id,
                                timeout=timeout)
            except RuntimeError as e:
                raise RuntimeError(
                    f"PS server at {c.host}:{c.port} does not support "
                    f"CMD_STATS (server too old — rebuild/redeploy the "
                    f"server tier to match this client): {e}") from e
            except (ConnectionError, OSError, TimeoutError):
                # A dead/unreachable server must not break the whole
                # stats plane — that is exactly when an operator reads
                # it.  Its row reports alive=False; the survivors' rows
                # still merge.
                merged["servers"][sid] = {"alive": False, "keys_owned": 0,
                                          "draining": False}
                continue
            st = _json.loads(bytes(raw).decode())
            merged["ring_epoch"] = max(merged["ring_epoch"],
                                       int(st.get("ring_epoch", 0)))
            # Row key: the server-reported id only when the ring is
            # armed (ids are then meaningful and unique).  Unarmed
            # deployments all report server_id 0 (DMLC_SERVER_ID is not
            # required there) — keying by it would collapse N servers
            # into one row and hide a dead one from the exact panel
            # built to expose it.
            row_id = (int(st.get("server_id", sid))
                      if st.get("ring_armed") else sid)
            merged["servers"][row_id] = {
                "alive": True,
                "keys_owned": int(st.get("keys_owned", 0)),
                "draining": bool(st.get("draining", 0)),
                "migrations_in": int(st.get("migrations_in", 0)),
                "migrations_out": int(st.get("migrations_out", 0)),
                "moved_frames": int(st.get("moved_frames", 0)),
                # Per-server wire volume, kept on the row (not just the
                # merged totals): the doctor's server_hot_shard rule
                # weights keys_owned by per-window bytes_in deltas to
                # name the byte-heavy server, not just the key-heavy one.
                "bytes_in": int(st.get("bytes_in", 0)),
                "bytes_out": int(st.get("bytes_out", 0)),
            }
            merged["bytes_in"] += int(st.get("bytes_in", 0))
            merged["bytes_out"] += int(st.get("bytes_out", 0))
            merged["scatter_frames"] += int(st.get("scatter_frames", 0))
            merged["async"] = merged["async"] or bool(st.get("async"))
            merged["num_workers"] = max(merged["num_workers"],
                                        int(st.get("num_workers", 0)))
            # Hierarchical reduction: the slice size the server counts
            # round completion in (1 = flat; old servers omit it).
            merged["slice_size"] = max(merged["slice_size"],
                                       int(st.get("slice_size", 1)))
            # Elastic membership — the one merge law (_merge_member_rec):
            # freshest epoch wins, alive = AND across servers, age = max.
            # Old servers omit these keys entirely.
            merged["epoch"] = max(merged["epoch"], int(st.get("epoch", 0)))
            merged["deferred_joins"] += int(st.get("deferred_joins", 0))
            # Codec renegotiation counters (accepted proposals /
            # format-mismatch rejections); old servers omit them.
            merged["codec_sets"] += int(st.get("codec_sets", 0))
            merged["codec_stale_frames"] += int(
                st.get("codec_stale_frames", 0))
            # Server-resident optimizer plane; old servers omit these
            # (and per-key param_version/opt_mode rows flow through the
            # wholesale key-row copy below).
            merged["opt_sets"] += int(st.get("opt_sets", 0))
            merged["opt_updates"] += int(st.get("opt_updates", 0))
            merged["opt_slot_bytes"] += int(st.get("opt_slot_bytes", 0))
            merged["servers"][row_id]["opt_slot_bytes"] = int(
                st.get("opt_slot_bytes", 0))
            # Row-sparse embedding plane (old servers omit both).
            merged["embed_rows_served"] += int(
                st.get("embed_rows_served", 0))
            merged["embed_table_bytes"] += int(
                st.get("embed_table_bytes", 0))
            merged["servers"][row_id]["embed_table_bytes"] = int(
                st.get("embed_table_bytes", 0))
            # Chain replication (CMD_REPL; old servers omit all of
            # these).  Per-server rows keep the publish-side lag and
            # replica census — the doctor's replication_lag rule and the
            # autoscaler both read the ROWS, because lag is a property of
            # one owner→successor edge, not of the tier.
            merged["repl_armed"] = (merged["repl_armed"]
                                    or bool(st.get("repl_armed", 0)))
            merged["repl_bytes_total"] += int(st.get("repl_bytes_out", 0))
            merged["repl_lag_rounds"] = max(
                merged["repl_lag_rounds"], int(st.get("repl_lag_rounds", 0)))
            merged["repl_replicas_held"] += int(
                st.get("repl_replicas_held", 0))
            merged["repl_promotions"] += int(st.get("repl_promotions", 0))
            merged["servers"][row_id]["repl_lag_rounds"] = int(
                st.get("repl_lag_rounds", 0))
            merged["servers"][row_id]["repl_bytes_out"] = int(
                st.get("repl_bytes_out", 0))
            merged["servers"][row_id]["repl_replicas_held"] = int(
                st.get("repl_replicas_held", 0))
            merged["servers"][row_id]["repl_promotions"] = int(
                st.get("repl_promotions", 0))
            # Fleet observability plane (CMD_WINDOW rings; old servers
            # omit all of these).  worker/ring counts stay per-row too:
            # after a drain the elastic tests compare the survivor's
            # census against the drained server's.
            merged["fleet_armed"] = (merged["fleet_armed"]
                                     or bool(st.get("fleet_armed", 0)))
            merged["fleet_workers"] = max(
                merged["fleet_workers"], int(st.get("fleet_workers", 0)))
            merged["fleet_windows_held"] += int(
                st.get("fleet_windows_held", 0))
            merged["fleet_publishes"] += int(st.get("fleet_publishes", 0))
            merged["servers"][row_id]["fleet_windows_held"] = int(
                st.get("fleet_windows_held", 0))
            for w, rec in (st.get("members") or {}).items():
                _merge_member_rec(merged["members"], int(w), rec)
            for k, v in (st.get("keys") or {}).items():
                merged["keys"][int(k)] = v
            for w, v in (st.get("workers") or {}).items():
                w = int(w)
                prev = merged["workers"].get(w)
                if prev is None:
                    merged["workers"][w] = dict(v)
                else:
                    prev["pushes"] = (int(prev.get("pushes", 0))
                                      + int(v.get("pushes", 0)))
                    prev["round"] = min(int(prev.get("round", 0)),
                                        int(v.get("round", 0)))
        return merged

    # -- value-domain consistency auditor (docs/monitoring.md) --------------
    def _audit_probe(self, conn: "_ServerConn",
                     timeout: float = 10.0) -> dict:
        """One CMD_AUDIT round trip, parsed.  A pre-audit server routes
        the unknown command to an engine whose default arm answers an
        error status — surfaced as a clean "server too old" RuntimeError,
        never a hang (the kStats pattern)."""
        import json as _json
        try:
            raw = conn.request(CMD_AUDIT, worker_id=self.worker_id,
                               timeout=timeout)
        except RuntimeError as e:
            raise RuntimeError(
                f"PS server at {conn.host}:{conn.port} does not support "
                f"CMD_AUDIT (server too old — rebuild/redeploy the server "
                f"tier to match this client): {e}") from e
        return _json.loads(bytes(raw).decode())

    def _audit_bootstrap(self) -> None:
        """Arm the pull-side digest wire — but only after proving the
        server tier actually records digests (CMD_AUDIT probe).  A
        mixed/old/async deployment downgrades loudly to "auditing off"
        instead of sending trailer markers nothing will honor; the
        unarmed wire therefore stays byte-identical whichever side is
        missing the feature."""
        if self.server_async:
            get_logger().warning(
                "BYTEPS_TPU_AUDIT armed but the server tier runs ASYNC "
                "mode (no sync rounds, nothing publishes a digest); pull "
                "auditing disabled")
            return
        # EVERY server must be armed: a mixed fleet would return
        # trailerless pulls from the unarmed members, and a
        # marker-sending client would strip 24 bytes of real payload.
        for c in self.conns:
            try:
                doc = self._audit_probe(c)
            except Exception as e:
                get_logger().warning(
                    "BYTEPS_TPU_AUDIT armed but the server tier cannot "
                    "answer CMD_AUDIT (%s); pull auditing disabled", e)
                return
            if not doc.get("armed"):
                get_logger().warning(
                    "BYTEPS_TPU_AUDIT armed on this worker but NOT on "
                    "PS server %s:%d (set BYTEPS_TPU_AUDIT=1 on every "
                    "server); pull auditing disabled", c.host, c.port)
                return
        self._audit_wire = True
        get_logger().info(
            "consistency auditor armed: pulls carry publish digests "
            "(last-%d window per key)", self.audit_window)

    def _audit_split(self, part: "_PartTask", raw):
        """Strip one audited pull's 24-byte trailer.  Returns ``(body,
        verify)`` where ``verify`` is a no-arg closure running the
        digest pass + verdict — or None when there is nothing to verify
        (short frame, no digest recorded).  The split is O(1); the
        caller runs ``verify`` only after the handle resolved, keeping
        the CRC off the round's critical path."""
        mv = raw if isinstance(raw, memoryview) else memoryview(raw)
        if len(mv) < _AUDIT_TRAILER.size:
            get_logger().error(
                "AUDIT: pull for key %d returned %d bytes — too short to "
                "carry the trailer an audit-armed server always appends; "
                "treating as unverified", part.pkey, len(mv))
            with self._audit_lock:
                self._audit_stats["unverified"] += 1
            return mv, None
        body = mv[:-_AUDIT_TRAILER.size]
        digest, rnd, epoch, n_contrib = _AUDIT_TRAILER.unpack(
            mv[-_AUDIT_TRAILER.size:])
        if n_contrib == 0:
            # No digest recorded for the served buffer (pre-first armed
            # publish, or state freshly migrated in): skip, don't flag.
            with self._audit_lock:
                self._audit_stats["unverified"] += 1
            return body, None
        return body, lambda: self._audit_verify(part, body, digest, rnd,
                                                epoch, n_contrib)

    def _audit_verify(self, part: "_PartTask", body, digest: int,
                      rnd: int, epoch: int, n_contrib: int) -> None:
        """Re-digest one audited pull's body and verify it against what
        the server recorded at publish.  Verdicts are observations: a
        mismatch fires a structured ERROR naming key/round/contributors/
        epoch, bumps the counters, flight-records the event, and (once)
        drops a postmortem bundle — the payload already landed, because
        a detected-corrupt round that loudly names itself beats a handle
        failure that throws away the evidence."""
        local = audit_digest(body)
        if epoch > self._last_epoch:
            self._last_epoch = int(epoch)   # trailer-borne epoch observation
        with self._audit_lock:
            self._audit_stats["checked"] += 1
            dq = self._audit_window_log.get(part.pkey)
            if dq is None:
                dq = self._audit_window_log[part.pkey] = deque(
                    maxlen=self.audit_window)
            dq.append((int(rnd), int(local), int(epoch), int(n_contrib)))
        self._m_audit_checked.inc()
        ring_epoch = self._ring.epoch if self._ring is not None else 0
        if local != digest:
            with self._audit_lock:
                self._audit_stats["mismatches"] += 1
                first = self._audit_stats["mismatches"] == 1
                self._audit_last = {
                    "kind": "digest_mismatch", "key": part.pkey,
                    "label": part.label, "round": int(rnd),
                    "local": int(local), "server": int(digest),
                    "contributors": int(n_contrib), "epoch": int(epoch),
                    "ring_epoch": int(ring_epoch)}
            self._m_audit_mismatch.inc()
            get_logger().error(
                "AUDIT MISMATCH: pulled bytes for key %d (%s) round %d "
                "differ from the server's publish digest "
                "(local=%08x server=%08x; %d contributors, membership "
                "epoch %d, ring epoch %d, worker %d) — single-bit "
                "corruption in transit, or a divergent published sum; "
                "run bps.get_audit(cross_check=True) or "
                "tools/postmortem.py for cross-worker attribution",
                part.pkey, part.label, rnd, local, digest, n_contrib,
                epoch, ring_epoch, self.worker_id)
            _flightrec.record(
                "audit_mismatch", key=part.pkey, label=part.label,
                round=int(rnd), local=int(local), server=int(digest),
                contributors=int(n_contrib), epoch=int(epoch),
                ring_epoch=int(ring_epoch), worker=self.worker_id)
            if first:
                _flightrec.dump_bundle("audit-mismatch")
        elif int(rnd) != part.round:
            # The digest matches the bytes — but they are a DIFFERENT
            # round than this worker staged: a lost/skewed round (the
            # elastic failover publish-to-last-pull window,
            # docs/elasticity.md) now detected instead of silently
            # training on a stale sum.
            with self._audit_lock:
                self._audit_stats["round_skew"] += 1
                self._audit_last = {
                    "kind": "round_skew", "key": part.pkey,
                    "label": part.label, "staged_round": part.round,
                    "served_round": int(rnd), "epoch": int(epoch),
                    "ring_epoch": int(ring_epoch)}
            self._m_audit_skew.inc()
            get_logger().error(
                "AUDIT LOST ROUND: pull for key %d (%s) staged round %d "
                "but the server served round %d's publish (%d "
                "contributors, membership epoch %d, ring epoch %d, "
                "worker %d) — a round was lost or skewed across a "
                "failover/restart boundary (docs/elasticity.md)",
                part.pkey, part.label, part.round, rnd, n_contrib,
                epoch, ring_epoch, self.worker_id)
            _flightrec.record(
                "audit_lost_round", key=part.pkey, label=part.label,
                staged_round=part.round, served_round=int(rnd),
                epoch=int(epoch), ring_epoch=int(ring_epoch),
                worker=self.worker_id)

    def fetch_server_audit(self, timeout: float = 10.0) -> dict:
        """Drain every live server's CMD_AUDIT window, merged (keys are
        disjoint across servers).  ``{"armed", "window", "epoch",
        "ring_epoch", "keys": {pkey: [{"r","d","e","w"}, ...]}}``."""
        merged = {"armed": False, "window": 0, "epoch": 0,
                  "ring_epoch": 0, "keys": {}, "servers_down": 0}
        for slot, c in enumerate(self.conns):
            if slot in self._dead_slots:
                merged["servers_down"] += 1
                continue
            try:
                doc = self._audit_probe(c, timeout=timeout)
            except (ConnectionError, OSError, TimeoutError):
                # A dead server must not break the audit plane — it is
                # exactly when the operator reads it.
                merged["servers_down"] += 1
                continue
            merged["armed"] = merged["armed"] or bool(doc.get("armed"))
            merged["window"] = max(merged["window"],
                                   int(doc.get("window", 0)))
            merged["epoch"] = max(merged["epoch"],
                                  int(doc.get("epoch", 0)))
            merged["ring_epoch"] = max(merged["ring_epoch"],
                                       int(doc.get("ring_epoch", 0)))
            for k, rows in (doc.get("keys") or {}).items():
                # Merge BY ROUND, not dict-overwrite: around a key
                # migration two servers may briefly both hold rows for
                # the key (the old owner's pre-migration rounds, the new
                # owner's post-migration ones) — dropping either half
                # would blind the cross-check exactly at the boundary it
                # exists for.  A same-round collision keeps the later
                # server's row (the current owner republishes it).
                by_round = {int(r["r"]): r
                            for r in merged["keys"].get(int(k), ())}
                for r in rows:
                    by_round[int(r["r"])] = r
                merged["keys"][int(k)] = [by_round[r]
                                          for r in sorted(by_round)]
        return merged

    # -- fleet observability plane (docs/monitoring.md "Fleet plane") -------
    def _fleet_probe(self, conn: "_ServerConn",
                     timeout: float = 10.0) -> dict:
        """One CMD_FLEET round trip, parsed.  A pre-fleet server routes
        the unknown command to an engine whose default arm answers an
        error status — surfaced as a clean "server too old" RuntimeError,
        never a hang (the kStats pattern)."""
        import json as _json
        try:
            raw = conn.request(CMD_FLEET, worker_id=self.worker_id,
                               timeout=timeout)
        except RuntimeError as e:
            raise RuntimeError(
                f"PS server at {conn.host}:{conn.port} does not support "
                f"CMD_FLEET (server too old — rebuild/redeploy the server "
                f"tier to match this client): {e}") from e
        return _json.loads(bytes(raw).decode())

    def _fleet_bootstrap(self) -> None:
        """Arm the fleet publish wire — but only after proving the
        server tier actually retains windows (CMD_FLEET probe on EVERY
        server: rings must survive a drain onto any member).  A
        mixed/old deployment downgrades loudly to "fleet plane off"
        instead of publishing summaries nothing retains; the unarmed
        wire therefore stays byte-identical whichever side is missing
        the feature (the CMD_AUDIT bootstrap law)."""
        for c in self.conns:
            try:
                doc = self._fleet_probe(c)
            except Exception as e:
                get_logger().warning(
                    "BYTEPS_TPU_FLEET armed but the server tier cannot "
                    "answer CMD_FLEET (%s); fleet plane disabled", e)
                return
            if not doc.get("armed"):
                get_logger().warning(
                    "BYTEPS_TPU_FLEET armed on this worker but NOT on "
                    "PS server %s:%d (set BYTEPS_TPU_FLEET=1 on every "
                    "server); fleet plane disabled", c.host, c.port)
                return
        self._fleet_wire = True
        get_logger().info(
            "fleet plane armed: window summaries publish to the server "
            "tier (last-%d ring per worker)", self.fleet_windows)

    def fleet_clock_offset(self, max_age_s: float = 60.0,
                           samples: int = 3,
                           timeout: float = 5.0) -> Optional[dict]:
        """This worker's clock offset vs its rank-0 server, for the
        published window summary (the fleet doctor's clock_skew rule
        compares workers against the fleet median).  NTP-style estimate
        over CMD_PING round trips, cached for ``max_age_s`` so a window
        roll does not cost ping frames every time; called only from the
        signal-plane thread, never on a round's critical path.  None
        when no live server can answer."""
        now = time.monotonic()
        if self._fleet_clock is not None \
                and now - self._fleet_clock[0] < max_age_s:
            return self._fleet_clock[1]
        for slot, c in enumerate(self.conns):
            if slot in self._dead_slots:
                continue
            try:
                off, rtt = estimate_clock_offset(self._ping_server_clock(
                    c, samples=samples, timeout=timeout))
            except (ConnectionError, OSError, TimeoutError, ValueError,
                    RuntimeError):
                continue
            est = {"offset_us": float(off), "rtt_us": float(rtt),
                   "server": slot}
            self._fleet_clock = (now, est)
            return est
        return None

    def publish_window(self, window: int, doc: dict,
                       timeout: float = 10.0) -> bool:
        """Publish one window summary (CMD_WINDOW, key = window index)
        to this worker's rank-0 server — the first live conn, so a
        drained/dead server 0 fails over to the next member instead of
        silencing the worker's row.  Swallows wire errors (the plane
        must outlive a flaky server; the ring just misses a window) and
        returns whether the publish landed."""
        if not self._fleet_wire:
            return False
        import json as _json
        payload = _json.dumps(doc, separators=(",", ":")).encode()
        for slot, c in enumerate(self.conns):
            if slot in self._dead_slots:
                continue
            try:
                c.request(CMD_WINDOW, key=int(window), payload=payload,
                          worker_id=self.worker_id, timeout=timeout)
                self._fleet_publishes += 1
                return True
            except (ConnectionError, OSError, TimeoutError,
                    RuntimeError) as e:
                self._fleet_publish_errors += 1
                get_logger().debug(
                    "fleet publish of window %d to server %d failed: %s",
                    window, slot, e)
                return False
        self._fleet_publish_errors += 1
        return False

    def fetch_fleet(self, timeout: float = 10.0) -> dict:
        """The merged fleet view: every live server's CMD_FLEET rings,
        folded per (worker, window index).  After a drain two servers
        may briefly both hold a worker's windows (the migrated copy and
        the publisher's ongoing ring) — same-index rows are identical by
        construction (publishes are idempotent replace-in-place), so
        first-seen wins.  ``{"armed", "cap", "workers": {wid:
        [summary, ...]}, "servers_down"}`` with each worker's summaries
        ordered by window index."""
        merged: dict = {"armed": False, "cap": 0, "workers": {},
                        "servers_down": 0}
        by_idx: Dict[int, Dict[int, dict]] = {}
        for slot, c in enumerate(self.conns):
            if slot in self._dead_slots:
                merged["servers_down"] += 1
                continue
            try:
                doc = self._fleet_probe(c, timeout=timeout)
            except (ConnectionError, OSError, TimeoutError,
                    RuntimeError):
                # A dead server must not break the fleet plane — it is
                # exactly when the operator reads it.
                merged["servers_down"] += 1
                continue
            merged["armed"] = merged["armed"] or bool(doc.get("armed"))
            merged["cap"] = max(merged["cap"], int(doc.get("cap", 0)))
            for wid, rows in (doc.get("workers") or {}).items():
                ring = by_idx.setdefault(int(wid), {})
                for row in rows:
                    if not isinstance(row, dict) or "window" not in row:
                        continue   # a malformed publish poisons only
                        #            its own row, never the merge
                    ring.setdefault(int(row["window"]), row)
        for wid, ring in by_idx.items():
            merged["workers"][wid] = [ring[i] for i in sorted(ring)]
        return merged

    def fleet_stats(self) -> dict:
        """Publish-side accounting for telemetry / the /fleet route."""
        return {"armed": self._fleet_wire,
                "publishes": self._fleet_publishes,
                "publish_errors": self._fleet_publish_errors}

    def audit_check(self, timeout: float = 10.0) -> dict:
        """Cross-check this worker's last-K pulled-digest window against
        the servers' published-digest windows (CMD_AUDIT).

        Catches what the per-pull trailer check cannot: a round this
        worker pulled that the server no longer agrees on (divergence
        after the fact), and rounds missing from the server's window
        while inside its span (lost rounds across a failover).  Returns
        ``{"armed", "compared", "mismatches": [...], "lost_rounds":
        [...], "counters": {...}}``."""
        report = {"armed": self._audit_wire, "compared": 0,
                  "mismatches": [], "lost_rounds": []}
        with self._audit_lock:
            local = {k: list(dq)
                     for k, dq in self._audit_window_log.items()}
            report["counters"] = dict(self._audit_stats)
        if not self._audit_wire:
            return report
        srv = self.fetch_server_audit(timeout=timeout)
        report["servers_down"] = srv.get("servers_down", 0)
        for pkey, recs in local.items():
            rows = {int(r["r"]): r
                    for r in srv["keys"].get(pkey, ())}
            for rnd, dig, epoch, n in recs:
                row = rows.get(rnd)
                if row is None:
                    if rows and min(rows) <= rnd <= max(rows):
                        # Inside the server's retained window yet absent:
                        # the server never published (or lost) this round.
                        report["lost_rounds"].append(
                            {"key": pkey, "round": rnd})
                    continue
                report["compared"] += 1
                if int(row["d"]) != dig:
                    report["mismatches"].append({
                        "key": pkey, "round": rnd, "local": dig,
                        "server": int(row["d"]),
                        "contributors": row.get("w", [])})
        if report["mismatches"] or report["lost_rounds"]:
            _flightrec.record(
                "audit_cross_check",
                mismatches=len(report["mismatches"]),
                lost_rounds=len(report["lost_rounds"]),
                worker=self.worker_id)
        return report

    def audit_stats(self) -> dict:
        """Local auditor counters + the last verdict detail (no wire
        traffic; ``audit_check()`` is the cross-checking sibling)."""
        with self._audit_lock:
            return {"armed": self._audit_wire,
                    "window": self.audit_window,
                    **self._audit_stats,
                    "last": dict(self._audit_last)
                            if self._audit_last else None}

    def health_snapshot(self) -> dict:
        """The gradient-health monitor's last per-key samples (empty when
        BYTEPS_TPU_HEALTH_SAMPLE_ROUNDS is 0)."""
        return self._health.snapshot() if self._health is not None else {}

    def _bundle_extra(self) -> dict:
        """Session sections for a postmortem bundle — everything here is
        LOCAL state (no wire fetches): a bundle is dumped exactly when
        the wire may be the broken part."""
        out: dict = {"worker_id": self.worker_id}
        try:
            out["transport"] = self.transport_stats()
        except Exception:
            pass
        try:
            out["audit"] = self.audit_stats()
            # The worker's pulled-digest window rides the bundle so
            # tools/postmortem.py can compare (key, round) digests
            # ACROSS workers' bundles — two workers that pulled
            # different bytes for the same round is the silent
            # divergence this whole plane exists to name.
            with self._audit_lock:
                out["audit_window"] = {
                    str(k): [list(r) for r in dq]
                    for k, dq in self._audit_window_log.items()}
        except Exception:
            pass
        try:
            out["health"] = self.health_snapshot()
        except Exception:
            pass
        try:
            with self._ring_lock:
                if self._ring is not None:
                    out["ring"] = {"epoch": self._ring.epoch,
                                   "vnodes": self._ring.vnodes,
                                   "servers": list(self._ring.servers),
                                   "dead_slots":
                                       sorted(self._dead_slots)}
        except Exception:
            pass
        return out

    # -- distributed tracing: clock sync + server span fetch ----------------
    def _ping_server_clock(self, conn: "_ServerConn", samples: int = 5,
                           timeout: float = 10.0) -> list:
        """``samples`` timestamped ping exchanges with one server:
        [(t0_us, server_ts_us, t1_us), ...] on the tracer clock.  Raises a
        "server too old" RuntimeError against a server whose CMD_PING
        predates the timestamped response (it answers 0 bytes)."""
        core = get_core()
        out = []
        for _ in range(max(1, samples)):
            t0 = core.trace_now_us()
            raw = conn.request(CMD_PING, worker_id=self.worker_id,
                               flags=FLAG_TRACED, timeout=timeout)
            t1 = core.trace_now_us()
            if len(raw) < 8:
                raise RuntimeError(
                    f"PS server at {conn.host}:{conn.port} does not answer "
                    f"timestamped pings (server too old — rebuild/redeploy "
                    f"the server tier to match this client)")
            (ts,) = struct.unpack("<q", bytes(raw[:8]))
            out.append((t0, ts, t1))
        return out

    def sync_clocks(self, samples: int = 5) -> dict:
        """Estimate every server's clock offset (min-RTT NTP midpoint
        over timestamped CMD_PINGs) and APPEND it to the per-server
        offset history.  Called at trace-enable, by the periodic sync
        thread (every ``clock_sync_s``), and again at each fetch; the
        fetch corrects every span with the history entry nearest the
        span's timestamp, so periodic samples are what bounds drift
        across a long trace window.  Returns {server_idx: (offset_us,
        rtt_us)} for the fresh estimates."""
        est = {}
        for i, c in enumerate(self.conns):
            off, rtt = estimate_clock_offset(
                self._ping_server_clock(c, samples))
            self._append_clock_sample(i, off, rtt)
            est[i] = (off, rtt)
        return est

    @staticmethod
    def _server_clock_now(offset_us: float) -> float:
        """The server's clock 'now' implied by an offset estimate."""
        return get_core().trace_now_us() + offset_us

    def _append_clock_sample(self, srv: int, off: float,
                             rtt: float) -> list:
        """Record one offset estimate in server `srv`'s history; returns a
        snapshot of the history.  A jump far beyond what drift or RTT
        noise explains means the server process RESTARTED (a fresh
        steady_clock epoch) — the old entries would place post-restart
        spans wildly off the timeline, so the history resets to the new
        epoch instead of only logging."""
        with self._clock_lock:
            hist = self._clock_offsets.setdefault(srv, [])
            if hist:
                jump = abs(hist[-1][1] - off)
                if jump > max(1e6, 100 * rtt):
                    get_logger().warning(
                        "server %d clock offset jumped %.0fms (restart/"
                        "epoch change): resetting offset history",
                        srv, jump / 1e3)
                    hist.clear()
                elif jump > 1000:
                    get_logger().debug(
                        "server %d clock offset drifted %.0fus since "
                        "last sync", srv, jump)
            # Keyed by the SERVER clock at sync time, so a span's own
            # (server-clock) timestamp selects its nearest estimate
            # without a correction chicken-and-egg.
            hist.append((self._server_clock_now(off), off))
            del hist[:-64]              # bounded history
            return list(hist)

    def start_clock_sync(self) -> None:
        """Idempotently start the background re-sync thread: every
        ``clock_sync_s`` (BYTEPS_TPU_CLOCK_SYNC_S) it re-estimates the
        offsets — but only while the tracer is actually on, so an
        untraced run sends no extra wire traffic."""
        if self._clock_sync_thread is not None:
            return
        self._clock_sync_thread = threading.Thread(
            target=self._clock_sync_loop, daemon=True,
            name="bps-ps-clocksync")
        self._clock_sync_thread.start()

    def _clock_sync_loop(self) -> None:
        while not self._clock_sync_stop.wait(self.clock_sync_s):
            if not get_core().trace_on:
                continue
            try:
                self.sync_clocks()
            except Exception as e:
                get_logger().debug("periodic clock sync failed: %s", e)

    def set_trace_members(self, declared_key: int, names: list) -> None:
        """Record a fusion bucket's member-leaf names so the merged trace
        can annotate the bucket's spans with the real parameters riding
        it (the analyzer's slow-bucket attribution)."""
        self._trace_members[declared_key] = list(names)

    def trace_members(self) -> dict:
        return dict(self._trace_members)

    def fetch_server_trace(self, timeout: float = 30.0,
                           ping_timeout: float = 10.0,
                           ping_samples: int = 5) -> list:
        """Drain every server's span ring (CMD_TRACE) and return the
        spans offset-corrected onto THIS worker's tracer clock.

        Each span is ``{"server", "stage", "key", "round", "worker",
        "ts_us", "dur_us", "bytes"}`` with stage one of RECV / SUM /
        MERGE_WAIT / PUBLISH / PULL_SEND.  A fresh offset is estimated
        at the drain, then each span is corrected with the offset-history
        entry (trace-enable + periodic syncs + this one) NEAREST the
        span's own timestamp — early-window spans use early estimates,
        so clock drift across a long window is bounded by the sync
        cadence, not the window length.  Fetch-and-clear on the server:
        each span is returned to exactly one fetching worker.

        A pre-CMD_TRACE server surfaces as a clean "server too old"
        RuntimeError (the unknown command draws an error status from the
        engine's default arm) — never a hang.
        """
        import json as _json
        spans = []
        for i, c in enumerate(self.conns):
            off, rtt = estimate_clock_offset(self._ping_server_clock(
                c, samples=ping_samples, timeout=ping_timeout))
            hist = self._append_clock_sample(i, off, rtt)
            try:
                raw = c.request(CMD_TRACE, worker_id=self.worker_id,
                                timeout=timeout)
            except RuntimeError as e:
                raise RuntimeError(
                    f"PS server at {c.host}:{c.port} does not support "
                    f"CMD_TRACE (server too old — rebuild/redeploy the "
                    f"server tier to match this client): {e}") from e
            st = _json.loads(bytes(raw).decode())
            if st.get("dropped"):
                get_logger().warning(
                    "server %s:%d trace ring dropped %d spans — raise "
                    "BYTEPS_SERVER_TRACE_EVENTS or fetch more often",
                    c.host, c.port, st["dropped"])
            for s in st.get("spans", ()):
                ts = s["ts"]
                # Nearest-in-time estimate: history is keyed by the
                # server clock, as is the span's ts.
                _, use_off = min(hist, key=lambda h: abs(h[0] - ts))
                spans.append({
                    "server": i, "stage": s["st"], "key": int(s["k"]),
                    "round": int(s["r"]), "worker": int(s["w"]),
                    "ts_us": int(round(ts - use_off)),
                    "dur_us": int(s["d"]), "bytes": int(s["b"]),
                })
        return spans

    # -- test/introspection hooks -------------------------------------------
    def pause_dispatch(self) -> None:
        """Hold dispatch so several push_pull_async calls can enqueue before
        any push is issued (deterministic priority-order tests)."""
        with self._cv:
            self._paused = True

    def resume_dispatch(self) -> None:
        with self._cv:
            self._paused = False
            self._cv.notify_all()

    # -- public API ---------------------------------------------------------
    def push_pull_async(self, declared_key: int, tensor,
                        priority: int = 0, raw: bool = False,
                        seed: bool = False, copy: bool = False) -> PSHandle:
        """Partitioned, priority-scheduled asynchronous push_pull.

        ZERO-COPY CONTRACT: when `tensor` is already a contiguous float32
        buffer, partitions are wire views of the caller's memory (the
        reference's ZPush zero-copy SArray semantics) — the caller must
        not mutate it until the returned handle completes.  Non-f32 or
        non-contiguous inputs are converted (snapshotted) first.
        copy=True restores the old snapshot semantics unconditionally for
        callers that need to keep mutating the buffer after dispatch
        (documented in docs/migration.md "wire semantics").

        raw=True pushes last-write-wins bytes instead of f32-summed values.
        seed=True (async servers only) writes the store ONLY if the key has
        never been pushed — idempotent initial-weight seeding that cannot
        reset a live run when a worker joins late or rejoins.
        """
        handle, parts = self._stage(declared_key, tensor, priority, raw,
                                    seed, copy)
        self._enqueue([(parts, priority)])
        return handle

    def push_pull_group(self, items, raw: bool = False, seed: bool = False,
                        copy: bool = False) -> List[PSHandle]:
        """Grouped staging: stage EVERY (declared_key, tensor, priority)
        item, then enqueue them all under one dispatcher wakeup.

        This is the fusion layer's dispatch face (common/fusion.py): the
        priority ScheduledQueue sees the whole bucket set before the
        dispatcher picks, so buckets leave in strict (priority desc, key
        asc) order even without a credit limit slowing the first pick —
        and N buckets cost one lock round-trip instead of N.  Each item
        follows the same zero-copy contract as push_pull_async.
        """
        staged: List[tuple] = []
        handles: List[PSHandle] = []
        seen: set = set()
        try:
            for declared_key, tensor, priority in items:
                if declared_key in seen:
                    # A repeated key inside one group would deadlock: its
                    # _stage blocks on the earlier round's completion,
                    # which can't happen until that round is enqueued.
                    # Flush what's staged so the guard can make progress.
                    self._enqueue(staged)
                    staged, seen = [], set()
                h, parts = self._stage(declared_key, tensor, priority, raw,
                                       seed, copy)
                handles.append(h)
                staged.append((parts, priority))
                seen.add(declared_key)
        except Exception:
            # The failing item rolled back its own parts in _stage; the
            # EARLIER items are staged but will never be enqueued — unpin
            # them too, or their keys wedge every later push (the
            # sequential-use guard would wait on done_evts nothing sets).
            with self._inflight_lock:
                for parts, _ in staged:
                    for p in parts:
                        if self._inflight.get(p.pkey) is p:
                            del self._inflight[p.pkey]
                        p.done_evt.set()
            raise
        self._enqueue(staged)
        return handles

    def _stage(self, declared_key: int, tensor, priority: int, raw: bool,
               seed: bool, copy: bool) -> tuple:
        """Partition + stage one tensor into _inflight (INITs included)
        WITHOUT enqueueing — the caller batches the queue adds so grouped
        pushes enter the scheduler atomically."""
        arr = np.asarray(tensor)
        payload = np.ascontiguousarray(arr, dtype=np.float32).ravel()
        if copy and np.may_share_memory(payload, arr):
            # Snapshot only when the wire view would alias the caller's
            # memory — the non-f32/non-contiguous path already copied.
            payload = payload.copy()
        # Zero-copy wire: partitions are sent as memoryview slices of the
        # caller's buffer (no tobytes snapshot) — the reference's ZPush
        # contract: the tensor must not be mutated until the handle
        # completes.  The sequential-use guard in _stage_parts already
        # serializes re-pushes of the same key.
        plan = self._plan(declared_key, payload.nbytes)
        # np.empty, not np.zeros: every partition's pull fills its slice
        # before wait() can return the buffer (and a failed handle never
        # returns it at all), so pre-zeroing a 64MB result buffer every
        # round was a pure memset tax on the pull path.
        handle = PSHandle(arr.shape, arr.dtype, len(plan),
                          np.empty(payload.nbytes // 4, np.float32))
        mv = memoryview(payload).cast("B")
        # Pending codec renegotiation whose round boundary this push
        # reaches applies HERE, before the kwargs/INIT and any encode —
        # the worker half of the atomic switch.  The GLOBAL knob table
        # applies at the same boundary (staged CMD_KNOB switch whose
        # effective round this session has reached): pool resize and
        # lane dial happen before any of this round's parts stage.
        self._maybe_apply_knobs(self._round.get(plan[0][0], 0))
        comp = self._current_compressor(declared_key, plan)
        kw_bytes = comp.kwargs_string().encode() if comp else b""
        label = self._label(declared_key)
        if self._health is not None and not raw and not seed:
            # Push-side value health (every Nth round of this key):
            # norm/absmax/NaN/Inf of the gradient about to ride the
            # wire, plus the EF residual when a compressor carries one.
            # Keyed by the key's REAL round (first partition's counter)
            # so push and pull samples align; the numpy pass runs on the
            # codec pool over a snapshot when there is one.
            self._health.sample_push(
                label, payload, self._round.get(plan[0][0], 0),
                pool=self._codec_pool, comp=comp)
        parts: list = []
        consumed_folds: dict = {}
        for attempt in range(4):
            try:
                self._stage_parts(plan, payload, mv, comp, kw_bytes,
                                  handle, parts, raw, seed, label,
                                  priority, consumed_folds)
                # Stamp the fusion-layout generation these parts were
                # staged under — the dispatcher gate and the KNOB_STALE
                # replay use it to withdraw layout-dependent pushes that
                # a later FUSION_BYTES switch orphans.
                gen = self._knob_gen
                for p in parts:
                    p.knob_gen = gen
                return handle, parts
            except _KeyMoved as e:
                # A staging INIT hit a ring transition: roll back, adopt
                # the attached table, re-plan against it, retry (partition
                # BOUNDS are placement-independent, so the handle stays
                # valid).  Bounded — a healthy ring settles in one hop.
                self._rollback_stage(parts)
                self._restore_folds(consumed_folds)
                parts = []
                self._adopt_ring_doc(e.doc)
                if attempt == 3:
                    raise RuntimeError(
                        f"ring kept moving while staging key "
                        f"{declared_key}") from e
                plan = self._plan(declared_key, payload.nbytes)
            except Exception:
                # Roll back partitions already staged in _inflight:
                # leaving them would wedge the key forever (the
                # sequential-use guard waits on done_evt, which nothing
                # would ever set).
                self._rollback_stage(parts)
                self._restore_folds(consumed_folds)
                raise
        return handle, parts

    def _restore_folds(self, consumed: dict) -> None:
        """Re-stage EF folds a rolled-back staging attempt consumed (the
        residual must ride the RETRY, not vanish with the rollback).
        Folds adopted into an EF compressor's state need no restore —
        that state survives the rollback."""
        for pkey, fold in consumed.items():
            if pkey not in self._ef_fold:
                self._ef_fold[pkey] = fold
        consumed.clear()

    def _rollback_stage(self, parts: list) -> None:
        with self._inflight_lock:
            for p in parts:
                if self._inflight.get(p.pkey) is p:
                    del self._inflight[p.pkey]
                p.done_evt.set()

    def _enqueue(self, staged) -> None:
        """Enqueue staged partitions ([(parts, priority), ...]) into the
        scheduler under ONE condition-variable hold."""
        core = get_core()
        enq = core.trace_now_us() if core.trace_on else 0
        # New work resets the stall clock: an idle session's age must not
        # count against the first round staged after the lull.
        self._mark_progress()
        enq_mono = time.monotonic()
        with self._cv:
            for parts, priority in staged:
                for p in parts:
                    p.enq_ts = enq
                    p.enq_mono = enq_mono
                    # credit_ln: actual wire bytes for ready parts; the
                    # codec's worst-case bound for pipelined encodes (their
                    # true size doesn't exist yet and p.wire_ln is racing
                    # the encoder).  The queue returns the same figure at
                    # get(), so report_finish stays symmetric either way.
                    self._queue.add(p.pkey, priority, p.credit_ln)
            self._cv.notify_all()

    def _label(self, declared_key: int) -> str:
        """Tensor name for trace rows (falls back to the numeric key for
        sessions driven outside the declare() registry)."""
        lbl = self._trace_labels.get(declared_key)
        if lbl is None:
            name = get_core().declared_name(declared_key)
            lbl = name if name else f"key_{declared_key}"
            self._trace_labels[declared_key] = lbl
        return lbl

    def _init_parts(self, plan, kw_bytes) -> None:
        """Pipelined per-partition CMD_INIT: issue every needed INIT
        concurrently, then await them all — one round-trip time per tensor
        instead of one blocking round-trip per partition (a 64-partition
        tensor's first push used to pay 64 serial RTTs here).  All futures
        resolve before any partition is staged, so the PUSH of a key can
        never beat its INIT to the server."""
        deadline = time.monotonic() + 60.0
        inits = []
        for pkey, off, ln, srv in plan:
            if self._inited.get(pkey) != (ln, kw_bytes):
                conn = self.conns[srv]    # control traffic: primary lane
                init_payload = struct.pack(
                    "<QI", ln, len(kw_bytes)) + kw_bytes
                inits.append((pkey, ln, conn, init_payload,
                              self._send_init(conn, pkey, init_payload,
                                              deadline)))
        for pkey, ln, conn, init_payload, fut in inits:
            while True:
                try:
                    resp = fut.wait(max(0.1, deadline - time.monotonic()))
                    break
                except _ConnLost as e:
                    # Dropped mid-outage with reconnect active: INIT is
                    # idempotent, so ride out the re-dial and re-issue it
                    # until the deadline — a staging caller should survive
                    # the same faults the in-flight parts do.
                    if not e.will_reconnect or time.monotonic() > deadline:
                        raise
                    fut = self._send_init(conn, pkey, init_payload, deadline)
            # Seed the round counter from server state so a reconnected
            # worker can never pull a stale previous round.
            (completed,) = struct.unpack("<Q", resp)
            self._round[pkey] = completed
            self._inited[pkey] = (ln, kw_bytes)

    def _send_init(self, conn: "_ServerConn", pkey: int, payload: bytes,
                   deadline: float) -> "_Future":
        """Send one CMD_INIT, waiting out a mid-reconnect window (sends
        raise `_ConnLost(will_reconnect=True)` while the conn re-dials)."""
        while True:
            try:
                return conn.send(CMD_INIT, pkey, payload,
                                 worker_id=self.worker_id)
            except _ConnLost as e:
                if not e.will_reconnect or time.monotonic() > deadline:
                    raise
                time.sleep(0.05)

    def _encode_part(self, part: "_PartTask", comp, seg) -> None:
        """Produce one partition's compressed wire payload on a codec pool
        thread, recording the ENCODE span; always resolves part.ready (an
        unset event would hang the dispatcher on this key forever)."""
        core = get_core()
        t0 = core.trace_now_us()
        try:
            blob = comp.encode(part.pkey, seg)
            part.payload = blob
            part.wire_ln = len(blob)
        except Exception as e:
            part.enc_err = e
        finally:
            # ready FIRST: if the tracer/stats below ever raised, an unset
            # event would wedge the in-order dispatcher forever (the
            # pool's catch-all only logs).
            part.ready.set()
            dur = core.trace_now_us() - t0
            if core.trace_on:
                core.trace_record_part(part.label, "ENCODE", t0, dur,
                                       part.pkey, part.wire_ln,
                                       part.priority)
            self._codec_pool.record("ENCODE", dur)
            _signals.note_codec(part.label or f"key_{part.pkey >> 16}",
                                "encode", dur)

    def _stage_parts(self, plan, payload, mv, comp, kw_bytes, handle,
                     parts, raw, seed, label="", priority=0,
                     consumed_folds=None) -> None:
        self._init_parts(plan, kw_bytes)
        pool = self._codec_pool
        core = get_core()
        for pkey, off, ln, srv in plan:
            seg = payload[off // 4:(off + ln) // 4]
            # BYTEPS_MIN_COMPRESS_BYTES floor: small partitions go raw
            # (reference: operations.cc:362-364).
            use_comp = (comp is not None and not raw and not seed
                        and ln >= self.min_compress_bytes)
            # EF residual detached by a codec switch whose target cannot
            # carry it: fold it into this partition's push exactly once
            # (the EF-across-switch conservation law).  If the current
            # codec CAN carry it (a later switch back to an EF codec),
            # adopt it instead — same total either way.
            folded = False
            fold = self._ef_fold.get(pkey)
            if fold is not None and not raw and not seed \
                    and fold.size == ln // 4:
                self._ef_fold.pop(pkey, None)
                if use_comp and comp.ef:
                    comp.adopt_ef_state({pkey: fold})
                else:
                    seg = (seg + fold).astype(np.float32)
                    folded = True
                    if consumed_folds is not None:
                        consumed_folds[pkey] = fold
            if use_comp and pool is None:
                # Inline fallback (BYTEPS_TPU_COMPRESS_THREADS=0): encode
                # on the caller thread, the pre-pipeline data path.
                t0 = (core.trace_now_us()
                      if core.trace_on or _signals.plane() is not None
                      else 0)
                wire_payload = comp.encode(pkey, seg)
                if t0:
                    dur = core.trace_now_us() - t0
                    if core.trace_on:
                        core.trace_record_part(
                            f"{label}.part{pkey & 0xFFFF}", "ENCODE", t0,
                            dur, pkey, len(wire_payload), priority)
                    # Inline encodes must feed the signal plane too, or
                    # the compute_bound class is unreachable in the
                    # compress_threads=0 config.
                    _signals.note_codec(
                        label or f"key_{pkey >> 16}", "encode", dur)
                dtype = DT_COMPRESSED
            elif use_comp:
                wire_payload = None     # pipelined: the pool fills it in
                dtype = DT_COMPRESSED
            else:
                # A folded segment is a fresh array: its bytes ride the
                # wire (part.seg keeps it alive); otherwise the caller's
                # buffer rides zero-copy as before.
                wire_payload = (memoryview(seg).cast("B") if folded
                                else mv[off:off + ln])
                dtype = DT_SEED if seed else (DT_RAW if raw else DT_F32)
            # Sequential-use guard: a second async push_pull of the same
            # tensor before the first completed waits for that partition.
            # Check-and-insert is atomic under _inflight_lock, and the round
            # tag is read inside the same critical section (after any
            # previous round's _on_pull bumped it).
            while True:
                with self._inflight_lock:
                    prev = self._inflight.get(pkey)
                    if prev is None:
                        part = _PartTask(
                            pkey, wire_payload, off, ln,
                            self._round.get(pkey, 0), srv, handle,
                            dtype=dtype,
                            bidirectional=use_comp and comp.bidirectional,
                            label=f"{label}.part{pkey & 0xFFFF}")
                        part.priority = priority
                        if not raw and not seed:
                            part.seg = seg   # re-encode source (CODEC_STALE)
                        if wire_payload is None:
                            part.ready = threading.Event()
                            # Credit charge for a not-yet-encoded part:
                            # the codec's worst-case wire size (never the
                            # raw 4n — that would cut credit-gated
                            # concurrency by the compression ratio).
                            part.credit_ln = min(
                                ln, comp.wire_cap_bytes(ln // 4))
                        self._inflight[pkey] = part
                        parts.append(part)
                        handle._register_part(pkey)
                        break
                prev.done_evt.wait(timeout=60.0)
            if part.ready is not None:
                # Submitted AFTER the guard admits the part, so the encoder
                # reads this round's EF/momentum/PRNG state strictly after
                # the previous round's encode finished with it; the pool
                # drains jobs in (priority desc, key asc) order, ahead of
                # the dispatcher's identical order, overlapping partition
                # k's wire send with the encode of k+1.
                pool.submit(priority, pkey,
                            lambda part=part, seg=seg:
                                self._encode_part(part, comp, seg))

    # -- row-sparse embedding plane (docs/sparse-embedding.md) ----------
    #
    # Embedding keys bypass the partitioned dispatcher entirely: a table
    # is ONE wire key (part 0) on ONE server, its payloads are
    # (indices, rows) pairs — wire bytes proportional to touched rows,
    # never to table size — and its pulls are batched row lookups.  The
    # ring still places the key, MOVED still redirects it, and a
    # reconnecting conn still replays it, all through the same retry
    # laws the dense path uses; it just never pays partition planning,
    # fusion, or codec staging built for dense trees.

    def _embed_pkey(self, key: int) -> int:
        return get_core().encode_key(key, 0)

    def _embed_srv(self, pkey: int) -> int:
        if self._ring is not None:
            with self._ring_lock:
                return self._srv_slot[self._ring.owner(pkey)]
        return get_core().key_to_server(pkey, len(self.conns),
                                        self.hash_fn)

    def _embed_request(self, cmd: int, pkey: int, payload: bytes,
                       dtype: int = 0, flags: int = 0,
                       timeout: float = 60.0) -> bytes:
        """One blocking embed-plane round trip that survives the same
        faults the dense path does: MOVED adopts the attached ring table
        and re-routes to the new owner (state-before-redirect is the
        server's contract, so a ring drain mid-request is invisible
        beyond latency), and a reconnecting conn's `_ConnLost` rides out
        the re-dial.  Both replays are safe by construction — pushes
        dedup on the server's per-round `seen` set, reads and INIT are
        idempotent."""
        deadline = time.monotonic() + max(0.1, timeout)
        while True:
            conn = self.conns[self._embed_srv(pkey)]
            try:
                return conn.request(
                    cmd, pkey, payload, worker_id=self.worker_id,
                    dtype=dtype, flags=flags,
                    timeout=max(0.1, deadline - time.monotonic()))
            except _KeyMoved as e:
                if time.monotonic() > deadline:
                    raise
                self._safe_adopt_ring(e.doc)
                with self._transport_lock:
                    self._tstats["ring_redirects"] += 1
            except _ConnLost as e:
                if not e.will_reconnect or time.monotonic() > deadline:
                    raise
                time.sleep(0.05)

    def declare_embedding(self, key: int, rows: int, width: int,
                          kwargs: str = "", timeout: float = 60.0) -> None:
        """Declare a server-resident embedding table of ``rows`` x
        ``width`` f32 under declared key ``key`` (idempotent: a same-
        shape re-declare preserves server state, so reconnects and extra
        sessions are safe).  ``kwargs`` rides the INIT kwargs string —
        the same surface CMD_OPT arming uses (e.g. ``opt=adagrad,
        lr=0.01``)."""
        rows, width = int(rows), int(width)
        if rows <= 0 or width <= 0:
            raise ValueError("embedding shape must be positive, got "
                             f"{rows}x{width}")
        kw = f"embed_rows={rows},embed_width={width}"
        if kwargs:
            kw += "," + kwargs
        kw_bytes = kw.encode()
        pkey = self._embed_pkey(key)
        resp = self._embed_request(
            CMD_INIT, pkey,
            struct.pack("<QI", 0, len(kw_bytes)) + kw_bytes,
            timeout=timeout)
        # Seed the accumulating round from server state, exactly like
        # the dense _init_parts law — a re-declaring session can never
        # push into (or pull from) a stale round.
        (completed,) = struct.unpack("<Q", resp)
        self._round[pkey] = completed
        # Register with the control planes the dense path feeds from
        # _init_parts/_plan: _inited makes propose_opt()'s pkey
        # enumeration see the table, _pkey_srv routes its CMD_OPT frames
        # (refreshed by the MOVED handler like any other key's).
        self._inited[pkey] = (0, kw_bytes)
        self._pkey_srv[pkey] = self._embed_srv(pkey)
        with self._embed_lock:
            self._embed_meta[key] = (rows, width)
            self._embed_cache.pop(key, None)
            self._embed_ver.pop(key, None)
            self._embed_ver_ts.pop(key, None)

    def arm_embedding(self, key: int, kwargs, table=None,
                      effective_round: int = 0) -> dict:
        """Arm the row-wise server-resident optimizer on an embedding
        key: CMD_OPT SET (the dense propose_opt law — epoch-versioned,
        idempotent, applied at a round boundary) plus an optional
        full-table initial-parameter seed.  From the effective round on,
        publishes serve post-update PARAMETER rows and optimizer slots
        materialize row-by-row, only for pushed rows — dense optimizer
        state never exists on any worker."""
        rows, width = self._embed_shape(key)
        doc = self.propose_opt(key, kwargs,
                               effective_round=effective_round)
        if table is not None:
            t = np.ascontiguousarray(np.asarray(table, dtype=np.float32))
            if t.shape != (rows, width):
                raise ValueError(f"seed table shape {t.shape} != "
                                 f"declared {(rows, width)}")
            # Full-table seed in ONE frame to the one owner — the dense
            # seed_params() partitioner must not split an embed key.
            self._embed_request(CMD_OPT, self._embed_pkey(key),
                                t.tobytes(), flags=2)
        return doc

    def _embed_shape(self, key: int) -> Tuple[int, int]:
        with self._embed_lock:
            meta = self._embed_meta.get(key)
        if meta is None:
            raise KeyError(f"embedding key {key} not declared "
                           "(call declare_embedding first)")
        return meta

    @staticmethod
    def _embed_coalesce(indices, rows, width: int):
        """Sort + dedup the caller's (indices, rows) pair into the wire
        form: unique ascending u32 indices with duplicate rows SUMMED
        (gradient semantics — two touches of one row in a batch are one
        accumulated update).  Returns (uniq, acc, inverse)."""
        idx = np.ascontiguousarray(np.asarray(indices).ravel(),
                                   dtype=np.uint32)
        uniq, inv = np.unique(idx, return_inverse=True)
        if rows is None:
            return uniq, None, inv
        dense = np.ascontiguousarray(
            np.asarray(rows, dtype=np.float32)).reshape(idx.size, width)
        if uniq.size == idx.size:
            acc = dense[np.argsort(idx, kind="stable")]
        else:
            acc = np.zeros((uniq.size, width), dtype=np.float32)
            np.add.at(acc, inv, dense)
        return uniq, acc, inv

    def push_pull_sparse(self, key: int, indices, rows,
                         timeout: float = 60.0) -> np.ndarray:
        """Row-sparse push_pull: merge this worker's (indices, rows)
        gradient into the server-resident table's accumulating round,
        wait for the round to publish (every member pushed; the server
        runs the row-wise optimizer step on exactly the touched rows),
        and return the published rows for the SAME indices, aligned to
        the caller's index order.  Wire bytes are proportional to
        touched rows on both legs — never to table size."""
        if self.pull_only:
            raise RuntimeError("pull-only session cannot push_pull_sparse"
                               " (it is not a round member); use "
                               "pull_rows")
        trows, width = self._embed_shape(key)
        uniq, acc, inv = self._embed_coalesce(indices, rows, width)
        if uniq.size and int(uniq[-1]) >= trows:
            raise IndexError(f"row index {int(uniq[-1])} out of range "
                             f"for embedding of {trows} rows")
        from .wire import (decode_sparse_response, encode_sparse_block)
        pkey = self._embed_pkey(key)
        rnd = self._round.get(pkey, 0)
        flags = rnd & ROUND_MASK
        push = encode_sparse_block(uniq, acc, width)
        self._embed_request(CMD_PUSH, pkey, push, dtype=DT_SPARSE,
                            flags=flags, timeout=timeout)
        # Round-gated pull: same round tag, parks server-side until the
        # round publishes, then serves the optimizer-stepped (armed) or
        # merged-sum (unarmed) rows.
        req = encode_sparse_block(uniq, None, width)
        resp = self._embed_request(CMD_PULL, pkey, req, dtype=DT_SPARSE,
                                   flags=flags, timeout=timeout)
        ver, out = decode_sparse_response(resp, uniq.size, width)
        self._round[pkey] = rnd + 1
        self._m_embed_pull_bytes.inc(len(req) + len(resp))
        self._embed_note_version(key, ver, uniq, out)
        return out[inv]

    def pull_rows(self, key: int, indices,
                  timeout: float = 60.0) -> np.ndarray:
        """Batched row lookup against the last PUBLISHED table state —
        the read path recsys serving wants.  Ungated on the wire
        (DT_SPARSE_READ): served immediately from the server's published
        rows, never parking on a round and never touching round state,
        so a pull-only session can hammer it freely.

        Hot rows are served from the param_version-keyed LRU cache: when
        every requested row is cached at the key's last-seen version and
        that version is still fresh (refreshed by any embed response
        within BYTEPS_TPU_SPARSE_CACHE_TTL_MS), the lookup completes
        with ZERO wire frames.  Misses are coalesced into batched wire
        units (fusion.plan_row_batches) capped at partition_bytes."""
        trows, width = self._embed_shape(key)
        uniq, _, inv = self._embed_coalesce(indices, None, width)
        if uniq.size and int(uniq[-1]) >= trows:
            raise IndexError(f"row index {int(uniq[-1])} out of range "
                             f"for embedding of {trows} rows")
        out = np.empty((uniq.size, width), dtype=np.float32)
        missing: List[int] = []
        hits = 0
        now = time.monotonic()
        with self._embed_lock:
            cache = self._embed_cache.get(key)
            fresh = (cache is not None
                     and key in self._embed_ver
                     and self._embed_cache_ttl > 0
                     and now - self._embed_ver_ts.get(key, 0.0)
                     <= self._embed_cache_ttl)
            for j in range(uniq.size):
                r = int(uniq[j])
                row = cache.get(r) if fresh else None
                if row is None:
                    missing.append(j)
                else:
                    out[j] = row
                    cache.move_to_end(r)
                    hits += 1
        if hits:
            self._m_embed_hits.inc(hits)
        if not missing:
            return out[inv]     # warm path: zero wire frames
        self._m_embed_misses.inc(len(missing))
        from ..common.fusion import plan_row_batches
        from .wire import (decode_sparse_response, encode_sparse_block)
        pkey = self._embed_pkey(key)
        miss = np.asarray(missing, dtype=np.int64)
        miss_idx = uniq[miss]
        for start, stop in plan_row_batches(miss_idx.size, width,
                                            self.partition_bytes):
            sub = miss_idx[start:stop]
            req = encode_sparse_block(sub, None, width)
            resp = self._embed_request(CMD_PULL, pkey, req,
                                       dtype=DT_SPARSE_READ,
                                       timeout=timeout)
            ver, got = decode_sparse_response(resp, sub.size, width)
            self._m_embed_pull_bytes.inc(len(req) + len(resp))
            out[miss[start:stop]] = got
            self._embed_note_version(key, ver, sub, got)
        return out[inv]

    def embed_version(self, key: int) -> Optional[int]:
        """Last param_version observed for ``key`` (None before any
        embed response) — what pull-only readers assert monotone."""
        with self._embed_lock:
            return self._embed_ver.get(key)

    def embed_cache_stats(self) -> dict:
        """Hot-row cache counters + occupancy (for bps_top / tests)."""
        with self._embed_lock:
            held = sum(len(c) for c in self._embed_cache.values())
        return {"hits": self._m_embed_hits.value(),
                "misses": self._m_embed_misses.value(),
                "rows_cached": held,
                "capacity_rows": self._embed_cache_rows}

    def _embed_note_version(self, key: int, ver: int, uniq,
                            got) -> None:
        """Fold one embed response into the hot-row cache under the
        invalidation law: a param_version ADVANCE drops every cached row
        of the key (they are rows of a superseded table state); matching
        versions insert/refresh.  Any response refreshes the freshness
        clock — the TTL bounds how long a version is trusted without
        hearing from the server."""
        if self._embed_cache_rows <= 0:
            with self._embed_lock:
                self._embed_ver[key] = int(ver)
                self._embed_ver_ts[key] = time.monotonic()
            return
        with self._embed_lock:
            if self._embed_ver.get(key) != int(ver):
                self._embed_cache[key] = OrderedDict()
                self._embed_ver[key] = int(ver)
            self._embed_ver_ts[key] = time.monotonic()
            cache = self._embed_cache.setdefault(key, OrderedDict())
            for j in range(len(uniq)):
                r = int(uniq[j])
                cache[r] = np.array(got[j], dtype=np.float32, copy=True)
                cache.move_to_end(r)
            while len(cache) > self._embed_cache_rows:
                cache.popitem(last=False)

    def push_pull(self, key: int, tensor, priority: int = 0,
                  **kw) -> np.ndarray:
        return self.push_pull_async(key, tensor, priority, **kw).wait()

    def barrier(self, generation: int = 0) -> None:
        """Global barrier across workers (reference: Postoffice::Barrier via
        the scheduler; here server 0 plays the rendezvous role).

        Waits forever by default (peers are allowed to be slow), logging a
        periodic "still waiting" warning; BYTEPS_TPU_BARRIER_TIMEOUT_S > 0
        turns a dead peer into a loud TimeoutError instead of a silent
        hang.  Warnings and the timeout report the live epoch membership
        and which ranks the barrier is actually waiting on (CMD_MEMBERS),
        so a dead/evicted peer is named rather than guessed at.

        Generations are ONE-SHOT (use a fresh, monotonically increasing
        number per rendezvous): once a generation releases, any later
        arrival at it — an elastic joiner catching up to the startup
        rendezvous the incumbents passed long ago — returns immediately
        instead of waiting for arrivals that will never come."""
        self.conns[0].request(
            CMD_BARRIER, generation, worker_id=self.worker_id,
            timeout=self.barrier_timeout_s or None,
            barrier_diag=lambda gen=generation:
                self._barrier_diag_text(gen))

    def shutdown_servers(self) -> None:
        for c in self.conns:
            try:
                c.request(CMD_SHUTDOWN, worker_id=self.worker_id)
            except (ConnectionError, OSError) as e:
                get_logger().debug("shutdown race: %s", e)

    def codec_stats(self) -> dict:
        """Codec pipeline counters (parts encoded/decoded off-thread and
        busy time); zeros with the pipeline disabled (compress_threads=0,
        where codec work runs inline on the caller/receiver threads)."""
        if self._codec_pool is None:
            return dict(CompressionPool.ZERO_STATS)
        return self._codec_pool.stats()

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        # Detach the bundle provider (only if still ours — a later
        # session owns the slot otherwise).
        _flightrec.remove_extra_provider("session", owner=self)
        self._watchdog_stop.set()
        self._srvdown_stop.set()
        self._clock_sync_stop.set()
        self._lease_stop.set()
        # Detach the queue-depth gauge's sampler: the registry outlives the
        # session, and a lazy gauge holding `self` would both leak the
        # session and report a dead scheduler's depth.  Only if the gauge
        # still carries OUR sampler — a later session owns it otherwise,
        # and zeroing here would silence a live scheduler's depth.
        if self._m_queue_depth._fn is self._queue_depth_fn:
            self._m_queue_depth.set_fn(None)
            self._m_queue_depth.set(0)
        # Dispatcher first (it may be waiting on an encode the pool still
        # owes), then the codec pool (drains queued jobs so every staged
        # handle resolves), then the sockets.
        self._dispatcher.join(timeout=self._join_timeout_s)
        self._warn_if_wedged(self._dispatcher)
        if self._codec_pool is not None:
            self._codec_pool.close()
        for pool in self._data_conns:
            for c in pool:
                c.close()
        if self._watchdog is not None:
            self._watchdog.join(timeout=5)

    def _warn_if_wedged(self, thread: threading.Thread) -> None:
        """A join() that expired used to leak the thread silently; name it
        and what it was blocked on so a shutdown hang is diagnosable."""
        if not thread.is_alive():
            return
        with self._inflight_lock:
            keys = sorted(self._inflight)
        get_logger().warning(
            "PS session close: thread %s did not exit within its join "
            "timeout and is being leaked (daemon); in-flight partition "
            "keys it may be blocked on: %s%s", thread.name, keys[:16],
            f" (+{len(keys) - 16} more)" if len(keys) > 16 else "")
